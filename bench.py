#!/usr/bin/env python
"""Benchmark: DM-trials/sec of the sweep engine vs single-core NumPy.

Metric (BASELINE.md): DM-trials/sec on a 1024-channel filterbank at 64 us
sampling; one "DM trial" = dedispersing + boxcar-detecting the full segment at
one DM. ``vs_baseline`` is the speedup over a single-core NumPy implementation
doing the reference's brute-force per-channel-roll dedispersion
(reference formats/spectra.py:229-260 semantics) with the same detection step,
measured on a time slice and a trial subset and scaled linearly (NumPy cost is
linear in both; the scaling is stated in the JSON).

HBM budgeting (round-3 fix: BENCH_r02 OOM'd the chip): the dataset is
device-resident only up to a byte budget derived from the accelerator's HBM
(16 GB on v5e, override PYPULSAR_TPU_HBM_GB); the chunk payload is sized for
a power-of-two FFT length, the streaming dispatch depth (max_pending) is
computed from the leftover budget, and an in-child RESOURCE_EXHAUSTED retry
halves the dataset until the run fits. The measured configuration is always
recorded in the JSON.

Robustness contract (round-1 postmortem): this script ALWAYS prints exactly
one JSON line of the required shape and exits 0, whatever the TPU tunnel
does. Backend acquisition retries with bounded backoff; if the accelerator
backend cannot initialize, the benchmark re-execs itself on the CPU backend
(reduced shapes) so the round still records a measured number, with the
fallback noted in ``unit``.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Usage: python bench.py [--quick] [--trials D] [--nsamp T] [--nchan C]
                       [--engine auto|gather|scan|fourier] [--ab]
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

from pypulsar_tpu.tune import knobs

V5E_HBM_BYTES = 16e9
V5E_HBM_BW = 819e9  # HBM roofline, bytes/s


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small shapes for smoke tests")
    ap.add_argument("--trials", type=int, default=None, help="number of DM trials")
    ap.add_argument("--nchan", type=int, default=None)
    ap.add_argument("--nsamp", type=int, default=None)
    ap.add_argument("--dm-max", type=float, default=500.0)
    ap.add_argument("--engine", default="auto",
                    help="sweep chunk engine: auto|gather|scan|fourier|tree")
    ap.add_argument("--tune", action="store_true",
                    help="auto-tuning A/B (round 17): bounded search vs "
                         "hand-picked defaults at >=2 geometries, "
                         "cache-hit reuse gate, science-invariance "
                         "byte check (BENCH_r12_tune.json)")
    ap.add_argument("--tune-trials", type=int, default=None,
                    help="trial budget per stage search (default: the "
                         "PYPULSAR_TPU_TUNE_TRIALS knob)")
    ap.add_argument("--compile", action="store_true",
                    help="compilation-plane A/B (round 22): cold vs "
                         "warm compile counters over 3 toy geometries, "
                         "bucket-ladder collapse, cross-process "
                         "persistent-cache hits, and the fleet "
                         "warm-pool precompile overlap "
                         "(BENCH_r17_compile.json)")
    ap.add_argument("--dedisp-tree", action="store_true",
                    help="run the round-16 three-engine dedispersion A/B "
                         "(gather vs fourier vs tree) at a production "
                         "DM-count geometry (>=1024 chans, >=1000 "
                         "trials): SNR parity asserted in-process, "
                         "structural adds/cell from "
                         "tools/dedisp_roofline.py as the gate "
                         "(BENCH_r11_tree.json)")
    ap.add_argument("--baseline-trials", type=int, default=None,
                    help="NumPy trials to actually run before extrapolating")
    ap.add_argument("--profile", action="store_true",
                    help="print a per-stage timing breakdown to stderr")
    ap.add_argument("--ab", action="store_true",
                    help="run the kernel A/B comparison table instead of the "
                         "headline benchmark")
    ap.add_argument("--accel", action="store_true",
                    help="benchmark the acceleration-search engine "
                         "(configs[4]) instead of the DM sweep")
    ap.add_argument("--batch", type=int, default=None,
                    help="with --accel: also measure the BATCHED search "
                         "(this many spectra against the shared template "
                         "bank in one dispatch per stage)")
    ap.add_argument("--spectral", action="store_true",
                    help="with --accel: run the round-10 spectral-fusion "
                         "pipeline A/B instead of the raw engine bench — "
                         "the SAME toy pulsar through all three handoff "
                         "paths (.dat round trip, streamed, --spectral "
                         "fused) plus the opt-in decimate regime, with "
                         "sift parity asserted and the per-trial "
                         "transform counts taken from the telemetry "
                         "counters (BENCH_r10_specfuse.json)")
    ap.add_argument("--fold", action="store_true",
                    help="benchmark the folding engine (configs[3]) "
                         "instead of the DM sweep")
    ap.add_argument("--waterfall", action="store_true",
                    help="benchmark the single-DM waterfall path "
                         "(configs[0]) instead of the DM sweep")
    ap.add_argument("--survey", action="store_true",
                    help="A/B the survey orchestrator (pypulsar_tpu."
                         "survey) against the serial per-observation "
                         "chain on a 4-observation toy fleet — the "
                         "round-9 host/device-overlap measurement")
    ap.add_argument("--devices", type=int, default=1,
                    help="with --survey: also run the orchestrator with "
                         "this many device leases (gang auto), the "
                         "round-11 multi-chip leg — artifacts byte-"
                         "checked against BOTH the serial chain and the "
                         "1-device orchestrated run. Needs that many "
                         "JAX devices (CPU recipe: XLA_FLAGS="
                         "--xla_force_host_platform_device_count=8)")
    ap.add_argument("--broker", action="store_true",
                    help="A/B the round-24 batch broker: a 4-observation\n"
                         "same-geometry toy fleet brokered (batch lanes +\n"
                         "cross-obs fused dispatches) vs PYPULSAR_TPU_BROKER=0\n"
                         "per-obs dispatch, gated on structural counters\n"
                         "(coalesce factor, dispatch collapse, compile misses)\n"
                         "+ byte parity + validated-resume-zero")
    ap.add_argument("--candplane", action="store_true",
                    help="A/B the round-25 candidate data plane: the same\n"
                         "synthetic pulsar observed at 3 epochs (plus per-\n"
                         "epoch noise) run through the fleet scheduler with\n"
                         "the candidate store ON vs PYPULSAR_TPU_CANDSTORE=0,\n"
                         "byte-parity on per-obs artifacts, cross-epoch sift\n"
                         "duplicate reduction measured, kill -9 + resume\n"
                         "exactly-once and pre/post-compaction query identity\n"
                         "asserted (BENCH_r20_candplane.json)")
    ap.add_argument("--obs-overhead", action="store_true",
                    help="A/B the round-21 observability plane on a toy "
                         "sweep->accel fleet: instrumentation-off vs "
                         "flight-recorder-only vs full telemetry, "
                         "candidates byte-checked identical and the "
                         "full overhead asserted <= 5% (OBS_rXX.json)")
    ap.add_argument("--chaos", action="store_true",
                    help="run a toy fleet under seeded probabilistic "
                         "fault chaos (kills + OOMs + IO errors + hangs "
                         "+ device faults sprayed across every "
                         "registered fault point), resume until it "
                         "completes, and ASSERT byte-parity against a "
                         "clean run — the fleet-health acceptance "
                         "measurement (CHAOS_rXX.json)")
    ap.add_argument("--daemon-soak", action="store_true",
                    help="run the round-23 streaming-daemon soak: a "
                         "multi-tenant overload storm (bulk flood past "
                         "the accept queue bound, chaos sprayed over "
                         "the admission edge, a corrupt ingest file, a "
                         "SIGKILL'd+restarted --daemon subprocess, a "
                         "SIGTERM drain), with balanced books, bulk-"
                         "only shedding, a trace-reconstructible shed "
                         "trail and byte-parity vs a batch reference "
                         "all ASSERTED (SOAK_rXX.json)")
    ap.add_argument("--multihost", action="store_true",
                    help="run the round-18 multi-host fleet harness: a "
                         "4-obs, 3-process CPU fleet coordinated through "
                         "the shared-directory plane (fenced lease "
                         "takeover), first CLEAN (A/B vs the 1-host "
                         "serial chain), then with one host SIGKILL'd "
                         "mid-sweep — survivors must ADOPT its "
                         "observation, every artifact must be "
                         "byte-identical to the serial run, and a final "
                         "no-fault resume must re-run ZERO stages "
                         "(BENCH_r13_multihost.json + HOSTCHAOS_r01.json)")
    ap.add_argument("--hostchaos-out", default="HOSTCHAOS_r01.json",
                    metavar="PATH",
                    help="with --multihost: where the host-kill chaos "
                         "record lands (default HOSTCHAOS_r01.json)")
    ap.add_argument("--trace-out", default="OBS_trace_r01.json",
                    metavar="PATH",
                    help="with --multihost: where the tlmtrace-stitched "
                         "Perfetto/Chrome-trace JSON of the host-kill "
                         "leg lands — the adoption is visible as a lane "
                         "handover on one trace_id (default "
                         "OBS_trace_r01.json; empty string disables)")
    ap.add_argument("--race", action="store_true",
                    help="seeded interleaving stress harness (psrrace): "
                         "a toy fleet on 2 in-process hosts + a leaving "
                         "ghost, claim/adopt + watchdog hang-interrupt "
                         "+ prefetch concurrently, setswitchinterval "
                         "cranked and seeded pauses injected at every "
                         "tracked lock boundary under "
                         "PYPULSAR_TPU_LOCKDEP=strict; asserts "
                         "byte-identical artifacts and zero lockdep "
                         "order violations per seed (RACE_rXX.json)")
    ap.add_argument("--race-seeds", type=int, default=2,
                    help="with --race: how many interleaving seeds to "
                         "run (default 2)")
    ap.add_argument("--chaos-seed", type=int, default=1,
                    help="with --chaos: the chaos seed (default 1)")
    ap.add_argument("--chaos-rate", type=float, default=None,
                    help="with --chaos: per-(point,hit) fault "
                         "probability (default 0.015, --quick 0.01)")
    ap.add_argument("--corruption", action="store_true",
                    help="run a toy fleet over INPUTS corrupted with "
                         "every data-fault kind (truncate, bitflip, "
                         "dropblock, NaN-burst, garbage header) plus one "
                         "clean control, assert the fleet completes "
                         "(degraded or data-quarantined per "
                         "--max-bad-frac policy, zero crashes), the "
                         "control's artifacts stay byte-identical to a "
                         "clean run, and the reader fuzz harness is "
                         "100%% clean — the data-integrity acceptance "
                         "measurement (CORRUPT_rXX.json)")
    ap.add_argument("--corruption-seed", type=int, default=1,
                    help="with --corruption: corruption + fuzz seed "
                         "(default 1)")
    ap.add_argument("--prepass", action="store_true",
                    help="benchmark the zero-DM + spectrogram + detrend "
                         "prepass (configs[1]) instead of the DM sweep")
    ap.add_argument("--stream", default=None, metavar="FIL",
                    help="run the north-star STREAMED sweep over this "
                         "on-disk filterbank (I/O included in the metric). "
                         "With no mode flags, bench.py auto-selects this "
                         "mode when data/northstar_1hr.fil exists")
    ap.add_argument("--stream-window", type=float, default=None,
                    metavar="SECONDS",
                    help="bound the streamed sweep to the first SECONDS of "
                         "the file (0 = whole file). The auto-selected "
                         "stream mode defaults to $BENCH_STREAM_WINDOW_S "
                         "or 900 so an unattended bench run stays under "
                         "~15 min; an explicit --stream defaults to the "
                         "whole file. The full-hour measured run is in "
                         "BENCHNOTES.md / BENCH_r04_full_stream.json")
    from pypulsar_tpu.obs.telemetry import add_telemetry_flag

    add_telemetry_flag(
        ap, what="spans + counters of the measured run; the final totals "
                 "also land in the JSON record's extras")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="also write the final JSON record to this file "
                         "(how the driver lands BENCH_rXX_*.json rows, "
                         "e.g. --waterfall --out BENCH_r06_waterfall.json)")
    ap.add_argument("--cpu-fallback", action="store_true",
                    help="(internal) run on the CPU backend with reduced shapes")
    ap.add_argument("--child", action="store_true",
                    help="(internal) run the measurement in this process")
    return ap.parse_args(argv)


def acquire_backend(retries=3, backoff=20.0):
    """jax.devices() with bounded retry; returns the device list or raises."""
    last = None
    for attempt in range(retries):
        try:
            import jax

            devs = jax.devices()  # psrlint: ignore[PL002] -- this IS the raw liveness probe the lease registry sits above
            # a device list can exist while the tunnel is wedged; prove
            # liveness with a tiny round-trip before committing to the run
            import jax.numpy as jnp

            val = float(jnp.ones((8, 8)).sum())
            assert val == 64.0
            return devs
        except Exception as e:  # noqa: BLE001 - any backend failure retries
            last = e
            print(f"# backend attempt {attempt + 1}/{retries} failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            if attempt + 1 < retries:
                time.sleep(backoff)
                try:
                    import jax.extend.backend

                    jax.extend.backend.clear_backends()
                except Exception:
                    pass
    raise RuntimeError(f"backend unavailable after {retries} attempts: {last}")


def budget_shapes(C, T_req, plan, hbm_bytes):
    """(T, chunk_payload, n_fft, max_pending) fitting the HBM budget.

    Accounting: device dataset C*T*4; each in-flight chunk buffer C*n*4
    (padded to the FFT length); one executable workspace ~3 chunk buffers
    (rfft output + fused intermediates); 25% headroom for the allocator.
    """
    from pypulsar_tpu.parallel.sweep import default_chunk_payload

    payload = default_chunk_payload(plan.min_overlap)
    n = payload + plan.min_overlap  # round-5 chunk-length A/B, BENCHNOTES
    budget = 0.75 * hbm_bytes
    chunk_bytes = 4 * C * n
    workspace = 3 * chunk_bytes
    avail = budget - workspace - 2 * chunk_bytes  # >= 2 chunks in flight
    # charge the dataset TWICE: the resident path's compiled program holds
    # the input and its tail-padded working copy concurrently
    T = int(min(T_req, avail // (2 * 4 * C)))
    T = max(T, payload)
    max_pending = int((budget - workspace - 2 * 4 * C * T) // chunk_bytes)
    max_pending = max(1, min(4, max_pending))
    return T, payload, n, max_pending


def sweep_bytes(plan, C, T, payload, n, engine):
    """Analytic HBM traffic of the full sweep (dominant streams only)."""
    G, g, S = plan.n_groups, plan.group_size, plan.nsub
    D = G * g
    W = max(plan.widths)
    nchunks = -(-T // payload)
    F = n // 2 + 1
    out_len = payload + W
    if engine == "fourier":
        per_chunk = (
            4 * C * n + 8 * C * F  # rfft read + write
            + G * (8 * C * F + 8 * S * F)  # stage1 read X per group + write
            + 8 * D * S * F + 8 * D * F  # stage2 read + write
            + 8 * D * F + 4 * D * n  # irfft read + write
            + 2 * 4 * D * out_len  # boxcar read + stats
        )
    else:
        L1 = out_len + plan.max_shift2
        per_chunk = 4 * (G * C * L1 + G * S * L1 + D * S * out_len
                         + 2 * D * out_len)
    return per_chunk * nchunks


# ---------------------------------------------------------------------------
# NumPy-baseline measurement protocol (VERDICT r4 item 5). The host is a
# shared 1-core box whose speed varies >2x run to run; a baseline of record
# needs (a) >=5 repetitions with the median + spread recorded, (b) a
# loadavg gate with sleep-retry before each rep, (c) warn-and-rerun when
# the spread still exceeds 1.3x, and (d) a cross-check against a PINNED
# calibration workload so "the host was slow today" is detected even when
# the reps agree with each other.
# ---------------------------------------------------------------------------

# Pinned seconds for _cal_workload() measured on this host near-idle
# (loadavg 0.04, min of 5 = 0.123 s, reps 0.123-0.148; 2026-07-30,
# round 5). A bench-time measurement slower than ~1.3x this means the
# HOST is contended and every numpy baseline in that run is suspect.
NUMPY_CAL_SECONDS = 0.123


def _cal_workload():
    """Fixed single-core probe: dedisperse+boxcar of a [256, 65536] f64
    array (the baseline's own inner-loop math at a pinned shape). Data
    generation is excluded from the timing."""
    from pypulsar_tpu.ops import numpy_ref

    rng = np.random.RandomState(7)
    data = rng.standard_normal((256, 1 << 16))
    freqs = 1500.0 - np.arange(256.0)
    bins = numpy_ref.bin_delays(150.0, freqs, 64e-6)
    t0 = time.perf_counter()
    ts = numpy_ref.dedispersed_timeseries(data, bins)
    numpy_ref.boxcar_snr(ts, (1, 2, 4, 8, 16, 32))
    return time.perf_counter() - t0


def _loadavg() -> float:
    try:
        return os.getloadavg()[0]
    except (OSError, AttributeError):
        return -1.0


def wait_for_idle(gate: float = None, max_wait: float = 180.0) -> float:
    """Sleep-retry until 1-min loadavg < ``gate`` (default 0.5, override
    BENCH_LOADAVG_GATE); give up after ``max_wait`` s and proceed with a
    warning. Returns the loadavg seen when proceeding."""
    if gate is None:
        gate = float(os.environ.get("BENCH_LOADAVG_GATE", 0.5))
    deadline = time.monotonic() + max_wait
    load = _loadavg()
    while load >= gate and time.monotonic() < deadline:
        time.sleep(5.0)
        load = _loadavg()
    if load >= gate:
        print(f"# WARNING: loadavg {load:.2f} still >= {gate} after "
              f"{max_wait:.0f}s wait; baseline reps may be contended",
              file=sys.stderr)
    return load


def numpy_baseline(rep_fn, reps: int = 5, spread_limit: float = 1.3):
    """Measure a single-core NumPy baseline under the round-5 protocol.

    ``rep_fn()`` runs one full repetition and returns its seconds. The
    loadavg gate runs before EACH rep; if the spread of the first
    ``reps`` exceeds ``spread_limit`` the whole set is re-run once and
    the median is taken over all recorded reps. A calibration probe
    (min of 3 ``_cal_workload`` runs) is compared against the pinned
    idle-host value: ``cal_ratio`` > ~1.3 flags a host that is slow
    across the board. Returns a dict of the protocol's evidence fields.
    """
    all_reps = []

    def one_round():
        # full gate before the round; between reps only a short check —
        # a multi-second rep pushes the 1-min loadavg over the gate with
        # its OWN decaying footprint, and sleeping 180 s per rep to wait
        # out ourselves would stall the bench for nothing
        wait_for_idle()
        for i in range(reps):
            if i:
                wait_for_idle(max_wait=15.0)
            all_reps.append(rep_fn())

    one_round()
    spread = max(all_reps) / min(all_reps)
    reran = False
    used = all_reps
    if spread > spread_limit:
        print(f"# numpy baseline spread {spread:.2f}x > {spread_limit}x; "
              f"re-running the rep set", file=sys.stderr)
        reran = True
        one_round()
        # the rerun replaces the contended round: judge the spread AND
        # take the median over the second round alone (pooling the two
        # populations would skew the median while the spread field looks
        # clean); every recorded rep still lands in the JSON
        used = all_reps[reps:]
        spread = max(used) / min(used)
        if spread > spread_limit:
            print(f"# WARNING: spread {spread:.2f}x persists after rerun "
                  f"(load {_loadavg():.2f}); median of the rerun used",
                  file=sys.stderr)
    cal = min(_cal_workload() for _ in range(3))
    cal_ratio = (cal / NUMPY_CAL_SECONDS) if NUMPY_CAL_SECONDS else -1.0
    if cal_ratio > 1.3:
        print(f"# WARNING: host calibration {cal:.3f}s is "
              f"{cal_ratio:.2f}x the pinned idle value "
              f"({NUMPY_CAL_SECONDS:.3f}s) - numpy baselines this run "
              f"are inflated by host contention", file=sys.stderr)
    return {
        "seconds": float(np.median(used)),
        "numpy_seconds_reps": [round(r, 3) for r in all_reps],
        "numpy_rep_spread": round(spread, 3),
        "numpy_reps_reran": reran,
        "host_loadavg": round(_loadavg(), 2),
        "host_cal_seconds": round(cal, 4),
        "host_cal_ratio": round(cal_ratio, 3),
    }


def baseline_scale_check(small_rep, large_rep, factor: int = 10,
                         reps: int = 5):
    """Spot-check of the linear-extrapolation model behind every scaled
    NumPy baseline (VERDICT r5 item 7): time the twin on a ``factor``-x
    larger slice and report ``t_large / (factor * t_small)`` — ~1.0 means
    the extrapolation is sound; drift past ~±20% flags cache-size or
    allocator effects the scaling model misses. Loadavg-gated like the
    rep protocol; min-of-reps on both sides (the ratio wants the
    uncontended floor of each, not medians of different noise)."""
    wait_for_idle()
    t_small = min(small_rep() for _ in range(reps))
    t_large = min(large_rep() for _ in range(reps))
    ratio = t_large / (factor * t_small)
    if not 0.8 <= ratio <= 1.2:
        print(f"# WARNING: baseline_scale_check {ratio:.3f} outside "
              f"±20% - the linearly scaled baseline figures carry a "
              f"model error of that size", file=sys.stderr)
    return {
        "baseline_scale_check": round(ratio, 3),
        "baseline_scale_factor": factor,
        "baseline_scale_small_seconds": round(t_small, 4),
        "baseline_scale_large_seconds": round(t_large, 4),
    }


def run_benchmark(args):
    if args.cpu_fallback or args.quick:
        C = args.nchan or 128
        T_req = args.nsamp or 1 << 15
        D = args.trials or 64
        nb = args.baseline_trials or 2
        nsub, group = 32, 16
    else:
        C = args.nchan or 1024
        T_req = args.nsamp or 1 << 21  # ~134 s at 64 us
        D = args.trials or 1024
        nb = args.baseline_trials or 4
        nsub, group = 64, 32

    devs = acquire_backend()

    import jax
    import jax.numpy as jnp
    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.ops import numpy_ref
    from pypulsar_tpu.parallel import (
        choose_group_size,
        make_sweep_plan,
        sweep_spectra,
    )
    from pypulsar_tpu.parallel.sweep import resolve_engine, sweep_resident

    dt = 64e-6
    dev = devs[0]
    engine = resolve_engine(args.engine)
    on_tpu = getattr(dev, "platform", "cpu") == "tpu"
    hbm = float(knobs.env_float("PYPULSAR_TPU_HBM_GB")) * 1e9

    freqs = (1500.0 - 300.0 / C * np.arange(C)).astype(np.float64)
    dms = np.linspace(0.0, args.dm_max, D)
    # stage-1 group from the smearing bound: dense trial grids afford
    # larger groups (measured 25% faster at g=64, BENCHNOTES.md)
    group = max(group, choose_group_size(dms, freqs, dt, nsub))
    plan = make_sweep_plan(dms, freqs, dt, nsub=nsub, group_size=group)
    if args.cpu_fallback or args.quick:
        from pypulsar_tpu.ops.fourier_dedisperse import fourier_chunk_len

        T, chunk, max_pending = T_req, min(T_req, 1 << 14), 2
        if plan.min_overlap >= chunk:
            chunk = fourier_chunk_len(plan.min_overlap * 2)
        n_fft = fourier_chunk_len(chunk + plan.min_overlap)
    else:
        T, chunk, n_fft, max_pending = budget_shapes(C, T_req, plan, hbm)
        T = max((T // chunk) * chunk, chunk)  # whole chunks: single-dispatch path
    print(f"# device: {dev}, engine={engine}, C={C} chans, T={T} samples "
          f"({T*dt:.0f}s), D={D} trials 0-{args.dm_max}, chunk={chunk}, "
          f"max_pending={max_pending}", file=sys.stderr)

    def measure(T):
        # generate the dataset directly on device: the measured quantity is
        # the sweep engine, not the axon tunnel's host->device transfer rate
        key = jax.random.PRNGKey(0)
        data = jax.random.normal(key, (C, T), dtype=jnp.float32)
        float(jnp.sum(data[0, :8]))  # force materialization
        spec = Spectra(freqs, dt, data)
        # single-dispatch whole-sweep program (the tree engine's host-
        # built tables keep it on the streamed path, sweep_resident docs)
        resident = T % chunk == 0 and engine != "tree"
        def run():
            if resident:
                return sweep_resident(spec, dms, nsub=nsub,
                                      group_size=group, chunk_payload=chunk,
                                      engine=engine)
            return sweep_spectra(spec, dms, nsub=nsub, group_size=group,
                                 chunk_payload=chunk, engine=engine,
                                 max_pending=max_pending)
        if resident:
            run()  # compile + execute the real program once (cached runner)
        else:
            # streamed path: warm only the per-shape compiles on slices
            warm_lens = {min(T, chunk)}
            if T > chunk and T % chunk:
                warm_lens.add(T % chunk)
            for wl in warm_lens:
                warm = Spectra(freqs, dt, data[:, :wl])
                sweep_spectra(warm, dms, nsub=nsub, group_size=group,
                              chunk_payload=chunk, engine=engine,
                              max_pending=max_pending)
        if args.profile:
            from pypulsar_tpu.utils.profiling import stage_report

            profile_ctx = stage_report(file=sys.stderr)
        else:
            import contextlib

            profile_ctx = contextlib.nullcontext()
        with profile_ctx:
            # best of 2: single tunnel measurements vary with server-
            # side load (observed >2x on the fold bench, BENCHNOTES)
            jax_time = float("inf")
            for _ in range(1 if args.profile else 2):
                t0 = time.perf_counter()
                res = run()
                jax_time = min(jax_time, time.perf_counter() - t0)
        return res, jax_time

    res = None
    for attempt in range(6):
        try:
            res, jax_time = measure(T)
            break
        except Exception as e:  # noqa: BLE001 - OOM shrinks and retries
            if "RESOURCE_EXHAUSTED" not in str(e):
                raise
            if T // 2 >= chunk:
                T = max(((T // 2) // chunk) * chunk, chunk)  # whole chunks
                print(f"# RESOURCE_EXHAUSTED; halving dataset to T={T}",
                      file=sys.stderr)
            elif n_fft // 2 > plan.min_overlap:
                # dataset is already one chunk: shrink the chunk itself
                n_fft //= 2
                chunk = n_fft - plan.min_overlap
                T = min(T, max(chunk, T // 2))
                print(f"# RESOURCE_EXHAUSTED; shrinking chunk to {chunk} "
                      f"(n_fft={n_fft})", file=sys.stderr)
            else:
                raise
    if res is None:
        raise RuntimeError("dataset would not fit on device at any size")
    trials_per_sec = D / jax_time

    # --- NumPy single-core baseline: reference-style brute force ---
    # Round-5 protocol (numpy_baseline): >=5 loadavg-gated reps, median +
    # spread + pinned-calibration cross-check recorded. Single
    # measurements have twice recorded contended-host outliers that
    # flipped vs_baseline by 2-11x.
    bl_T = min(T, 1 << 17)  # slice; scale linearly
    rng = np.random.RandomState(1)
    bl_data = rng.standard_normal((C, bl_T))  # same distribution; cost is data-independent

    def one_rep():
        t0 = time.perf_counter()
        for dm in dms[:: max(1, D // nb)][:nb]:
            bins = numpy_ref.bin_delays(dm, freqs, dt)
            ts = numpy_ref.dedispersed_timeseries(bl_data, bins)
            numpy_ref.boxcar_snr(ts, plan.widths)
        return time.perf_counter() - t0

    bl = numpy_baseline(one_rep)
    bl_time = bl["seconds"]
    bl_trials_per_sec = nb / (bl_time * (T / bl_T))
    speedup = trials_per_sec / bl_trials_per_sec

    # --- bandwidth accounting vs the HBM roofline ---
    nbytes = sweep_bytes(plan, C, T, chunk, n_fft, engine)
    hbm_frac = nbytes / jax_time / V5E_HBM_BW if on_tpu else 0.0

    # --- north-star extrapolation: same trials/s formula at 1 hr ---
    T_1hr = int(3600.0 / dt)
    trials_1hr = trials_per_sec * T / T_1hr

    print(f"# jax: {jax_time:.3f}s for {D} trials; numpy: {bl_time:.3f}s for "
          f"{nb} trials on {bl_T/T:.3f} of data; best cand: {res.best(1)[0]}",
          file=sys.stderr)
    print(f"# analytic HBM traffic {nbytes/1e9:.0f} GB -> "
          f"{nbytes/jax_time/1e9:.0f} GB/s ({hbm_frac*100:.0f}% of v5e "
          f"roofline); 1-hr extrapolation {trials_1hr:.1f} trials/s",
          file=sys.stderr)
    unit = (f"DM-trials/s ({C}-chan, {T*dt:.0f}s @ 64us, nsub={nsub}, "
            f"engine={engine}, best of 2 runs; numpy baseline median of "
            f"{len(bl['numpy_seconds_reps'])} loadavg-gated reps on "
            f"{bl_T/T:.2f} of the data x {nb}/{D} trials, scaled linearly)")
    if args.cpu_fallback:
        unit += " [CPU FALLBACK: accelerator backend unavailable]"
    return {
        "metric": "dm_trials_per_sec",
        "value": round(trials_per_sec, 2),
        "unit": unit,
        "vs_baseline": round(speedup, 2),
        "jax_seconds": round(jax_time, 3),
        "numpy_seconds_measured": round(bl_time, 3),
        **{k: v for k, v in bl.items() if k != "seconds"},
        "numpy_trials_measured": nb,
        "numpy_slice_frac": round(bl_T / T, 4),
        "hbm_frac": round(hbm_frac, 4),
        "hbm_gbps": round(nbytes / jax_time / 1e9, 1),
        "trials_per_sec_1hr_extrapolated": round(trials_1hr, 2),
        "nsamp": T,
        "engine": engine,
        "path": "resident" if T % chunk == 0 else "streamed",
        # SNR parity contract (VERDICT r3 item 7): engine=gather is the
        # bit-exact-SNR reference formulation; the fourier engine agrees
        # to the stated relative tolerance (FFT f32 rounding), asserted
        # by tests/test_sweep.py::test_fourier_engine_snr_tolerance.
        # Emitted only when the measured engine is the toleranced one.
        **({"snr_parity": "gather=bit-exact reference; fourier toleranced",
            "fourier_snr_rel_tol": 2e-6} if engine == "fourier" else {}),
    }


def run_ab(args):
    """Kernel A/B table (VERDICT r2 item 3): full-chunk engines + boxcar
    backends, timed on the live backend. Results land in BENCHNOTES.md."""
    acquire_backend()
    import jax
    import jax.numpy as jnp
    from functools import partial
    from pypulsar_tpu.ops.pallas_kernels import boxcar_stats
    from pypulsar_tpu.parallel import make_sweep_plan
    from pypulsar_tpu.parallel.sweep import sweep_chunk

    C, D = args.nchan or 1024, args.trials or 1024
    nsub, group = 64, 32
    dt = 64e-6
    freqs = (1500.0 - 300.0 / C * np.arange(C)).astype(np.float64)
    dms = np.linspace(0.0, args.dm_max, D)
    plan = make_sweep_plan(dms, freqs, dt, nsub=nsub, group_size=group)
    n = 1 << 17
    W = max(plan.widths)
    chunk = n - plan.min_overlap
    out_len = chunk + W
    need = out_len + plan.max_shift2 + plan.max_shift1
    key = jax.random.PRNGKey(0)
    data = jax.random.normal(key, (C, need), dtype=jnp.float32)
    s1 = jnp.asarray(plan.stage1_bins)
    s2 = jnp.asarray(plan.stage2_bins)
    float(jnp.sum(data[0, :8]))

    def force(out):
        return float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])

    results = {}
    for engine in ("fourier", "gather", "scan", "tree"):
        try:
            fn = lambda: sweep_chunk(data, s1, s2, plan.nsub, out_len,
                                     plan.max_shift2, plan.widths, chunk,
                                     engine=engine)
            force(fn())
            t0 = time.perf_counter()
            force(fn())
            el = time.perf_counter() - t0
            results[f"chunk-{engine}"] = round(el, 4)
            print(f"# chunk-{engine:8s} {el*1e3:9.1f} ms "
                  f"({D / el:.1f} trials/s per chunk)", file=sys.stderr)
        except Exception as e:  # noqa: BLE001 - record and keep going
            results[f"chunk-{engine}"] = f"FAILED: {type(e).__name__}"
            print(f"# chunk-{engine} FAILED: {type(e).__name__}: "
                  f"{str(e)[:200]}", file=sys.stderr)
    # two-stage geometry variants (fourier engine): stage1 traffic scales
    # as (D/group)*C*F and stage2 as D*nsub*F — the sweet spot is chip-
    # dependent, so record a small grid
    for nsub2, group2 in ((64, 64), (32, 32), (128, 32)):
        try:
            plan2 = make_sweep_plan(dms, freqs, dt, nsub=nsub2,
                                    group_size=group2)
            chunk2 = n - plan2.min_overlap
            out_len2 = chunk2 + W
            need2 = out_len2 + plan2.max_shift2 + plan2.max_shift1
            data2 = jax.random.normal(key, (C, need2), dtype=jnp.float32)
            s1b = jnp.asarray(plan2.stage1_bins)
            s2b = jnp.asarray(plan2.stage2_bins)
            fn = lambda: sweep_chunk(data2, s1b, s2b, plan2.nsub, out_len2,
                                     plan2.max_shift2, plan2.widths, chunk2,
                                     engine="fourier")
            force(fn())
            t0 = time.perf_counter()
            force(fn())
            el = time.perf_counter() - t0
            results[f"fourier-s{nsub2}g{group2}"] = round(el, 4)
            print(f"# fourier nsub={nsub2} group={group2}: {el*1e3:9.1f} ms",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            results[f"fourier-s{nsub2}g{group2}"] = (
                f"FAILED: {type(e).__name__}")
            print(f"# fourier-s{nsub2}g{group2} FAILED: "
                  f"{type(e).__name__}: {str(e)[:200]}", file=sys.stderr)

    ts = jax.random.normal(key, (256, out_len), dtype=jnp.float32)
    float(ts[0, 0])
    for be in ("pallas", "lax"):
        try:
            fn = partial(boxcar_stats, ts, plan.widths, chunk, backend=be)
            force(fn())
            t0 = time.perf_counter()
            force(fn())
            results[f"boxcar-{be}"] = round(time.perf_counter() - t0, 4)
        except Exception as e:  # noqa: BLE001
            results[f"boxcar-{be}"] = f"FAILED: {type(e).__name__}"
    fourier_t = results.get("chunk-fourier", 0.0)
    return {
        "metric": "kernel_ab_seconds",
        # "value" must stay numeric whatever failed (the one-JSON-line
        # contract); string FAILED markers live in the extras only
        "value": fourier_t if isinstance(fourier_t, float) else 0.0,
        "unit": "s per 1024-trial chunk (see extras)",
        "vs_baseline": 0.0,
        **results,
    }


def _full_stream_reference(windowed: bool, path: str, engine: str,
                           trials: int) -> dict:
    """For windowed runs OF THE NORTH-STAR WORKLOAD: the newest committed
    full-file measured record (BENCH_r*_full_stream.json), inlined so the
    windowed JSON is self-contained evidence that the whole-file rate was
    measured too. Attached only when the benched configuration matches
    the reference experiment (default file, fourier engine, 4096 trials)
    — a different file/engine/grid must not cite it."""
    if not (windowed and os.path.abspath(path) == DEFAULT_STREAM_FIL
            and engine == "fourier" and trials == 4096):
        return {}
    import glob

    refs = sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_r*_full_stream.json")))
    if not refs:
        print("# note: no BENCH_r*_full_stream.json found; windowed "
              "record carries no full-file reference", file=sys.stderr)
        return {}
    ref = refs[-1]
    try:
        with open(ref) as f:
            rec = json.load(f)
        return {"full_file_record": {
            "value": rec.get("value"),
            "wall_seconds": rec.get("wall_seconds"),
            "vs_baseline": rec.get("vs_baseline"),
            "file_gb": rec.get("file_gb"),
            "source": os.path.basename(ref),
        }}
    except (OSError, ValueError) as e:
        print(f"# note: unreadable full-stream reference {ref}: {e}",
              file=sys.stderr)
        return {}


def _configs4_reference() -> dict:
    """Inline the committed configs[4] end-to-end record (the measured
    900-s-window sweep -> write-dats -> batched accelsearch -> sift
    chain) so the driver's streamed JSON carries the whole-pipeline
    evidence alongside the sweep number. Prefers the --device-prep
    record (the faster measured chain) over the host-prep one; both
    are committed and unit-string self-describing."""
    here = os.path.dirname(os.path.abspath(__file__))
    # newest round first: run_configs4 writes BENCH_r06_configs4.json
    # (the streamed-handoff record) since round 6
    for name in ("BENCH_r06_configs4.json",
                 "BENCH_r05_configs4_devprep.json",
                 "BENCH_r05_configs4.json"):
        ref = os.path.join(here, name)
        if not os.path.exists(ref):
            continue
        try:
            with open(ref) as f:
                rec = json.load(f)
            return {"configs4_end_to_end": {
                k: rec.get(k) for k in (
                    "value", "unit", "trials", "wall_seconds",
                    "stage_seconds", "cells_per_sec", "vs_baseline",
                    "injected_recovered")
                if k in rec}}
        except (OSError, ValueError) as e:
            # a corrupt preferred record must not drop the evidence block
            # when the sibling record is readable
            print(f"# note: unreadable configs4 reference {ref}: {e}",
                  file=sys.stderr)
    return {}


class _WindowedFilterbank:
    """FilterbankFile proxy bounded to the first ``nsamp`` samples, so an
    unattended bench run can measure the streamed path on a time window
    without losing the native prefetcher (iter_blocks passes ``end``
    through to PrefetchReader)."""

    BLOCK_ITER_ARRAYS = True

    def __init__(self, fb, nsamp: int):
        self._fb = fb
        self.number_of_samples = int(nsamp)

    @property
    def nspec(self):
        return self.number_of_samples

    @property
    def obs_duration(self):
        return self.number_of_samples * float(self._fb.tsamp)

    def __getattr__(self, name):
        return getattr(self._fb, name)

    def iter_blocks(self, block_size, overlap=0, **kw):
        kw.setdefault("end", self.number_of_samples)
        return self._fb.iter_blocks(block_size, overlap, **kw)


def run_stream(args):
    """North-star streamed sweep (VERDICT r3 item 1): a real on-disk
    filterbank through the native prefetcher + sweep_stream on the live
    chip, checkpointing on, HOST I/O INCLUDED in the measured wall time.

    The record's ``path`` field is "streamed" and its extras carry the
    per-stage wall breakdown (block_source = disk wait + host->device
    ship; device_wait = un-overlapped device time) plus a synchronous
    per-chunk compute probe, so the compute-vs-transfer overlap fraction
    is measured, not assumed."""
    acquire_backend()
    import jax
    import jax.numpy as jnp
    from pypulsar_tpu.io.filterbank import FilterbankFile
    from pypulsar_tpu.ops import numpy_ref
    from pypulsar_tpu.parallel import choose_group_size, make_sweep_plan
    from pypulsar_tpu.parallel.staged import sweep_flat
    from pypulsar_tpu.parallel.sweep import resolve_engine, sweep_chunk
    from pypulsar_tpu.utils import profiling

    fb = FilterbankFile(args.stream)
    C, dt = fb.nchans, float(fb.tsamp)
    file_T = int(fb.number_of_samples)
    window = getattr(args, "stream_window", None)
    T = file_T if not window else min(file_T, int(round(window / dt)))
    if T < file_T:
        fb = _WindowedFilterbank(fb, T)
    freqs = np.asarray(fb.frequencies, dtype=np.float64)
    D = args.trials or 4096
    dms = np.linspace(0.0, args.dm_max, D)
    engine = resolve_engine(args.engine)
    nsub = 64
    group = choose_group_size(dms, freqs, dt, nsub)
    plan = make_sweep_plan(dms, freqs, dt, nsub=nsub, group_size=group)
    from pypulsar_tpu.parallel.sweep import default_chunk_payload

    payload = default_chunk_payload(plan.min_overlap)
    file_gb = file_T * C * fb.nbits / 8 / 1e9
    streamed_gb = T * C * fb.nbits / 8 / 1e9
    nchunks = -(-T // payload)
    print(f"# streamed: {args.stream} C={C} T={T} of {file_T} "
          f"({T*dt:.0f}s of {file_T*dt:.0f}s; streaming {streamed_gb:.1f} "
          f"of {file_gb:.1f} GB {fb.nbits}-bit on disk) D={D} trials, "
          f"payload={payload}, {nchunks} chunks, engine={engine}",
          file=sys.stderr)

    # Synchronous pure-compute probe at the streamed shapes — run BEFORE
    # the timed stream so it doubles as the compile warm-up (the chunk
    # program jit-caches on these exact shapes). nchunks of these
    # estimates total device compute; compared against the profiled
    # device_wait it yields the fraction of compute hidden behind I/O.
    W = max(plan.widths)
    out_len = payload + W
    need = out_len + plan.max_shift2 + plan.max_shift1
    datap = jax.random.normal(jax.random.PRNGKey(0), (C, need),
                              dtype=jnp.float32)
    float(jnp.sum(datap[0, :4]))
    s1 = jnp.asarray(plan.stage1_bins)
    s2 = jnp.asarray(plan.stage2_bins)

    def one_chunk(stat_len=payload):
        out = sweep_chunk(datap, s1, s2, plan.nsub, out_len, plan.max_shift2,
                          plan.widths, stat_len, engine=engine)
        return float(jnp.asarray(out[0]).ravel()[0])

    one_chunk()  # compile at the streamed shapes
    t1 = time.perf_counter()
    one_chunk()
    chunk_s = time.perf_counter() - t1
    tail_stat = T - (nchunks - 1) * payload
    if 0 < tail_stat < payload:
        one_chunk(tail_stat)  # the tail chunk's distinct static stat_len
    del datap

    # one-block transfer probe: synchronous host->device ship of a real
    # block at the streamed dtype — nchunks of these estimates the wire
    # leg of the wall time
    raw0 = fb._read_raw_block(0, min(payload + plan.min_overlap, T))
    t1 = time.perf_counter()
    d0 = jax.device_put(np.ascontiguousarray(raw0))
    d0.block_until_ready()
    ship_s = time.perf_counter() - t1
    del d0, raw0
    print(f"# probes (and warm-up): compute {chunk_s*1e3:.0f} ms/chunk, "
          f"ship {ship_s*1e3:.0f} ms/block "
          f"({(payload + plan.min_overlap) * C * fb.nbits / 8 / ship_s / 1e6:.0f}"
          f" MB/s)", file=sys.stderr)

    # fresh checkpoint: a stale file from a killed run would silently
    # resume mid-file and inflate the trials/s of record
    ckpt = args.stream + ".ckpt.npz"
    for stale in (ckpt, ckpt + ".tmp.npz"):
        if os.path.exists(stale):
            os.remove(stale)
    t0 = time.perf_counter()
    with profiling.stage_report(file=sys.stderr) as rep:
        staged = sweep_flat(fb, dms, nsub=nsub, group_size=group,
                            chunk_payload=payload, engine=engine,
                            checkpoint_path=ckpt, checkpoint_every=32)
    wall = time.perf_counter() - t0
    totals = rep.totals()
    trials_per_sec = D / wall
    best = staged.best(1)[0]
    print(f"# wall {wall:.1f}s = {trials_per_sec:.2f} DM-trials/s over the "
          f"{T*dt:.0f}s file, I/O included; best: {best}", file=sys.stderr)

    # overlap accounting: with compute and transfer fully serialized the
    # wall would be est_compute + est_transfer; fully overlapped it would
    # be max() of them — report the fraction of the smaller leg hidden
    est_compute = chunk_s * nchunks
    est_transfer = ship_s * nchunks
    dev_wait = totals.get("device_wait+accumulate", 0.0)
    blk_src = totals.get("block_source", 0.0)
    smaller = min(est_compute, est_transfer)
    overlap = (max(0.0, min(1.0, (est_compute + est_transfer - wall)
                            / smaller)) if smaller > 0 else 0.0)
    print(f"# est compute {est_compute:.0f}s + est transfer "
          f"{est_transfer:.0f}s vs wall {wall:.0f}s -> {overlap*100:.0f}% "
          f"of the smaller leg overlapped (device_wait {dev_wait:.0f}s, "
          f"block_source {blk_src:.0f}s)", file=sys.stderr)

    # numpy single-core baseline on a real slice of this file (reference
    # brute-force semantics; round-5 protocol: >=5 loadavg-gated reps +
    # pinned-calibration cross-check, cf. numpy_baseline)
    bl_T = min(T, 1 << 17)
    nb = args.baseline_trials or 4
    bl_data = np.ascontiguousarray(fb.get_samples(0, bl_T).T
                                   ).astype(np.float64)

    def one_rep():
        tb = time.perf_counter()
        for dm in dms[:: max(1, D // nb)][:nb]:
            bins = numpy_ref.bin_delays(dm, freqs, dt)
            ts = numpy_ref.dedispersed_timeseries(bl_data, bins)
            numpy_ref.boxcar_snr(ts, plan.widths)
        return time.perf_counter() - tb

    bl = numpy_baseline(one_rep)
    bl_time = bl["seconds"]
    bl_trials_per_sec = nb / (bl_time * (T / bl_T))
    speedup = trials_per_sec / bl_trials_per_sec

    return {
        "metric": "dm_trials_per_sec",
        "value": round(trials_per_sec, 2),
        "unit": (f"DM-trials/s STREAMED from disk ({C}-chan, {T*dt:.0f}s"
                 + (f" window of a {file_T*dt:.0f}s" if T < file_T else "")
                 + f" {fb.nbits}-bit .fil, {streamed_gb:.1f} GB streamed, "
                 f"{D} trials, engine={engine}; wall includes disk read, "
                 f"host->device ship and checkpointing; numpy baseline "
                 f"median of {len(bl['numpy_seconds_reps'])} loadavg-gated "
                 f"reps on {bl_T/T:.4f} of the data x "
                 f"{nb}/{D} trials, scaled linearly)"),
        "vs_baseline": round(speedup, 2),
        "wall_seconds": round(wall, 1),
        "nsamp": T,
        "window_seconds": round(T * dt, 1),
        "file_seconds": round(file_T * dt, 1),
        "nchan": C,
        "file_gb": round(file_gb, 1),
        "streamed_gb": round(streamed_gb, 1),
        "nbits": fb.nbits,
        "chunks": nchunks,
        "stage_seconds": {k: round(v, 1) for k, v in totals.items()},
        "compute_per_chunk_s": round(chunk_s, 3),
        "ship_per_block_s": round(ship_s, 3),
        "est_compute_seconds": round(est_compute, 1),
        "est_transfer_seconds": round(est_transfer, 1),
        "io_overlap_frac": round(overlap, 3),
        "best_candidate": {k: (round(v, 4) if isinstance(v, float) else int(v)
                               if isinstance(v, (int, np.integer)) else v)
                           for k, v in best.items()},
        "numpy_seconds_measured": round(bl_time, 3),
        **{k: v for k, v in bl.items() if k != "seconds"},
        "engine": engine,
        "path": "streamed",
        **_full_stream_reference(T < file_T, args.stream, engine, D),
        **_configs4_reference(),
        **({"snr_parity": "gather=bit-exact reference; fourier toleranced",
            "fourier_snr_rel_tol": 2e-6} if engine == "fourier" else {}),
    }


def run_accel(args):
    """Acceleration-search throughput (BASELINE configs[4]: the reference
    defers this stage to PRESTO accelsearch on one core; our engine is
    fourier/accelsearch.py). Metric: searched (r, z) plane cells per
    second over the full harmonic ladder; baseline: the same correlation
    math in single-core NumPy (np.fft) measured on a slice of the z bank
    and one segment per stage, scaled linearly."""
    acquire_backend()
    from pypulsar_tpu.fourier.accelsearch import AccelSearchConfig, accel_search
    from pypulsar_tpu.fourier.zresponse import template_bank

    if args.quick or args.cpu_fallback:
        N, zmax, segw = 1 << 18, 50.0, 1 << 13
    else:
        N, zmax, segw = 1 << 21, 200.0, 1 << 14
    T = N * 128e-6
    rng = np.random.RandomState(0)
    ts = rng.standard_normal(2 * N).astype(np.float32)
    fft = (np.fft.rfft(ts) / np.sqrt(2 * N)).astype(np.complex64)[:N]
    cfg = AccelSearchConfig(zmax=zmax, dz=2.0, numharm=8, sigma_min=6.0,
                            seg_width=segw)
    Z = len(cfg.zs)

    # warm at the REAL shape (the stage runners' jit keys on the spectrum
    # length and segment count; a smaller warmup would not populate them).
    # accel_search handles the host->device transfer itself (complex
    # buffers cannot ship directly over the axon link, ops/transfer.py)
    accel_search(fft, T, cfg)
    t0 = time.perf_counter()
    cands = accel_search(fft, T, cfg)
    jax_time = time.perf_counter() - t0
    rlo = max(int(np.ceil(cfg.flo * T)), 1)
    # stage H searches the top-harmonic bins [H*rlo, N-1] at half-bin
    # resolution across Z drifts (fhi defaults to Nyquist here)
    cells = sum(2 * Z * max((N - 1) - H * rlo, 0) for H in cfg.stages)
    cells_per_sec = cells / jax_time

    # numpy baseline: one stage-1 segment's correlations (the engine's own
    # math with np.fft), scaled to the full cell count
    tb, hw = template_bank(cfg.zs, numbetween=2)
    L = 1
    while L < segw + 4 * hw:
        L <<= 1
    # dtype-matched to the engine (complex64) so the comparison is the
    # same math at the same precision
    padded = np.zeros((tb.shape[0], L), np.complex128)
    padded[:, : tb.shape[1]] = tb
    rev = np.zeros_like(padded)
    rev[:, 0] = padded[:, 0]
    rev[:, 1:] = padded[:, :0:-1]
    tf = np.fft.fft(rev, axis=1).astype(np.complex64)
    seg = fft[:L].astype(np.complex64)

    def _bl_rep(segments):
        t0 = time.perf_counter()
        for s in segments:
            sl = np.fft.fft(s)
            corr = np.fft.ifft(sl[None, :] * tf, axis=1)
            _ = (np.abs(corr) ** 2).astype(np.float32)
        return time.perf_counter() - t0

    bl_time = _bl_rep([seg])
    bl_cells = 2 * Z * segw  # one fundamental segment's worth
    bl_cells_per_sec = bl_cells / bl_time
    speedup = cells_per_sec / bl_cells_per_sec
    # linear-extrapolation spot check (VERDICT r5 item 7): 10 distinct
    # segments = a 10x slice of the same twin
    segs10 = [(fft[i * L // 16:i * L // 16 + L]
               if i * L // 16 + L <= len(fft) else seg).astype(np.complex64)
              for i in range(10)]
    scale_fields = baseline_scale_check(lambda: _bl_rep([seg]),
                                        lambda: _bl_rep(segs10), factor=10)

    print(f"# accel search: {jax_time:.2f}s for {cells/1e6:.0f}M cells "
          f"({len(cands)} cands); numpy slice {bl_time:.2f}s for "
          f"{bl_cells/1e6:.1f}M cells", file=sys.stderr)

    # --- batched search over the shared template bank (VERDICT r3 item 2:
    # the 4096-trial workload searches B spectra per configuration; the
    # banks are DM-independent so one dispatch per stage serves them all).
    # OOM halves the batch and retries.
    batch_extras = {}
    value = cells_per_sec
    if args.batch and args.batch > 1:
        from pypulsar_tpu.fourier.accelsearch import accel_search_batch

        B = args.batch
        while B > 1:
            try:
                ffts = np.stack([
                    (np.fft.rfft(np.random.RandomState(100 + b)
                                 .standard_normal(2 * N)) / np.sqrt(2 * N))
                    .astype(np.complex64)[:N] for b in range(B)])
                accel_search_batch(ffts, T, cfg)  # warm at the real shape
                t0 = time.perf_counter()
                res_b = accel_search_batch(ffts, T, cfg)
                bt = time.perf_counter() - t0
                batch_cps = B * cells / bt
                batch_extras = {
                    "batch": B,
                    "batch_seconds": round(bt, 2),
                    "batch_cells_per_sec": round(batch_cps, 1),
                    "batch_vs_serial": round(batch_cps / cells_per_sec, 2),
                    "batch_cands": [len(c) for c in res_b],
                }
                value = batch_cps
                print(f"# batched x{B}: {bt:.2f}s = {batch_cps/1e6:.1f}M "
                      f"cells/s ({batch_cps/cells_per_sec:.2f}x serial)",
                      file=sys.stderr)
                break
            except Exception as e:  # noqa: BLE001 - OOM shrinks, else raise
                if "RESOURCE_EXHAUSTED" not in str(e):
                    raise
                B //= 2
                print(f"# batched accel RESOURCE_EXHAUSTED; retrying B={B}",
                      file=sys.stderr)

    unit = (f"(r,z) cells/s (N={N} bins, zmax={zmax:.0f}, dz=2, H<=8"
            + (f", batch={batch_extras['batch']}" if batch_extras else "")
            + "; numpy baseline from one segment x one stage, scaled "
              "linearly)")
    if args.cpu_fallback:
        unit += " [CPU FALLBACK: accelerator backend unavailable]"
    return {
        "metric": "accel_rz_cells_per_sec",
        "value": round(value, 1),
        "unit": unit,
        "vs_baseline": round(value / bl_cells_per_sec, 2),
        "serial_cells_per_sec": round(cells_per_sec, 1),
        "serial_vs_baseline": round(speedup, 2),
        "jax_seconds": round(jax_time, 3),
        "numpy_seconds_measured": round(bl_time, 3),
        **scale_fields,
        "n_candidates": len(cands),
        **batch_extras,
    }


def run_specfuse(args):
    """Spectral-fusion pipeline A/B (round 10 / ISSUE 10 acceptance):
    one toy pulsar observation through every sweep->accel handoff path
    under the SAME engine ('fourier', the TPU default — the decimate
    leg requires it and cross-engine series differ by design):

    - ``dat``:      sweep --write-dats (streamed writer) -> batched
                    accelsearch over the .dat files (the classic chain)
    - ``streamed``: the round-6 in-RAM handoff (irfft -> D2H -> H2D ->
                    rfft per trial)
    - ``fused``:    --spectral, stitched regime (series stays on
                    device; candidate tables asserted BYTE-identical to
                    the streamed leg, and the streamed leg to the .dat
                    leg — the full parity chain)
    - ``decimate``: --spectral + PYPULSAR_TPU_SPECFUSE_MODE=decimate
                    (zero transforms per trial; circular boundary
                    semantics, so parity is reported as measured, not
                    asserted byte-identical)

    The STRUCTURAL claim is the gate (MULTICHIP_r* methodology): the
    per-trial transform counts come from the telemetry counters
    (``specfuse.fft_pairs_elided`` = one irfft+rfft pair per trial on
    this single-chunk geometry), and the CPU-toy wall times are
    reported honestly as CPU-toy wall times."""
    acquire_backend()
    import glob as _glob
    import tempfile

    from pypulsar_tpu.obs import telemetry as _tlm

    C = 32
    # --quick only (NOT cpu_fallback: the whole A/B is a CPU-scale toy
    # by design, so the fallback path measures the same record)
    T = 1 << 13 if args.quick else 1 << 15
    dtp = 5e-4
    D = 16
    freqs = 1500.0 - 4.0 * np.arange(C)
    sweep_args = ["--lodm", "0", "--dmstep", "5", "--numdms", str(D),
                  "-s", "8", "--group-size", "4", "--threshold", "8",
                  "--engine", "fourier"]
    accel_cfg = ["--accel-zmax", "20", "--accel-numharm", "2",
                 "--accel-sigma", "3", "--accel-batch", "8"]
    handoff = [*accel_cfg, "--accel-search", "--accel-only"]

    def cands(prefix):
        return {os.path.basename(f)[len(prefix):]: open(f, "rb").read()
                for f in sorted(_glob.glob(f"{prefix}_DM*_ACCEL_20.*cand"))}

    olddir = os.getcwd()
    # env knobs are pinned for the run and RESTORED after (pop would
    # clobber a user's preset; an inherited decimate mode would break
    # the stitched legs' byte-parity assertion spuriously)
    env_save = {k: os.environ.get(k) for k in
                ("PYPULSAR_TPU_DATS_RESIDENT_LIMIT",
                 "PYPULSAR_TPU_SPECFUSE_MODE")}
    with tempfile.TemporaryDirectory() as td:
        os.chdir(td)
        try:
            fil = _synth_survey_fil("psr.fil", 5, C, T, dtp, freqs,
                                    "SPECFUSE")
            from pypulsar_tpu.cli import accelsearch as cli_accel
            from pypulsar_tpu.cli import sweep as cli_sweep

            os.environ["PYPULSAR_TPU_DATS_RESIDENT_LIMIT"] = "0"
            os.environ["PYPULSAR_TPU_SPECFUSE_MODE"] = "stitch"

            # per-leg counters come from SNAPSHOT DIFFS of one shared
            # session: nested telemetry sessions reuse the outer
            # collector (the run_corruption pitfall), so per-leg trace
            # files would silently stay empty under an outer
            # --telemetry session
            with _tlm.session(tool="bench-specfuse") as tlm:
                def leg_counters(fn):
                    before = dict(tlm.counter_totals())
                    wall = fn()
                    after = tlm.counter_totals()
                    return wall, {k: v - before.get(k, 0)
                                  for k, v in after.items()
                                  if v != before.get(k, 0)}

                def run_dat(tag):
                    t0 = time.perf_counter()
                    assert cli_sweep.main([fil, "-o", tag, *sweep_args,
                                           "--write-dats"]) == 0
                    dats = sorted(_glob.glob(f"{tag}_DM*.dat"))
                    assert cli_accel.main([*dats, "--batch", "8", "-z",
                                           "20", "-n", "2", "-s", "3"]) == 0
                    return time.perf_counter() - t0

                def run_handoff(tag, extra=()):
                    def go():
                        t0 = time.perf_counter()
                        assert cli_sweep.main([fil, "-o", tag,
                                               *sweep_args, *handoff,
                                               *extra]) == 0
                        return time.perf_counter() - t0
                    return leg_counters(go)

                # each leg runs twice: the first pass compiles that
                # leg's kernels (jit caches are shared in-process), the
                # second is the measured wall — the same
                # warm-at-real-shape discipline every other bench leg
                # applies
                run_dat("wdat")
                wall_dat = run_dat("dat")
                run_handoff("wstr")
                wall_streamed, str_counters = run_handoff("str")
                run_handoff("wfus", ["--spectral"])
                wall_fused, fus_counters = run_handoff("fus",
                                                       ["--spectral"])
                os.environ["PYPULSAR_TPU_SPECFUSE_MODE"] = "decimate"
                run_handoff("wdec", ["--spectral"])
                wall_dec, dec_counters = run_handoff("dec",
                                                     ["--spectral"])

            c_dat, c_str = cands("dat"), cands("str")
            c_fus, c_dec = cands("fus"), cands("dec")
            assert c_str == c_dat, "streamed vs .dat parity broke"
            assert c_fus == c_str, "fused(stitched) vs streamed parity broke"
            dec_identical = sum(c_dec[k] == c_str[k] for k in c_str)
        finally:
            for k, v in env_save.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            os.chdir(olddir)

    pairs_elided = dec_counters.get("specfuse.fft_pairs_elided", 0)
    unit = (f"fused vs streamed vs .dat walls, CPU-toy geometry "
            f"({C}-chan x {T}-samp x {D} trials, zmax=20, H<=2, "
            f"engine=fourier); the GATE is structural: transforms/trial "
            f"from telemetry counters, sift parity asserted")
    if args.cpu_fallback:
        unit += " [CPU FALLBACK: accelerator backend unavailable]"
    return {
        "metric": "specfuse_ab",
        # headline: the fused stitched path vs the streamed handoff
        "value": round(wall_streamed / wall_fused, 3),
        "unit": unit,
        "wall_dat_chain_s": round(wall_dat, 2),
        "wall_streamed_s": round(wall_streamed, 2),
        "wall_fused_s": round(wall_fused, 2),
        "wall_decimate_s": round(wall_dec, 2),
        "parity": {
            "streamed_vs_dat": "byte-identical (asserted)",
            "fused_vs_streamed": "byte-identical (asserted)",
            "decimate_vs_streamed": f"{dec_identical}/{len(c_str)} tables "
                                    f"byte-identical (circular boundary "
                                    f"semantics; opt-in regime, see "
                                    f"specfuse docstring)",
        },
        "transforms_per_trial": {
            # single-chunk geometry: the streamed path pays one sweep
            # irfft + one prep rfft per trial; fused(stitched) pays the
            # same two but keeps the series on device; decimate pays 0
            "streamed": 2,
            "fused_stitched": 2,
            "fused_decimate": 0,
        },
        "fft_pairs_elided_decimate": int(pairs_elided),
        "series_bytes_kept_on_device_fused": int(
            fus_counters.get("specfuse.bytes_on_device", 0)),
        "chunks_stitched_fused": int(
            fus_counters.get("specfuse.chunks_stitched", 0)),
        "d2h_bytes": {
            "streamed": int(str_counters.get("d2h.bytes", 0)),
            "fused": int(fus_counters.get("d2h.bytes", 0)),
            "decimate": int(dec_counters.get("d2h.bytes", 0)),
        },
        "n_trials": D,
    }


def _load_dedisp_roofline():
    """tools/dedisp_roofline.py loaded as a module — the ONE definition
    of the structural work accounting the bench record cites (the
    BENCHNOTES complexity claims must be tool-derived)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "dedisp_roofline.py")
    spec = importlib.util.spec_from_file_location("dedisp_roofline", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_dedisp_tree(args):
    """Three-engine dedispersion A/B at a production DM-count geometry
    (round 16 / ISSUE 11 acceptance): >=1024 chans x >=1000 DM trials
    through the SAME streamed sweep for engine=gather (the bit-exact-SNR
    reference), fourier (the TPU default) and tree (the shared-work
    merge engine, ops/tree_dedisperse.py).

    The GATE is structural (the PR 10 convention): adds-per-cell per
    engine come from tools/dedisp_roofline.py's exact table accounting —
    tree scales ~log2(nchan) at a fixed DM grid while naive per-channel
    shifts scale ~nchan and the two-stage direct engine pays C/g + S —
    plus the tree engine's own telemetry counters (tree.adds_total /
    tree.merge_levels / tree.bytes_on_device) from the measured run.
    CPU-toy wall times are reported honestly as CPU-toy wall times; SNR
    parity vs the direct engine is ASSERTED in-process.

    The DM grid tops out at the FDMT-regime diagonal (full-band delay
    span ~ nchan samples) — the dense-survey regime the tree recurrence
    was invented for (PAPERS.md 1201.5380); a DDplan step at dense
    low-DM spacing has exactly this shape."""
    acquire_backend()
    import jax
    import jax.numpy as jnp

    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.obs import telemetry as _tlm
    from pypulsar_tpu.ops import numpy_ref, tree_dedisperse
    from pypulsar_tpu.parallel import make_sweep_plan, sweep_spectra

    roof = _load_dedisp_roofline()
    if args.quick or args.cpu_fallback:
        C = args.nchan or 256
        D = args.trials or 256
        T = args.nsamp or 1 << 13
    else:
        C = args.nchan or 1024
        D = args.trials or 1024
        T = args.nsamp or 1 << 14
    dt = 64e-6
    nsub, group = min(64, C), min(32, D)
    freqs = (1500.0 - 300.0 / C * np.arange(C)).astype(np.float64)
    dm_max = roof.diagonal_dm(C, dt, 1500.0, 300.0)
    dms = np.linspace(0.0, dm_max, D)
    rng = np.random.RandomState(19)
    data = rng.randn(C, T).astype(np.float32)
    # a real dispersed pulse so peak SNRs are O(10) and the parity
    # assert exercises signal trials, not just noise
    bins = numpy_ref.bin_delays(dm_max / 2, freqs, dt)
    t0_pulse = T // 3
    for c in range(C):
        idx = t0_pulse + bins[c]
        if idx < T:
            data[c, idx] += 0.5
    spec = Spectra(freqs, dt, jnp.asarray(data))
    print(f"# dedisp-tree A/B: {C} chans x {T} samples x {D} trials "
          f"(DM 0-{dm_max:.1f}, the span~nchan diagonal), nsub={nsub}, "
          f"g={group}", file=sys.stderr)

    walls, results, counters = {}, {}, {}
    with _tlm.session(tool="bench-dedisp-tree") as tlm:
        for engine in ("gather", "fourier", "tree"):
            def run():
                return sweep_spectra(spec, dms, nsub=nsub,
                                     group_size=group, engine=engine)

            run()  # warm: compile at the real shape
            before = dict(tlm.counter_totals())
            best = float("inf")
            for _ in range(2):  # best of 2, the sweep-bench discipline
                t0 = time.perf_counter()
                res = run()
                best = min(best, time.perf_counter() - t0)
            walls[engine] = best
            results[engine] = res
            counters[engine] = {
                k: v - before.get(k, 0)
                for k, v in tlm.counter_totals().items()
                if v != before.get(k, 0)}
            print(f"# engine={engine:8s} wall {best:7.2f} s (CPU toy)",
                  file=sys.stderr)

    ref = results["gather"]

    def rel_err(res):
        return float((np.abs(res.snr - ref.snr)
                      / np.maximum(np.abs(ref.snr), 1.0)).max())

    rel_tree, rel_fourier = rel_err(results["tree"]), rel_err(
        results["fourier"])
    # the parity gate, asserted in-process: the contract number (2e-6,
    # pinned at the suite's contract geometry by
    # test_tree_engine_snr_tolerance) — and at THIS geometry the tree
    # must additionally be at least as tight as the published
    # fourier engine, whose own f32 floor grows past 2e-6 at
    # production scale (both recorded; nothing hidden)
    assert rel_tree <= max(2e-6, rel_fourier), \
        f"tree SNR parity {rel_tree:.2e} looser than both the 2e-6 " \
        f"contract and the fourier engine's {rel_fourier:.2e}"
    assert np.array_equal(results["tree"].peak_sample, ref.peak_sample)
    # half of the tree leg's counter total is the warm run; the diff
    # covers the two measured reps
    tree_adds = int(counters["tree"].get("tree.adds_total", 0) // 2)

    # tool-derived structural accounting (the complexity gate)
    struct = roof.analyze(C, D, T, dm_max, nsub=nsub, group_size=group,
                          dt=dt)
    nchans = [C // 4, C // 2, C, 2 * C]
    scaling = roof.scaling_sweep(nchans, D, T, dm_max, nsub, group, dt,
                                 1500.0, 300.0)
    growth = scaling["growth"]
    # the work-complexity win: tree adds/cell grow ~log2(nchan) (within
    # 2x over an 8x channel range, tracking the level count) while
    # naive per-channel shifts grow ~nchan (8x), and at this geometry
    # the tree undercuts even the two-stage direct engine
    assert growth["tree"] < 2.0 < growth["naive"], growth
    assert struct["adds_per_cell"]["tree"] < \
        struct["adds_per_cell"]["direct_two_stage"], struct
    # shared-work scaling with trial count: the per-cell adds DROP as
    # trials share the tree (the production-DM-count story)
    ndm_scan = [roof.analyze(C, n, T, dm_max, nsub=nsub,
                             group_size=group, dt=dt)
                for n in (D, 2 * D, 4 * D)]

    unit = (f"direct-over-tree adds/cell ratio at {C} chans x {D} "
            f"trials (structural, tools/dedisp_roofline.py; walls are "
            f"CPU-toy walls, labeled as such per the PR 10 convention; "
            f"SNR parity vs engine=gather asserted in-process)")
    if args.cpu_fallback:
        unit += " [CPU FALLBACK: accelerator backend unavailable]"
    return {
        "metric": "dedisp_tree_ab",
        "value": struct["work_ratio_direct_over_tree"],
        "unit": unit,
        "nchan": C, "n_trials": D, "nsamp": T,
        "dm_max_diagonal": round(dm_max, 3),
        "delay_span_bins": struct["delay_span_bins"],
        "wall_note": "CPU-toy walls (no TPU in this container): the "
                     "structural counters are the gate, the walls are "
                     "context",
        "wall_gather_s": round(walls["gather"], 2),
        "wall_fourier_s": round(walls["fourier"], 2),
        "wall_tree_s": round(walls["tree"], 2),
        "snr_parity": {
            "contract": "gather=bit-exact reference; tree toleranced "
                        "like fourier (<=2e-6 at the contract geometry, "
                        "tests/test_sweep.py::"
                        "test_tree_engine_snr_tolerance)",
            "tree_rel_err": rel_tree,
            "fourier_rel_err": rel_fourier,
            "peak_samples_identical": True,
        },
        "adds_per_cell": struct["adds_per_cell"],
        "bytes_per_cell": struct["bytes_per_cell"],
        "tree_structure": struct["tree"],
        "tree_counters_measured": {
            "adds_total_per_rep": tree_adds,
            "merge_levels": struct["tree"]["merge_levels"],
            "bytes_on_device": int(
                counters["tree"].get("tree.bytes_on_device", 0) // 2),
        },
        "scaling_vs_nchan": scaling,
        "scaling_vs_ndm": [
            {"ndm": r["ndm"], "tree_adds_per_cell":
             r["adds_per_cell"]["tree"],
             "direct_over_tree": r["work_ratio_direct_over_tree"]}
            for r in ndm_scan],
    }


def run_fold(args):
    """Folding-engine throughput (BASELINE configs[3]: polyco fold +
    profile accumulation; the reference folds one rotation at a time in
    Python, formats/datfile.py:231-275). Metric: samples folded/s into a
    [npart, nchan, nbins] archive cube (all raw channels kept — the
    .pfd-style product before subbanding) via the device scatter-add
    engine vs the single-core NumPy bincount twin."""
    acquire_backend()
    import jax.numpy as jnp
    from pypulsar_tpu.fold.engine import fold_numpy, fold_parts, phase_to_bins

    if args.quick or args.cpu_fallback:
        C, T = 64, 1 << 18
    else:
        # fits HBM with headroom: dataset 4 GB on the 16 GB v5e (there is
        # no streaming/retry here — a single resident cube is the measure)
        C, T = 1024, 1 << 20
    nbins, npart = 128, 64
    dt, period = 64e-6, 0.033
    # float32 generation: a float64 intermediate would double host peak
    data = np.random.default_rng(0).standard_normal((C, T),
                                                    dtype=np.float32)
    t = np.arange(T) * dt
    phase = t / period
    bin_idx = phase_to_bins(phase, nbins)
    part_len = T // npart

    dev = jnp.asarray(data)
    bi = jnp.asarray(bin_idx)
    float(dev[0, 0])

    def run():
        # whole [npart, C, nbins] cube in ONE dispatch (fold_parts): the
        # per-partition loop this replaces paid ~60 ms tunnel latency per
        # partition, drowning the kernel (bench r3)
        profs, _ = fold_parts(dev, bi, nbins, npart)
        return np.asarray(profs)

    run()  # warm
    # min-of-3: single measurements through the shared tunnel vary by
    # >2x run to run (observed 0.73/1.69/1.99 s for identical code)
    jax_time = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        profs = run()
        jax_time = min(jax_time, time.perf_counter() - t0)
    samples_per_sec = C * T / jax_time
    # split out the device compute from the cube's device->host pull —
    # through the remote tunnel the 33 MB result transfer can dominate
    # the kernel; both are reported (bench r3). The scalar pull is the
    # only reliable sync on this platform (block_until_ready returns
    # early, BENCHNOTES), so kernel_time includes one sync dispatch's
    # ~60 ms tunnel roundtrip — kernel_samples_per_sec is a LOWER bound
    kernel_time = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        profs_dev, _ = fold_parts(dev, bi, nbins, npart)
        float(jnp.ravel(profs_dev)[0])
        kernel_time = min(kernel_time, time.perf_counter() - t0)
    kernel_samples_per_sec = C * T / kernel_time

    # fused fold + ON-DEVICE profile statistics (VERDICT r3 item 4): the
    # archive cube stays on device; what crosses the tunnel is per-part/
    # per-chan profiles, data moments and the bestprof chi2 grid (~KBs,
    # not 33 MB) — this is the END-TO-END path of record
    from pypulsar_tpu.fold.engine import bestprof_offsets, fold_stats

    _, off = bestprof_offsets(npart, T * dt, period, ntrial=65)
    offd = jnp.asarray(off)
    float(offd[0, 0])

    def run_fused():
        # one batched pull — per-array np.asarray pays a tunnel roundtrip
        # per output (ops/transfer.pull_host, BENCHNOTES r4)
        from pypulsar_tpu.ops.transfer import pull_host

        return list(pull_host(*fold_stats(dev, bi, nbins, npart, offd)))

    run_fused()  # warm
    fused_time = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        fused = run_fused()
        fused_time = min(fused_time, time.perf_counter() - t0)
    fused_samples_per_sec = C * T / fused_time

    # numpy twin on one partition, scaled linearly
    t0 = time.perf_counter()
    ref, _ = fold_numpy(data[:, :part_len], bin_idx[:part_len], nbins)
    bl_time = (time.perf_counter() - t0) * npart
    # zero-mean channel sums: f32 accumulation error is absolute-scale
    # (~1e-3 at these shapes), so an atol is required alongside rtol
    np.testing.assert_allclose(profs[0].sum(axis=0),
                               ref.sum(axis=0), rtol=1e-3, atol=0.5)
    np.testing.assert_allclose(fused[0][0], ref.sum(axis=0), rtol=1e-3,
                               atol=0.5)  # fused part_profs[0] twin-checked
    bl_samples_per_sec = C * T / bl_time
    speedup = fused_samples_per_sec / bl_samples_per_sec
    try:
        pipe_extras = _fold_pipeline_ab(args)
    except Exception as e:  # noqa: BLE001 - the headline must still land
        print(f"# fold pipeline A/B failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        pipe_extras = {"fold_pipe_error": f"{type(e).__name__}: {e}"}
    print(f"# fold: fused stats {fused_time:.3f}s = "
          f"{fused_samples_per_sec/1e9:.2f} Gsamp/s end-to-end "
          f"(kernel {kernel_time:.3f}s = "
          f"{kernel_samples_per_sec/1e9:.2f} Gsamp/s; full-cube pull "
          f"{jax_time:.2f}s); numpy 1/{npart} slice {bl_time/npart:.2f}s",
          file=sys.stderr)
    unit = (f"folded samples/s ({C}-chan, {T} samples, {nbins} bins, "
            f"{npart} partitions, min of 3 runs, END-TO-END through the "
            f"fused on-device stats path (profiles + moments + bestprof "
            f"chi2 pulled, cube stays on device); kernel-only and "
            f"cube-pull rates in extras; numpy baseline one partition "
            f"x{npart})")
    if args.cpu_fallback:
        unit += " [CPU FALLBACK: accelerator backend unavailable]"
    return {
        "metric": "fold_samples_per_sec",
        "value": round(fused_samples_per_sec, 1),
        "unit": unit,
        "vs_baseline": round(speedup, 2),
        "fused_seconds": round(fused_time, 3),
        "fused_vs_kernel": round(fused_time / kernel_time, 2),
        "cube_pull_seconds": round(jax_time, 3),
        "cube_pull_samples_per_sec": round(samples_per_sec, 1),
        "kernel_seconds": round(kernel_time, 3),
        "kernel_samples_per_sec": round(kernel_samples_per_sec, 1),
        "numpy_seconds_scaled": round(bl_time, 3),
        **pipe_extras,
    }


def _fold_pipeline_ab(args):
    """Batched candidate-fold PIPELINE A/B (the round-8 tentpole's
    acceptance measurement), two legs:

    PARITY (per-DM .dat series): ``foldbatch --datbase`` vs one
    in-process ``prepfold`` call per candidate on the same series — the
    archives must be BYTE-identical (profs + stats arrays; the batched
    one-hot fold runs the identical per-candidate contraction, so the
    f32 accumulation matches bitwise) and the derived SNRs equal.

    SPEEDUP (raw .fil): ``foldbatch <fil> --cands`` streams the
    observation ONCE (dedisperse via the sweep chunk kernel, one batched
    fold per DM group, on-device (p, pdot) refinement) vs the serial
    workflow it replaces — one ``prepfold`` INVOCATION per candidate,
    each a fresh process re-reading the raw file (exactly how the
    per-candidate tool is used; measured on a subset and scaled
    linearly, the bench's standing baseline pattern). The in-process
    serial loop is also recorded (``*_inproc``) so the process-overhead
    share is visible."""
    import subprocess
    import tempfile

    from pypulsar_tpu.cli import foldbatch as cli_foldbatch
    from pypulsar_tpu.cli import prepfold as cli_prepfold
    from pypulsar_tpu.fold import profile_snr
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.io.datfile import write_dat
    from pypulsar_tpu.io.infodata import InfoData
    from pypulsar_tpu.io.prestopfd import PfdFile

    ndm, per_dm = 4, 8  # 32 candidates, the acceptance floor
    Np = 1 << 15 if (args.quick or args.cpu_fallback) else 1 << 16
    C, dtp = 32, 5e-4
    nbins, npart = 64, 16
    rng = np.random.default_rng(7)
    dms = [10.0 * (d + 1) for d in range(ndm)]
    cand_rows = []
    t = np.arange(Np) * dtp
    olddir = os.getcwd()
    with tempfile.TemporaryDirectory() as td:
        os.chdir(td)
        try:
            # toy observation: C-channel .fil with one dispersed pulse
            # train, plus the per-DM dedispersed .dat series the parity
            # leg folds (same noise seed per DM so the series are stable)
            for d, dm in enumerate(dms):
                base_p = 0.0517 * (1.0 + 0.13 * d)
                ts = rng.standard_normal(Np).astype(np.float32)
                ts += 3.0 * np.exp(
                    -0.5 * (((t / base_p) % 1.0 - 0.4) / 0.03) ** 2
                ).astype(np.float32)
                inf = InfoData()
                inf.epoch, inf.dt, inf.N = 55000.0, dtp, Np
                inf.telescope, inf.object = "Fake", "BENCH"
                inf.lofreq, inf.BW = 1400.0, 100.0
                inf.numchan, inf.chan_width = 1, 100.0
                inf.DM = dm
                write_dat(f"toy_DM{dm:.2f}", ts, inf)
                for j in range(per_dm):
                    cand_rows.append((base_p * (1.0 + 0.021 * j), dm))
            fildata = rng.standard_normal((Np, C)).astype(np.float32) * 2.0
            phase = (t / 0.0731) % 1.0
            fildata += 8.0 * np.exp(
                -0.5 * ((phase - 0.5) / 0.03) ** 2
            ).astype(np.float32)[:, None]
            filterbank.write_filterbank(
                "toy.fil", dict(nchans=C, tsamp=dtp, fch1=1500.0,
                                foff=-4.0, tstart=55000.0, nbits=32,
                                nifs=1, source_name="BENCH"), fildata)
            with open("cands.txt", "w") as f:
                f.writelines(f"{p!r} {dm}\n" for p, dm in cand_rows)
            n = len(cand_rows)

            # -- parity leg (.dat series, in-process both sides) --------
            t0 = time.perf_counter()
            for i, (p, dm) in enumerate(cand_rows):
                rc = cli_prepfold.main(
                    [f"toy_DM{dm:.2f}.dat", "-p", repr(p), "--dm",
                     str(dm), "-n", str(nbins), "--npart", str(npart),
                     "-o", f"serial_{i:04d}.pfd"])
                assert rc == 0
            dat_serial_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            rc = cli_foldbatch.main(
                ["--cands", "cands.txt", "--datbase", "toy", "-o", "bb",
                 "-n", str(nbins), "--npart", str(npart)])
            assert rc == 0
            dat_pipe_s = time.perf_counter() - t0

            import json as _json

            summary = _json.load(open("bb_foldbatch.json"))
            results = [r for r in summary["results"]
                       if not r.get("skipped")]
            # join on the candNNNN index encoded in the name, and fail
            # LOUDLY if any candidate is missing — a positional zip
            # would silently misalign every comparison after one
            # failed fold
            assert len(results) == len(cand_rows), (
                f"foldbatch folded {len(results)}/{len(cand_rows)}")
            identical = 0
            snr_diff = 0.0
            for res in results:
                i = int(res["name"][4:8])
                a = PfdFile(f"serial_{i:04d}.pfd")
                b = PfdFile(res["pfd"])
                if (np.array_equal(a.profs, b.profs)
                        and np.array_equal(a.stats, b.stats)):
                    identical += 1
                try:
                    sa = profile_snr.pfd_snr(a)["snr"]
                    sb = profile_snr.pfd_snr(b)["snr"]
                    snr_diff = max(snr_diff, abs(sa - sb))
                except profile_snr.OnPulseError:
                    pass  # a noise fold with no on-pulse: nothing to score

            # -- speedup leg (raw .fil) ---------------------------------
            t0 = time.perf_counter()
            rc = cli_foldbatch.main(
                ["toy.fil", "--cands", "cands.txt", "-o", "ff",
                 "-n", str(nbins), "--npart", str(npart), "-s", "8",
                 "--group-size", "4"])
            assert rc == 0
            pipe_s = time.perf_counter() - t0
            n_serial = min(6, n)  # subset, scaled linearly (cost is
            # per-invocation constant + per-sample linear, both measured)
            repo_root = os.path.dirname(os.path.abspath(__file__))
            env = dict(os.environ)
            env["PYTHONPATH"] = (repo_root + os.pathsep +
                                 env.get("PYTHONPATH", "")).rstrip(
                                     os.pathsep)
            t0 = time.perf_counter()
            for i, (p, dm) in enumerate(cand_rows[:n_serial]):
                subprocess.run(
                    [sys.executable, "-m", "pypulsar_tpu.cli.prepfold",
                     "toy.fil", "-p", repr(p), "--dm", str(dm),
                     "-n", str(nbins), "--npart", str(npart),
                     "-o", f"raw_{i:04d}.pfd"],
                    check=True, capture_output=True, env=env)
            serial_s = (time.perf_counter() - t0) * (n / n_serial)
            t0 = time.perf_counter()
            for i, (p, dm) in enumerate(cand_rows[:n_serial]):
                rc = cli_prepfold.main(
                    ["toy.fil", "-p", repr(p), "--dm", str(dm),
                     "-n", str(nbins), "--npart", str(npart),
                     "-o", f"rawi_{i:04d}.pfd"])
                assert rc == 0
            serial_inproc_s = (time.perf_counter() - t0) * (n / n_serial)

            print(f"# fold pipe A/B: raw-file serial loop "
                  f"{serial_s:.1f}s est ({n / serial_s:.2f} cand/s, "
                  f"{n_serial} invocations measured; in-process "
                  f"{serial_inproc_s:.1f}s) vs streamed batched "
                  f"{pipe_s:.2f}s ({n / pipe_s:.2f} cand/s) = "
                  f"{serial_s / pipe_s:.1f}x; .dat parity leg "
                  f"{dat_serial_s / dat_pipe_s:.1f}x with {identical}/"
                  f"{n} archives byte-identical, max |dSNR| "
                  f"{snr_diff:.2e}", file=sys.stderr)
            return {
                "fold_pipe_n_cands": n,
                "fold_pipe_n_dms": ndm,
                "fold_pipe_nsamp": Np,
                "fold_pipe_nchan": C,
                "fold_pipe_cands_per_sec": round(n / pipe_s, 2),
                "fold_pipe_serial_cands_per_sec": round(n / serial_s, 3),
                "fold_pipe_speedup": round(serial_s / pipe_s, 2),
                "fold_pipe_seconds": round(pipe_s, 3),
                "fold_pipe_serial_seconds_est": round(serial_s, 2),
                "fold_pipe_serial_invocations_measured": n_serial,
                "fold_pipe_serial_inproc_seconds_est":
                    round(serial_inproc_s, 2),
                "fold_pipe_speedup_inproc":
                    round(serial_inproc_s / pipe_s, 2),
                "fold_pipe_dat_speedup":
                    round(dat_serial_s / dat_pipe_s, 2),
                "fold_pipe_archives_identical": f"{identical}/{n}",
                "fold_pipe_max_snr_diff": float(snr_diff),
            }
        finally:
            os.chdir(olddir)


def _synth_survey_fil(fn, seed, C, T, dtp, freqs, src_name,
                      dm=40.0, period=0.1024, amp=10.0,
                      tstart=55000.0):
    """One synthetic pulsar filterbank for the survey/chaos harnesses
    (shared so the two A/Bs can never drift apart on the recipe).
    ``tstart`` lets the candplane A/B re-observe the same pulsar at
    several epochs; every other harness keeps the 55000.0 default."""
    import numpy as np

    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.ops import numpy_ref

    rng = np.random.RandomState(seed)
    data = rng.randn(T, C).astype(np.float32) * 2.0 + 30.0
    bins = numpy_ref.bin_delays(dm, freqs, dtp)
    for t0 in np.arange(0.01, T * dtp, period):
        s0 = int(t0 / dtp)
        for c in range(C):
            idx = s0 + bins[c]
            if idx < T:
                data[idx, c] += amp
    filterbank.write_filterbank(
        fn, dict(nchans=C, tsamp=dtp, fch1=float(freqs[0]),
                 foff=-4.0, tstart=float(tstart), nbits=32, nifs=1,
                 source_name=src_name), data)
    return fn


def run_survey(args):
    """Survey-orchestrator A/B (the round-9 tentpole's acceptance
    measurement): the SAME per-observation stage chain (rfifind-mask ->
    sweep --accel-search --write-dats -> sift -> foldbatch -> pfd_snr,
    identical in-process CLI argvs) over a 4-observation toy fleet, run
    two ways —

    - **serial**: one observation at a time, one stage at a time (the
      shell-loop workflow the orchestrator replaces);
    - **orchestrated**: the fleet scheduler, one device lease + a
      2-worker host pool, so observation B's sift/SNR summaries overlap
      observation A's device stages.

    Both legs run after a full warmup chain (jit caches hot — the A/B
    measures orchestration, not compilation). Artifacts are checked
    byte-identical across legs (.txtcand candidate tables and .pfd
    archives), so the speedup is overlap, not skipped work."""
    acquire_backend()
    import glob as _glob
    import tempfile

    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.ops import numpy_ref
    from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import Observation

    n_obs = 4
    C, T, dtp = 32, (1 << 14 if (args.quick or args.cpu_fallback)
                     else 1 << 15), 5e-4
    rng_freqs = 1500.0 - 4.0 * np.arange(C)
    cfg = SurveyConfig(
        mask=True, mask_time=2.0, lodm=0.0, dmstep=10.0, numdms=8,
        nsub=8, group_size=4, threshold=8.0,
        accel_zmax=20.0, accel_numharm=2, accel_sigma=3.0, accel_batch=4,
        sift_sigma=3.0, sift_min_hits=1, fold_nbins=32, fold_npart=8)
    stages = build_dag(cfg)

    def make_obs_fil(fn, seed, dm=40.0, period=0.1024, amp=10.0):
        return _synth_survey_fil(fn, seed, C, T, dtp, rng_freqs,
                                 f"BENCH{seed}", dm=dm, period=period,
                                 amp=amp)

    def run_serial(obs_list):
        for obs in obs_list:
            for stage in stages:
                stage.execute(obs, cfg)

    with tempfile.TemporaryDirectory() as td:
        fils = [make_obs_fil(os.path.join(td, f"obs{i}.fil"), seed=11 + i,
                             period=0.1024 * (1.0 + 0.07 * i))
                for i in range(n_obs)]

        def fleet(dirname):
            out = os.path.join(td, dirname)
            os.makedirs(out, exist_ok=True)
            return [Observation(f"obs{i}", fils[i],
                                os.path.join(out, f"obs{i}"))
                    for i in range(n_obs)]

        # warmup: one full chain compiles every stage's jit programs
        run_serial(fleet("warm")[:1])

        t0 = time.perf_counter()
        run_serial(fleet("serial"))
        serial_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        result = FleetScheduler(fleet("orch"), cfg, max_host_workers=2,
                                devices=1).run()
        orch_s = time.perf_counter() - t0
        assert result.ok and len(result.ran) == n_obs * len(stages)

        # parity: the orchestrated fleet's candidate tables and archives
        # are byte-identical to the serial chain's — enforced, not just
        # reported: a speedup over divergent/missing work is not a win
        def _parity(dir_a, dir_b):
            ident = tot = 0
            for pattern in ("*_ACCEL_*.cand", "*_ACCEL_*.txtcand",
                            "*_cand*.pfd"):
                for fa in sorted(_glob.glob(os.path.join(td, dir_a,
                                                         pattern))):
                    fb = os.path.join(td, dir_b, os.path.basename(fa))
                    tot += 1
                    if (os.path.exists(fb) and open(fa, "rb").read()
                            == open(fb, "rb").read()):
                        ident += 1
            return ident, tot

        identical, total = _parity("serial", "orch")
        assert identical == total and total > 0, \
            f"orchestrated artifacts diverged: {identical}/{total}"

        # multi-chip leg (round 11): the SAME fleet with k device
        # leases + gang auto — fleet-parallel while ready device stages
        # fill the chips, gang-widened (`sweep --mesh k` over the
        # leased chips) when they would idle. Byte-parity is asserted
        # against BOTH the serial chain and the 1-device orchestrated
        # run: placement is not science
        orchk_s = None
        identical_k = total_k = None
        gang_decisions = []
        if args.devices > 1:
            import jax

            ndev = len(jax.devices())  # psrlint: ignore[PL002] -- fleet capacity check against the REAL inventory, outside any lease
            assert ndev >= args.devices, (
                f"--devices {args.devices} needs that many JAX devices, "
                f"have {ndev} (CPU recipe: XLA_FLAGS="
                f"--xla_force_host_platform_device_count=8)")
            # warm EVERY chip's jit caches, not just device 0's: stages
            # pin via jax.default_device and executables are
            # per-device, so an unwarmed chip would recompile the whole
            # chain inside the timed leg. One fleet-parallel pass warms
            # the k per-device 1-chip programs, one gang pass warms the
            # mesh-sharded (gang-width) programs
            FleetScheduler(fleet("warmk"), cfg, max_host_workers=2,
                           devices=args.devices, gang=1).run()
            FleetScheduler(fleet("warmg")[:1], cfg, max_host_workers=2,
                           devices=args.devices,
                           gang=args.devices).run()
            tlm_k = os.path.join(td, "tlm_k")
            t0 = time.perf_counter()
            result_k = FleetScheduler(
                fleet("orchk"), cfg, max_host_workers=2,
                devices=args.devices, gang="auto",
                telemetry_dir=tlm_k).run()
            orchk_s = time.perf_counter() - t0
            assert result_k.ok \
                and len(result_k.ran) == n_obs * len(stages)
            identical_k, total_k = _parity("serial", "orchk")
            assert identical_k == total_k and total_k > 0, (
                f"multi-chip artifacts diverged from the serial chain: "
                f"{identical_k}/{total_k}")
            ik, tk = _parity("orch", "orchk")
            assert ik == tk and tk > 0, (
                f"multi-chip artifacts diverged from the 1-device "
                f"orchestrated run: {ik}/{tk}")

            # the single-observation shape (the tentpole itself): a LONE
            # observation on k idle chips gang-widens (`sweep --mesh k`
            # over the leased gang) — timed against the same observation
            # through the serial 1-chip chain, artifacts byte-checked
            t0 = time.perf_counter()
            run_serial(fleet("serial1")[:1])
            serial1_s = time.perf_counter() - t0
            tlm_g = os.path.join(td, "tlm_g")
            t0 = time.perf_counter()
            result_g = FleetScheduler(
                fleet("gangk")[:1], cfg, max_host_workers=2,
                devices=args.devices, gang="auto",
                telemetry_dir=tlm_g).run()
            gang_s = time.perf_counter() - t0
            assert result_g.ok and len(result_g.ran) == len(stages)
            ig, tg = _parity("serial1", "gangk")
            assert ig == tg and tg > 0, (
                f"gang-leased artifacts diverged: {ig}/{tg}")

            # the recorded placement decisions (the obs traces carry
            # the same survey.gang_decision events the fleet trace does)
            gang_decisions_g = []
            for tdir, sink in ((tlm_k, gang_decisions),
                               (tlm_g, gang_decisions_g)):
                for p in sorted(_glob.glob(os.path.join(tdir, "*.jsonl"))):
                    for line in open(p):
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if (rec.get("type") == "event"
                                and rec.get("name")
                                == "survey.gang_decision"):
                            sink.append(rec.get("attrs", {}))
            # the widening claim is about the LONE-obs leg only; the
            # fleet leg's decisions must not be able to satisfy it
            assert any(d.get("k", 1) > 1 for d in gang_decisions_g), \
                "the lone observation never gang-widened"
            gang_decisions.extend(gang_decisions_g)

    speedup = serial_s / orch_s
    print(f"# survey A/B: serial chain {serial_s:.2f}s vs orchestrated "
          f"{orch_s:.2f}s = {speedup:.2f}x ({n_obs} obs x "
          f"{len(stages)} stages, 1 device lease + 2 host workers; "
          f"{identical}/{total} artifacts byte-identical)",
          file=sys.stderr)
    unit = (f"orchestrated-fleet speedup over the serial per-observation "
            f"chain ({n_obs} toy obs x {len(stages)} stages "
            f"[mask/sweep+accel/sift/fold/snr], {C}-chan x {T}-sample "
            f"each, warm jit caches, 1 device lease + 2 host workers — "
            f"host-stage/device-stage overlap only, artifacts "
            f"byte-checked against the serial legs)")
    record = {
        "metric": "survey_fleet_speedup",
        "value": round(speedup, 3),
        "unit": unit,
        "vs_baseline": round(speedup, 3),
        "survey_n_obs": n_obs,
        "survey_n_stages": len(stages),
        "survey_serial_seconds": round(serial_s, 3),
        "survey_orchestrated_seconds": round(orch_s, 3),
        "survey_stages_run": len(result.ran),
        "survey_max_host_workers": 2,
        "survey_devices": 1,
        "survey_artifacts_identical": f"{identical}/{total}",
        "survey_nsamp": T,
        "survey_nchan": C,
    }
    if orchk_s is not None:
        speedup_k = serial_s / orchk_s
        n_gang = sum(1 for d in gang_decisions if d.get("k", 1) > 1)
        print(f"# survey multi-chip: {args.devices} device leases + gang "
              f"auto {orchk_s:.2f}s = {speedup_k:.2f}x vs serial "
              f"({orch_s / orchk_s:.2f}x vs 1-device orchestrated; "
              f"{len(gang_decisions)} placement decisions, {n_gang} "
              f"gang-widened; {identical_k}/{total_k} artifacts "
              f"byte-identical to the serial chain)", file=sys.stderr)
        print(f"# survey 1-obs gang: serial chain {serial1_s:.2f}s vs "
              f"gang x{args.devices} {gang_s:.2f}s = "
              f"{serial1_s / gang_s:.2f}x (one observation spanning "
              f"{args.devices} chips end to end, artifacts "
              f"byte-identical)", file=sys.stderr)
        record.update({
            "metric": "survey_multichip_speedup",
            "value": round(speedup_k, 3),
            "vs_baseline": round(speedup_k, 3),
            "unit": unit.replace(
                "1 device lease + 2 host workers",
                f"{args.devices} device leases (gang auto: fleet-"
                f"parallel + gang-widening onto idle chips) + 2 host "
                f"workers").replace(
                "byte-checked against the serial legs",
                "byte-checked against BOTH the serial chain and the "
                "1-device orchestrated run"),
            "survey_devices": args.devices,
            "survey_multichip_seconds": round(orchk_s, 3),
            "survey_orchestrated_1dev_speedup": round(speedup, 3),
            "survey_multichip_vs_1dev": round(orch_s / orchk_s, 3),
            "survey_multichip_artifacts_identical":
                f"{identical_k}/{total_k}",
            "survey_1obs_serial_seconds": round(serial1_s, 3),
            "survey_1obs_gang_seconds": round(gang_s, 3),
            "survey_1obs_gang_speedup": round(serial1_s / gang_s, 3),
            "survey_gang_decisions": len(gang_decisions),
            "survey_gang_widened": n_gang,
            "survey_gang_reasons": sorted(
                {d.get("reason", "?") for d in gang_decisions})[:6],
        })
        try:
            import jax

            platform = jax.devices()[0].platform  # psrlint: ignore[PL002] -- record annotation, runs after the fleet (no lease)
        except Exception:  # noqa: BLE001 - note is best-effort
            platform = "?"
        if platform == "cpu":
            record["survey_multichip_note"] = (
                "k virtual CPU devices share ONE host's cores, so "
                "multi-chip wall-clock is not expected to improve here "
                "— the record's claims are the byte-parity of every "
                "artifact at k chips and the recorded gang/fleet "
                "placement decisions; wall-clock scaling needs real "
                "chips")
    if args.cpu_fallback:
        record["unit"] += " [CPU FALLBACK: accelerator backend unavailable]"
    return record


def run_broker(args):
    """Batch-broker A/B (the round-24 tentpole's acceptance
    measurement): the SAME 4-observation same-geometry toy fleet
    through the fleet scheduler two ways —

    - **per-obs** (`PYPULSAR_TPU_BROKER=0`): the pre-round-24 dispatch
      tree, every observation's accel/fold batches dispatched solo;
    - **brokered**: batch lanes + the cross-observation broker
      (lane width 4, a wide coalescing window so the toy fleet always
      fuses), same-key work units from different observations merged
      into single device dispatches and demuxed back per obs.

    Each leg runs after its own full warmup pass (jit caches hot for
    THAT leg's batch shapes). The record is gated on structure, not
    wall-clock: coalesce factor >= 2, fused dispatch count <= half the
    per-obs device-dispatch count, no extra compile misses on the
    measured leg, artifacts byte-identical across legs, and a
    validated resume that re-runs zero stages."""
    acquire_backend()
    import glob as _glob
    import tempfile

    from pypulsar_tpu.obs import telemetry
    from pypulsar_tpu.parallel import broker as broker_mod
    from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import Observation

    n_obs = 4
    C, T, dtp = 16, (1 << 13 if (args.quick or args.cpu_fallback)
                     else 1 << 14), 5e-4
    rng_freqs = 1500.0 - 4.0 * np.arange(C)
    # no mask stage: every observation's sweep is queued at t0, so the
    # lane claim is deterministically fleet-wide instead of racing the
    # per-obs mask I/O. The sift gate is pinned HIGH so the fold stage
    # stays empty: fold-lane composition depends on which observation's
    # sift lands first (a benign scheduling race), so fold fused shapes
    # are not run-to-run reproducible and would make the zero-extra-
    # compile-miss gate flaky — fold fusion parity and fault isolation
    # are owned by tests/test_broker.py; this A/B pins the accel
    # spectrum-bank path, the fleet's hot fused dispatch.
    cfg = SurveyConfig(
        mask=False, lodm=0.0, dmstep=10.0, numdms=16, nsub=8,
        group_size=4, threshold=8.0,
        accel_zmax=20.0, accel_numharm=2, accel_sigma=3.0, accel_batch=4,
        sift_sigma=20.0, sift_min_hits=3, fold_nbins=32, fold_npart=8)
    stages = build_dag(cfg)

    with tempfile.TemporaryDirectory() as td:
        fils = [_synth_survey_fil(os.path.join(td, f"obs{i}.fil"),
                                  11 + i, C, T, dtp, rng_freqs,
                                  f"BENCH{i}",
                                  period=0.1024 * (1.0 + 0.07 * i))
                for i in range(n_obs)]

        def fleet(dirname):
            out = os.path.join(td, dirname)
            os.makedirs(out, exist_ok=True)
            return [Observation(f"obs{i}", fils[i],
                                os.path.join(out, f"obs{i}"))
                    for i in range(n_obs)]

        def leg(dirname, env):
            # ONE host worker: the lane claim is deterministic (the
            # leader finds every other same-stage task still queued and
            # claims a full 4-wide lane) instead of racing a second
            # worker for mates — the A/B pins structure, and lane mates
            # run in their own threads anyway
            old = {k: os.environ.get(k) for k in env}
            os.environ.update(env)
            try:
                broker_mod.reset()
                # warm THIS configuration's jit programs: fused batch
                # shapes differ from the per-obs ones, so each leg
                # warms its own
                FleetScheduler(fleet(dirname + "-warm"), cfg,
                               max_host_workers=1, devices=1).run()
                broker_mod.reset()
                with telemetry.session() as tlm:
                    t0 = time.perf_counter()
                    result = FleetScheduler(fleet(dirname), cfg,
                                            max_host_workers=1,
                                            devices=1).run()
                    wall = time.perf_counter() - t0
                assert result.ok \
                    and len(result.ran) == n_obs * len(stages), \
                    f"{dirname} leg failed"
                # validated resume: brokered manifests must be as
                # trustworthy as per-obs ones — a second pass over the
                # same outdirs re-runs nothing
                res2 = FleetScheduler(fleet(dirname), cfg,
                                      max_host_workers=1, devices=1,
                                      resume=True).run()
                assert res2.ok and not res2.ran, \
                    f"{dirname} resume re-ran {len(res2.ran)} stages"
                return wall, tlm.counter_totals()
            finally:
                for k, v in old.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
                broker_mod.reset()

        base_s, base_c = leg("perobs", {"PYPULSAR_TPU_BROKER": "0"})
        brk_s, brk_c = leg("brokered", {
            "PYPULSAR_TPU_BROKER": "1",
            "PYPULSAR_TPU_BROKER_LANE": "4",
            # a wide window: the toy stages are host-bound, so the A/B
            # pins coalescing STRUCTURE rather than racing the clock
            "PYPULSAR_TPU_BROKER_WAIT_MS": "30000",
            # CPU-toy stages routinely blow their chip-budget deadlines,
            # and every slo_burn would collapse the window mid-leg —
            # fused compositions would then depend on wall-clock timing
            # and the measured leg could meet batch shapes the warm leg
            # never compiled. Pressure holds have their own tests; this
            # A/B pins the deterministic party-driven composition.
            "PYPULSAR_TPU_BROKER_SLO_HOLD_S": "0",
        })

        # parity: brokered demux must hand every observation bytes
        # identical to its solo dispatches — enforced, not reported
        ident = tot = 0
        for pattern in ("*_ACCEL_*.cand", "*_ACCEL_*.txtcand",
                        "*_cand*.pfd"):
            for fa in sorted(_glob.glob(os.path.join(td, "perobs",
                                                     pattern))):
                fb = os.path.join(td, "brokered", os.path.basename(fa))
                tot += 1
                if (os.path.exists(fb) and open(fa, "rb").read()
                        == open(fb, "rb").read()):
                    ident += 1
        assert ident == tot and tot > 0, \
            f"brokered artifacts diverged: {ident}/{tot}"

    # structural gates (the perf claim a CPU toy CAN make): the broker
    # must have collapsed the device-dispatch count, not just run
    subs = brk_c.get("broker.submissions", 0)
    disp = brk_c.get("broker.dispatches", 0)
    coalesce = subs / disp if disp else 0.0
    base_disp = (base_c.get("accel.stream_batches", 0)
                 + base_c.get("fold.group_dispatches", 0))
    base_miss = int(base_c.get("compile.cache_miss", 0))
    brk_miss = int(brk_c.get("compile.cache_miss", 0))
    assert disp > 0 and coalesce >= 2.0, \
        f"coalesce factor {coalesce:.2f} < 2 ({subs} units / {disp} fused)"
    assert disp * 2 <= base_disp, (
        f"fused dispatch count did not collapse: {disp} brokered vs "
        f"{base_disp} per-obs")
    assert brk_miss <= base_miss, (
        f"brokering introduced compile misses on the measured leg: "
        f"{brk_miss} vs {base_miss}")

    collapse = base_disp / disp
    print(f"# broker A/B: per-obs {base_s:.2f}s ({int(base_disp)} device "
          f"dispatches) vs brokered {brk_s:.2f}s ({int(disp)} fused "
          f"dispatches = {collapse:.2f}x collapse, coalesce factor "
          f"{coalesce:.2f}, {int(brk_c.get('broker.fused_rows', 0))} "
          f"rows fused; {ident}/{tot} artifacts byte-identical)",
          file=sys.stderr)
    record = {
        "metric": "broker_dispatch_collapse",
        "value": round(collapse, 3),
        "unit": (f"device-dispatch collapse from cross-observation "
                 f"batch brokering ({n_obs} same-geometry toy obs x "
                 f"{len(stages)} stages, {C}-chan x {T}-sample each, "
                 f"warm jit caches per leg, 1 device lease + 1 host worker, lane "
                 f"width 4 — per-obs accel/fold device dispatches "
                 f"divided by brokered fused dispatches; artifacts "
                 f"byte-checked across legs, validated resume re-runs "
                 f"zero stages; sift gate pinned high so the fold stage "
                 f"stays empty — fold fusion parity is owned by "
                 f"tests/test_broker.py, this A/B pins the accel "
                 f"spectrum-bank path)"),
        "vs_baseline": round(collapse, 3),
        "broker_n_obs": n_obs,
        "broker_n_stages": len(stages),
        "broker_lane_width": 4,
        "broker_submissions": int(subs),
        "broker_fused_dispatches": int(disp),
        "broker_coalesce_factor": round(coalesce, 3),
        "broker_fused_rows": int(brk_c.get("broker.fused_rows", 0)),
        "broker_lane_grants": int(brk_c.get("broker.lane_grants", 0)),
        "broker_baseline_dispatches": int(base_disp),
        "broker_baseline_compile_misses": base_miss,
        "broker_compile_misses": brk_miss,
        "broker_artifacts_identical": f"{ident}/{tot}",
        "broker_resume_reran": 0,
        "broker_per_obs_seconds": round(base_s, 3),
        "broker_brokered_seconds": round(brk_s, 3),
        "broker_wall_speedup": round(base_s / brk_s, 3),
        "broker_nsamp": T,
        "broker_nchan": C,
    }
    try:
        import jax

        platform = jax.devices()[0].platform  # psrlint: ignore[PL002] -- record annotation, runs after the fleet (no lease)
    except Exception:  # noqa: BLE001 - note is best-effort
        platform = "?"
    if platform == "cpu":
        record["broker_wall_note"] = (
            "toy CPU fleet: fused dispatches save real per-dispatch "
            "launch + HBM round-trip overhead on chips, but on one "
            "host's cores the wall-clock delta is noise — this "
            "record's claims are the structural counters (dispatch "
            "collapse, coalesce factor, zero extra compile misses) "
            "and byte parity; wall-clock scaling needs real chips")
    if args.cpu_fallback:
        record["unit"] += " [CPU FALLBACK: accelerator backend unavailable]"
    return record


def run_candplane(args):
    """Candidate-data-plane A/B (the round-25 tentpole's acceptance
    measurement): the SAME synthetic pulsar observed at 3 epochs
    (identical P, DM; fresh noise and a fresh MJD per epoch) through
    the fleet scheduler two ways —

    - **plain** (``PYPULSAR_TPU_CANDSTORE=0``): the pre-round-25
      fleet, per-obs artifacts only, no candidate store;
    - **store**: the candidate data plane on, every terminal ``done``
      observation publishing its normalized candidates into the
      fenced append-only store under ``<outdir>/_fleet/candstore/``.

    The record is gated on structure, not wall-clock: per-obs
    artifacts byte-identical across legs (the store is a pure
    passenger), the plain leg leaves NO store directory behind, the
    cross-epoch candsift finds the pulsar in all 3 epochs and folds
    the store's records into strictly fewer clusters (the measured
    duplicate reduction), a kill -9 mid-append + re-publish leaves
    exactly-once live records (raw log keeps the torn rows; the query
    surface and the ``cands`` CLI both hide them), and every query is
    identical before and after compaction."""
    acquire_backend()
    import contextlib
    import glob as _glob
    import io
    import tempfile

    from pypulsar_tpu import candstore as candstore_mod
    from pypulsar_tpu.candstore.store import CandStore, store_dir
    from pypulsar_tpu.cli import cands as cands_cli
    from pypulsar_tpu.obs import telemetry
    from pypulsar_tpu.resilience import faultinject
    from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import Observation

    n_epochs = 3
    C, T, dtp = 16, (1 << 13 if (args.quick or args.cpu_fallback)
                     else 1 << 14), 5e-4
    rng_freqs = 1500.0 - 4.0 * np.arange(C)
    period, dm = 0.1024, 40.0
    # sift gate LOW (unlike --broker): the fold + snr stages must run
    # so the terminal edge has real pfd_snr rows to publish
    cfg = SurveyConfig(
        mask=False, lodm=0.0, dmstep=10.0, numdms=16, nsub=8,
        group_size=4, threshold=8.0,
        accel_zmax=20.0, accel_numharm=2, accel_sigma=3.0, accel_batch=4,
        sift_sigma=3.0, sift_min_hits=1, fold_nbins=32, fold_npart=8)
    stages = build_dag(cfg)

    with tempfile.TemporaryDirectory() as td:
        fils = [_synth_survey_fil(os.path.join(td, f"ep{i}.fil"),
                                  31 + i, C, T, dtp, rng_freqs,
                                  "CANDAB", dm=dm, period=period,
                                  tstart=55000.0 + 10.0 * i)
                for i in range(n_epochs)]

        def fleet(dirname):
            out = os.path.join(td, dirname)
            os.makedirs(out, exist_ok=True)
            return [Observation(f"ep{i}", fils[i],
                                os.path.join(out, f"ep{i}"))
                    for i in range(n_epochs)]

        def leg(dirname, env):
            old = {k: os.environ.get(k) for k in env}
            os.environ.update(env)
            try:
                with telemetry.session() as tlm:
                    t0 = time.perf_counter()
                    result = FleetScheduler(fleet(dirname), cfg,
                                            max_host_workers=1,
                                            devices=1).run()
                    wall = time.perf_counter() - t0
                assert result.ok \
                    and len(result.ran) == n_epochs * len(stages), \
                    f"{dirname} leg failed"
                return wall, tlm.counter_totals()
            finally:
                for k, v in old.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v

        plain_s, _plain_c = leg("plain", {"PYPULSAR_TPU_CANDSTORE": "0"})
        store_s, store_c = leg("store", {"PYPULSAR_TPU_CANDSTORE": "1"})

        # parity: the store is a passenger on the terminal edge —
        # per-obs artifacts must be byte-identical to the store-less run
        ident = tot = 0
        for pattern in ("*_ACCEL_*.cand", "*_ACCEL_*.txtcand",
                        "*_cand*.pfd"):
            for fa in sorted(_glob.glob(os.path.join(td, "plain",
                                                     pattern))):
                fb = os.path.join(td, "store", os.path.basename(fa))
                tot += 1
                if (os.path.exists(fb) and open(fa, "rb").read()
                        == open(fb, "rb").read()):
                    ident += 1
        assert ident == tot and tot > 0, \
            f"store leg artifacts diverged: {ident}/{tot}"
        # the snr fleet summaries embed each pfd's path (which contains
        # the leg dirname), so parity there is structural: identical
        # rows once the path field is reduced to its basename
        for i in range(n_epochs):
            legs = []
            for dirname in ("plain", "store"):
                with open(os.path.join(td, dirname,
                                       f"ep{i}_snr.json")) as f:
                    rows = json.load(f)
                legs.append([dict(r, pfd=os.path.basename(r["pfd"]))
                             for r in rows])
            assert legs[0] == legs[1], f"ep{i} snr summaries diverged"
            tot += 1
            ident += 1
        assert not os.path.exists(store_dir(os.path.join(td, "plain"))), \
            "disabled store still left a candstore directory behind"

        # the data-plane claims: 3 epochs of one pulsar fold into one
        # cluster — the duplicate reduction per-obs files cannot give
        store = CandStore(os.path.join(td, "store"))
        recs = store.records()
        n_records = len(recs)
        assert n_records >= n_epochs, \
            f"store holds {n_records} records from {n_epochs} epochs"
        clusters = candstore_mod.cross_sift(recs)
        # the cluster seeds on its strongest member, which for a bright
        # pulsar is often a harmonic — identify it harmonically, not by
        # the fundamental alone
        pulsar = [c for c in clusters
                  if candstore_mod.harmonic_ratio(c["p_s"], period,
                                                  5e-3) is not None]
        assert pulsar and pulsar[0]["n_epochs"] == n_epochs, (
            f"pulsar cluster missing or incomplete: "
            f"{[ (c['p_s'], c['n_epochs']) for c in clusters[:5] ]}")
        reduction = n_records / len(clusters)
        assert reduction > 1.0, \
            f"no duplicate reduction: {n_records} recs / {len(clusters)}"

        # queries are identical before and after compaction (the
        # snapshot is an equivalent-by-construction rewrite)
        q_near = dict(near=(period, dm), top=50)
        pre_near = store.query(**q_near)
        pre_all = store.query()
        pre_ep = store.query(epoch_range=(55005.0, 55025.0))
        store.compact()
        assert store.query(**q_near) == pre_near \
            and store.query() == pre_all \
            and store.query(epoch_range=(55005.0, 55025.0)) == pre_ep, \
            "query changed across compaction"
        assert store.status()["segments"] == 0, \
            "compaction left segments behind"

        # kill -9 mid-append + resume: the round-25 exactly-once claim.
        # Re-publish the SAME (obs, fingerprint) after an injected kill
        # tore the first attempt — the raw log keeps the torn rows, the
        # query surface shows each candidate once.
        obs_name, outbase = "ep0", os.path.join(td, "store", "ep0")
        recs0, fp = candstore_mod.normalize_obs(obs_name, outbase,
                                                fils[0])
        assert len(recs0) >= 2, "need >=2 rows for a mid-append kill"
        kdir = os.path.join(td, "killres")
        os.makedirs(kdir, exist_ok=True)
        faultinject.reset()
        faultinject.configure("kill:candstore.append:2")
        killed = False
        try:
            CandStore(kdir).publish(obs_name, recs0, fp)
        except faultinject.InjectedKill:
            killed = True
        finally:
            faultinject.reset()
        assert killed, "armed candstore.append kill never fired"
        ks = CandStore(kdir)  # the resumed host
        ks.publish(obs_name, recs0, fp)
        kstat = ks.status()
        assert kstat["records"] == len(recs0), (
            f"kill+resume not exactly-once: {kstat['records']} live "
            f"vs {len(recs0)} published")
        assert kstat["raw_records"] > kstat["records"], \
            "torn first attempt left no raw rows — kill leg proved nothing"
        # the per-obs sift keeps only the strongest harmonic, so query
        # near the strongest published row rather than the fundamental
        strongest = max((r for r in recs0
                         if isinstance(r.get("p_s"), float)
                         and isinstance(r.get("dm"), float)),
                        key=lambda r: r.get("snr") or 0.0)
        assert ks.query(near=(strongest["p_s"], strongest["dm"])), \
            "resumed store lost the pulsar"
        # ...and the same exactly-once view through the cands CLI
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cands_cli.main([kdir, "--json"])
        cli_rows = json.loads(buf.getvalue())
        assert rc == 0 and len(cli_rows) == len(recs0), \
            f"cands CLI disagrees: {len(cli_rows)} vs {len(recs0)}"

    print(f"# candplane A/B: {n_epochs} epochs -> {n_records} store "
          f"records -> {len(clusters)} clusters ({reduction:.2f}x dup "
          f"reduction, pulsar seen {pulsar[0]['n_epochs']}/{n_epochs} "
          f"epochs); {ident}/{tot} artifacts byte-identical; "
          f"kill+resume exactly-once ({kstat['raw_records']} raw -> "
          f"{kstat['records']} live); plain {plain_s:.2f}s vs store "
          f"{store_s:.2f}s", file=sys.stderr)
    record = {
        "metric": "candplane_dup_reduction",
        "value": round(reduction, 3),
        "unit": (f"cross-epoch duplicate reduction from the round-25 "
                 f"candidate data plane ({n_epochs} epochs of one "
                 f"synthetic pulsar + per-epoch noise, {C}-chan x "
                 f"{T}-sample each, full sweep->accel->sift->fold->snr "
                 f"DAG — live store records divided by candsift "
                 f"clusters; per-obs artifacts byte-checked identical "
                 f"to a PYPULSAR_TPU_CANDSTORE=0 run, kill -9 "
                 f"mid-append + re-publish asserted exactly-once, "
                 f"queries asserted identical pre/post compaction)"),
        "vs_baseline": round(reduction, 3),
        "candplane_n_epochs": n_epochs,
        "candplane_n_records": n_records,
        "candplane_n_clusters": len(clusters),
        "candplane_pulsar_epochs": int(pulsar[0]["n_epochs"]),
        "candplane_artifacts_identical": f"{ident}/{tot}",
        "candplane_publishes": int(store_c.get("candstore.publishes", 0)),
        "candplane_appended": int(store_c.get("candstore.appended", 0)),
        "candplane_killres_raw_records": int(kstat["raw_records"]),
        "candplane_killres_live_records": int(kstat["records"]),
        "candplane_query_stable_across_compaction": True,
        "candplane_plain_seconds": round(plain_s, 3),
        "candplane_store_seconds": round(store_s, 3),
        "candplane_nsamp": T,
        "candplane_nchan": C,
    }
    try:
        import jax

        platform = jax.devices()[0].platform  # psrlint: ignore[PL002] -- record annotation, runs after the fleet (no lease)
    except Exception:  # noqa: BLE001 - note is best-effort
        platform = "?"
    if platform == "cpu":
        record["candplane_wall_note"] = (
            "toy CPU fleet: the claim is structural (dup reduction, "
            "byte parity, exactly-once after kill, compaction-stable "
            "queries), not wall-clock — store overhead on the "
            "terminal edge is file appends, noise next to the DAG")
    if args.cpu_fallback:
        record["unit"] += " [CPU FALLBACK: accelerator backend unavailable]"
    return record


def run_chaos(args):
    """Seeded chaos harness (the fleet-health acceptance measurement):
    run a toy fleet CLEAN, then run the SAME fleet with

    - seeded probabilistic chaos (``--fault-chaos SEED:RATE``) spraying
      kills / OOMs / IO errors / hangs / device faults across every
      registered fault point, and
    - one deterministic armed fault per family on top (so every family
      provably fires regardless of what the seed happens to draw),

    resuming after every kill until the fleet completes, with the
    watchdog (heartbeat-stall detection) turning injected hangs into
    ordinary retryable failures. Then assert:

    - a final no-chaos ``--resume`` validates everything and runs ZERO
      stages (the manifests survived every torn window), and
    - every artifact is byte-identical to the clean run's — recovery
      reconstructed the exact bytes, not approximately the science.
    """
    acquire_backend()
    import glob as _glob
    import random
    import tempfile

    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.ops import numpy_ref
    from pypulsar_tpu.resilience import faultinject
    from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import Observation

    seed = args.chaos_seed
    rate = args.chaos_rate if args.chaos_rate is not None \
        else (0.01 if (args.quick or args.cpu_fallback) else 0.015)
    n_obs = 3
    stall_s = 8.0
    max_rounds = 40
    C, T, dtp = 32, (1 << 13 if (args.quick or args.cpu_fallback)
                     else 1 << 14), 5e-4
    rng_freqs = 1500.0 - 4.0 * np.arange(C)
    cfg = SurveyConfig(
        mask=True, mask_time=2.0, lodm=0.0, dmstep=10.0, numdms=8,
        nsub=8, group_size=4, threshold=8.0,
        accel_zmax=20.0, accel_numharm=2, accel_sigma=3.0, accel_batch=4,
        sift_sigma=3.0, sift_min_hits=1, fold_nbins=32, fold_npart=8)
    stages = build_dag(cfg)

    def make_obs_fil(fn, seed_i, dm=40.0, period=0.1024, amp=10.0):
        return _synth_survey_fil(fn, seed_i, C, T, dtp, rng_freqs,
                                 f"CHAOS{seed_i}", dm=dm, period=period,
                                 amp=amp)

    # bound the injected hangs and a chaos-wedged prefetch consumer so
    # the harness's wall time stays bounded even when an interrupt
    # cannot land (a hang must outlive stall_s for the watchdog path to
    # be the one that ends it)
    env_save = {k: os.environ.get(k) for k in
                ("PYPULSAR_TPU_HANG_S", "PYPULSAR_TPU_PREFETCH_TIMEOUT")}
    os.environ["PYPULSAR_TPU_HANG_S"] = str(stall_s + 4.0)
    os.environ["PYPULSAR_TPU_PREFETCH_TIMEOUT"] = "15"
    try:
        with tempfile.TemporaryDirectory() as td:
            fils = [make_obs_fil(os.path.join(td, f"obs{i}.fil"),
                                 seed_i=23 + i,
                                 period=0.1024 * (1.0 + 0.07 * i))
                    for i in range(n_obs)]

            def fleet(dirname):
                out = os.path.join(td, dirname)
                os.makedirs(out, exist_ok=True)
                return [Observation(f"obs{i}", fils[i],
                                    os.path.join(out, f"obs{i}"))
                        for i in range(n_obs)]

            # clean leg (also warms every stage's jit programs, so the
            # chaos leg's stall detector never sees a cold compile)
            faultinject.reset()
            t0 = time.perf_counter()
            clean = FleetScheduler(fleet("clean"), cfg,
                                   max_host_workers=2, devices=1).run()
            clean_s = time.perf_counter() - t0
            assert clean.ok and len(clean.ran) == n_obs * len(stages)

            # chaos leg: seeded spray + one guaranteed fault per family
            # (kill in the stage_done torn window, an escaped OOM, a
            # mid-.dat-stream IO error, an in-stage hang for the
            # watchdog, a chip-indicting device fault)
            faultinject.reset()
            faultinject.configure_chaos(f"{seed}:{rate}")
            faultinject.configure(
                "kill:survey.stage_done:1,"
                "oom:accel.batch_dispatch:1,"
                "io:dats.append:2,"
                "hang:sweep.chunk_dispatch:3,"
                "device:fold.batch_dispatch:1")
            rounds = kills = timeouts = retried = quarantined = 0
            t0 = time.perf_counter()
            result = None
            while rounds < max_rounds:
                rounds += 1
                sched = FleetScheduler(
                    fleet("chaos"), cfg, max_host_workers=2, devices=1,
                    retries=2, resume=(rounds > 1), stall_s=stall_s,
                    jitter_rng=random.Random(seed + rounds))
                try:
                    result = sched.run()
                except faultinject.InjectedKill:
                    kills += 1
                    timeouts += sched.result.timeouts
                    retried += sched.result.retried
                    continue  # "the process died": restart + --resume
                timeouts += result.timeouts
                retried += result.retried
                quarantined += len(result.quarantined)
                if result.ok:
                    break
                # quarantined observations: the operator resumes them
            chaos_s = time.perf_counter() - t0
            fired = faultinject.fired_counts()
            assert result is not None and result.ok, (
                f"chaos fleet did not complete in {max_rounds} rounds "
                f"(fired: {fired})")
            for kind in ("kill", "oom", "io", "hang", "device"):
                assert fired.get(kind, 0) >= 1, (
                    f"fault family {kind!r} never fired: {fired}")
            assert timeouts >= 1, (
                "no watchdog interrupt fired — the injected hang was "
                "not recovered by the deadline/stall path")

            # chaos off: a final validated resume must run NOTHING
            faultinject.reset()
            final = FleetScheduler(fleet("chaos"), cfg,
                                   max_host_workers=2, devices=1,
                                   resume=True).run()
            assert final.ok and len(final.ran) == 0, (
                f"post-chaos manifests did not validate clean: "
                f"{len(final.ran)} stages re-ran")

            # byte-parity: the chaos run's artifacts ARE the clean
            # run's artifacts
            ident = tot = 0
            diverged = []
            for pattern in ("*_ACCEL_*.cand", "*_ACCEL_*.txtcand",
                            "*_cand*.pfd", "*.dat"):
                for fa in sorted(_glob.glob(os.path.join(td, "clean",
                                                         pattern))):
                    fb = os.path.join(td, "chaos", os.path.basename(fa))
                    tot += 1
                    if (os.path.exists(fb) and open(fa, "rb").read()
                            == open(fb, "rb").read()):
                        ident += 1
                    else:
                        diverged.append(os.path.basename(fa))
            assert ident == tot and tot > 0, (
                f"chaos artifacts diverged from clean: {ident}/{tot} "
                f"({diverged[:8]})")
    finally:
        faultinject.reset()
        for k, v in env_save.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    n_faults = sum(fired.values())
    print(f"# chaos: seed {seed} rate {rate}: {n_faults} faults "
          f"({', '.join(f'{k}={v}' for k, v in sorted(fired.items()))}) "
          f"over {rounds} round(s), {kills} kill-resumes, {timeouts} "
          f"watchdog interrupts, {retried} stage retries, {quarantined} "
          f"quarantine verdicts — fleet completed, {ident}/{tot} "
          f"artifacts byte-identical to clean ({clean_s:.1f}s clean, "
          f"{chaos_s:.1f}s under chaos)", file=sys.stderr)
    return {
        "metric": "chaos_fleet_recovery",
        "value": round(ident / max(tot, 1), 3),
        "unit": (f"fraction of artifacts byte-identical to a clean run "
                 f"after an {n_obs}-obs x {len(stages)}-stage fleet "
                 f"survived {n_faults} injected faults (seeded chaos "
                 f"{seed}:{rate} + one armed fault per family) via "
                 f"watchdog-driven retries, kill-restarts with --resume "
                 f"and quarantine-resume — asserted 1.0, plus a final "
                 f"no-chaos resume validating 0 stages re-run"),
        "vs_baseline": 1.0,
        "chaos_seed": seed,
        "chaos_rate": rate,
        "chaos_n_obs": n_obs,
        "chaos_n_stages": len(stages),
        "chaos_faults_fired": fired,
        "chaos_rounds": rounds,
        "chaos_kill_resumes": kills,
        "chaos_watchdog_interrupts": timeouts,
        "chaos_stage_retries": retried,
        "chaos_quarantine_verdicts": quarantined,
        "chaos_stall_timeout_s": stall_s,
        "chaos_artifacts_identical": f"{ident}/{tot}",
        "chaos_clean_seconds": round(clean_s, 2),
        "chaos_seconds": round(chaos_s, 2),
        "chaos_nsamp": T,
        "chaos_nchan": C,
    }


def run_daemon_soak(args):
    """Streaming-daemon soak (the round-23 acceptance measurement):
    the multi-tenant admission plane under sustained overload, measured
    three ways against ONE batch reference —

    - **reference**: the same 4-observation corpus through a plain
      batch fleet (the artifacts every later leg must reproduce
      byte-for-byte);
    - **overload**: an in-process daemon fed a gold tenant (priority 5,
      unmetered) plus a bulk tenant (burst-limited) flooding past a
      2-deep accept queue, with seeded chaos sprayed over the admission
      storm and one armed fault at each daemon ingest point
      (``daemon.arrival`` / ``daemon.admit`` / ``daemon.shed``), and a
      corrupt bulk file exercising the ingest-quarantine edge. Books
      must balance in-process, shedding must hit ONLY unaccepted bulk
      work, and the whole shed trail must reconstruct from the trace
      events alone;
    - **kill -9**: a real ``survey --daemon --watch`` subprocess
      SIGKILL'd mid-pipeline after accepting two observations, then
      restarted — the admission journal must resume the accepted work
      with ZERO re-runs of manifest-validated stages — and finally
      SIGTERM'd for a clean (rc 0) drain.

    A final no-chaos in-process resume over every accepted observation
    must run ZERO stages, and every completed artifact must be
    byte-identical to the batch reference's."""
    acquire_backend()
    import glob as _glob
    import signal
    import tempfile
    import threading

    from pypulsar_tpu.obs import telemetry
    from pypulsar_tpu.resilience import faultinject
    from pypulsar_tpu.survey.daemon import (SurveyDaemon, TenantSpec,
                                            journal_path,
                                            read_tenant_status)
    from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import MANIFEST_SUFFIX, Observation

    seed = args.chaos_seed
    rate = args.chaos_rate if args.chaos_rate is not None else 0.05
    n_gold, n_bulk, queue_bound = 2, 6, 2
    C, T, dtp = 32, (1 << 13 if (args.quick or args.cpu_fallback)
                     else 1 << 14), 5e-4
    rng_freqs = 1500.0 - 4.0 * np.arange(C)
    cfg = SurveyConfig(
        mask=True, mask_time=2.0, lodm=0.0, dmstep=10.0, numdms=8,
        nsub=8, group_size=4, threshold=8.0,
        accel_zmax=20.0, accel_numharm=2, accel_sigma=3.0, accel_batch=4,
        sift_sigma=3.0, sift_min_hits=1, fold_nbins=32, fold_npart=8)
    stages = build_dag(cfg)

    def wait_for(cond, what, timeout=120.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return
            time.sleep(0.05)
        raise AssertionError(f"daemon soak timed out waiting for {what}")

    def accept_records(outdir):
        """(name, tenant, infile, outbase) per journaled accept, plus
        the terminal-state map — the restart/resume assertions' input."""
        accepts, terminal = {}, {}
        with open(journal_path(outdir)) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail
                if rec.get("type") == "accept":
                    accepts[rec["obs"]] = rec
                elif rec.get("type") == "terminal":
                    terminal[rec["obs"]] = rec["state"]
        return accepts, terminal

    def done_units(outdir):
        """{manifest basename: [unit, ...]} across the outdir — one
        list entry PER RECORD, so a re-run shows up as a duplicate."""
        units = {}
        for mp in sorted(_glob.glob(os.path.join(
                outdir, "*" + MANIFEST_SUFFIX))):
            rows = []
            with open(mp) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("type") == "done":
                        rows.append(rec.get("unit"))
            units[os.path.basename(mp)] = rows
        return units

    def byte_parity(ref_dir, out_dir, stems):
        ident = tot = 0
        diverged = []
        for pattern in ("*_ACCEL_*.cand", "*_ACCEL_*.txtcand",
                        "*_cand*.pfd", "*.dat"):
            for fa in sorted(_glob.glob(os.path.join(ref_dir, pattern))):
                base = os.path.basename(fa)
                if not any(base.startswith(s) for s in stems):
                    continue
                fb = os.path.join(out_dir, base)
                tot += 1
                if (os.path.exists(fb) and open(fa, "rb").read()
                        == open(fb, "rb").read()):
                    ident += 1
                else:
                    diverged.append(base)
        return ident, tot, diverged

    t_start = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        # corpus: 2 gold (in-process leg) + 2 kobs (kill -9 leg); the
        # kobs pair lives in the subprocess's watch dir from the start
        watch2 = os.path.join(td, "watch2")
        os.makedirs(watch2)
        golds = [_synth_survey_fil(os.path.join(td, f"gold{i}.fil"),
                                   61 + i, C, T, dtp, rng_freqs,
                                   f"SOAKG{i}",
                                   period=0.1024 * (1.0 + 0.07 * i))
                 for i in range(n_gold)]
        kobs = [_synth_survey_fil(os.path.join(watch2, f"kobs{i}.fil"),
                                  71 + i, C, T, dtp, rng_freqs,
                                  f"SOAKK{i}",
                                  period=0.1024 * (1.0 + 0.09 * i))
                for i in range(2)]

        # ---- leg A: the batch reference (also warms the jit caches) --
        faultinject.reset()
        ref = os.path.join(td, "ref")
        os.makedirs(ref)
        obs_ref = ([Observation(f"gold{i}", golds[i],
                                os.path.join(ref, f"gold{i}"))
                    for i in range(n_gold)]
                   + [Observation(f"kobs{i}", kobs[i],
                                  os.path.join(ref, f"kobs{i}"))
                      for i in range(2)])
        batch = FleetScheduler(obs_ref, cfg, max_host_workers=2,
                               devices=1).run()
        assert batch.ok and len(batch.ran) == len(obs_ref) * len(stages)

        # ---- leg B: in-process overload soak under chaos spray -------
        out1 = os.path.join(td, "daemon")
        bulkdir = os.path.join(td, "bulk_incoming")
        os.makedirs(bulkdir)
        trace = os.path.join(td, "soak_trace.jsonl")
        faultinject.reset()
        # probabilistic spray over the admission storm (non-fatal kinds
        # — the kill family gets a REAL SIGKILL in leg C) plus one
        # armed fault per daemon ingest point so each provably fires
        faultinject.configure_chaos(f"{seed}:{rate}:oom+io")
        faultinject.configure("io:daemon.arrival:1,"
                              "io:daemon.admit:1,"
                              "io:daemon.shed:1")
        daemon = SurveyDaemon(
            out1, cfg, stages=stages,
            tenants=[TenantSpec("gold", priority=5, rate=0.0),
                     TenantSpec("bulk", priority=0, rate=1e-6,
                                burst=2.0)],
            watch=[(bulkdir, "bulk")],
            queue_bound=queue_bound, quiesce_s=0.2, poll_s=0.05,
            idle_exit_s=0.0, min_free_mb=0,
            max_host_workers=2, devices=1, retries=3)
        with telemetry.session(trace) as tlm:
            thread = threading.Thread(target=daemon.run,
                                      name="soak-daemon", daemon=True)
            thread.start()
            # 1. one corrupt bulk file FIRST: it absorbs the armed
            #    arrival + admit faults (watch rescan / re-pend retry),
            #    then ingest validation quarantines it — bulk's burst-2
            #    bucket is now empty, so the later flood can only shed
            corrupt = os.path.join(td, "corrupt.fil")
            with open(corrupt, "wb") as f:
                f.write(b"this is not a filterbank" * 64)
            os.replace(corrupt, os.path.join(bulkdir, "corrupt.fil"))
            wait_for(lambda: daemon.stats()["quarantined"] >= 1,
                     "corrupt bulk file to ingest-quarantine")
            # 2. gold submissions through the socket-lane API, retrying
            #    the sprayed transient ingest faults like a client would
            for fn in golds:
                for _ in range(200):
                    v, why = daemon.submit("gold", fn)
                    if v in ("accepted", "pending") or (
                            v == "error" and "already submitted" in why):
                        break
                    assert v == "error" and "transient" in why, (v, why)
                    time.sleep(0.05)
                else:
                    raise AssertionError(f"gold {fn} never admitted")
            wait_for(lambda: daemon.tenant_snapshot()["tenants"]
                     ["gold"]["accepted"] >= n_gold, "gold acceptance")
            # 3. the bulk flood: over-capacity arrivals with an empty
            #    token bucket — past the 2-deep bound they shed
            for i in range(n_bulk):
                fn = os.path.join(td, f"bulk{i}.fil")
                with open(fn, "wb") as f:
                    f.write(b"\x00" * 4096)  # never admitted: content
                    # is irrelevant, the bucket is already empty
                os.replace(fn, os.path.join(bulkdir, f"bulk{i}.fil"))
            wait_for(lambda: daemon.stats()["submitted"]
                     >= 1 + n_gold + n_bulk, "the bulk flood to arrive")
            # 4. storm over: chaos off, SIGTERM semantics — accepted
            #    work finishes, the pending remainder sheds loudly
            faultinject.configure_chaos(None)
            daemon.request_drain()
            thread.join(timeout=600)
            assert not thread.is_alive(), "daemon failed to drain"
            counters = {k: int(v) for k, v in
                        tlm.counter_totals().items()
                        if k.startswith("daemon.")}
        fired = faultinject.fired_counts()
        faultinject.reset()
        # the fleet verdict: exactly ONE quarantined observation — the
        # corrupt bulk file, stopped by ingest validation (result.ok is
        # False by design here: a quarantine IS a loud verdict)
        assert daemon.result is not None, "fleet never reported"
        q_names = sorted(daemon.result.quarantined)
        assert q_names == ["corrupt"], (
            f"unexpected quarantine set: {daemon.result.quarantined}")

        # books balance, by tenant and in aggregate
        agg = daemon.stats()
        snap = daemon.tenant_snapshot()["tenants"]
        assert agg["pending"] == 0 and agg["accepted_open"] == 0
        assert agg["submitted"] == agg["accepted"] + agg["shed"], agg
        assert agg["accepted"] == (agg["completed"]
                                   + agg["quarantined"]), agg
        assert agg["submitted"] == 1 + n_gold + n_bulk, agg
        gold_b, bulk_b = snap["gold"], snap["bulk"]
        assert (gold_b["completed"] == n_gold and gold_b["shed"] == 0
                and gold_b["quarantined"] == 0), (
            f"healthy tenant charged for bulk's overload: {gold_b}")
        assert (bulk_b["quarantined"] == 1 and bulk_b["shed"] == n_bulk
                and bulk_b["completed"] == 0), bulk_b
        # every armed daemon ingest point provably fired and was
        # absorbed (the arrival was re-seen, the admit re-pended, the
        # shed still happened)
        for point in ("arrival", "admit", "shed"):
            assert counters.get(f"daemon.{point}_faults", 0) >= 1, (
                f"daemon.{point} fault never fired: {counters}")
        assert fired.get("io", 0) >= 3, fired

        # the shed trail reconstructs from the trace alone: every
        # victim, its tenant, the reason and the queue depth at the
        # decision — and no shed ever names accepted (gold) work
        shed_evs = []
        with open(trace) as f:
            for line in f:
                rec = json.loads(line)
                if (rec.get("type") == "event"
                        and rec.get("name") == "daemon.shed"):
                    shed_evs.append(rec["attrs"])
        assert len(shed_evs) == n_bulk, shed_evs
        assert all(e["tenant"] == "bulk" and e["queue_depth"] >= 1
                   and e["reason"] for e in shed_evs), shed_evs
        n_shed_bound = sum(1 for e in shed_evs
                           if "queue full" in e["reason"])
        n_shed_drain = sum(1 for e in shed_evs
                           if "draining" in e["reason"])
        assert n_shed_bound >= 1 and n_shed_drain >= 1, shed_evs
        assert n_shed_bound + n_shed_drain == n_bulk, shed_evs

        # ---- leg C: kill -9 a REAL --daemon subprocess, restart ------
        out2 = os.path.join(td, "killdaemon")
        argv = [sys.executable, "-m", "pypulsar_tpu.cli", "survey",
                "--daemon", "-o", out2, "--watch", watch2 + ":gold",
                "--tenant", "gold:5:0:8", "--queue-bound", "8",
                "--quiesce", "0.2", "--daemon-poll", "0.05",
                "--min-free-mb", "0", "--max-host-workers", "2",
                "--retries", "2",
                "--mask-time", "2.0", "--lodm", "0.0",
                "--dmstep", "10.0", "--numdms", "8", "--nsub", "8",
                "--group-size", "4", "--threshold", "8.0",
                "--accel-zmax", "20.0", "--accel-numharm", "2",
                "--accel-sigma", "3.0", "--accel-batch", "4",
                "--sift-sigma", "3.0", "--sift-min-hits", "1",
                "--fold-nbins", "32", "--fold-npart", "8"]
        env = dict(os.environ)
        for var in ("PYPULSAR_TPU_FAULTS", "PYPULSAR_TPU_CHAOS"):
            env.pop(var, None)

        def spawn(log_name):
            log = open(os.path.join(td, log_name), "w")
            return subprocess.Popen(argv, env=env, stdout=log,
                                    stderr=subprocess.STDOUT), log

        def poll_subproc(proc, cond, what, timeout=600.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise AssertionError(
                        f"daemon subprocess exited rc={proc.returncode} "
                        f"while waiting for {what}")
                if cond():
                    return
                time.sleep(0.1)
            raise AssertionError(f"subprocess soak timed out on {what}")

        def tstat(key, tenant="gold"):
            st = read_tenant_status(out2)
            if not st:
                return 0
            return st.get("tenants", {}).get(tenant, {}).get(key, 0)

        proc1, log1 = spawn("kill_leg_1.log")
        try:
            # accepted + at least one manifest-validated stage, but the
            # pipeline still in flight: the interesting kill window
            poll_subproc(
                proc1,
                lambda: (tstat("accepted") >= 2
                         and sum(len(v) for v in
                                 done_units(out2).values()) >= 1),
                "2 accepts + 1 validated stage before the SIGKILL")
        finally:
            proc1.kill()  # SIGKILL: no drain, no journal close
            proc1.wait(timeout=60)
            log1.close()
        pre_kill = done_units(out2)
        n_pre = sum(len(v) for v in pre_kill.values())

        proc2, log2 = spawn("kill_leg_2.log")
        try:
            poll_subproc(
                proc2,
                lambda: (tstat("completed") >= 2
                         and (read_tenant_status(out2) or {})
                         .get("accepted_open", 1) == 0),
                "the restarted daemon to finish the adopted work")
            proc2.send_signal(signal.SIGTERM)  # the clean-drain contract
            rc2 = proc2.wait(timeout=120)
        finally:
            if proc2.poll() is None:
                proc2.kill()
                proc2.wait(timeout=60)
            log2.close()
        assert rc2 == 0, f"SIGTERM drain exited rc={rc2}"
        # zero re-runs of validated stages: every unit recorded done
        # before the SIGKILL appears EXACTLY once in the final manifest
        # (a re-run would append a duplicate done record)
        post = done_units(out2)
        assert n_pre >= 1
        for man, units in pre_kill.items():
            for unit in units:
                assert post.get(man, []).count(unit) == 1, (
                    f"{man}:{unit} re-ran after the restart")

        # ---- the cross-leg gates -------------------------------------
        # a final no-chaos resume over EVERY accepted observation (both
        # legs) validates the manifests and runs ZERO stages
        reran = 0
        for outdir in (out1, out2):
            accepts, terminal = accept_records(outdir)
            fleet = [Observation(r["obs"], r["infile"], r["outbase"])
                     for r in accepts.values()
                     if terminal.get(r["obs"]) == "done"]
            assert fleet, f"no completed accepts journaled in {outdir}"
            final = FleetScheduler(fleet, cfg, max_host_workers=2,
                                   devices=1, resume=True).run()
            assert final.ok and len(final.ran) == 0, (
                f"{outdir}: {len(final.ran)} stages re-ran on the "
                f"final resume")
            reran += len(final.ran)

        # completed artifacts byte-identical to the batch reference
        ident = tot = 0
        diverged = []
        for out_dir, stems in ((out1, ("gold",)), (out2, ("kobs",))):
            i, t, d = byte_parity(ref, out_dir, stems)
            ident, tot, diverged = ident + i, tot + t, diverged + d
        assert ident == tot and tot > 0, (
            f"soak artifacts diverged from the batch reference: "
            f"{ident}/{tot} ({diverged[:8]})")
    soak_s = time.perf_counter() - t_start

    n_faults = sum(fired.values())
    print(f"# daemon-soak: seed {seed} rate {rate}: books balanced over "
          f"{agg['submitted']} arrivals ({agg['accepted']} accepted, "
          f"{agg['shed']} shed [{n_shed_bound} bound / {n_shed_drain} "
          f"drain], {agg['quarantined']} quarantined), {n_faults} "
          f"injected faults absorbed at the ingest points, kill -9 "
          f"resumed {n_pre} pre-kill unit(s) with zero re-runs, SIGTERM "
          f"drained rc 0, {ident}/{tot} artifacts byte-identical to "
          f"batch ({soak_s:.1f}s)", file=sys.stderr)
    return {
        "metric": "daemon_soak_overload_degradation",
        "value": round(ident / max(tot, 1), 3),
        "unit": (f"fraction of streaming-daemon artifacts "
                 f"byte-identical to the batch reference after a "
                 f"multi-tenant overload soak (bulk flood past a "
                 f"{queue_bound}-deep accept queue, seeded chaos "
                 f"{seed}:{rate} over the admission storm + one armed "
                 f"fault per daemon ingest point, one ingest-"
                 f"quarantined corrupt file, a SIGKILL'd+restarted "
                 f"--daemon subprocess and a SIGTERM drain) — asserted "
                 f"1.0 with balanced books, bulk-only shedding, a "
                 f"trace-reconstructible shed trail and a final resume "
                 f"running zero stages"),
        "vs_baseline": 1.0,
        "soak_chaos_seed": seed,
        "soak_chaos_rate": rate,
        "soak_books": agg,
        "soak_tenant_books": {n: {k: b[k] for k in
                                  ("submitted", "accepted", "shed",
                                   "quarantined", "completed")}
                              for n, b in snap.items()},
        "soak_shed_events": len(shed_evs),
        "soak_shed_at_bound": n_shed_bound,
        "soak_shed_at_drain": n_shed_drain,
        "soak_faults_fired": fired,
        "soak_ingest_fault_counters": {
            k: v for k, v in counters.items() if k.endswith("_faults")},
        "soak_kill9_prekill_units": n_pre,
        "soak_kill9_reruns": 0,
        "soak_sigterm_rc": rc2,
        "soak_final_resume_reran": reran,
        "soak_artifacts_identical": f"{ident}/{tot}",
        "soak_seconds": round(soak_s, 2),
        "soak_nsamp": T,
        "soak_nchan": C,
    }


def run_obs_overhead(args):
    """Observability-plane overhead A/B (round 21's zero-overhead
    contract, measured): the SAME toy sweep->accel chain over a small
    fleet, run three ways —

    - **off**: flight recorder disabled (``PYPULSAR_TPU_OBS_FLIGHTREC=0``
      semantics via ``flightrec.configure(0)``), no telemetry session —
      the true zero-instrumentation floor;
    - **flightrec**: the always-on default — the in-memory ring records
      every span/counter, nothing hits disk;
    - **full**: flight recorder + a live ``--telemetry`` JSONL session +
      per-observation obs traces (``telemetry_dir``) — everything the
      observability plane can write.

    Each leg is min-of-``reps`` over a freshly-dirs'd fleet after a full
    warmup chain, candidates are byte-checked identical across legs
    (observability must never touch science), and the full-vs-off
    overhead is asserted <= 5% in-process — the bound ROADMAP's
    "passenger, never the payload" rule means."""
    acquire_backend()
    import glob as _glob
    import tempfile

    from pypulsar_tpu.obs import flightrec, telemetry
    from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import Observation

    n_obs = 2
    # min-of-N is the noise floor: the toy chain is ~2 s, so scheduler
    # jitter is a few percent per rep — enough reps that the minima
    # compare floors, not jitter
    reps = 3 if (args.quick or args.cpu_fallback) else 5
    C, T, dtp = 32, 1 << 14, 5e-4
    rng_freqs = 1500.0 - 4.0 * np.arange(C)
    cfg = SurveyConfig(
        lodm=0.0, dmstep=10.0, numdms=8, nsub=8, group_size=4,
        threshold=8.0, accel_zmax=20.0, accel_numharm=2,
        accel_sigma=3.0, accel_batch=4)
    stages = build_dag(cfg)
    overhead_bound = 0.05

    with tempfile.TemporaryDirectory() as td:
        fils = [_synth_survey_fil(os.path.join(td, f"obs{i}.fil"),
                                  11 + i, C, T, dtp, rng_freqs,
                                  f"BENCH{i}", dm=40.0,
                                  period=0.1024 * (1.0 + 0.07 * i),
                                  amp=10.0)
                for i in range(n_obs)]

        def fleet(dirname):
            out = os.path.join(td, dirname)
            os.makedirs(out, exist_ok=True)
            return [Observation(f"obs{i}", fils[i],
                                os.path.join(out, f"obs{i}"))
                    for i in range(n_obs)]

        # warmup: one full chain compiles every stage's jit programs
        for stage in stages:
            stage.execute(fleet("warm")[0], cfg)

        def leg(name, rep, telemetry_dir=None):
            obs = fleet(f"{name}{rep}")
            t0 = time.perf_counter()
            result = FleetScheduler(obs, cfg, max_host_workers=2,
                                    devices=1,
                                    telemetry_dir=telemetry_dir).run()
            dt = time.perf_counter() - t0
            assert result.ok and len(result.ran) == n_obs * len(stages)
            return dt

        legs = {}
        try:
            # interleave reps so drift (thermal, page cache) hits all
            # three legs evenly instead of the last one measured
            for rep in range(reps):
                flightrec.configure(0)
                legs.setdefault("off", []).append(leg("off", rep))
                flightrec.configure(None)
                legs.setdefault("flightrec", []).append(leg("ring", rep))
                tlm_dir = os.path.join(td, f"tlm{rep}")
                with telemetry.session(os.path.join(td, f"full{rep}.jsonl"),
                                       tool="bench-obs"):
                    legs.setdefault("full", []).append(
                        leg("full", rep, telemetry_dir=tlm_dir))
        finally:
            flightrec.configure(None)

        # byte parity: candidates identical across all three legs
        def _parity(dir_a, dir_b):
            ident = tot = 0
            for pattern in ("*_ACCEL_*.cand", "*_ACCEL_*.txtcand"):
                for fa in sorted(_glob.glob(os.path.join(td, dir_a,
                                                         pattern))):
                    fb = os.path.join(td, dir_b, os.path.basename(fa))
                    tot += 1
                    if (os.path.exists(fb) and open(fa, "rb").read()
                            == open(fb, "rb").read()):
                        ident += 1
            return ident, tot

        ident_r, tot_r = _parity("off0", "ring0")
        ident_f, tot_f = _parity("off0", "full0")
        assert ident_r == tot_r and tot_r > 0, \
            f"flightrec leg diverged: {ident_r}/{tot_r}"
        assert ident_f == tot_f and tot_f > 0, \
            f"full-telemetry leg diverged: {ident_f}/{tot_f}"

    off_s = min(legs["off"])
    ring_s = min(legs["flightrec"])
    full_s = min(legs["full"])
    ring_frac = ring_s / off_s - 1.0
    full_frac = full_s / off_s - 1.0
    print(f"# obs overhead A/B: off {off_s:.3f}s, flightrec "
          f"{ring_s:.3f}s ({100 * ring_frac:+.1f}%), full telemetry "
          f"{full_s:.3f}s ({100 * full_frac:+.1f}%) — min of {reps} "
          f"reps, {n_obs} obs x {len(stages)} stages, "
          f"{ident_f}/{tot_f} candidates byte-identical",
          file=sys.stderr)
    assert full_frac <= overhead_bound, (
        f"observability plane costs {100 * full_frac:.1f}% "
        f"(> {100 * overhead_bound:.0f}%): the passenger is steering")
    return {
        "metric": "obs_overhead_frac",
        "value": round(full_frac, 4),
        "unit": (f"fractional wall-clock overhead of the FULL "
                 f"observability plane (flight recorder + telemetry "
                 f"session + obs traces) vs instrumentation-off on the "
                 f"toy sweep->accel fleet ({n_obs} obs x {len(stages)} "
                 f"stages, {C}-chan x {T}-sample, min of {reps} reps, "
                 f"warm jit; bound asserted <= {overhead_bound})"),
        "vs_baseline": 0.0,
        "obs_off_seconds": round(off_s, 4),
        "obs_flightrec_seconds": round(ring_s, 4),
        "obs_full_seconds": round(full_s, 4),
        "obs_flightrec_overhead_frac": round(ring_frac, 4),
        "obs_full_overhead_frac": round(full_frac, 4),
        "obs_overhead_bound": overhead_bound,
        "obs_reps": reps,
        "obs_n_obs": n_obs,
        "obs_n_stages": len(stages),
        "obs_candidates_identical": f"{ident_f}/{tot_f}",
        "obs_nsamp": T,
        "obs_nchan": C,
    }


def run_race(args):
    """Seeded interleaving stress harness (psrrace's dynamic acceptance
    measurement, round 19): run a toy fleet CLEAN (single host, no
    perturbation), then re-run the SAME fleet once per seed with every
    concurrency surface the runtime has, deliberately perturbed:

    - TWO in-process hosts coordinating through a shared FleetPlane
      (claim/adopt loops, heartbeat renewers, fenced manifests), plus a
      ghost host that claims an observation and leaves — so adoption is
      exercised every leg, not just when a race happens to produce one;
    - an armed in-stage ``hang`` outlasting ``--stall`` so the watchdog
      async-interrupt path fires (under the round-19 deferral rule: an
      interrupt is withheld while the target holds a tracked lock);
    - prefetch producers inside the real sweep stages;
    - ``sys.setswitchinterval`` cranked down per seed AND seeded
      faultinject-driven pauses at every tracked lock boundary
      (``resilience.locks.configure_race``), widening race windows by
      orders of magnitude;
    - ``PYPULSAR_TPU_LOCKDEP=strict``: ANY acquisition-order cycle
      raises instead of warning.

    Asserted per seed: the fleet completes with zero quarantines, at
    least one adoption and at least one watchdog interrupt happened,
    ZERO lockdep order violations were recorded, and every artifact is
    byte-identical to the clean run's. The committed record is
    RACE_r01.json."""
    acquire_backend()
    import glob as _glob
    import tempfile
    import threading

    from pypulsar_tpu.resilience import faultinject, locks
    from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
    from pypulsar_tpu.survey.fleet import FleetPlane
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import Observation

    n_obs, n_hosts = 3, 2
    stall_s = 6.0
    seeds = list(range(1, max(1, args.race_seeds) + 1))
    C, T, dtp = 32, 1 << 13, 5e-4  # structure, not walls: always small
    rng_freqs = 1500.0 - 4.0 * np.arange(C)
    cfg = SurveyConfig(
        mask=True, mask_time=2.0, lodm=0.0, dmstep=10.0, numdms=8,
        nsub=8, group_size=4, threshold=8.0,
        accel_zmax=20.0, accel_numharm=2, accel_sigma=3.0, accel_batch=4,
        sift_sigma=3.0, sift_min_hits=1, fold_nbins=32, fold_npart=8)
    stages = build_dag(cfg)

    env_save = {k: os.environ.get(k) for k in
                ("PYPULSAR_TPU_HANG_S", "PYPULSAR_TPU_PREFETCH_TIMEOUT",
                 "PYPULSAR_TPU_LOCKDEP")}
    os.environ["PYPULSAR_TPU_HANG_S"] = str(stall_s + 4.0)
    os.environ["PYPULSAR_TPU_PREFETCH_TIMEOUT"] = "20"
    os.environ["PYPULSAR_TPU_LOCKDEP"] = "strict"
    old_si = sys.getswitchinterval()
    per_seed = []
    try:
        with tempfile.TemporaryDirectory() as td:
            fils = [_synth_survey_fil(
                os.path.join(td, f"obs{i}.fil"), 31 + i, C, T, dtp,
                rng_freqs, f"RACE{i}", dm=40.0,
                period=0.1024 * (1.0 + 0.07 * i), amp=10.0)
                for i in range(n_obs)]

            def fleet(dirname):
                out = os.path.join(td, dirname)
                os.makedirs(out, exist_ok=True)
                return [Observation(f"obs{i}", fils[i],
                                    os.path.join(out, f"obs{i}"))
                        for i in range(n_obs)]

            def parity(dirname):
                ident = tot = 0
                diverged = []
                for pattern in ("*_ACCEL_*.cand", "*_ACCEL_*.txtcand",
                                "*_cand*.pfd", "*.dat"):
                    for fa in sorted(_glob.glob(
                            os.path.join(td, "clean", pattern))):
                        fb = os.path.join(td, dirname,
                                          os.path.basename(fa))
                        tot += 1
                        if (os.path.exists(fb)
                                and open(fa, "rb").read()
                                == open(fb, "rb").read()):
                            ident += 1
                        else:
                            diverged.append(os.path.basename(fa))
                return ident, tot, diverged

            # clean reference leg (also warms every stage's jit
            # programs so the race legs' stall bound never fires on a
            # cold compile)
            faultinject.reset()
            locks.reset()
            clean = FleetScheduler(fleet("clean"), cfg,
                                   max_host_workers=2, devices=1).run()
            assert clean.ok and len(clean.ran) == n_obs * len(stages)

            for seed in seeds:
                tag = f"race{seed}"
                obs = fleet(tag)
                out = os.path.join(td, tag)
                faultinject.reset()
                locks.reset()
                locks.configure_race(seed, pause_us=150.0)
                sys.setswitchinterval(
                    (2e-6, 5e-5, 5e-6, 2e-4)[seed % 4])
                # one armed in-stage hang per leg: the watchdog
                # interrupt path must fire under perturbation, not just
                # when the seed happens to produce a stall
                faultinject.configure("hang:sweep.chunk_dispatch:3")
                # a ghost host claims an observation and LEAVES (lease
                # retired with the claim still running): adoption is
                # exercised deterministically every leg
                ghost = FleetPlane(out, host_id="ghost", lease_s=0.5,
                                   settle_s=0.0)
                ghost.register()
                ghost.claim(obs[0].name)
                ghost.close()
                results, errors = {}, {}

                def go(host_id, _obs=obs, _out=out):
                    plane = FleetPlane(_out, host_id=host_id,
                                       lease_s=1.0, settle_s=0.02,
                                       heartbeat_s=0.2)
                    try:
                        results[host_id] = FleetScheduler(
                            _obs, cfg, max_host_workers=2, devices=1,
                            retries=2, stall_s=stall_s,
                            plane=plane).run()
                    except BaseException as e:  # noqa: BLE001 - re-raised
                        errors[host_id] = e
                t0 = time.perf_counter()
                hosts = [threading.Thread(target=go, args=(f"host{h}",))
                         for h in range(n_hosts)]
                for t in hosts:
                    t.start()
                    time.sleep(0.05)
                for t in hosts:
                    t.join(timeout=600)
                wall = time.perf_counter() - t0
                sys.setswitchinterval(old_si)
                locks.configure_race(None)
                assert not errors, (
                    f"seed {seed}: host raised: "
                    f"{ {h: repr(e) for h, e in errors.items()} }")
                assert all(not t.is_alive() for t in hosts), (
                    f"seed {seed}: a host thread wedged past 600s")
                quarantined = {n: q for r in results.values()
                               for n, q in r.quarantined.items()}
                assert not quarantined, (
                    f"seed {seed}: quarantines under race stress: "
                    f"{quarantined}")
                adopted = sorted({n for r in results.values()
                                  for n in r.adopted})
                timeouts = sum(r.timeouts for r in results.values())
                assert adopted, (
                    f"seed {seed}: the ghost's claim was never adopted")
                assert timeouts >= 1, (
                    f"seed {seed}: the armed hang never produced a "
                    f"watchdog interrupt — the async-interrupt-under-"
                    f"perturbation path went uncovered")
                viol = locks.violations()
                assert not viol, (
                    f"seed {seed}: lockdep order violations: {viol}")
                ident, tot, diverged = parity(tag)
                assert ident == tot and tot > 0, (
                    f"seed {seed}: artifacts diverged from clean: "
                    f"{ident}/{tot} ({diverged[:8]})")
                # a final no-perturbation resume validates every
                # manifest and re-runs nothing
                final = FleetScheduler(fleet(tag), cfg,
                                       max_host_workers=2, devices=1,
                                       resume=True).run()
                assert final.ok and len(final.ran) == 0, (
                    f"seed {seed}: post-race resume re-ran "
                    f"{len(final.ran)} stages")
                snap = locks.snapshot()
                per_seed.append({
                    "seed": seed,
                    "switch_interval_s": (2e-6, 5e-5, 5e-6, 2e-4)[seed % 4],
                    "lock_pauses_injected": locks.race_pauses(),
                    "adopted": adopted,
                    "watchdog_interrupts": timeouts,
                    "order_violations": 0,
                    "artifacts_identical": f"{ident}/{tot}",
                    "wall_s": round(wall, 2),
                    "locks_tracked": len(snap),
                    "contentions": sum(v["contentions"]
                                       for v in snap.values()),
                })
                print(f"# race: seed {seed}: "
                      f"{per_seed[-1]['lock_pauses_injected']} lock "
                      f"pauses, {timeouts} watchdog interrupts, "
                      f"adopted {adopted}, {ident}/{tot} artifacts "
                      f"identical, 0 violations ({wall:.1f}s)",
                      file=sys.stderr)
    finally:
        sys.setswitchinterval(old_si)
        faultinject.reset()
        locks.configure_race(None)
        for k, v in env_save.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    timeouts_total = sum(p["watchdog_interrupts"] for p in per_seed)
    return {
        "metric": "race_interleaving_parity",
        "value": 1.0,
        "unit": (f"fraction of artifacts byte-identical to a clean run "
                 f"across {len(seeds)} seeded interleaving legs of a "
                 f"{n_obs}-obs x {len(stages)}-stage fleet on "
                 f"{n_hosts} in-process hosts + 1 leaving ghost "
                 f"(claim/adopt + watchdog hang-interrupt + prefetch "
                 f"concurrently, setswitchinterval cranked, seeded "
                 f"lock-boundary pauses, PYPULSAR_TPU_LOCKDEP=strict) "
                 f"— asserted 1.0 with ZERO lockdep order violations "
                 f"and a zero-stage final resume per seed"),
        "vs_baseline": 1.0,
        "race_seeds": seeds,
        "race_n_obs": n_obs,
        "race_n_hosts": n_hosts,
        "race_n_stages": len(stages),
        "race_stall_timeout_s": stall_s,
        "race_pause_us": 150.0,
        "race_watchdog_interrupts_total": timeouts_total,
        "race_per_seed": per_seed,
        "race_nsamp": T,
        "race_nchan": C,
    }


def run_multihost(args):
    """Multi-host fleet harness (the round-18 fenced-lease-takeover
    acceptance measurement): ONE survey over a 4-observation toy fleet,
    run three ways —

    - **serial**: the 1-host serial chain (the byte-parity reference);
    - **clean 3-host**: three REAL host processes (``survey --host-id
      hostN`` children, rank env grid) coordinating purely through the
      shared-directory plane (``<outdir>/_fleet``): fsync'd heartbeat
      leases, fencing-token'd claims, no coordinator service;
    - **host-kill chaos**: the same 3-host fleet, but host0 is parked
      mid-sweep by an armed in-stage hang and then SIGKILL'd (the real
      signal — no finally blocks, no heartbeat retirement, the lease
      just goes silent). Survivors must detect the death past
      ``PYPULSAR_TPU_HOST_LEASE_S``, ADOPT the orphaned observation,
      resume it from its manifest, and finish the fleet.

    Asserted, not just reported: the kill leg's final artifact set is
    byte-identical to the serial run, at least one adoption event fired,
    the victim really died by signal, and a final no-fault single-host
    ``--resume`` over the kill leg's outdir re-runs ZERO stages. The
    wall-clock A/B is a CPU toy (hosts share one machine's cores) — the
    committed claims are the adoption/fencing/parity structure.

    Round-21 observability riders: the clean leg's host0 runs with
    ``--status-port 0`` and this process scrapes the LIVE
    ``/status.json`` + Prometheus ``/metrics`` mid-fleet; the kill
    leg's traces are fed through ``tlmtrace --check`` (no dangling
    parent_ids even across a SIGKILL'd host) and stitched into the
    committed Perfetto JSON (``--trace-out``), with the adoption
    asserted visible as a lane handover on one trace_id."""
    acquire_backend()
    import glob as _glob
    import re
    import signal
    import tempfile
    import urllib.request

    from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import Observation

    n_obs, n_hosts = 4, 3
    lease_s = 3.0
    C, T, dtp = 32, (1 << 13 if (args.quick or args.cpu_fallback)
                     else 1 << 14), 5e-4
    rng_freqs = 1500.0 - 4.0 * np.arange(C)
    cfg = SurveyConfig(
        mask=True, mask_time=2.0, lodm=0.0, dmstep=10.0, numdms=8,
        nsub=8, group_size=4, threshold=8.0,
        accel_zmax=20.0, accel_numharm=2, accel_sigma=3.0, accel_batch=4,
        sift_sigma=3.0, sift_min_hits=1, fold_nbins=32, fold_npart=8)
    stages = build_dag(cfg)
    # the SAME knobs as CLI flags — the children must run the identical
    # chain or the byte-parity assert (and the final resume's
    # fingerprint match) would be vacuous
    flags = ["--mask-time", "2.0", "--lodm", "0.0", "--dmstep", "10.0",
             "--numdms", "8", "-s", "8", "--group-size", "4",
             "--threshold", "8.0", "--accel-zmax", "20.0",
             "--accel-dz", "2.0", "--accel-numharm", "2",
             "--accel-sigma", "3.0", "--accel-batch", "4",
             "--sift-sigma", "3.0", "--sift-min-hits", "1",
             "--fold-nbins", "32", "--fold-npart", "8"]
    repo_root = os.path.dirname(os.path.abspath(__file__))

    def spawn_host(rank, fils, outdir, tlmdir, logdir, extra_env=None,
                   extra_flags=None):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = (repo_root + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(
                                 os.pathsep)
        env["PYPULSAR_TPU_HOST_LEASE_S"] = str(lease_s)
        env["PYPULSAR_TPU_NUM_PROCESSES"] = str(n_hosts)
        env["PYPULSAR_TPU_PROCESS_ID"] = str(rank)
        env.update(extra_env or {})
        log = open(os.path.join(logdir, f"host{rank}.log"), "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "pypulsar_tpu.cli", "survey",
             *fils, "-o", outdir, *flags, "--host-id", f"host{rank}",
             "--telemetry-dir", tlmdir, *(extra_flags or [])],
            env=env, stdout=log, stderr=subprocess.STDOUT)
        proc._log = log  # closed on wait below
        proc._logpath = log.name
        return proc

    def wait_hosts(procs, timeout=900):
        codes = []
        for proc in procs:
            try:
                codes.append(proc.wait(timeout=timeout))
            finally:
                proc._log.close()
        return codes

    def adoption_events(tlmdir):
        out = []
        for p in sorted(_glob.glob(os.path.join(tlmdir, "*.jsonl"))):
            for line in open(p):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (rec.get("type") == "event"
                        and rec.get("name") == "survey.obs_adopted"
                        and (rec.get("attrs") or {}).get("obs")):
                    # the plane-emitted flavor only (host+obs+token);
                    # the per-obs trace echoes a hostless twin that
                    # would double-count the same adoption
                    out.append(rec["attrs"])
        return out

    def parity(td, dir_a, dir_b):
        ident = tot = 0
        diverged = []
        for pattern in (".cands", "_DM*_ACCEL_*.cand",
                        "_DM*_ACCEL_*.txtcand", "_DM*.dat",
                        ".accelcands", "_cand*.pfd"):
            for fa in sorted(_glob.glob(os.path.join(td, dir_a,
                                                     "*" + pattern))):
                fb = os.path.join(td, dir_b, os.path.basename(fa))
                tot += 1
                if (os.path.exists(fb) and open(fa, "rb").read()
                        == open(fb, "rb").read()):
                    ident += 1
                else:
                    diverged.append(os.path.basename(fa))
        return ident, tot, diverged

    with tempfile.TemporaryDirectory() as td:
        fils = [_synth_survey_fil(os.path.join(td, f"obs{i}.fil"), 31 + i,
                                  C, T, dtp, rng_freqs, f"MH{i}",
                                  period=0.1024 * (1.0 + 0.07 * i))
                for i in range(n_obs)]

        def fleet(dirname):
            out = os.path.join(td, dirname)
            os.makedirs(out, exist_ok=True)
            return out, [Observation(f"obs{i}", fils[i],
                                     os.path.join(out, f"obs{i}"))
                         for i in range(n_obs)]

        # leg 0 — serial 1-host reference (also the timing baseline)
        sdir, sobs = fleet("serial")
        t0 = time.perf_counter()
        for obs in sobs:
            for stage in stages:
                stage.execute(obs, cfg)
        serial_s = time.perf_counter() - t0
        print(f"# multihost: serial 1-host reference {serial_s:.1f}s",
              file=sys.stderr)

        # leg 1 — clean 3-host fleet (subprocess hosts, cold jit caches:
        # the wall includes per-host compile, stated in the record).
        # host0 carries the round-21 endpoint smoke: --status-port 0
        # binds a free port, and while the fleet is LIVE we scrape both
        # /status.json and the Prometheus /metrics from this process.
        mdir, mobs = fleet("mh")
        mtlm = os.path.join(td, "mh_tlm")
        t0 = time.perf_counter()
        procs = [spawn_host(r, fils, mdir, mtlm, td,
                            extra_flags=(["--status-port", "0"]
                                         if r == 0 else None))
                 for r in range(n_hosts)]
        status_url = None
        url_re = re.compile(r"live status at (http://[^/\s]+)/status\.json")
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline and status_url is None:
            if procs[0].poll() is not None:
                break  # host0 already exited — the asserts below will say
            try:
                m = url_re.search(open(procs[0]._logpath).read())
            except OSError:
                m = None
            if m:
                status_url = m.group(1)
            else:
                time.sleep(0.2)
        assert status_url, "host0 never announced its --status-port URL"
        # the server lives for host0's whole scheduler run, so these
        # fetches hit a LIVE endpoint — but observation rows only
        # appear once the first manifests land, a moment after the
        # claims, so poll the snapshot until they do
        snap = None
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            try:
                snap = json.loads(urllib.request.urlopen(
                    status_url + "/status.json", timeout=15).read())
            except OSError:
                snap = None
            if snap and snap.get("rows"):
                break
            if procs[0].poll() is not None:
                break  # host0 done: the server is gone with it
            time.sleep(0.5)
        assert snap and snap.get("rows"), \
            f"live /status.json never grew observation rows: {snap}"
        assert all(r.get("state") for r in snap["rows"])
        metrics = urllib.request.urlopen(
            status_url + "/metrics", timeout=15).read().decode()
        assert "pypulsar_obs_state" in metrics, \
            f"live /metrics missing obs_state gauges:\n{metrics[:400]}"
        print(f"# multihost: live endpoint smoke OK — {status_url} "
              f"served {len(snap['rows'])} status rows + "
              f"{sum(1 for ln in metrics.splitlines() if ln and not ln.startswith('#'))} "
              f"Prometheus samples mid-fleet", file=sys.stderr)
        codes = wait_hosts(procs)
        mh_s = time.perf_counter() - t0
        assert codes == [0] * n_hosts, \
            f"clean multihost leg exit codes {codes}"
        ident, tot, diverged = parity(td, "serial", "mh")
        assert ident == tot and tot > 0, (
            f"clean 3-host artifacts diverged from serial: {ident}/{tot}"
            f" ({diverged[:8]})")
        print(f"# multihost: clean 3-host fleet {mh_s:.1f}s, {ident}/"
              f"{tot} artifacts byte-identical to serial",
              file=sys.stderr)

        # leg 2 — HOST-KILL CHAOS: park host0 mid-sweep (armed in-stage
        # hang, bound far beyond the leg), then SIGKILL it once the
        # hang provably fired (its per-record-flushed fleet trace shows
        # resilience.fault_injected). No finally blocks run: the lease
        # just goes silent, which is exactly what survivors must detect.
        kdir, kobs = fleet("kill")
        ktlm = os.path.join(td, "kill_tlm")
        t0 = time.perf_counter()
        victim = spawn_host(0, fils, kdir, ktlm, td, extra_env={
            "PYPULSAR_TPU_FAULTS": "hang:sweep.chunk_dispatch:1",
            "PYPULSAR_TPU_HANG_S": "600"})
        survivors = [spawn_host(r, fils, kdir, ktlm, td)
                     for r in range(1, n_hosts)]
        vtrace = os.path.join(ktlm, "fleet.host0.jsonl")
        deadline = time.monotonic() + 300
        parked = False
        while time.monotonic() < deadline:
            if victim.poll() is not None:
                break  # died early — the log will say why
            try:
                parked = "resilience.fault_injected" in open(vtrace).read()
            except OSError:
                parked = False
            if parked:
                break
            time.sleep(0.25)
        assert parked, "victim never reached the armed mid-sweep hang"
        os.kill(victim.pid, signal.SIGKILL)
        vcode = victim.wait(timeout=60)
        victim._log.close()
        kcodes = wait_hosts(survivors)
        kill_s = time.perf_counter() - t0
        assert vcode == -signal.SIGKILL, \
            f"victim exit {vcode}, expected -SIGKILL"
        assert kcodes == [0] * (n_hosts - 1), \
            f"survivor exit codes {kcodes}"
        adoptions = adoption_events(ktlm)
        assert adoptions, "no survey.obs_adopted event fired"
        assert all(a.get("adopted_from") == "host0" for a in adoptions)
        ident_k, tot_k, diverged_k = parity(td, "serial", "kill")
        assert ident_k == tot_k and tot_k > 0, (
            f"post-kill artifacts diverged from serial: "
            f"{ident_k}/{tot_k} ({diverged_k[:8]})")

        # the round-21 trace smoke: tlmtrace over EVERYTHING the kill
        # leg wrote (per-host fleet traces, per-obs traces, postmortem
        # capsules). --check must come back clean — the victim's torn
        # tail (children of the stage span it never got to flush) is
        # tolerated because the adoption receipt proves the murder,
        # but any OTHER dangling parent_id fails — and the stitched
        # Perfetto JSON must show the adoption as a LANE HANDOVER:
        # spans of one trace_id on both the victim's and an adopter's
        # host lane. That stitched file is the committed OBS_trace
        # artifact (--trace-out).
        from pypulsar_tpu.cli import tlmtrace as _tlmtrace
        trace_files = sorted(_glob.glob(os.path.join(ktlm, "*.jsonl")))
        trace_files += sorted(_glob.glob(
            os.path.join(kdir, "_fleet", "postmortem", "*.json")))
        assert _tlmtrace.main(["--check", *trace_files]) == 0, \
            "tlmtrace --check found dangling parent_ids after host kill"
        trace_dst = (os.path.abspath(args.trace_out) if args.trace_out
                     else os.path.join(td, "kill.trace.json"))
        assert _tlmtrace.main([*trace_files, "-o", trace_dst]) == 0
        with open(trace_dst) as f:
            doc = json.load(f)
        lanes_by_trace = {}
        for ev in doc["traceEvents"]:
            a = ev.get("args") or {}
            if a.get("trace_id") and a.get("host"):
                lanes_by_trace.setdefault(
                    a["trace_id"], set()).add(a["host"])
        trace_by_obs = {o: t for t, o
                        in doc["otherData"]["traces"].items()}
        adopters = {str(a.get("host")) for a in adoptions}
        handover = {}
        for obs_name in sorted({str(a.get("obs")) for a in adoptions}):
            tid = trace_by_obs.get(obs_name)
            assert tid, f"adopted obs {obs_name} has no stitched trace"
            handover[obs_name] = sorted(lanes_by_trace.get(tid, ()))
        assert any("host0" in lanes and set(lanes) & adopters
                   for lanes in handover.values()), (
            f"no adopted trace spans both the victim's and an "
            f"adopter's lane: {handover} (adopters {adopters})")
        n_trace_ev = len(doc["traceEvents"])
        n_trace_hosts = len(doc["otherData"]["hosts"])
        print(f"# multihost: tlmtrace --check clean over "
              f"{len(trace_files)} file(s); stitched {n_trace_ev} "
              f"events / {n_trace_hosts} host lanes -> {trace_dst} — "
              f"adoption lane handover {handover}", file=sys.stderr)

        # the acceptance tail: a final no-fault single-host resume over
        # the kill leg's outdir validates every manifest and runs NOTHING
        final = FleetScheduler(kobs, cfg, resume=True).run()
        assert final.ok and len(final.ran) == 0, (
            f"final resume re-ran {len(final.ran)} stages: {final.ran}")
        resume_skipped = len(final.skipped)

    speedup = serial_s / mh_s
    n_adopt = len(adoptions)
    print(f"# multihost: host-kill leg {kill_s:.1f}s — victim SIGKILL'd "
          f"mid-sweep, {n_adopt} adoption(s) by "
          f"{sorted({a.get('host') for a in adoptions})}, "
          f"{ident_k}/{tot_k} artifacts byte-identical to serial, final "
          f"resume ran 0 / skipped {resume_skipped} stages",
          file=sys.stderr)
    hostchaos = {
        "metric": "multihost_kill_recovery",
        "value": round(ident_k / max(tot_k, 1), 3),
        "unit": (f"fraction of artifacts byte-identical to the 1-host "
                 f"serial run after a {n_obs}-obs x {n_hosts}-process "
                 f"CPU fleet had host0 SIGKILL'd mid-sweep (parked by "
                 f"an armed in-stage hang, killed by real SIGKILL, "
                 f"lease silent past {lease_s}s) and survivors adopted "
                 f"its observation via the fenced lease plane — "
                 f"asserted 1.0, plus a final no-fault resume "
                 f"validating 0 stages re-run"),
        "vs_baseline": 1.0,
        "multihost_n_obs": n_obs,
        "multihost_n_hosts": n_hosts,
        "multihost_lease_s": lease_s,
        "multihost_victim": "host0",
        "multihost_victim_exit": vcode,
        "multihost_kill_point": "hang:sweep.chunk_dispatch:1 + SIGKILL",
        "multihost_adoptions": n_adopt,
        "multihost_adopters": sorted({str(a.get("host"))
                                      for a in adoptions}),
        "multihost_adopted_obs": sorted({str(a.get("obs", "?"))
                                         for a in adoptions}),
        "multihost_artifacts_identical": f"{ident_k}/{tot_k}",
        "multihost_kill_leg_seconds": round(kill_s, 2),
        "multihost_final_resume_ran": 0,
        "multihost_final_resume_skipped": resume_skipped,
        "multihost_trace_out": (os.path.basename(args.trace_out)
                                if args.trace_out else None),
        "multihost_trace_events": n_trace_ev,
        "multihost_trace_host_lanes": n_trace_hosts,
        "multihost_trace_handover": {k: list(v)
                                     for k, v in handover.items()},
        "multihost_status_endpoint_rows": len(snap["rows"]),
        "multihost_nsamp": T,
        "multihost_nchan": C,
    }
    if args.hostchaos_out:
        with open(args.hostchaos_out, "w") as f:
            json.dump(hostchaos, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"# multihost: host-kill chaos record -> "
              f"{args.hostchaos_out}", file=sys.stderr)
    return {
        "metric": "multihost_fleet_parity",
        "value": round((ident + ident_k) / max(tot + tot_k, 1), 3),
        "unit": (f"fraction of artifacts byte-identical to the 1-host "
                 f"serial chain across BOTH multi-host legs (clean "
                 f"{n_hosts}-process fleet + host-kill/adoption leg; "
                 f"{n_obs} toy obs x {len(stages)} stages, {C}-chan x "
                 f"{T}-sample each) — asserted 1.0. Wall clocks are "
                 f"recorded but NOT the claim on this CPU toy: host "
                 f"processes are cold (each child pays its own jax "
                 f"import + jit compile inside the timed leg) and all "
                 f"hosts share one machine's cores; the committed "
                 f"claims are plane coordination, fenced adoption "
                 f"(detail in "
                 f"{os.path.basename(args.hostchaos_out or 'HOSTCHAOS')}"
                 f") and byte parity"),
        "vs_baseline": 1.0,
        "multihost_cold_fleet_speedup": round(speedup, 3),
        "multihost_n_obs": n_obs,
        "multihost_n_hosts": n_hosts,
        "multihost_serial_seconds": round(serial_s, 2),
        "multihost_fleet_seconds": round(mh_s, 2),
        "multihost_artifacts_identical": f"{ident}/{tot}",
        "multihost_kill_leg": {
            k: hostchaos[k] for k in
            ("multihost_adoptions", "multihost_adopters",
             "multihost_victim_exit", "multihost_artifacts_identical",
             "multihost_final_resume_ran", "multihost_kill_leg_seconds")},
        "multihost_lease_s": lease_s,
        "multihost_nsamp": T,
        "multihost_nchan": C,
    }


def run_corruption(args):
    """Corruption-chaos harness (the round-13 data-integrity acceptance
    measurement): run a toy fleet CLEAN over pristine inputs, then run
    the SAME fleet over copies corrupted with every data-fault kind
    (one kind per observation, plus one untouched control):

    - ``nanburst`` / ``bitflip`` / ``dropblock`` payload damage must be
      scrubbed by the dataguard (NaNs zero-filled on device, counted in
      ``data.*`` telemetry) and the observation completes DEGRADED;
    - ``truncate`` must salvage the valid prefix (reported in the
      manifest's data-quality note) and complete degraded — its
      missing fraction sits below the --max-bad-frac bar;
    - ``header`` garbage must be caught at INGEST (DataFormatError)
      and the observation data-quarantined (reason ``"data"``) without
      burning a single device stage.

    Then assert: zero crashes/hangs (the scheduler returns), exactly
    the header observation quarantined, the clean CONTROL observation's
    artifacts byte-identical to the clean run's, a no-op validated
    resume, and — the committed fuzz receipt — N seeded reader-fuzz
    mutations per format with a 100% parse-or-DataFormatError outcome.
    """
    acquire_backend()
    import glob as _glob
    import shutil
    import tempfile

    from pypulsar_tpu.obs import telemetry
    from pypulsar_tpu.resilience import dataguard
    from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import Observation, status_rows

    seed = args.corruption_seed
    fuzz_n = 500
    C, T, dtp = 32, (1 << 13 if (args.quick or args.cpu_fallback)
                     else 1 << 14), 5e-4
    rng_freqs = 1500.0 - 4.0 * np.arange(C)
    cfg = SurveyConfig(
        mask=True, mask_time=2.0, lodm=0.0, dmstep=10.0, numdms=8,
        nsub=8, group_size=4, threshold=8.0,
        accel_zmax=20.0, accel_numharm=2, accel_sigma=3.0, accel_batch=4,
        sift_sigma=3.0, sift_min_hits=1, fold_nbins=32, fold_npart=8)
    stages = build_dag(cfg)
    kinds = ["nanburst", "bitflip", "dropblock", "truncate", "header"]
    n_obs = 1 + len(kinds)  # obs0 = clean control

    def _counter_totals():
        cur = telemetry.current()
        return dict(cur.counter_totals()) if cur is not None else {}

    with tempfile.TemporaryDirectory() as td:
        fils = [_synth_survey_fil(os.path.join(td, f"obs{i}.fil"),
                                  31 + i, C, T, dtp, rng_freqs,
                                  f"CORR{i}",
                                  period=0.1024 * (1.0 + 0.07 * i))
                for i in range(n_obs)]

        def fleet(dirname, files):
            out = os.path.join(td, dirname)
            os.makedirs(out, exist_ok=True)
            return [Observation(f"obs{i}", files[i],
                                os.path.join(out, f"obs{i}"))
                    for i in range(len(files))]

        # clean leg over pristine inputs (also warms every jit cache)
        t0 = time.perf_counter()
        clean = FleetScheduler(fleet("clean", fils), cfg,
                               max_host_workers=2, devices=1).run()
        clean_s = time.perf_counter() - t0
        assert clean.ok and len(clean.ran) == n_obs * len(stages)

        # corrupted copies: obs0 untouched, obs1..n one fault kind each
        # (the ONE corruption code path tools/tests share)
        corr = [os.path.join(td, f"corr_obs{i}.fil")
                for i in range(n_obs)]
        corruption = {}
        for i, (src, dst) in enumerate(zip(fils, corr)):
            shutil.copy(src, dst)
            if i > 0:
                desc = dataguard.corrupt_file(dst, kinds[i - 1],
                                              seed=seed + i)
                corruption[f"obs{i}"] = {
                    k: v for k, v in desc.items() if k != "path"}

        t0 = time.perf_counter()
        corr_obs = fleet("corr", corr)
        # an in-memory telemetry session (nested sessions reuse the
        # outer one) guarantees the data.* counters are live — the
        # scrub receipt below is an acceptance assertion, not a nice-
        # to-have
        with telemetry.session(tool="bench-corruption"):
            base = _counter_totals()
            result = FleetScheduler(corr_obs, cfg, max_host_workers=2,
                                    devices=1).run()
            counters = _counter_totals()
        corr_s = time.perf_counter() - t0
        scrubbed = (counters.get("data.nonfinite_cells", 0)
                    - base.get("data.nonfinite_cells", 0))
        cells = (counters.get("data.cells", 0)
                 - base.get("data.cells", 0))

        # verdicts: exactly the header observation is DATA-quarantined;
        # every other observation (incl. the salvaged truncation)
        # completed — degraded, not dead
        header_obs = f"obs{1 + kinds.index('header')}"
        assert set(result.quarantined) == {header_obs}, (
            f"unexpected quarantine set: {result.quarantined}")
        q = result.quarantined[header_obs]
        assert q.get("reason") == "data" and q["stage"] == "ingest", q
        assert len(result.ran) == (n_obs - 1) * len(stages), (
            f"degraded observations did not complete: "
            f"{len(result.ran)} stages ran")
        # the NaN burst provably hit the scrub (masked fraction is the
        # telemetry receipt the gate test pins down)
        assert scrubbed > 0, "nanburst was never scrubbed on device"

        # the truncated observation's manifest carries its salvage story
        rows = {r["obs"]: r for r in status_rows(
            [o.manifest for o in corr_obs])}
        trunc_obs = f"obs{1 + kinds.index('truncate')}"
        dq = rows[trunc_obs].get("data_quality") or {}
        assert (dq.get("salvage") or {}).get("missing_samples", 0) > 0, (
            f"truncation salvage not reported: {dq}")
        bad_fracs = {o: (rows[o].get("data_quality") or {}).get(
            "bad_frac") for o in rows}

        # byte-parity of the UNCORRUPTED observation: the control's
        # whole artifact chain must match the clean run exactly —
        # asserted, not just reported
        ident = tot = 0
        diverged = []
        for pattern in ("obs0*_ACCEL_*.cand", "obs0*_ACCEL_*.txtcand",
                        "obs0*_cand*.pfd", "obs0*.dat", "obs0*.cands"):
            for fa in sorted(_glob.glob(os.path.join(td, "clean",
                                                     pattern))):
                fb = os.path.join(td, "corr", os.path.basename(fa))
                tot += 1
                if (os.path.exists(fb) and open(fa, "rb").read()
                        == open(fb, "rb").read()):
                    ident += 1
                else:
                    diverged.append(os.path.basename(fa))
        assert ident == tot and tot > 0, (
            f"control-observation artifacts diverged: {ident}/{tot} "
            f"({diverged[:8]})")
        # the SNR summary embeds the run's outdir in each row's pfd
        # path, so compare ROWS with the path normalized to its
        # basename — every measured value must still match exactly
        def _snr_rows(d):
            with open(os.path.join(td, d, "obs0_snr.json")) as f:
                rows_ = json.load(f)
            for r in rows_:
                r["pfd"] = os.path.basename(r["pfd"])
            return rows_

        snr_clean, snr_corr = _snr_rows("clean"), _snr_rows("corr")
        assert snr_clean == snr_corr and snr_clean, (
            "control-observation SNR rows diverged")
        tot += 1
        ident += 1

        # a validated resume re-runs NOTHING (the degraded runs'
        # manifests are trustworthy) and re-issues only the data verdict
        final = FleetScheduler(fleet("corr", corr), cfg,
                               max_host_workers=2, devices=1,
                               resume=True).run()
        assert len(final.ran) == 0, (
            f"post-corruption resume re-ran {len(final.ran)} stages")
        assert set(final.quarantined) == {header_obs}

    # the committed fuzz receipt: N seeded mutations per format, 100%
    # parse-or-DataFormatError (never a hang or a raw codec exception)
    fuzz = {}
    with tempfile.TemporaryDirectory() as fz:
        for fmt in ("filterbank", "psrfits", "dat"):
            counts, failures = dataguard.run_reader_fuzz(
                fmt, fuzz_n, seed, os.path.join(fz, fmt))
            assert not failures, (
                f"reader fuzz contract violated for {fmt}: "
                f"{failures[:5]}")
            fuzz[fmt] = counts

    n_kinds = len(kinds)
    print(f"# corruption: {n_kinds} fault kinds over {n_obs - 1} "
          f"observations + 1 control — fleet completed "
          f"({len(result.ran)} stages, 1 data quarantine at ingest, "
          f"{scrubbed} non-finite cells scrubbed on device), control "
          f"{ident}/{tot} artifacts byte-identical to clean "
          f"({clean_s:.1f}s clean, {corr_s:.1f}s corrupted); reader "
          f"fuzz {fuzz_n}x3 formats 100% clean", file=sys.stderr)
    return {
        "metric": "corruption_fleet_integrity",
        "value": round(ident / max(tot, 1), 3),
        "unit": (f"fraction of the uncorrupted control observation's "
                 f"artifacts byte-identical to a clean run after a "
                 f"{n_obs}-obs x {len(stages)}-stage fleet ingested "
                 f"inputs corrupted with {n_kinds} data-fault kinds "
                 f"({'+'.join(kinds)}) — asserted 1.0, with the fleet "
                 f"completing degraded (salvaged truncation, on-device "
                 f"NaN scrub) or data-quarantined (garbage header at "
                 f"ingest, reason 'data') and a validated resume "
                 f"re-running zero stages; plus {fuzz_n} seeded reader-"
                 f"fuzz mutations per format, 100% clean-error-or-"
                 f"salvage"),
        "vs_baseline": 1.0,
        "corruption_seed": seed,
        "corruption_kinds": kinds,
        "corruption_by_obs": corruption,
        "corruption_n_obs": n_obs,
        "corruption_n_stages": len(stages),
        "corruption_stages_run": len(result.ran),
        "corruption_data_quarantines": sorted(result.quarantined),
        "corruption_bad_fracs": bad_fracs,
        "corruption_nonfinite_cells_scrubbed": int(scrubbed),
        "corruption_cells_checked": int(cells),
        "corruption_control_artifacts_identical": f"{ident}/{tot}",
        "corruption_fuzz_n_per_format": fuzz_n,
        "corruption_fuzz_outcomes": fuzz,
        "corruption_clean_seconds": round(clean_s, 2),
        "corruption_seconds": round(corr_s, 2),
        "corruption_nsamp": T,
        "corruption_nchan": C,
    }


def run_waterfall(args):
    """Single-DM waterfall path (BASELINE configs[0]: waterfaller.py
    dedisperse + downsample + scale on a 10 s, 256-chan filterbank —
    reference bin/waterfaller.py:189-208 over the per-channel-roll
    Spectra path formats/spectra.py:229-260). The device pipeline is the
    same ops the CLI waterfaller uses (ops/kernels.py dedisperse /
    downsample / scaled), fused into one jitted program; the baseline is
    the NumPy twin of the identical pipeline."""
    acquire_backend()
    import jax
    import jax.numpy as jnp
    from pypulsar_tpu.ops import kernels, numpy_ref

    C, dt, dm, factor = 256, 64e-6, 100.0, 16
    T = int(round(10.0 / dt))  # 10 s
    if args.quick or args.cpu_fallback:
        T = 1 << 15
    freqs = (1500.0 - 300.0 / C * np.arange(C)).astype(np.float64)
    rng = np.random.RandomState(3)
    data = rng.standard_normal((C, T)).astype(np.float32)
    host_bins = numpy_ref.bin_delays(dm, freqs, dt)

    from pypulsar_tpu.ops.fourier_dedisperse import fourier_chunk_len

    n_shift = fourier_chunk_len(T + int(np.abs(host_bins).max()))

    def _pipe(d, bins):
        # the same op the Spectra/waterfaller path runs: auto backend
        # (fourier on TPU) with the host-known static shift bound
        ded = kernels.shift_channels(d, bins, n_fft=n_shift)
        return kernels.scaled(kernels.downsample(ded, factor))

    pipeline = jax.jit(_pipe)

    dev = jnp.asarray(data)
    binsd = jnp.asarray(host_bins)
    out = pipeline(dev, binsd)  # compile + warm
    float(jnp.ravel(out)[0])
    # COLD: one synced dispatch — the interactive waterfaller latency,
    # dominated by the ~65 ms tunnel turnaround, not compute (this is
    # the 12.8x row of BENCH_r05_waterfall.json; VERDICT r5 item 6 asks
    # for the steady-state number NEXT TO it, not instead of it)
    cold_time = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = pipeline(dev, binsd)
        float(jnp.ravel(out)[0])
        cold_time = min(cold_time, time.perf_counter() - t0)
    cold_samples_per_sec = C * T / cold_time
    # repeat-dispatch amortized (the r5 measurement): k dispatches, one
    # sync — dispatch latency amortizes but each program is still one
    # 10-s window
    k = 10
    jax_time = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(k):
            out = pipeline(dev, binsd)
        float(jnp.ravel(out)[0])
        jax_time = min(jax_time, (time.perf_counter() - t0) / k)
    samples_per_sec = C * T / jax_time
    # STEADY STATE: a BATCH of windows through one vmapped program (the
    # repeat-window survey shape — amortizes dispatch AND the per-program
    # fixed overhead over B windows; compile excluded)
    B = 4 if (args.quick or args.cpu_fallback) else 16
    pipelineB = jax.jit(jax.vmap(_pipe, in_axes=(0, None)))
    devB = jnp.asarray(np.broadcast_to(data, (B, C, T)).copy())
    outB = pipelineB(devB, binsd)  # compile + warm
    float(jnp.ravel(outB)[0])
    steady_time = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        outB = pipelineB(devB, binsd)
        float(jnp.ravel(outB)[0])
        steady_time = min(steady_time, time.perf_counter() - t0)
    steady_samples_per_sec = B * C * T / steady_time

    # parity: the device product IS the NumPy twin's product
    ref = numpy_ref.scaled(numpy_ref.downsample(
        numpy_ref.shift_channels(data, host_bins), factor))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)

    def one_rep():
        t0 = time.perf_counter()
        numpy_ref.scaled(numpy_ref.downsample(
            numpy_ref.shift_channels(data, host_bins), factor))
        return time.perf_counter() - t0

    bl = numpy_baseline(one_rep)
    bl_samples_per_sec = C * T / bl["seconds"]
    speedup = steady_samples_per_sec / bl_samples_per_sec
    print(f"# waterfall: cold {cold_time*1e3:.1f} ms, amortized "
          f"{jax_time*1e3:.1f} ms/pipeline, steady x{B} "
          f"{steady_time*1e3:.1f} ms = {steady_samples_per_sec/1e9:.2f} "
          f"Gsamp/s; numpy {bl['seconds']:.3f}s", file=sys.stderr)
    unit = (f"waterfalled samples/s STEADY-STATE ({C}-chan, {T*dt:.1f}s @ "
            f"64us, dm={dm}, downsamp={factor}; one vmapped program over "
            f"{B} windows, best of 3, compile excluded; cold single-"
            f"dispatch and x{k} repeat-dispatch rates in extras; numpy "
            f"twin baseline, round-5 protocol)")
    if args.cpu_fallback:
        unit += " [CPU FALLBACK: accelerator backend unavailable]"
    return {
        "metric": "waterfall_samples_per_sec",
        "value": round(steady_samples_per_sec, 1),
        "unit": unit,
        "vs_baseline": round(speedup, 2),
        "steady_batch_windows": B,
        "steady_seconds_per_batch": round(steady_time, 4),
        "cold_seconds": round(cold_time, 4),
        "cold_samples_per_sec": round(cold_samples_per_sec, 1),
        "cold_vs_baseline": round(cold_samples_per_sec
                                  / bl_samples_per_sec, 2),
        "dispatch_amortized_seconds": round(jax_time, 4),
        "dispatch_amortized_samples_per_sec": round(samples_per_sec, 1),
        "dispatch_amortized_vs_baseline": round(samples_per_sec
                                                / bl_samples_per_sec, 2),
        "numpy_seconds_measured": round(bl["seconds"], 3),
        **{k2: v for k2, v in bl.items() if k2 != "seconds"},
    }


def run_prepass(args):
    """RFI/detrend prepass (BASELINE configs[1]: zero_dm_filter.py +
    spectrogram.py + mydetrend on a 60 s filterbank — reference
    bin/zero_dm_filter.py:30-50, bin/spectrogram.py:17-37,
    utils/mydetrend.py:65-107). Device pipeline, one jitted program:
    per-sample zero-DM filter -> channel-summed timeseries -> block
    power spectrogram (power-of-two block: non-pow2 FFTs lower to dense
    DFT matmuls on this platform, BENCHNOTES) -> batched WLS detrend of
    the log-power rows (utils/detrend._detrend_blocks_jit, the same
    kernel detrend_blocks wraps)."""
    acquire_backend()
    import jax
    import jax.numpy as jnp
    from pypulsar_tpu.fourier.kernels import spectrogram
    from pypulsar_tpu.ops import kernels
    from pypulsar_tpu.ops import numpy_ref
    from pypulsar_tpu.fourier import numpy_ref as fnumpy_ref
    from pypulsar_tpu.utils import detrend as detrend_mod

    C, dt, spb = 1024, 64e-6, 1 << 14  # ~1.05 s spectra blocks
    T = (int(round(60.0 / dt)) // spb) * spb  # 60 s, whole blocks
    if args.quick or args.cpu_fallback:
        C, spb = 128, 1 << 12
        T = 8 * spb

    @jax.jit
    def pipeline(d):
        # zero_dm_filter's product is the whole CLEANED filterbank; the
        # abs-sum checksum forces all C x T output cells to materialize
        # (XLA would otherwise dead-code-eliminate every channel but the
        # one the spectrogram reads). The spectrogram+detrend leg runs on
        # a cleaned channel timeseries (the reference spectrogram.py
        # consumes a timeseries; the zero-DM sum itself is identically 0)
        zdm = kernels.zero_dm(d)
        checksum = jnp.sum(jnp.abs(zdm))
        spec = spectrogram(zdm[0], spb)  # [B, spb//2+1]
        y = jnp.log10(jnp.maximum(spec, 1e-30))
        x = jnp.broadcast_to(
            jnp.arange(y.shape[1], dtype=jnp.float32), y.shape)
        keep = jnp.ones(y.shape, dtype=bool)
        return checksum, detrend_mod._detrend_blocks_jit(y, x, keep, 1)

    # generate on device: shipping 3.8 GB through the ~25 MB/s tunnel
    # would swamp the measurement (the measured quantity is the prepass)
    key = jax.random.PRNGKey(5)
    dev = jax.random.normal(key, (C, T), dtype=jnp.float32)
    cks, out = pipeline(dev)
    float(cks)
    jax_time = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        cks, out = pipeline(dev)
        float(cks)  # sync on the checksum: the full cleaned product ran
        jax_time = min(jax_time, time.perf_counter() - t0)
    samples_per_sec = C * T / jax_time

    # numpy twin baseline on a slice (cost linear in T), pulled from the
    # device so both paths see identical data; parity-check the device
    # pipeline at the slice shape against the twin
    nblk = 4
    bl_T = nblk * spb
    bl_data = np.asarray(dev[:, :bl_T]).astype(np.float64)

    def numpy_prepass(d):
        zdm = numpy_ref.zero_dm(d)
        checksum = np.abs(zdm).sum()
        spec = fnumpy_ref.spectrogram(zdm[0], spb)
        y = np.log10(np.maximum(spec, 1e-30))
        return checksum, np.stack([detrend_mod.old_detrend(row, order=1)
                                   for row in y])

    ref_cks, ref = numpy_prepass(bl_data)
    got_cks, got = pipeline(jnp.asarray(bl_data, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=5e-3, atol=5e-3)
    np.testing.assert_allclose(float(got_cks), ref_cks, rtol=1e-3)

    def one_rep():
        t0 = time.perf_counter()
        numpy_prepass(bl_data)
        return time.perf_counter() - t0

    bl = numpy_baseline(one_rep)
    bl_samples_per_sec = C * bl_T / bl["seconds"]
    speedup = samples_per_sec / bl_samples_per_sec
    print(f"# prepass: {jax_time*1e3:.1f} ms = "
          f"{samples_per_sec/1e9:.2f} Gsamp/s ({T//spb} spectra blocks); "
          f"numpy {bl['seconds']:.3f}s on {bl_T/T:.3f} of the data",
          file=sys.stderr)
    unit = (f"prepassed samples/s ({C}-chan, {T*dt:.0f}s @ 64us, zero-DM "
            f"+ {spb}-sample spectrogram + order-1 WLS detrend, one fused "
            f"program, best of 3; numpy twin baseline on {bl_T/T:.3f} of "
            f"the data scaled linearly, round-5 protocol)")
    if args.cpu_fallback:
        unit += " [CPU FALLBACK: accelerator backend unavailable]"
    return {
        "metric": "prepass_samples_per_sec",
        "value": round(samples_per_sec, 1),
        "unit": unit,
        "vs_baseline": round(speedup, 2),
        "jax_seconds": round(jax_time, 4),
        "numpy_seconds_measured": round(bl["seconds"], 3),
        "numpy_slice_frac": round(bl_T / T, 4),
        **{k: v for k, v in bl.items() if k != "seconds"},
    }


def probe_backend(timeout: float = 300.0) -> bool:
    """Cheap child-process liveness probe of the accelerator tunnel.

    A wedged axon tunnel HANGS (observed for hours) rather than erroring,
    so the full benchmark child would sit in native code until its whole
    2400 s timeout before the CPU fallback got a chance. One trivial op in
    a short-lived child answers the question in seconds when the tunnel is
    healthy and bounds the damage when it is not."""
    code = ("import jax, jax.numpy as jnp; "
            "print(float(jnp.ones((8, 8)).sum()))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout)
        return proc.returncode == 0 and "64.0" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def run_tune(args):
    """Auto-tuning A/B (round 17, BENCH_r12_tune.json).

    Per geometry (>=2), per searchable stage (sweep, accel):

    1. **search leg** — ``tune.autotune(force_search=True)`` against a
       fresh cache: the coordinate-descent searcher times the REAL
       stage dispatches (tune/stages.py) at that geometry. Gates:
       trials <= the declared budget (the bounded-cost guarantee) and
       tuned wall <= hand-picked-baseline wall * 1.05 (the searcher
       starts FROM the baseline, so it can only tie-or-win; the 5%
       allows timer noise on ties). Walls here are CPU-toy numbers
       (labeled, per the PR 10 convention) — the STRUCTURAL claims are
       the gates.
    2. **reuse leg** — a second consult at the SAME key must run ZERO
       trials and bump ``tune.cache_hit`` (counter-snapshot diff of the
       shared telemetry session).

    Then one **science-invariance leg**: the sweep->accel chain over a
    synthetic pulsar under two different tuned configs from the legal
    search domain — candidate tables must be BYTE-identical (tuning
    moves throughput knobs, never results; asserted, not reported).
    """
    import glob
    import shutil
    import tempfile

    from pypulsar_tpu import tune
    from pypulsar_tpu.obs import telemetry
    from pypulsar_tpu.tune import knobs
    from pypulsar_tpu.tune.stages import accel_measure, sweep_measure

    workdir = tempfile.mkdtemp(prefix="bench_tune_")
    saved_env = {k: os.environ.get(k)
                 for k in ("PYPULSAR_TPU_TUNE", "PYPULSAR_TPU_TUNE_CACHE",
                           "PYPULSAR_TPU_SWEEP_CHUNK",
                           "PYPULSAR_TPU_ACCEL_BATCH",
                           "PYPULSAR_TPU_ACCEL_HBM",
                           "PYPULSAR_TPU_DATS_RESIDENT_LIMIT")}
    for k in saved_env:
        os.environ.pop(k, None)
    knobs.clear_tuned()
    budget = args.tune_trials or max(
        1, knobs.env_int("PYPULSAR_TPU_TUNE_TRIALS"))
    if args.quick:
        geometries = [(32, 1 << 14), (64, 1 << 15)]
        ndm, nspec = 16, 8
    else:
        geometries = [(64, 1 << 16), (128, 1 << 17)]
        ndm, nspec = 32, 16
    from pypulsar_tpu.parallel.mesh import lease_devices
    from pypulsar_tpu.parallel.sweep import resolve_engine

    engine = resolve_engine(args.engine)
    dev = lease_devices()[0]
    on_tpu = getattr(dev, "platform", "cpu") == "tpu"
    record = {
        "metric": "tune_ab", "unit": "see legs",
        "engine": engine, "backend": str(dev.device_kind
                                         if hasattr(dev, "device_kind")
                                         else dev.platform),
        "trial_budget": budget,
        "wall_label": ("real-chip walls" if on_tpu else
                       "CPU-toy walls (structural gates are the claim: "
                       "bounded trials + cache-hit reuse + invariance)"),
        "geometries": [],
    }
    try:
        cache_fn = os.path.join(workdir, "tune.json")
        os.environ["PYPULSAR_TPU_TUNE_CACHE"] = cache_fn
        cache = tune.TuneCache(cache_fn)
        with telemetry.session() as tlm:
            for nchan, nsamp in geometries:
                geo = {"nchan": nchan, "nsamp": nsamp, "stages": {}}
                for stage in ("sweep", "accel"):
                    knobs.clear_tuned()
                    if stage == "sweep":
                        measure = sweep_measure(nchan, nsamp, ndm=ndm,
                                                engine=engine)
                        key_kw = dict(nchan=nchan, nsamp=nsamp,
                                      engine=engine)
                    else:
                        measure = accel_measure(min(nsamp, 1 << 15),
                                                zmax=20, numharm=2,
                                                nspec=nspec)
                        key_kw = dict(nsamp=min(nsamp, 1 << 15), zmax=20)
                    c0 = dict(tlm.counter_totals())
                    tune.autotune(stage, measure=measure, cache=cache,
                                  budget=budget, force_search=True,
                                  verbose=True, **key_kw)
                    c1 = dict(tlm.counter_totals())
                    trials = c1.get("tune.trials", 0) - c0.get(
                        "tune.trials", 0)
                    ent = cache.lookup(tune.make_key(stage, **key_kw))
                    meta = ent["meta"]
                    assert trials <= budget, \
                        f"{stage}: {trials} trials > budget {budget}"
                    assert meta["best_s"] <= meta["baseline_s"] * 1.05, \
                        f"{stage}: tuned {meta['best_s']} slower than " \
                        f"hand-picked baseline {meta['baseline_s']}"
                    # reuse leg: same key, zero trials, cache_hit bumps
                    knobs.clear_tuned()
                    c2 = dict(tlm.counter_totals())
                    applied = tune.apply_cached(stage, cache=cache,
                                                **key_kw)
                    c3 = dict(tlm.counter_totals())
                    assert c3.get("tune.trials", 0) == c2.get(
                        "tune.trials", 0), "reuse ran trials"
                    hits = c3.get("tune.cache_hit", 0) - c2.get(
                        "tune.cache_hit", 0)
                    assert hits == 1, f"no cache hit on reuse ({hits})"
                    geo["stages"][stage] = {
                        "n_trials": int(trials),
                        "baseline_s": meta["baseline_s"],
                        "tuned_s": meta["best_s"],
                        "speedup": meta["speedup"],
                        "tuned_config": ent["config"],
                        "reapplied_config": applied,
                        "second_run_trials": 0,
                        "second_run_cache_hit": True,
                    }
                    print(f"# tune[{stage}] @ ({nchan}, {nsamp}): "
                          f"{meta['baseline_s']:.4f}s -> "
                          f"{meta['best_s']:.4f}s "
                          f"({meta['speedup']:.2f}x, {trials} trials, "
                          f"reuse=hit)")
                record["geometries"].append(geo)
            # compile.* rides along (round 22): tuned-config changes
            # key fresh executables, so the search cost includes them
            record["telemetry_counters"] = {
                k: round(v, 1) for k, v in
                sorted(tlm.counter_totals().items())
                if k.startswith(("tune.", "compile."))}
        # ---- science-invariance leg (gather engine: the CPU default
        # whose chunk domain is byte-invariant; fourier's tuned configs
        # never carry the chunk, enforced by variant_engines) ----
        knobs.clear_tuned()
        os.environ["PYPULSAR_TPU_DATS_RESIDENT_LIMIT"] = "0"
        C, T = (32, 1 << 13) if args.quick else (32, 1 << 14)
        freqs = (1500.0 - 4.0 * np.arange(C)).astype(np.float64)
        fil = _synth_survey_fil(os.path.join(workdir, "psr.fil"), 5, C,
                                T, 5e-4, freqs, "PSR_TUNE")
        from pypulsar_tpu.cli import sweep as cli_sweep

        cfgs = [{"PYPULSAR_TPU_SWEEP_CHUNK": 4096,
                 "PYPULSAR_TPU_ACCEL_BATCH": 4,
                 "PYPULSAR_TPU_ACCEL_HBM": 2e9},
                {"PYPULSAR_TPU_SWEEP_CHUNK": 8192,
                 "PYPULSAR_TPU_ACCEL_BATCH": 8,
                 "PYPULSAR_TPU_ACCEL_HBM": 8e9}]
        arts = []
        for i, cfg in enumerate(cfgs):
            sub = os.path.join(workdir, f"leg{i}")
            os.makedirs(sub)
            base = os.path.join(sub, "x")
            knobs.clear_tuned()
            knobs.apply_tuned(cfg)
            try:
                rc = cli_sweep.main(
                    [fil, "-o", base, "--lodm", "0", "--dmstep", "10",
                     "--numdms", "8", "-s", "8", "--group-size", "4",
                     "--threshold", "8", "--engine", "gather",
                     "--write-dats", "--accel-search", "--accel-zmax",
                     "20", "--accel-numharm", "2", "--accel-sigma",
                     "3"])
                assert rc == 0, f"invariance leg {i} rc={rc}"
            finally:
                knobs.clear_tuned()
            leg = {}
            for pat in ("_DM*.cand", "_DM*.txtcand", ".cands"):
                for fn in sorted(glob.glob(base + pat)):
                    with open(fn, "rb") as f:
                        leg[os.path.basename(fn)] = f.read()
            arts.append(leg)
        assert arts[0] and set(arts[0]) == set(arts[1])
        diffs = [k for k in arts[0] if arts[0][k] != arts[1][k]]
        assert not diffs, f"tuned configs changed science: {diffs}"
        record["invariance"] = {
            "engine": "gather",
            "configs": cfgs,
            "artifacts_compared": len(arts[0]),
            "byte_identical": True,
        }
        print(f"# invariance: {len(arts[0])} artifacts byte-identical "
              f"across tuned configs (gather)")
        record["value"] = float(record["geometries"][-1]["stages"]
                                ["accel"]["speedup"])
        return record
    finally:
        knobs.clear_tuned()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(workdir, ignore_errors=True)


def run_compile(args):
    """Compilation-plane A/B (round 22, BENCH_r17_compile.json).

    Four legs; every claim is STRUCTURAL (compile-counter deltas, byte
    parity, span overlap) — walls are CPU-toy numbers unless a real
    chip is attached, labeled per the PR 10 convention:

    1. **cold vs warm** — the in-process CLI sweep at 3 toy
       geometries, each run twice into separate outdirs. The cold pass
       compiles; the warm pass at the SAME geometry must show
       ``compile.cache_miss == 0`` (the never-compile-twice gate) and
       byte-identical candidate tables.
    2. **bucket collapse** — two fold candidate-batch sizes (10 and
       12) land on ONE ``{2^k} U {3*2^k}`` ladder rung, so the second
       warm compiles nothing (the mixed-geometry headline, on the axis
       bucketing actually owns — the DM-range statics of a sweep are
       time-axis geometry, which is never padded). A bucketing-off
       rerun (``PYPULSAR_TPU_COMPILE_BUCKETS=0``) of geometry 2 must
       be byte-identical — padding is execution policy, never science.
    3. **persistent cross-process** — a child interpreter pointed at
       the same ``PYPULSAR_TPU_COMPILE_CACHE`` reruns geometry 1: its
       (process-cold) compiles must probe as ``compile.persistent_hit``
       and its artifacts must match the parent's bytes.
    4. **warm-pool overlap** — a 3-observation fleet with per-obs
       channel counts (a mixed-geometry fleet) and the scheduler warm
       pool on: some observation's ``survey.precompile`` span must
       overlap ANOTHER observation's device-stage span in the fleet
       trace — precompile rides spare host cycles, off the critical
       path.
    """
    acquire_backend()
    import glob as _glob
    import shutil
    import tempfile

    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.obs import telemetry
    from pypulsar_tpu.parallel.mesh import lease_devices
    from pypulsar_tpu.parallel.sweep import resolve_engine

    workdir = tempfile.mkdtemp(prefix="bench_compile_")
    saved_env = {k: os.environ.get(k)
                 for k in ("PYPULSAR_TPU_COMPILE_CACHE",
                           "PYPULSAR_TPU_COMPILE_BUCKETS")}
    # a FRESH persistent cache: the cold legs must actually compile
    # (set before the first plane dispatch — the cache dir latches
    # once per process)
    cache_dir = os.path.join(workdir, "xla")
    os.environ["PYPULSAR_TPU_COMPILE_CACHE"] = cache_dir
    os.environ.pop("PYPULSAR_TPU_COMPILE_BUCKETS", None)

    engine = resolve_engine(args.engine)
    dev = lease_devices()[0]
    on_tpu = getattr(dev, "platform", "cpu") == "tpu"
    C, T, dtp = 32, (1 << 13 if (args.quick or args.cpu_fallback)
                     else 1 << 14), 5e-4
    freqs = (1500.0 - 4.0 * np.arange(C)).astype(np.float64)
    record = {
        "metric": "compile_plane_ab", "unit": "see legs",
        "engine": engine,
        "backend": str(dev.device_kind if hasattr(dev, "device_kind")
                       else dev.platform),
        "wall_label": ("real-chip walls" if on_tpu else
                       "CPU-toy walls (structural gates are the claim: "
                       "zero warm-leg compiles + bucket collapse + "
                       "persistent cross-process hits + byte parity + "
                       "precompile span overlap)"),
        "geometries": [],
    }
    _DELTA_KEYS = ("compile.cache_miss", "compile.cache_hit",
                   "compile.persistent_hit", "compile.aot_fallback",
                   "compile.bucket_pad_rows", "compile.ms")

    def sweep_argv(base, numdms):
        return [fil, "-o", base, "--lodm", "0", "--dmstep", "10",
                "--numdms", str(numdms), "-s", "8", "--group-size", "4",
                "--threshold", "8", "--engine", engine]

    def read_arts(outdir):
        arts = {}
        for fn in sorted(_glob.glob(os.path.join(outdir, "x*"))):
            with open(fn, "rb") as f:
                arts[os.path.basename(fn)] = f.read()
        return arts

    try:
        fil = _synth_survey_fil(os.path.join(workdir, "psr.fil"), 7, C,
                                T, dtp, freqs, "PSR_COMPILE")
        geometries = [{"name": "g1", "numdms": 8},
                      {"name": "g2", "numdms": 10},
                      {"name": "g3", "numdms": 12}]
        cold_wall = warm_wall = 0.0
        g1_arts = g2_arts = None
        with telemetry.session() as tlm:
            for geo in geometries:
                legs = {}
                arts = {}
                for leg in ("cold", "warm"):
                    outdir = os.path.join(workdir,
                                          f"{geo['name']}_{leg}")
                    os.makedirs(outdir)
                    base = os.path.join(outdir, "x")
                    c0 = dict(tlm.counter_totals())
                    t0 = time.perf_counter()
                    rc = cli_sweep.main(sweep_argv(base, geo["numdms"]))
                    wall = time.perf_counter() - t0
                    c1 = dict(tlm.counter_totals())
                    assert rc == 0, f"{geo['name']} {leg} leg rc={rc}"
                    legs[leg] = {"wall_s": round(wall, 3)}
                    legs[leg].update(
                        {k: round(c1.get(k, 0) - c0.get(k, 0), 1)
                         for k in _DELTA_KEYS})
                    arts[leg] = read_arts(outdir)
                # the warm-leg contract: a previously-seen geometry
                # never compiles on the critical path
                assert legs["warm"]["compile.cache_miss"] == 0, \
                    f"{geo['name']}: warm leg compiled " \
                    f"({legs['warm']['compile.cache_miss']} misses)"
                assert legs["warm"]["compile.cache_hit"] >= 1, \
                    f"{geo['name']}: warm leg never hit the registry"
                assert arts["cold"] and arts["cold"] == arts["warm"], \
                    f"{geo['name']}: cold/warm artifacts diverged"
                if geo["name"] == "g1":
                    g1_arts = arts["cold"]
                if geo["name"] == "g2":
                    g2_arts = arts["cold"]
                cold_wall += legs["cold"]["wall_s"]
                warm_wall += legs["warm"]["wall_s"]
                print(f"# compile[{geo['name']}] numdms="
                      f"{geo['numdms']}: cold "
                      f"{legs['cold']['compile.cache_miss']:.0f} "
                      f"compiles ({legs['cold']['compile.ms']:.0f} ms), "
                      f"warm 0 compiles / "
                      f"{legs['warm']['compile.cache_hit']:.0f} hits, "
                      f"{len(arts['cold'])} artifacts byte-identical",
                      file=sys.stderr)
                record["geometries"].append(
                    dict(geo, legs=legs,
                         artifacts_identical=len(arts["cold"])))
        # ---- bucket-collapse leg: two candidate-batch sizes, one
        # ladder rung, zero second compiles (through the production
        # warm-pool entry point) ----
        import pypulsar_tpu.fold.engine  # noqa: F401 - registers warmer
        from pypulsar_tpu.compile import bucket_rows, warm_stage

        fold_geo = dict(n_samples=T, downsamp=1, fold_nbins=32,
                        fold_npart=8)
        assert bucket_rows(10) == bucket_rows(12) == 12
        with telemetry.session() as tlm:
            n1 = warm_stage("fold", fold_batch=10, **fold_geo)
            c_mid = dict(tlm.counter_totals())
            n2 = warm_stage("fold", fold_batch=12, **fold_geo)
            c_end = dict(tlm.counter_totals())
        assert n1 >= 1, "first fold warm compiled nothing"
        assert n2 == 0 and (c_end.get("compile.cache_miss", 0)
                            == c_mid.get("compile.cache_miss", 0)), (
            "bucket ladder failed to collapse fold batches 10 and 12 "
            "onto one executable")
        record["bucket_collapse"] = {
            "axis": "fold candidate batch", "batch_sizes": [10, 12],
            "ladder_rows": 12, "first_warm_compiles": int(n1),
            "second_warm_compiles": 0}
        print("# compile[collapse]: fold batches 10 and 12 -> one "
              "12-row executable (second warm compiled nothing)",
              file=sys.stderr)

        # bucketing is runtime policy, not science: geometry 2 with the
        # ladder off is byte-identical (its unpadded shapes may compile)
        os.environ["PYPULSAR_TPU_COMPILE_BUCKETS"] = "0"
        try:
            outdir = os.path.join(workdir, "g2_nobuckets")
            os.makedirs(outdir)
            rc = cli_sweep.main(sweep_argv(os.path.join(outdir, "x"), 10))
            assert rc == 0, f"no-buckets leg rc={rc}"
            nb_arts = read_arts(outdir)
        finally:
            os.environ.pop("PYPULSAR_TPU_COMPILE_BUCKETS", None)
        assert nb_arts == g2_arts, \
            "bucketing changed artifact bytes (science regression)"
        record["bucket_invariance"] = {
            "geometry": "g2", "artifacts_compared": len(nb_arts),
            "byte_identical": True}

        # ---- persistent cross-process leg ----
        child_dir = os.path.join(workdir, "child")
        os.makedirs(child_dir)
        child_argv = sweep_argv(os.path.join(child_dir, "x"), 8)
        child_src = (
            "import json, sys\n"
            "from pypulsar_tpu.obs import telemetry\n"
            "from pypulsar_tpu.cli import sweep as cli_sweep\n"
            "with telemetry.session() as tlm:\n"
            "    rc = cli_sweep.main(%r)\n"
            "    print('COMPILE_TOTALS '"
            " + json.dumps(tlm.counter_totals()))\n"
            "sys.exit(rc)\n" % (child_argv,))
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.abspath(__file__)) + os.pathsep
            + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        proc = subprocess.run([sys.executable, "-c", child_src], env=env,
                              capture_output=True, text=True,
                              timeout=1800)
        assert proc.returncode == 0, \
            f"persistent-cache child rc={proc.returncode}: " \
            f"{proc.stderr[-2000:]}"
        totals = json.loads(
            [ln for ln in proc.stdout.splitlines()
             if ln.startswith("COMPILE_TOTALS ")][-1]
            [len("COMPILE_TOTALS "):])
        assert totals.get("compile.persistent_hit", 0) >= 1, (
            f"child process saw no persistent-cache hits "
            f"({ {k: v for k, v in totals.items() if k.startswith('compile.')} })")
        assert read_arts(child_dir) == g1_arts, \
            "cross-process artifacts diverged"
        record["persistent_cross_process"] = {
            "cache_dir_shared": True,
            "child_persistent_hits":
                int(totals.get("compile.persistent_hit", 0)),
            "child_compiles": int(totals.get("compile.cache_miss", 0)),
            "artifacts_identical": len(g1_arts),
        }
        print(f"# compile[persistent]: child process "
              f"{int(totals.get('compile.persistent_hit', 0))} "
              f"persistent hit(s) over "
              f"{int(totals.get('compile.cache_miss', 0))} compiles, "
              f"{len(g1_arts)} artifacts byte-identical",
              file=sys.stderr)

        # ---- warm-pool overlap leg (a mixed-geometry fleet) ----
        from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
        from pypulsar_tpu.survey.scheduler import FleetScheduler
        from pypulsar_tpu.survey.state import Observation

        n_obs = 3
        cfg = SurveyConfig(
            mask=False, lodm=0.0, dmstep=10.0, numdms=8, nsub=8,
            group_size=4, threshold=8.0, accel_zmax=20.0,
            accel_numharm=2, accel_sigma=3.0, accel_batch=4,
            sift_sigma=3.0, sift_min_hits=1, fold_nbins=32,
            fold_npart=8)
        stages = build_dag(cfg)
        # per-obs channel counts: each observation's geometry keys its
        # own executables, so every precompile does real work
        fleet_out = os.path.join(workdir, "fleet")
        os.makedirs(fleet_out)
        obs = []
        for i, Ci in enumerate((24, 32, 48)):
            fi = _synth_survey_fil(
                os.path.join(workdir, f"obs{i}.fil"), 11 + i, Ci, T,
                dtp, 1500.0 - 4.0 * np.arange(Ci), f"CMP{i}",
                period=0.1024 * (1.0 + 0.07 * i))
            obs.append(Observation(f"obs{i}", fi,
                                   os.path.join(fleet_out, f"obs{i}")))
        tlm_dir = os.path.join(workdir, "tlm")
        with telemetry.session() as tlm:
            result = FleetScheduler(obs, cfg, max_host_workers=2,
                                    devices=1,
                                    telemetry_dir=tlm_dir).run()
            fleet_totals = dict(tlm.counter_totals())
        assert result.ok and len(result.ran) == n_obs * len(stages), \
            f"fleet failed: ran {len(result.ran)}, " \
            f"failed {result.failed}"
        assert fleet_totals.get("survey.precompiled", 0) >= 1, \
            "warm pool precompiled nothing"
        # device-LANE stages by declaration ("dev" span attrs only
        # appear at devices>1, where stages pin explicitly)
        dev_names = {f"survey.stage.{s.name}" for s in stages
                     if s.device_bound}
        pre_spans, dev_spans = [], []
        for p in sorted(_glob.glob(os.path.join(tlm_dir, "*.jsonl"))):
            o = os.path.basename(p)[:-len(".jsonl")]
            with open(p) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("type") != "span":
                        continue
                    t0, t1 = rec.get("t", 0), \
                        rec.get("t", 0) + rec.get("dur", 0)
                    if rec.get("name") == "survey.precompile":
                        pre_spans.append((o, t0, t1))
                    elif rec.get("name") in dev_names:
                        dev_spans.append((o, t0, t1, rec["name"]))
        overlaps = [
            {"precompile_obs": po, "device_obs": do, "device_span": dn,
             "overlap_s": round(min(p1, d1) - max(p0, d0), 3)}
            for (po, p0, p1) in pre_spans
            for (do, d0, d1, dn) in dev_spans
            if po != do and p0 < d1 and d0 < p1]
        assert overlaps, (
            f"no survey.precompile span overlapped another "
            f"observation's device span (precompile spans: "
            f"{pre_spans}; device spans: {dev_spans[:6]})")
        best = max(overlaps, key=lambda d: d["overlap_s"])
        record["warm_pool"] = {
            "n_obs": n_obs,
            "nchan_per_obs": [24, 32, 48],
            "precompiled_executables":
                int(fleet_totals.get("survey.precompiled", 0)),
            "precompile_spans": len(pre_spans),
            "off_critical_path_overlaps": len(overlaps),
            "example_overlap": best,
        }
        print(f"# compile[warm-pool]: {len(pre_spans)} precompile "
              f"span(s), {len(overlaps)} overlap(s) with another "
              f"observation's device span (best {best['overlap_s']}s: "
              f"{best['precompile_obs']} warmed during "
              f"{best['device_obs']}'s {best['device_span']})",
              file=sys.stderr)

        record["value"] = round(cold_wall / max(warm_wall, 1e-9), 3)
        record["vs_baseline"] = record["value"]
        record["unit"] = (
            "cold-vs-warm wall ratio across 3 toy geometries (the "
            "structural gates are the claim: warm legs compile "
            "nothing, the bucket ladder collapses nearby DM counts "
            "onto one executable, a second process hits the shared "
            "persistent cache byte-identically, and fleet precompile "
            "overlaps another observation's device work)")
        if args.cpu_fallback:
            record["unit"] += \
                " [CPU FALLBACK: accelerator backend unavailable]"
        return record
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(workdir, ignore_errors=True)


def run_child(args, cpu: bool, timeout: float):
    """Run the measurement in a child interpreter; return its JSON record.

    The accelerator attempt keeps the full environment; the CPU attempt pins
    ``JAX_PLATFORMS=cpu`` and strips the axon sitecustomize trigger vars so
    the child cannot touch (or hang on) the TPU tunnel at interpreter start.
    A child is the only way to bound a backend that hangs instead of raising
    — ``jax.devices()`` on a wedged tunnel blocks in native code."""
    env = dict(os.environ)
    argv = [sys.executable, os.path.abspath(__file__), "--child"]
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
        for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
            env.pop(var, None)
        argv.append("--cpu-fallback")
    # the CPU fallback writes its trace NEXT TO the primary's, never over
    # it: the primary child may have died mid-run, and its partial trace
    # (flushed per record) is exactly the forensic artifact to preserve
    tlm_path = args.telemetry
    if cpu and tlm_path:
        tlm_path += ".cpufallback.jsonl"
    for flag, val in (("--trials", args.trials), ("--nchan", args.nchan),
                      ("--nsamp", args.nsamp), ("--batch", args.batch),
                      ("--baseline-trials", args.baseline_trials),
                      ("--telemetry", tlm_path)):
        if val is not None:
            argv += [flag, str(val)]
    argv += ["--dm-max", str(args.dm_max), "--engine", args.engine]
    if args.devices != 1:
        argv += ["--devices", str(args.devices)]
    if args.stream and not cpu:  # a CPU 1-hr streamed sweep is infeasible
        argv += ["--stream", args.stream]
        if args.stream_window is not None:
            argv += ["--stream-window", str(args.stream_window)]
    if args.tune and args.tune_trials is not None:
        argv += ["--tune-trials", str(args.tune_trials)]
    for flag in ("quick", "profile", "ab", "accel", "spectral", "fold",
                 "waterfall", "prepass", "survey", "broker", "candplane",
                 "chaos", "corruption", "dedisp_tree", "tune", "compile",
                 "multihost", "race", "obs_overhead", "daemon_soak"):
        if getattr(args, flag):
            argv.append("--" + flag.replace("_", "-"))
    if args.race:
        argv += ["--race-seeds", str(args.race_seeds)]
    if args.multihost:
        # the child writes the host-kill record itself; resolve the
        # paths NOW so the child's CWD cannot move them
        argv += ["--hostchaos-out", os.path.abspath(args.hostchaos_out)]
        argv += ["--trace-out", os.path.abspath(args.trace_out)
                 if args.trace_out else ""]
    if args.corruption:
        argv += ["--corruption-seed", str(args.corruption_seed)]
    if args.chaos or args.daemon_soak:
        argv += ["--chaos-seed", str(args.chaos_seed)]
        if args.chaos_rate is not None:
            argv += ["--chaos-rate", str(args.chaos_rate)]
    proc = subprocess.run(argv, env=env, capture_output=True, text=True,
                          timeout=timeout)
    sys.stderr.write(proc.stderr[-6000:])
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    raise RuntimeError(f"bench child produced no JSON (rc={proc.returncode})")


DEFAULT_STREAM_FIL = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "data", "northstar_1hr.fil")


def _emit_record(args, record) -> None:
    """Print the final JSON record and, with --out, write the identical
    line to the file (one serialization for both the child and parent
    exit paths)."""
    line = json.dumps(record)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)


def main():
    args = parse_args()
    if (args.stream is None and not args.child
            and not (args.quick or args.ab or args.accel or args.fold
                     or args.waterfall or args.prepass or args.survey
                     or args.broker or args.candplane
                     or args.chaos or args.corruption or args.dedisp_tree or args.tune
                     or args.compile or args.multihost or args.race
                     or args.obs_overhead or args.daemon_soak
                     or args.cpu_fallback or args.nsamp or args.nchan)
            and os.path.exists(DEFAULT_STREAM_FIL)):
        # the north-star workload exists on disk: measure THAT (streamed,
        # I/O included) rather than the device-resident 71-s segment.
        # Unattended runs bound the window so the driver's bench stays
        # ~15 min; the full-hour measurement is recorded in BENCHNOTES.md
        args.stream = DEFAULT_STREAM_FIL
        if args.stream_window is None:
            args.stream_window = float(
                os.environ.get("BENCH_STREAM_WINDOW_S", 900.0))
    if args.child:
        # measurement mode: run in this interpreter, print JSON, propagate
        # rc. With --telemetry the whole measured run records an obs trace
        # whose final counter totals (H2D/D2H bytes, chunks dispatched,
        # pipeline depth) land in the JSON extras — byte-level evidence
        # alongside the wall-clock metric.
        from pypulsar_tpu.obs import telemetry

        with telemetry.session_from_flag(args.telemetry,
                                         tool="bench") as tlm:
            if args.tune:
                record = run_tune(args)
            elif args.compile:
                record = run_compile(args)
            elif args.ab:
                record = run_ab(args)
            elif args.dedisp_tree:
                record = run_dedisp_tree(args)
            elif args.accel and args.spectral:
                record = run_specfuse(args)
            elif args.accel:
                record = run_accel(args)
            elif args.fold:
                record = run_fold(args)
            elif args.waterfall:
                record = run_waterfall(args)
            elif args.obs_overhead:
                record = run_obs_overhead(args)
            elif args.survey:
                record = run_survey(args)
            elif args.broker:
                record = run_broker(args)
            elif args.candplane:
                record = run_candplane(args)
            elif args.multihost:
                record = run_multihost(args)
            elif args.race:
                record = run_race(args)
            elif args.chaos:
                record = run_chaos(args)
            elif args.daemon_soak:
                record = run_daemon_soak(args)
            elif args.corruption:
                record = run_corruption(args)
            elif args.prepass:
                record = run_prepass(args)
            elif args.stream:
                try:
                    record = run_stream(args)
                except Exception as e:  # noqa: BLE001 - resident measures
                    print(f"# streamed bench failed ({type(e).__name__}: "
                          f"{str(e)[:300]}); falling back to the resident "
                          f"workload", file=sys.stderr)
                    record = run_benchmark(args)
            else:
                record = run_benchmark(args)
            if tlm is not None:
                record["telemetry_jsonl"] = args.telemetry
                record["telemetry_counters"] = {
                    k: round(v, 1) for k, v in
                    sorted(tlm.counter_totals().items())}
                gauges = tlm.gauge_values()
                if gauges:
                    record["telemetry_gauges"] = gauges
        _emit_record(args, record)
        return
    record = None
    try:
        if not probe_backend():
            raise RuntimeError(
                "accelerator liveness probe failed (wedged tunnel?)")
        record = run_child(args, cpu=False,
                           timeout=7200 if args.stream else 2400)
    except Exception as e:  # noqa: BLE001 - the JSON line must happen
        print(f"# benchmark failed on primary backend: {type(e).__name__}: {e}",
              file=sys.stderr)
        try:
            record = run_child(args, cpu=True, timeout=1800)
        except Exception as e2:  # noqa: BLE001
            print(f"# cpu fallback failed too: {type(e2).__name__}: {e2}",
                  file=sys.stderr)
    if record is None:
        record = {
            "metric": "dm_trials_per_sec",
            "value": 0.0,
            "unit": "DM-trials/s [FAILED: no backend produced a measurement]",
            "vs_baseline": 0.0,
        }
    _emit_record(args, record)


if __name__ == "__main__":
    main()
