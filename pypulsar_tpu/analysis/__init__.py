"""``pypulsar_tpu.analysis`` — psrlint, the project-invariant static
analyzer (docs/ARCHITECTURE.md "Static analysis").

Each rule locks in a bug class a past PR fixed by hand; the catalog
lives in :mod:`pypulsar_tpu.analysis.rules`, the engine (AST walk,
suppressions, select/ignore, JSON report) in
:mod:`pypulsar_tpu.analysis.engine`.  The analysis modules themselves
use only the stdlib (``ast`` + ``tokenize``) — no jax/numpy dependency
of their own, though reaching them via ``pypulsar_tpu.cli`` still runs
the normal parent-package import.

>>> from pypulsar_tpu.analysis import run_psrlint
>>> report = run_psrlint(["pypulsar_tpu"], root=".")
>>> report.findings
[]
"""

from __future__ import annotations

from typing import Optional, Sequence

from pypulsar_tpu.analysis.engine import (  # noqa: F401
    Finding, Report, run,
)
from pypulsar_tpu.analysis.rules import ALL_RULES, all_rules  # noqa: F401

__all__ = ["Finding", "Report", "run_psrlint", "all_rules", "ALL_RULES"]


def run_psrlint(paths: Sequence[str], root: str,
                readme_path: Optional[str] = None,
                select: Optional[str] = None,
                ignore: Optional[str] = None,
                baseline: Optional[dict] = None,
                project_paths: Optional[Sequence[str]] = None) -> Report:
    """Run the full rule catalog over ``paths`` (repo-relative unless
    absolute).  ``readme_path`` defaults to ``<root>/README.md`` when
    present (the PL004 registry side); pass ``project_paths`` (the full
    default scope) when ``paths`` is a subset so cross-file rules keep
    whole-tree context."""
    import os

    if readme_path is None:
        cand = os.path.join(root, "README.md")
        readme_path = cand if os.path.exists(cand) else None
    return run(all_rules(), paths, root, readme_path=readme_path,
               select=select, ignore=ignore, baseline=baseline,
               project_paths=project_paths)
