"""psrlint's rule engine: file loading, AST parenting, suppressions.

The project grows a recurring-bug-class lint (docs/ARCHITECTURE.md
"Static analysis") because PRs 3/6/7/8 each ended with a by-hand audit
for a defect family the next PR could silently reintroduce.  Rules are
plain classes over the stdlib ``ast`` module — no third-party parser,
and the analysis modules themselves add no jax/numpy dependency (the
CLI route still performs the normal parent-package import).

Two rule shapes:

- :class:`Rule` — per-file; ``check(ctx)`` yields findings for one
  parsed file.
- :class:`ProjectRule` — cross-file; ``check_project(project)`` sees
  every parsed file at once (knob-registry drift, dead fault points).

Suppressions are per-line ``# psrlint: ignore[PL003]`` comments (comma
lists allowed; trailing justification text encouraged).  A suppression
that silences nothing is itself reported (PL010) so stale exemptions
cannot accrete — the same drift the knob rule exists to stop.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "FileContext", "ProjectContext", "Rule", "ProjectRule",
    "Report", "collect_files", "load_context", "run",
]

_SUPPRESS_RE = re.compile(r"#\s*psrlint:\s*ignore\[([A-Za-z0-9_,\s]+)\]")

# engine-level pseudo-rules (never in a rule registry)
PARSE_ERROR = "PL100"
UNUSED_SUPPRESSION = "PL010"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a file:line:col."""
    rule: str
    path: str            # repo-relative, '/'-separated
    line: int
    col: int
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class FileContext:
    """One parsed source file + lazy parent links + suppression table."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:  # a lint gate must report, not crash
            self.parse_error = e
        self._parents: Optional[Dict[ast.AST, Tuple[ast.AST, str]]] = None
        # {line: {code, ...}} parsed from comment tokens, not substring
        # scans, so a string literal containing the marker is inert
        self.suppressions: Dict[int, Set[str]] = _scan_suppressions(source)

    # -- parent links -------------------------------------------------
    @property
    def parents(self) -> Dict[ast.AST, Tuple[ast.AST, str]]:
        """child node -> (parent node, field name on the parent)."""
        if self._parents is None:
            table: Dict[ast.AST, Tuple[ast.AST, str]] = {}
            if self.tree is not None:
                for parent in ast.walk(self.tree):
                    for field, value in ast.iter_fields(parent):
                        for child in (value if isinstance(value, list)
                                      else [value]):
                            if isinstance(child, ast.AST):
                                table[child] = (parent, field)
            self._parents = table
        return self._parents

    def walk(self) -> Iterable[ast.AST]:
        return ast.walk(self.tree) if self.tree is not None else ()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def _scan_suppressions(source: str) -> Dict[int, Set[str]]:
    import io as _io

    table: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(_io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                codes = {c.strip().upper() for c in m.group(1).split(",")
                         if c.strip()}
                table.setdefault(tok.start[0], set()).update(codes)
    except (tokenize.TokenError, SyntaxError):
        # IndentationError/SyntaxError included: the PL100 parse-error
        # finding already covers a broken file — never crash the gate
        pass
    return table


class ProjectContext:
    """Everything a cross-file rule may see: parsed files + the docs
    that participate in registry-drift checks (README knob table)."""

    def __init__(self, root: str, contexts: Sequence[FileContext],
                 readme_path: Optional[str] = None):
        self.root = root
        self.contexts = list(contexts)
        self.readme_path = readme_path
        self.readme_text: Optional[str] = None
        self.readme_rel: Optional[str] = None
        if readme_path and os.path.exists(readme_path):
            with open(readme_path, encoding="utf-8", errors="replace") as f:
                self.readme_text = f.read()
            self.readme_rel = os.path.relpath(
                readme_path, root).replace(os.sep, "/")


class Rule:
    """Base per-file rule. Subclasses set ``code``/``name``/``summary``
    and implement :meth:`check`."""

    code: str = "PL000"
    name: str = "base"
    summary: str = ""

    def applies_to(self, ctx: FileContext) -> bool:
        return True

    def check(self, ctx: FileContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, ctx: FileContext, node, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        return Finding(self.code, ctx.relpath, line, col, message)


class ProjectRule(Rule):
    """Cross-file rule: sees the whole project once."""

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, project: ProjectContext
                      ) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


@dataclasses.dataclass
class Report:
    findings: List[Finding]
    files_scanned: int
    rules_run: List[str]

    @property
    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> str:
        return json.dumps({
            "files": self.files_scanned,
            "rules": self.rules_run,
            "counts": self.counts,
            "findings": [f.to_dict() for f in self.findings],
        }, indent=2, sort_keys=True)

    def to_text(self) -> str:
        out = [f.render() for f in self.findings]
        tail = (f"{len(self.findings)} finding(s) in "
                f"{self.files_scanned} file(s)"
                if self.findings else
                f"clean: {self.files_scanned} file(s), "
                f"{len(self.rules_run)} rule(s)")
        return "\n".join(out + [tail])


def collect_files(paths: Sequence[str], root: str) -> List[str]:
    """Expand dirs to ``**/*.py`` (sorted, __pycache__/fixtures
    skipped); keep explicit .py files as given."""
    out: List[str] = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", "fixtures"))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif full.endswith(".py") and os.path.exists(full):
            out.append(full)
    seen: Set[str] = set()
    uniq = []
    for f in out:
        if f not in seen:
            seen.add(f)
            uniq.append(f)
    return uniq


def load_context(path: str, root: str) -> FileContext:
    with open(path, encoding="utf-8", errors="replace") as f:
        source = f.read()
    return FileContext(path, os.path.relpath(path, root), source)


def _parse_codes(spec: Optional[str]) -> Optional[Set[str]]:
    if not spec:
        return None
    return {c.strip().upper() for c in spec.split(",") if c.strip()}


def run(rules: Sequence[Rule], paths: Sequence[str], root: str,
        readme_path: Optional[str] = None,
        select: Optional[str] = None, ignore: Optional[str] = None,
        baseline: Optional[dict] = None,
        project_paths: Optional[Sequence[str]] = None) -> Report:
    """Run ``rules`` over ``paths``; return a :class:`Report`.

    ``select``/``ignore`` are comma lists of rule codes (select wins
    first, then ignore removes).  ``baseline`` is the checked-in
    known-violations dict ({rule: [{path, line}]}); matching findings
    are dropped so a gate can be landed before its debt is paid —
    this repo's committed baseline is empty and stays that way.

    ``project_paths`` is the FULL scope cross-file rules reason over
    (defaults to ``paths``).  When a caller scans a subset (one file in
    an editor hook), pass the whole default scope here: registry-drift
    and dead-point rules are only meaningful against the entire tree,
    and a partial view would report the unscanned remainder as drift.
    Cross-file findings are still clipped to the scanned files (plus
    the README), so a single-file run stays about that file.
    """
    selected = _parse_codes(select)
    ignored = _parse_codes(ignore) or set()
    active = [r for r in rules
              if (selected is None or r.code in selected)
              and r.code not in ignored]
    active_codes = {r.code for r in active}
    run_unused = (UNUSED_SUPPRESSION not in ignored
                  and (selected is None or UNUSED_SUPPRESSION in selected))

    files = collect_files(paths, root)
    contexts = [load_context(f, root) for f in files]
    scanned = {c.relpath for c in contexts}
    proj_contexts = contexts
    # the whole-tree parse is only worth paying when a cross-file rule
    # actually runs (a --select PL007 single-file hook stays O(1 file))
    if project_paths is not None and any(
            isinstance(r, ProjectRule) for r in active):
        by_rel_all = {c.relpath: c for c in contexts}
        for f in collect_files(project_paths, root):
            rel = os.path.relpath(f, root).replace(os.sep, "/")
            if rel not in by_rel_all:
                c = load_context(f, root)
                by_rel_all[c.relpath] = c
        proj_contexts = list(by_rel_all.values())
    project = ProjectContext(root, proj_contexts, readme_path=readme_path)

    raw: List[Finding] = []
    for ctx in contexts:
        if ctx.parse_error is not None:
            e = ctx.parse_error
            raw.append(Finding(PARSE_ERROR, ctx.relpath, e.lineno or 1,
                               (e.offset or 0) + 1,
                               f"syntax error: {e.msg}"))
            continue
        for rule in active:
            if rule.applies_to(ctx):
                raw.extend(rule.check(ctx))
    readme_rel = project.readme_rel or "README.md"
    for rule in active:
        if isinstance(rule, ProjectRule):
            raw.extend(f for f in rule.check_project(project)
                       if f.path in scanned or f.path == readme_rel)

    # -- suppressions -------------------------------------------------
    by_rel: Dict[str, FileContext] = {c.relpath: c for c in proj_contexts}
    used: Set[Tuple[str, int, str]] = set()
    kept: List[Finding] = []
    for f in raw:
        ctx = by_rel.get(f.path)
        codes = ctx.suppressions.get(f.line, set()) if ctx else set()
        if f.rule in codes:
            used.add((f.path, f.line, f.rule))
        else:
            kept.append(f)

    if run_unused:
        for ctx in contexts:
            for line, codes in sorted(ctx.suppressions.items()):
                for code in sorted(codes):
                    # only meaningful for rules that actually ran
                    if code not in active_codes:
                        continue
                    if (ctx.relpath, line, code) not in used:
                        kept.append(Finding(
                            UNUSED_SUPPRESSION, ctx.relpath, line, 1,
                            f"unused suppression: ignore[{code}] "
                            f"matched no finding on this line"))

    if baseline:
        def _in_baseline(f: Finding) -> bool:
            for ent in baseline.get(f.rule, []):
                if ent.get("path") == f.path and ent.get("line") == f.line:
                    return True
            return False
        kept = [f for f in kept if not _in_baseline(f)]

    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return Report(kept, len(contexts), sorted(active_codes))
