"""psrlint's rule catalog — one rule per bug class this repo has
already paid to fix by hand.  Each docstring cites the PR that fixed
the class; the rule exists so the NEXT PR cannot reintroduce it.

Scopes are deliberate: a rule runs only where its invariant holds
(PL002 outside the lease registry, PL006 inside ``io/``, PL009 in the
resilience-adjacent modules), so a clean run means the invariant holds
where it matters, not that the rule was too timid to fire.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pypulsar_tpu.analysis.engine import (
    FileContext, Finding, ProjectContext, ProjectRule, Rule,
)

__all__ = ["ALL_RULES", "all_rules"]


# ---------------------------------------------------------------------------
# shared helpers

def _is_test(ctx: FileContext) -> bool:
    return (ctx.relpath.startswith("tests/")
            or ctx.relpath.rsplit("/", 1)[-1].startswith("test_"))


def _in_package(ctx: FileContext) -> bool:
    return ctx.relpath.startswith("pypulsar_tpu/")


def _call_name(node: ast.Call) -> str:
    """Dotted-ish name of a call target: 'os.environ.get', 'range'."""
    parts: List[str] = []
    cur = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# PL001 — py2 truediv feeding an index/size context

class TruedivIndexRule(Rule):
    """``x[a / b]`` / ``range(a / b)``: the reference's py2 heritage
    defect (PAPER.md; last hand-audit in PR 8's division sweep).  In
    py3 ``/`` is float division, so an index/size built from it either
    crashes or — worse, via downstream ``int()`` — silently truncates
    differently than the py2 original.  Use ``//``.

    Contexts covered: subscript indices/slice bounds and direct
    ``range(...)`` arguments.  Climbing stops at any other call
    boundary (``a[int(x / y)]`` is an explicit, visible coercion)."""

    code = "PL001"
    name = "py2-truediv-index"
    summary = "true division feeding an index/size context; use //"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        parents = ctx.parents
        for node in ctx.walk():
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Div)):
                continue
            cur = node
            while True:
                parent_entry = parents.get(cur)
                if parent_entry is None:
                    break
                parent, field = parent_entry
                if isinstance(parent, ast.Call):
                    if (isinstance(parent.func, ast.Name)
                            and parent.func.id == "range"
                            and field == "args"):
                        yield self.finding(
                            ctx, node,
                            "true division result used as a range() "
                            "bound; use // (py2-heritage defect)")
                    break
                if isinstance(parent, ast.Subscript) and field == "slice":
                    yield self.finding(
                        ctx, node,
                        "true division result used as a subscript "
                        "index; use // (py2-heritage defect)")
                    break
                if isinstance(parent, ast.stmt):
                    break
                cur = parent


# ---------------------------------------------------------------------------
# PL002 — bare jax.devices() outside the lease registry

class BareJaxDevicesRule(Rule):
    """``jax.devices()`` anywhere but ``parallel/mesh.py`` bypasses the
    gang-lease registry PR 6 introduced: a stage running under a lease
    that probes raw device 0 can address a chip another gang owns.
    Resolve through ``parallel.mesh.lease_devices()`` (lease first,
    then default_device, then local devices)."""

    code = "PL002"
    name = "bare-jax-devices"
    summary = "bare jax.devices() outside parallel/mesh.py"

    _EXEMPT = "pypulsar_tpu/parallel/mesh.py"

    def applies_to(self, ctx: FileContext) -> bool:
        if ctx.relpath == self._EXEMPT or _is_test(ctx):
            return False
        return (_in_package(ctx) or ctx.relpath.startswith("tools/")
                or ctx.relpath == "bench.py")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.walk():
            if (isinstance(node, ast.Call)
                    and _call_name(node) == "jax.devices"):
                yield self.finding(
                    ctx, node,
                    "bare jax.devices() bypasses the gang-lease "
                    "registry; use parallel.mesh.lease_devices() "
                    "(PR 6 invariant)")


# ---------------------------------------------------------------------------
# PL003 — non-atomic artifact write

_ARTIFACT_EXTS = (
    ".dat", ".inf", ".cand", ".cands", ".txtcand", ".pfd", ".fil",
    ".fits", ".sub", ".events", ".pulses", ".mask", ".json", ".jsonl",
)
_TMP_MARK = re.compile(r"\.tmp|tmp$|^tmp", re.IGNORECASE)
_OUT_NAME = re.compile(r"^(out|dest|dst)[a-z_]*$")


class NonAtomicWriteRule(Rule):
    """A resumable pipeline's artifacts are validated by size/sha256
    (PR 3): an ``open(path, 'w'/'wb')`` straight onto an artifact path
    leaves a torn file behind a kill that later validation may accept.
    Write ``path + '.tmp'`` and ``os.replace`` it, or use
    ``resilience.journal.atomic_write_bytes/_text``.

    Heuristic scope — flags a write-mode ``open`` whose path expression
    names an artifact extension or an out-ish variable, unless the path
    carries a tmp marker or the enclosing function calls
    ``os.replace`` (the tmp+rename idiom in place)."""

    code = "PL003"
    name = "non-atomic-artifact-write"
    summary = "write-mode open() on an artifact path without tmp+os.replace"

    def applies_to(self, ctx: FileContext) -> bool:
        return _in_package(ctx) and not _is_test(ctx)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        parents = ctx.parents
        replace_scopes = self._os_replace_scopes(ctx)
        for node in ctx.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "open" and node.args):
                continue
            mode = self._write_mode(node)
            if mode is None:
                continue
            path_expr = node.args[0]
            if not self._artifactish(path_expr):
                continue
            if self._tmp_marked(path_expr):
                continue
            if self._enclosing_function(node, parents) in replace_scopes:
                continue
            yield self.finding(
                ctx, node,
                f"open(..., {mode!r}) writes an artifact path in place; "
                "write a '.tmp' sibling and os.replace() it (or use "
                "resilience.journal.atomic_write_*) so a kill cannot "
                "leave a torn artifact (PR 3 invariant)")

    @staticmethod
    def _write_mode(node: ast.Call) -> Optional[str]:
        mode_node = None
        if len(node.args) >= 2:
            mode_node = node.args[1]
        for kw in node.keywords:
            if kw.arg == "mode":
                mode_node = kw.value
        mode = _const_str(mode_node)
        if mode and any(c in mode for c in "wax"):
            return mode
        return None

    @staticmethod
    def _artifactish(expr) -> bool:
        for sub in ast.walk(expr):
            s = _const_str(sub)
            if s and any(s.endswith(ext) or ext + "." in s
                         for ext in _ARTIFACT_EXTS):
                return True
            if isinstance(sub, ast.Name) and _OUT_NAME.match(sub.id):
                return True
        return False

    @staticmethod
    def _tmp_marked(expr) -> bool:
        for sub in ast.walk(expr):
            s = _const_str(sub)
            if s and _TMP_MARK.search(s):
                return True
            if isinstance(sub, ast.Name) and "tmp" in sub.id.lower():
                return True
        return False

    @staticmethod
    def _enclosing_function(node, parents):
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            entry = parents.get(cur)
            cur = entry[0] if entry else None
        return None

    def _os_replace_scopes(self, ctx: FileContext) -> Set[ast.AST]:
        scopes: Set[ast.AST] = set()
        parents = ctx.parents
        for node in ctx.walk():
            if (isinstance(node, ast.Call)
                    and _call_name(node) in ("os.replace", "os.rename")):
                fn = self._enclosing_function(node, parents)
                if fn is not None:
                    scopes.add(fn)
        return scopes


# ---------------------------------------------------------------------------
# PL004 — env-knob registry drift (code vs README "Runtime knobs")

_KNOB_RE = re.compile(r"PYPULSAR_TPU_[A-Z0-9_]+")


class KnobRegistryDriftRule(ProjectRule):
    """Every ``PYPULSAR_TPU_*`` env knob the code reads must have a row
    in the README "Runtime knobs" table, and every row must name a knob
    the code still reads (PR 7 added the table; PR 8's knobs drifted —
    an operator cannot tune what the registry does not list)."""

    code = "PL004"
    name = "knob-registry-drift"
    summary = "env knob missing from the README table (or vice versa)"

    _ENV_CALLS = ("os.environ.get", "environ.get", "os.getenv", "getenv")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        accesses: Dict[str, Tuple[str, int, int]] = {}
        for ctx in project.contexts:
            if _is_test(ctx):
                continue
            if not (_in_package(ctx) or ctx.relpath.startswith("tools/")
                    or ctx.relpath == "bench.py"):
                continue
            for name, node in self._env_reads(ctx):
                accesses.setdefault(
                    name, (ctx.relpath, node.lineno, node.col_offset + 1))

        if project.readme_text is None:
            return
        documented: Dict[str, int] = {}
        in_section = False
        for i, line in enumerate(project.readme_text.splitlines(), 1):
            if line.startswith("## "):
                in_section = line.strip().lower() == "## runtime knobs"
                continue
            if in_section and line.lstrip().startswith("|"):
                for m in _KNOB_RE.finditer(line):
                    documented.setdefault(m.group(0), i)

        for name in sorted(set(accesses) - set(documented)):
            path, line, col = accesses[name]
            yield Finding(
                self.code, path, line, col,
                f"env knob {name} is read here but has no row in the "
                f"README 'Runtime knobs' table (registry drift, PR 7/8)")
        for name in sorted(set(documented) - set(accesses)):
            yield Finding(
                self.code, project.readme_rel or "README.md",
                documented[name], 1,
                f"README 'Runtime knobs' documents {name} but no code "
                f"reads it (stale row, registry drift)")

    def _env_reads(self, ctx: FileContext):
        for node in ctx.walk():
            if isinstance(node, ast.Call):
                cn = _call_name(node)
                # os.environ/getenv plus the repo's typo-tolerant
                # env_float/env_int helpers (resilience.health)
                if ((cn in self._ENV_CALLS
                     or cn.split(".")[-1].startswith("env_"))
                        and node.args):
                    s = _const_str(node.args[0])
                    if s and s.startswith("PYPULSAR_TPU_"):
                        yield s, node
            elif isinstance(node, ast.Subscript):
                if (_attr_chain(node.value) in ("os.environ", "environ")):
                    s = _const_str(node.slice)
                    if s and s.startswith("PYPULSAR_TPU_"):
                        yield s, node
            elif isinstance(node, ast.Assign):
                # ENV_FAULTS = "PYPULSAR_TPU_FAULTS" constant bindings:
                # the binding site IS the knob's in-code registration
                # (the read goes through the constant).  Only the ENV_*
                # naming convention counts, and the value must be
                # EXACTLY one knob token — a doc/message string or a
                # stray constant that merely mentions a knob must not
                # mask real drift
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Name)
                            and tgt.id.startswith("ENV_")):
                        s = _const_str(node.value)
                        if s and _KNOB_RE.fullmatch(s):
                            yield s, node


def _attr_chain(node) -> str:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


# ---------------------------------------------------------------------------
# PL005 — fault-point literal in tests/bench with no defining trip site

_FAULT_KINDS = {"oom", "io", "kill", "exit", "hang", "device",
                "nanburst", "dropblock", "dcjump", "bitflip", "truncate"}
_POINT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_.]*$")


class DeadFaultPointRule(ProjectRule):
    """A fault spec in a test/bench naming a point no ``trip``/
    ``trip_data`` call site defines arms a fault that never fires: the
    test silently stops covering its failure path (the cousin of PR 7's
    ``configure()`` chaos-wipe bug).  A point counts as defined by a
    production literal, a production f-string prefix/suffix (dynamic
    stage points), a ``*POINT*`` string constant, or a trip call in the
    referencing test file itself (machinery self-tests)."""

    code = "PL005"
    name = "dead-fault-point"
    summary = "fault-point literal with no defining trip()/trip_data() site"

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        exact: Set[str] = set()
        prefixes: Set[str] = set()
        suffixes: Set[str] = set()
        per_file_exact: Dict[str, Set[str]] = {}
        per_file_prefix: Dict[str, Set[str]] = {}

        for ctx in project.contexts:
            fe, fp, fs = self._defined_points(ctx)
            if _in_package(ctx) and not _is_test(ctx):
                exact |= fe
                prefixes |= fp
                suffixes |= fs
            per_file_exact[ctx.relpath] = fe
            per_file_prefix[ctx.relpath] = fp

        for ctx in project.contexts:
            if not (_is_test(ctx) or ctx.relpath == "bench.py"):
                continue
            for point, node in self._referenced_points(ctx):
                if point in exact or point in per_file_exact[ctx.relpath]:
                    continue
                if any(point.startswith(p) for p in
                       prefixes | per_file_prefix[ctx.relpath] if p):
                    continue
                if any(point.endswith(s) for s in suffixes if s):
                    continue
                yield self.finding(
                    ctx, node,
                    f"fault point '{point}' is armed/inspected here but "
                    f"no trip()/trip_data() call site defines it — the "
                    f"fault can never fire (dead chaos coverage)")

    # -- definitions --------------------------------------------------
    def _defined_points(self, ctx: FileContext
                        ) -> Tuple[Set[str], Set[str], Set[str]]:
        exact: Set[str] = set()
        prefixes: Set[str] = set()
        suffixes: Set[str] = set()
        for node in ctx.walk():
            if isinstance(node, ast.Call):
                cn = _call_name(node)
                if cn.split(".")[-1] in ("trip", "trip_data") and node.args:
                    arg = node.args[0]
                    s = _const_str(arg)
                    if s is not None:
                        exact.add(s)
                    elif isinstance(arg, ast.JoinedStr) and arg.values:
                        first, last = arg.values[0], arg.values[-1]
                        fs = _const_str(first)
                        ls = _const_str(last)
                        if fs:
                            prefixes.add(fs)
                        elif ls:
                            suffixes.add(ls)
            elif isinstance(node, ast.Assign):
                # FAULT_POINT = "data.block" style registered constants,
                # plus FAULT_POINTS = ("a.b", "c.d") tuple/list registries
                # (round 24: the broker publishes its points as a tuple)
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Name)
                            and "POINT" in tgt.id):
                        continue
                    s = _const_str(node.value)
                    if s:
                        exact.add(s)
                    elif isinstance(node.value, (ast.Tuple, ast.List)):
                        for elt in node.value.elts:
                            es = _const_str(elt)
                            if es:
                                exact.add(es)
        return exact, prefixes, suffixes

    # -- references ---------------------------------------------------
    def _referenced_points(self, ctx: FileContext):
        seen: Set[Tuple[str, int]] = set()
        for node in ctx.walk():
            if isinstance(node, ast.Call):
                cn = _call_name(node)
                if cn.split(".")[-1] == "hits" and node.args:
                    s = _const_str(node.args[0])
                    if s and _POINT_RE.match(s):
                        key = (s, node.lineno)
                        if key not in seen:
                            seen.add(key)
                            yield s, node
            s = _const_str(node)
            if s is None:
                continue
            for part in s.split(","):
                fields = part.strip().split(":")
                if len(fields) < 2 or fields[0] not in _FAULT_KINDS:
                    continue
                if len(fields) >= 3 and not fields[2].isdigit():
                    continue
                point = fields[1]
                if not _POINT_RE.match(point):
                    continue
                key = (point, node.lineno)
                if key not in seen:
                    seen.add(key)
                    yield point, node


# ---------------------------------------------------------------------------
# PL006 — raw header reads in io/ bypassing read_exact

class RawHeaderReadRule(Rule):
    """``struct.unpack(fmt, f.read(n))`` trusts a short read: at EOF
    ``read`` returns ``b''`` and unpack raises a bare struct.error with
    no path/offset — the exact failure shape PR 8's DataFormatError
    taxonomy (``io/errors.py``) exists to locate.  Use
    ``read_exact(f, n, path, what)``.  Same for ``.read(n).decode()``
    header chains."""

    code = "PL006"
    name = "raw-header-read"
    summary = "struct.unpack / .read().decode() bypassing read_exact"

    def applies_to(self, ctx: FileContext) -> bool:
        return (ctx.relpath.startswith("pypulsar_tpu/io/")
                and ctx.relpath != "pypulsar_tpu/io/errors.py")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            cn = _call_name(node)
            if cn.split(".")[-1] in ("unpack", "unpack_from") \
                    and cn.split(".")[0] == "struct":
                if any(self._is_read_call(sub)
                       for a in node.args for sub in ast.walk(a)):
                    yield self.finding(
                        ctx, node,
                        "struct.unpack over a raw .read(): a short read "
                        "at EOF raises an unlocated struct.error — use "
                        "io.errors.read_exact (PR 8 taxonomy)")
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "decode"
                    and self._is_read_call(node.func.value)):
                yield self.finding(
                    ctx, node,
                    ".read(n).decode() header chain trusts a short "
                    "read — use io.errors.read_exact (PR 8 taxonomy)")

    @staticmethod
    def _is_read_call(node) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "read"
                and bool(node.args))


# ---------------------------------------------------------------------------
# PL007 — mutable default argument

class MutableDefaultRule(Rule):
    """A ``def f(x, acc=[])`` default is created once and shared across
    calls — in a fleet runtime that means cross-observation state
    bleed.  Default to ``None`` and materialize inside."""

    code = "PL007"
    name = "mutable-default-argument"
    summary = "mutable default argument ([], {}, set(), ...)"

    _MUTABLE_CALLS = {"list", "dict", "set", "bytearray", "defaultdict",
                      "OrderedDict", "Counter", "deque"}

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.walk():
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                if self._mutable(d):
                    name = getattr(node, "name", "<lambda>")
                    yield self.finding(
                        ctx, d,
                        f"mutable default argument in {name}(); the "
                        f"object is shared across calls — default to "
                        f"None and materialize inside")

    def _mutable(self, node) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            return _call_name(node).split(".")[-1] in self._MUTABLE_CALLS
        return False


# ---------------------------------------------------------------------------
# PL008 — telemetry span opened outside a with/finally discipline

class SpanLeakRule(Rule):
    """``telemetry.span()`` is a context manager; calling it without
    entering it records nothing (and an enter without a guaranteed exit
    corrupts span nesting for the whole thread — PR 1's discipline).
    Compliant shapes: ``with span(...)``, ``stack.enter_context(
    span(...))``, or returning the manager to the caller."""

    code = "PL008"
    name = "span-not-context-managed"
    summary = "telemetry span opened without with/enter_context"

    def applies_to(self, ctx: FileContext) -> bool:
        return not _is_test(ctx) and (
            _in_package(ctx) or ctx.relpath.startswith("tools/")
            or ctx.relpath == "bench.py")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        parents = ctx.parents
        for node in ctx.walk():
            if not (isinstance(node, ast.Call) and self._is_span(node)):
                continue
            entry = parents.get(node)
            parent = entry[0] if entry else None
            if isinstance(parent, ast.withitem):
                continue
            if isinstance(parent, ast.Return):
                continue
            if (isinstance(parent, ast.Call)
                    and isinstance(parent.func, ast.Attribute)
                    and parent.func.attr == "enter_context"):
                continue
            yield self.finding(
                ctx, node,
                "telemetry span created outside a with/enter_context — "
                "it either never records or can leak its nesting level "
                "on an exception (PR 1 discipline)")

    @staticmethod
    def _is_span(node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Name):
            return f.id == "span"
        if isinstance(f, ast.Attribute) and f.attr == "span":
            return (isinstance(f.value, ast.Name)
                    and f.value.id in ("telemetry", "_telemetry", "obs"))
        return False


# ---------------------------------------------------------------------------
# PL009 — except Exception swallowing must_propagate faults

class SwallowedFaultRule(Rule):
    """In the resilience-adjacent modules an ``except Exception`` that
    degrades silently can swallow a watchdog interrupt, a chip-indicting
    fault, or an injected fault — hiding a device strike and defeating
    the retry->quarantine path (PR 7's no_degrade contract).  Compliant
    handlers re-raise, gate on ``health.no_degrade``/``must_propagate``,
    propagate the exception as a value, or carry a reasoned trailing
    comment (the ``# noqa: BLE001 - why`` idiom) explaining why broad
    capture is safe HERE."""

    code = "PL009"
    name = "swallowed-propagating-fault"
    summary = "except Exception without no_degrade gate / reason"

    _SCOPES = ("pypulsar_tpu/parallel/", "pypulsar_tpu/survey/",
               "pypulsar_tpu/resilience/")
    # the reason marker is a space-delimited dash ("# noqa: BLE001 - why"
    # / "# — why"): a hyphenATED word ("# best-effort") must not count
    # as a reason, or the rule goes vacuous
    _REASON_RE = re.compile(r"#.*(?:\s|^)[-—]\s+\S")

    def applies_to(self, ctx: FileContext) -> bool:
        return any(ctx.relpath.startswith(s) for s in self._SCOPES)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.walk():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._catches_exception(node.type):
                continue
            if self._compliant(node, ctx):
                continue
            yield self.finding(
                ctx, node,
                "except Exception here can swallow must_propagate "
                "faults (watchdog interrupts, chip strikes, injected "
                "faults); gate with health.no_degrade(e)/re-raise, or "
                "justify with a reasoned trailing comment (PR 7 "
                "no_degrade contract)")

    @staticmethod
    def _catches_exception(type_node) -> bool:
        def _is_exc(n):
            return ((isinstance(n, ast.Name) and n.id == "Exception")
                    or (isinstance(n, ast.Attribute)
                        and n.attr == "Exception"))
        if _is_exc(type_node):
            return True
        if isinstance(type_node, ast.Tuple):
            return any(_is_exc(e) for e in type_node.elts)
        return False

    def _compliant(self, handler: ast.ExceptHandler,
                   ctx: FileContext) -> bool:
        if self._REASON_RE.search(ctx.line_text(handler.lineno)):
            return True
        bound = handler.name
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                if _call_name(node).split(".")[-1] in (
                        "no_degrade", "must_propagate"):
                    return True
            if (bound and isinstance(node, ast.Name)
                    and node.id == bound
                    and isinstance(node.ctx, ast.Load)):
                return True  # exception propagated as a value
        return False


# ---------------------------------------------------------------------------
# PL011 — raw PYPULSAR_TPU_* env read outside the knob registry

class RawKnobReadRule(Rule):
    """Round 17 made ``tune/knobs.py`` the single read path for every
    ``PYPULSAR_TPU_*`` tunable (``trial > env > tuned cache > default``
    precedence). A raw ``os.environ.get``/``getenv``/``environ[...]``
    read anywhere else silently bypasses the auto-tuning cache AND the
    typo-tolerance contract — the knob looks tunable but the tuner can
    never move it. Route through ``knobs.env_int/env_float/env_str``.

    Flags the constant-indirection idiom too (``os.environ.get(ENV_X)``
    with an ``ENV_``-named constant). Env *writes* (``os.environ[k] =
    v`` in bench/tests arming subprocess knobs) are fine — only Load
    context is a read. Suppressions are reserved for bootstrap probes
    where the registry genuinely cannot be imported."""

    code = "PL011"
    name = "raw-knob-read"
    summary = "raw PYPULSAR_TPU_* env read outside tune/knobs.py"

    _EXEMPT = "pypulsar_tpu/tune/knobs.py"
    _ENV_CALLS = ("os.environ.get", "environ.get", "os.getenv", "getenv")

    def applies_to(self, ctx: FileContext) -> bool:
        if ctx.relpath == self._EXEMPT or _is_test(ctx):
            return False
        return (_in_package(ctx) or ctx.relpath.startswith("tools/")
                or ctx.relpath == "bench.py")

    def _knob_name(self, node) -> Optional[str]:
        s = _const_str(node)
        if s is not None:
            return s if s.startswith("PYPULSAR_TPU_") else None
        if isinstance(node, ast.Name) and node.id.startswith("ENV_"):
            return node.id
        return None

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.walk():
            if (isinstance(node, ast.Call)
                    and _call_name(node) in self._ENV_CALLS
                    and node.args):
                name = self._knob_name(node.args[0])
                if name:
                    yield self.finding(
                        ctx, node,
                        f"raw env read of {name} bypasses the knob "
                        f"registry (env > tuned cache > default); use "
                        f"tune.knobs.env_int/env_float/env_str")
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and _attr_chain(node.value) in ("os.environ",
                                                    "environ")):
                name = self._knob_name(node.slice)
                if name:
                    yield self.finding(
                        ctx, node,
                        f"raw os.environ[{name!r}] read bypasses the "
                        f"knob registry; use tune.knobs accessors")


# ---------------------------------------------------------------------------
# psrrace static rules (PL012-PL016, round 19): the concurrency bug
# classes the threaded fleet runtime (PRs 5-13) paid for by hand — lock
# ordering, blocking under a lock, leak-prone acquires, unguarded
# condition waits, orphanable threads. The runtime half lives in
# resilience/locks.py (lockdep); these rules lock the SOURCE shapes in.

_LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|locks|mutex|cv|cond)$", re.I)
_CONDISH_RE = re.compile(r"(?:^|_)(?:cv|cond|condition)$", re.I)


def _enclosing_fn(node, parents):
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return cur
        entry = parents.get(cur)
        cur = entry[0] if entry else None
    return None


def _enclosing_class_name(node, parents) -> Optional[str]:
    cur = node
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        entry = parents.get(cur)
        cur = entry[0] if entry else None
    return None


def _lockish_name(expr) -> Optional[str]:
    """The final name segment of a lock-looking expression (``self._cv``
    -> ``_cv``), or None when the expression does not look like a lock.
    Name-convention based BY DESIGN: this repo's locks are uniformly
    ``*_lock`` / ``*_cv`` (and the tracked wrappers keep that idiom), so
    a miss means a naming drift worth fixing anyway."""
    if isinstance(expr, ast.Name):
        return expr.id if _LOCKISH_RE.search(expr.id) else None
    if isinstance(expr, ast.Attribute):
        return expr.attr if _LOCKISH_RE.search(expr.attr) else None
    return None


def _lock_key(ctx: FileContext, node, expr) -> Optional[str]:
    """Graph node identity for a lock expression: ``<Class>.<attr>`` for
    ``self._lock``-style attributes (the class IS the lock's home, so
    the same class merges across files), the receiver chain verbatim for
    other attributes (``sched._lock`` from any file is one node —
    variable naming is the convention-based join key, same philosophy
    as the lockish-name heuristic itself), and ``<module-stem>.<name>``
    for module-global lock names (two modules' private globals must NOT
    merge on a shared spelling)."""
    tail = _lockish_name(expr)
    if tail is None:
        return None
    if isinstance(expr, ast.Attribute):
        chain = _attr_chain(expr)
        root = chain.split(".", 1)[0]
        if root in ("self", "cls"):
            cls = _enclosing_class_name(node, ctx.parents)
            if cls:
                return f"{cls}.{tail}"
        return chain
    stem = ctx.relpath.rsplit("/", 1)[-1].removesuffix(".py")
    return f"{stem}.{tail}"


def _concurrency_scope(ctx: FileContext) -> bool:
    return not _is_test(ctx) and (
        _in_package(ctx) or ctx.relpath.startswith("tools/")
        or ctx.relpath == "bench.py")


# ---------------------------------------------------------------------------
# PL012 — cross-file lock-order inversion


class LockOrderInversionRule(ProjectRule):
    """Build the lock acquisition-order graph from lexically nested
    ``with <lock>`` scopes over the WHOLE project (edges merge across
    files via class-qualified lock keys) and flag every cycle — the
    static twin of ``resilience.locks``' runtime lockdep, catching the
    AB/BA deadlocks PR 7 and PR 13 each had to fix in review before any
    thread runs. Also flags a lexically nested re-``with`` of the same
    non-reentrant lock (instant self-deadlock). Lexical analysis only:
    a cross-function nesting is runtime lockdep's job."""

    code = "PL012"
    name = "lock-order-inversion"
    summary = "nested with-lock scopes form an ordering cycle"

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        graph: Dict[str, Set[str]] = {}
        sites: Dict[Tuple[str, str], Tuple[FileContext, ast.AST]] = {}
        self_deadlocks: List[Tuple[FileContext, ast.AST, str]] = []
        for ctx in project.contexts:
            if not _concurrency_scope(ctx) or ctx.tree is None:
                continue
            parents = ctx.parents
            for node in ctx.walk():
                if not isinstance(node, ast.With):
                    continue
                inner = self._with_keys(ctx, node)
                if not inner:
                    continue
                outer = self._outer_keys(ctx, node, parents)
                # multiple lockish items in ONE with are ordered too
                for i in range(len(inner)):
                    for j in range(i + 1, len(inner)):
                        graph.setdefault(inner[i], set()).add(inner[j])
                        sites.setdefault((inner[i], inner[j]),
                                         (ctx, node))
                for ok in outer:
                    for ik in inner:
                        if ok == ik:
                            if "rlock" not in ik.lower():
                                self_deadlocks.append((ctx, node, ik))
                            continue
                        graph.setdefault(ok, set()).add(ik)
                        sites.setdefault((ok, ik), (ctx, node))

        for ctx, node, key in self_deadlocks:
            yield self.finding(
                ctx, node,
                f"nested 'with' re-acquisition of the non-reentrant "
                f"lock {key!r}: a plain Lock self-deadlocks here — use "
                f"an RLock or restructure (runtime twin: "
                f"resilience.locks lockdep)")

        reported: Set[frozenset] = set()
        for a, b in sorted(sites):
            back = self._path(graph, b, a)
            if back is None:
                continue
            cycle = [a] + back  # a -> b -> ... -> a
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            ctx, node = sites[(a, b)]
            others = ", ".join(
                f"{c2.relpath}:{n2.lineno}"
                for (x, y), (c2, n2) in sorted(sites.items())
                if x in key and y in key and (x, y) != (a, b))
            yield self.finding(
                ctx, node,
                f"lock-order inversion: acquisition cycle "
                f"{' -> '.join(cycle)} (other edge sites: "
                f"{others or 'same statement'}); pick ONE order and "
                f"document it in the ARCHITECTURE lock hierarchy")

    def _with_keys(self, ctx: FileContext, node: ast.With) -> List[str]:
        out = []
        for item in node.items:
            key = _lock_key(ctx, node, item.context_expr)
            if key is not None:
                out.append(key)
        return out

    def _outer_keys(self, ctx, node, parents) -> List[str]:
        out: List[str] = []
        cur = node
        while True:
            entry = parents.get(cur)
            if entry is None:
                break
            parent, field = entry
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                break  # a closure body runs later, outside the with
            if isinstance(parent, ast.With) and field == "body":
                out.extend(self._with_keys(ctx, parent))
            cur = parent
        return out

    @staticmethod
    def _path(graph: Dict[str, Set[str]], src: str,
              dst: str) -> Optional[List[str]]:
        if src == dst:
            return [src]
        seen = {src}
        frontier = [[src]]
        while frontier:
            nxt = []
            for path in frontier:
                for peer in sorted(graph.get(path[-1], ())):
                    if peer == dst:
                        return path + [dst]
                    if peer not in seen:
                        seen.add(peer)
                        nxt.append(path + [peer])
            frontier = nxt
        return None


# ---------------------------------------------------------------------------
# PL013 — blocking call while holding a lock


class BlockingWhileLockedRule(Rule):
    """A sleep / file-open / subprocess / jax dispatch / ``.result()`` /
    thread-join inside a ``with <lock>`` body serializes every peer of
    that lock behind wall-clock time the lock was never meant to cover —
    the shape behind PR 7's first watchdog deadline bugs (and the reason
    the scheduler's retry backoff runs on a timer thread, not under the
    lease). Move the blocking work outside the critical section; a
    deliberate exception carries a suppression with its reason."""

    code = "PL013"
    name = "blocking-while-locked"
    summary = "blocking call (sleep/IO/subprocess/jax/.result) under a lock"

    _BLOCKING_DOTTED = {
        "time.sleep", "os.replace", "os.rename", "os.fsync",
        "os.remove", "os.unlink", "shutil.rmtree", "shutil.copy",
        "shutil.copyfile", "shutil.disk_usage",
    }
    _BLOCKING_ATTRS = {"result", "block_until_ready", "device_put"}

    def applies_to(self, ctx: FileContext) -> bool:
        return _concurrency_scope(ctx)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        parents = ctx.parents
        seen: Set[Tuple[int, int]] = set()  # nested lock withs: report once
        for node in ctx.walk():
            if not isinstance(node, ast.With):
                continue
            if not any(_lockish_name(item.context_expr)
                       for item in node.items):
                continue
            fn = _enclosing_fn(node, parents)
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    key = (sub.lineno, sub.col_offset)
                    if key in seen:
                        continue
                    if _enclosing_fn(sub, parents) is not fn:
                        continue  # closure body: runs later, unlocked
                    why = self._blocking(sub)
                    if why:
                        seen.add(key)
                        yield self.finding(
                            ctx, sub,
                            f"{why} inside a 'with <lock>' block: every "
                            f"peer of this lock now waits on wall-clock "
                            f"work the lock was not meant to cover — "
                            f"move it outside the critical section "
                            f"(scheduler precedent: retry backoff runs "
                            f"on a timer, never under the lease)")

    def _blocking(self, call: ast.Call) -> Optional[str]:
        cn = _call_name(call)
        if isinstance(call.func, ast.Name) and call.func.id == "open":
            return "file IO (open)"
        if cn == "sleep" or cn in self._BLOCKING_DOTTED:
            return f"blocking call {cn}()"
        if cn.startswith("subprocess."):
            return f"subprocess call {cn}()"
        root = cn.split(".", 1)[0]
        if root in ("jax", "jnp"):
            return f"jax dispatch {cn}()"
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in ("result", "block_until_ready") and not call.args:
                return f".{attr}() (blocks on async work)"
            if attr == "join" and self._threadish_join(call):
                return ".join() (blocks on another thread)"
        return None

    @staticmethod
    def _threadish_join(call: ast.Call) -> bool:
        """``t.join()`` / ``t.join(5)`` / ``t.join(timeout=...)`` —
        but never ``sep.join(parts)`` (one non-numeric positional)."""
        if any(kw.arg == "timeout" for kw in call.keywords):
            return True
        if not call.args and not call.keywords:
            return True
        if len(call.args) == 1 and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, (int, float)):
            return True
        return False


# ---------------------------------------------------------------------------
# PL014 — bare .acquire() without try/finally release


class BareAcquireRule(Rule):
    """``lock.acquire()`` with no ``try/finally: lock.release()`` leaks
    the lock on ANY exception between acquire and release — including
    the watchdog's async interrupts, which land at an arbitrary bytecode
    boundary. Use ``with lock:`` (preferred — the tracked wrappers make
    it lockdep-visible too), or acquire immediately before a
    ``try/finally`` that releases."""

    code = "PL014"
    name = "bare-acquire"
    summary = ".acquire() without a try/finally release"

    def applies_to(self, ctx: FileContext) -> bool:
        return _concurrency_scope(ctx)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        parents = ctx.parents
        for node in ctx.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                continue
            if _lockish_name(node.func.value) is None:
                continue
            chain = _attr_chain(node.func.value)
            if self._guarded(node, chain, parents):
                continue
            yield self.finding(
                ctx, node,
                f"bare {chain}.acquire() with no try/finally release: "
                f"any exception (including a watchdog async interrupt) "
                f"between acquire and release strands the lock — use "
                f"'with {chain}:' or acquire directly before a "
                f"try/finally that releases")

    def _guarded(self, node, chain: str, parents) -> bool:
        # (a) inside a Try whose finalbody releases the same lock
        cur = node
        while True:
            entry = parents.get(cur)
            if entry is None:
                break
            parent, field = entry
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                break
            if isinstance(parent, ast.Try) and field == "body" \
                    and self._releases(parent.finalbody, chain):
                return True
            cur = parent
        # (b) the acquire's statement is immediately followed by such a
        # Try (the classic acquire-then-guard idiom)
        stmt = node
        while stmt is not None and not isinstance(stmt, ast.stmt):
            entry = parents.get(stmt)
            stmt = entry[0] if entry else None
        if stmt is None:
            return False
        entry = parents.get(stmt)
        if entry is None:
            return False
        parent, field = entry
        body = getattr(parent, field, None)
        if not isinstance(body, list) or stmt not in body:
            return False
        idx = body.index(stmt)
        if idx + 1 < len(body):
            nxt = body[idx + 1]
            if isinstance(nxt, ast.Try) \
                    and self._releases(nxt.finalbody, chain):
                return True
        return False

    @staticmethod
    def _releases(stmts, chain: str) -> bool:
        for stmt in stmts:
            for sub in ast.walk(stmt):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "release"
                        and _attr_chain(sub.func.value) == chain):
                    return True
        return False


# ---------------------------------------------------------------------------
# PL015 — Condition.wait outside a predicate while loop


class ConditionWaitPredicateRule(Rule):
    """``cv.wait()`` not inside a ``while`` loop: condition variables
    have spurious wakeups and lost-wakeup races by contract — a bare
    ``if``/straight-line wait resumes with the predicate still false
    (the lost-completion shape PR 13 fixed in review). Re-test the
    predicate in a loop (``while not pred: cv.wait()``), or use
    ``cv.wait_for(pred)``."""

    code = "PL015"
    name = "condition-wait-no-predicate-loop"
    summary = "Condition.wait outside a predicate while loop"

    def applies_to(self, ctx: FileContext) -> bool:
        return _concurrency_scope(ctx)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        parents = ctx.parents
        for node in ctx.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"):
                continue
            recv = node.func.value
            tail = None
            if isinstance(recv, ast.Name):
                tail = recv.id
            elif isinstance(recv, ast.Attribute):
                tail = recv.attr
            if tail is None or not _CONDISH_RE.search(tail):
                continue
            if self._in_while(node, parents):
                continue
            yield self.finding(
                ctx, node,
                f"{_attr_chain(recv)}.wait() outside a predicate while "
                f"loop: spurious wakeups and notify races resume with "
                f"the predicate still false — 'while not <pred>: "
                f"{tail}.wait()' or wait_for(<pred>)")

    @staticmethod
    def _in_while(node, parents) -> bool:
        cur = node
        while True:
            entry = parents.get(cur)
            if entry is None:
                return False
            parent, _ = entry
            if isinstance(parent, ast.While):
                return True
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                return False
            cur = parent


# ---------------------------------------------------------------------------
# PL016 — threads without daemon-or-join discipline


class ThreadDisciplineRule(Rule):
    """A ``threading.Thread``/``Timer`` that is neither ``daemon=True``
    nor joined in its creating function outlives the fleet that spawned
    it: a non-daemon orphan blocks interpreter exit (the survey CLI
    hangs after the run 'finished'), and an unjoined worker races
    teardown for shared state. Every thread in this runtime declares its
    lifetime: daemon (watchdog, heartbeat renewers, prefetch producers,
    retry timers) or joined (lane workers, claim loop)."""

    code = "PL016"
    name = "thread-without-daemon-or-join"
    summary = "threading.Thread/Timer with neither daemon=True nor a join"

    _CTORS = {"threading.Thread", "Thread", "threading.Timer", "Timer"}

    def applies_to(self, ctx: FileContext) -> bool:
        return _concurrency_scope(ctx)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        parents = ctx.parents
        for node in ctx.walk():
            if not (isinstance(node, ast.Call)
                    and _call_name(node) in self._CTORS):
                continue
            if any(kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                   and kw.value.value is True for kw in node.keywords):
                continue
            fn = _enclosing_fn(node, parents)
            scope = fn if fn is not None else None
            if scope is not None and self._disciplined(scope):
                continue
            yield self.finding(
                ctx, node,
                f"{_call_name(node)}(...) with neither daemon=True nor "
                f"a join in the creating function: a non-daemon orphan "
                f"blocks interpreter exit and races teardown — declare "
                f"the thread's lifetime (daemon=True, t.daemon = True, "
                f"or join it)")

    @staticmethod
    def _disciplined(fn) -> bool:
        for sub in ast.walk(fn):
            # <var>.daemon = True
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and tgt.attr == "daemon"
                            and isinstance(sub.value, ast.Constant)
                            and sub.value.value is True):
                        return True
            # a thread-shaped .join() anywhere in the function
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "join"
                    and BlockingWhileLockedRule._threadish_join(sub)):
                return True
        return False


# ---------------------------------------------------------------------------
# PL017 — telemetry name drift between emitters and consumers


class TelemetryNameDriftRule(ProjectRule):
    """Telemetry names are a cross-file contract with no compiler: the
    tree emits ``telemetry.event("survey.slo_burn", ...)`` and tlmsum /
    bench / the tests consume the same dotted literal.  Rename one side
    and the other silently reads zeros — the observability flavor of
    PL004's knob drift (round 21).  Two directions, scoped to the
    dotted ``survey.`` / ``tree.`` / ``tune.`` families:

    - a consumer literal (``pypulsar_tpu/obs/summarize.py``,
      ``bench.py``, ``tests/``) nothing in the production tree emits is
      drift — the consumer reads a channel that never carries data;
    - a production ``event()`` literal no consumer references is drift
      the other way — a verdict nobody renders or asserts.  (Counters,
      gauges and spans render generically in tlmsum, so only the
      event channel — the verdict channel — needs a named consumer.)

    Emission counts via a literal first argument to ``counter`` /
    ``event`` / ``gauge`` / ``span`` / ``record_span``, an f-string
    family prefix (dynamic stage names), or a production string
    assignment that flows into an emit call (the watchdog's
    ``name = "survey.deadline_exceeded"`` shape).  Fault-point
    literals (PL005's domain) are excluded in both directions."""

    code = "PL017"
    name = "telemetry-name-drift"
    summary = "telemetry name referenced on one side of the emit/consume contract only"

    _FAMILIES = ("survey.", "tree.", "tune.")
    _EMIT_FNS = ("counter", "event", "gauge", "span", "record_span")
    _FAULT_FNS = ("trip", "trip_data", "hits", "configure",
                  "parse_chaos_spec")
    _NAME_RE = re.compile(
        r"^(?:survey|tree|tune)\.[A-Za-z0-9_.]*[A-Za-z0-9_]$")
    # dotted names that are files, not telemetry channels
    _EXT = (".json", ".jsonl", ".npz", ".npy", ".out", ".txt", ".fil",
            ".dat", ".csv", ".md")

    @classmethod
    def _is_name(cls, s: str) -> bool:
        return bool(cls._NAME_RE.match(s)) \
            and not s.endswith(cls._EXT)

    @staticmethod
    def _is_consumer(ctx: FileContext) -> bool:
        if ctx.relpath.rsplit("/", 1)[-1] == "test_psrlint.py":
            # the linter's own tests assert on fixture names that are
            # drift BY DESIGN — they are specimens, not consumers
            return False
        return (_is_test(ctx) or ctx.relpath == "bench.py"
                or ctx.relpath == "pypulsar_tpu/obs/summarize.py")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        emitted: Set[str] = set()
        emit_prefixes: Set[str] = set()
        event_sites: List[Tuple[FileContext, ast.AST, str]] = []
        fault_exact: Set[str] = set()
        fault_prefixes: Set[str] = set()
        consumed: Dict[str, List[Tuple[FileContext, ast.AST]]] = {}

        for ctx in project.contexts:
            is_prod = _in_package(ctx) and not _is_test(ctx)
            for node in ctx.walk():
                if isinstance(node, ast.Call):
                    fn = _call_name(node).split(".")[-1]
                    if fn in self._EMIT_FNS and node.args and is_prod:
                        arg = node.args[0]
                        s = _const_str(arg)
                        if s is not None and self._is_name(s):
                            emitted.add(s)
                            if fn == "event":
                                event_sites.append((ctx, node, s))
                        elif isinstance(arg, ast.JoinedStr) and arg.values:
                            fs = _const_str(arg.values[0])
                            if fs and fs.startswith(self._FAMILIES):
                                emit_prefixes.add(fs)
                    elif fn in self._FAULT_FNS and node.args:
                        arg = node.args[0]
                        s = _const_str(arg)
                        if s is not None:
                            fault_exact.add(s)
                        elif isinstance(arg, ast.JoinedStr) and arg.values:
                            fs = _const_str(arg.values[0])
                            if fs:
                                fault_prefixes.add(fs)
                elif isinstance(node, ast.Assign) and is_prod:
                    # the variable-flow shape: name = "survey.x" feeding
                    # a later emit call in the same production file
                    s = _const_str(node.value)
                    if s is not None and self._is_name(s):
                        emitted.add(s)
                if self._is_consumer(ctx):
                    s = _const_str(node)
                    if s is not None and self._is_name(s):
                        consumed.setdefault(s, []).append((ctx, node))

        def _is_fault_point(s: str) -> bool:
            return (s in fault_exact
                    or any(s.startswith(p) for p in fault_prefixes if p))

        # direction 1: consumer literal nothing emits
        seen: Set[Tuple[str, str]] = set()
        for s, sites in sorted(consumed.items()):
            if s in emitted or _is_fault_point(s):
                continue
            if any(s.startswith(p) for p in emit_prefixes):
                continue
            for ctx, node in sites:
                key = (ctx.relpath, s)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, node,
                    f"telemetry name '{s}' is consumed here but nothing "
                    f"in the tree emits it — the consumer reads a "
                    f"channel that never carries data (rename drift?)")

        # direction 2: production event nobody consumes
        seen2: Set[str] = set()
        for ctx, node, s in event_sites:
            if s in consumed or _is_fault_point(s) or s in seen2:
                continue
            seen2.add(s)
            yield self.finding(
                ctx, node,
                f"telemetry event '{s}' is emitted here but no consumer "
                f"(tlmsum, bench.py, tests/) references it — a verdict "
                f"nobody renders or asserts (rename drift?)")


# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# PL018 — raw jax.jit outside the compilation plane


class RawJitRule(Rule):
    """Raw ``jax.jit`` bypasses the compilation plane (round 22): a
    directly-jitted kernel gets no AOT executable registry entry, no
    compile telemetry (``compile.cache_miss`` stays blind to it) and no
    warm-pool precompile — exactly the critical-path trace+compile
    stall the plane exists to remove.  Every jit in the tree goes
    through :func:`pypulsar_tpu.compile.plane_jit` except the plane
    itself and the ``ops/`` leaf-kernel modules registered in
    :data:`pypulsar_tpu.compile.registry.OPS_LEAF_ALLOWLIST` (their
    call sites are reached through plane-wrapped stage runners one
    layer up, so re-wrapping them would double-count the same
    compiles).

    Any ``jax.jit`` attribute reference counts — ``@jax.jit``
    decorators (bare or parameterized), direct ``jax.jit(fn)`` calls,
    and indirections like ``functools.partial(jax.jit, ...)``.  Other
    modules' ``.jit`` attributes (``self.jit``, ``nn.jit``) and the
    word in strings/comments stay silent.  Tests are exempt."""

    code = "PL018"
    name = "raw-jax-jit"
    summary = "raw jax.jit outside the compilation plane; use compile.plane_jit"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _in_package(ctx) or _is_test(ctx):
            return
        if ctx.relpath.startswith("pypulsar_tpu/compile/"):
            return
        from pypulsar_tpu.compile.registry import OPS_LEAF_ALLOWLIST

        if ctx.relpath in OPS_LEAF_ALLOWLIST:
            return
        for node in ctx.walk():
            if (isinstance(node, ast.Attribute) and node.attr == "jit"
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "jax"):
                yield self.finding(
                    ctx, node,
                    "raw jax.jit bypasses the compilation plane (no AOT "
                    "registry entry, no compile telemetry, no warm-pool "
                    "precompile); use pypulsar_tpu.compile.plane_jit")


ALL_RULES: Tuple[type, ...] = (
    TruedivIndexRule, BareJaxDevicesRule, NonAtomicWriteRule,
    KnobRegistryDriftRule, DeadFaultPointRule, RawHeaderReadRule,
    MutableDefaultRule, SpanLeakRule, SwallowedFaultRule,
    RawKnobReadRule, LockOrderInversionRule, BlockingWhileLockedRule,
    BareAcquireRule, ConditionWaitPredicateRule, ThreadDisciplineRule,
    TelemetryNameDriftRule, RawJitRule,
)


def all_rules() -> List[Rule]:
    """Fresh instances of the full catalog, code order."""
    return [cls() for cls in ALL_RULES]
