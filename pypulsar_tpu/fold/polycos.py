"""TEMPO polyco parsing, evaluation, generation, and writing.

Behavioral parity target: reference utils/mypolycos.py (polyco :29-95,
polycos :98-174, create_polycos :213-276), itself lifted from PRESTO.
Redesigns:

- ``Polyco.rotation`` keeps the reference's Horner evaluation
  (mypolycos.py:73-84) in float64; a vectorized ``rotation_batch`` serves
  the fold engine (one call per block of samples instead of per sample).
- ``create_polycos`` spawns ``tempo -z`` exactly like the reference when
  the binary exists, but this framework also has a **native generator**
  (``create_polycos_from_spindown``): for a simple spin-down ephemeris
  (F0/F1/F2 about PEPOCH, no binary/barycentric terms) the phase
  polynomial is exact, so polyco blocks can be synthesized without TEMPO.
  That keeps folding self-contained for topocentric/barycentred data and
  for tests.
- A polyco.dat writer exists (the reference has none) for round-trip
  tests and interchange with PRESTO/TEMPO tooling.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional, Sequence, Union

import numpy as np

from pypulsar_tpu.astro.telescopes import id_to_telescope, telescope_to_id, telescope_to_maxha
from pypulsar_tpu.core import psrmath
from pypulsar_tpu.io.infodata import InfoData
from pypulsar_tpu.io.parfile import PsrPar

NUMCOEFFS_DEFAULT = 12
SPAN_DEFAULT = 60  # minutes


class PolycoError(Exception):
    pass


class Polyco:
    """One polyco block: phase polynomial about TMID.

    rotation(t) = RPHASE + DT*60*F0 + sum_i coeffs[i]*DT^i,  DT in minutes
    (reference mypolycos.py:73-84).
    """

    def __init__(self, psr, date, utc, tmid_str, dm, doppler, log10rms,
                 rphase, f0, obs, dataspan, numcoeff, obsfreq, coeffs,
                 binphase=None):
        self.psr = psr
        self.date = date
        self.UTC = utc
        # split TMID into integer+fractional *as printed* to keep precision
        self.TMIDi = float(tmid_str.split(".")[0])
        self.TMIDf = float("0." + tmid_str.split(".")[1]) if "." in tmid_str else 0.0
        self.TMID = self.TMIDi + self.TMIDf
        self.DM = dm
        self.doppler = doppler  # already in units of 1e-4 applied
        self.log10rms = log10rms
        self.RPHASE = rphase
        self.F0 = f0
        self.obs = obs
        self.dataspan = dataspan
        self.numcoeff = numcoeff
        self.obsfreq = obsfreq
        self.binphase = binphase
        self.coeffs = np.asarray(coeffs, dtype=np.float64)

    # -- parsing ----------------------------------------------------------
    @classmethod
    def read(cls, fileptr) -> Optional["Polyco"]:
        """Parse one block from an open polyco.dat; None at EOF
        (reference mypolycos.py:30-64, including the glued
        'doppler-log10rms' column case)."""
        line = fileptr.readline()
        if line == "" or not line.strip():
            return None
        sl = line.split()
        psr, date, utc, tmid_str = sl[0], sl[1], sl[2], sl[3]
        dm = float(sl[4])
        if len(sl) == 7:
            doppler = float(sl[5]) * 1e-4
            log10rms = float(sl[6])
        else:
            # doppler and log10rms glued together, split at the last '-'
            tail = sl[-1]
            log10rms_s = "-" + tail.split("-")[-1]
            doppler = float(tail[: tail.find(log10rms_s)]) * 1e-4
            log10rms = float(log10rms_s)
        sl = fileptr.readline().split()
        rphase = float(sl[0])
        f0 = float(sl[1])
        obs = sl[2]
        dataspan = int(sl[3])
        numcoeff = int(sl[4])
        obsfreq = float(sl[5])
        binphase = float(sl[6]) if len(sl) == 7 else None
        coeffs = []
        for _ in range((numcoeff + 2) // 3):
            sl = fileptr.readline().split()
            coeffs.extend(float(c.replace("D", "E")) for c in sl)
        return cls(psr, date, utc, tmid_str, dm, doppler, log10rms, rphase,
                   f0, obs, dataspan, numcoeff, obsfreq, coeffs[:numcoeff],
                   binphase)

    # -- evaluation -------------------------------------------------------
    def rotation(self, mjdi, mjdf) -> float:
        """Absolute (fractional) rotation count at mjdi+mjdf."""
        DT = ((mjdi - self.TMIDi) + (mjdf - self.TMIDf)) * 1440.0
        phase = self.coeffs[self.numcoeff - 1]
        for ii in range(self.numcoeff - 1, 0, -1):
            phase = DT * phase + self.coeffs[ii - 1]
        return phase + self.RPHASE + DT * 60.0 * self.F0

    def phase(self, mjdi, mjdf) -> float:
        return self.rotation(mjdi, mjdf) % 1

    def freq(self, mjdi, mjdf) -> float:
        """Apparent spin frequency (Hz)."""
        DT = ((mjdi - self.TMIDi) + (mjdf - self.TMIDf)) * 1440.0
        psrfreq = 0.0
        for ii in range(self.numcoeff - 1, 0, -1):
            psrfreq = DT * psrfreq + ii * self.coeffs[ii]
        return self.F0 + psrfreq / 60.0

    def rotation_batch(self, mjdi, mjdf: np.ndarray) -> np.ndarray:
        """Vectorized rotation for an array of fractional MJDs sharing one
        integer day — the fold engine's per-block path."""
        DT = ((mjdi - self.TMIDi) + (np.asarray(mjdf, np.float64) - self.TMIDf)) * 1440.0
        phase = np.full_like(DT, self.coeffs[self.numcoeff - 1])
        for ii in range(self.numcoeff - 1, 0, -1):
            phase = DT * phase + self.coeffs[ii - 1]
        return phase + self.RPHASE + DT * 60.0 * self.F0

    # -- writing ----------------------------------------------------------
    def format_block(self) -> str:
        """Serialize in TEMPO polyco.dat layout (readable by PRESTO and by
        our own parser)."""
        tmid = f"{self.TMIDi + self.TMIDf:.11f}"
        lines = [
            f"{self.psr:<10s} {self.date:>9s} {self.UTC:>11s} "
            f"{tmid:>20s} {self.DM:>21.6f} {self.doppler / 1e-4:>7.3f}"
            f"{self.log10rms:>7.3f}",
            f"{self.RPHASE:>20.6f} {self.F0:>18.12f} {self.obs:>5s} "
            f"{self.dataspan:>5d} {self.numcoeff:>5d} {self.obsfreq:>10.3f}"
            + (f" {self.binphase:>7.4f}" if self.binphase is not None else ""),
        ]
        for i in range(0, self.numcoeff, 3):
            chunk = self.coeffs[i : i + 3]
            lines.append("".join(f"{c:>25.17E}".replace("E", "D") for c in chunk))
        return "\n".join(lines) + "\n"


class Polycos:
    """Container over the blocks of a polyco.dat; selects the valid block
    by TMID (reference mypolycos.py:98-174)."""

    def __init__(self, filenm: str = "polyco.dat",
                 blocks: Optional[Sequence[Polyco]] = None):
        self.file = filenm
        self.polycos: List[Polyco] = []
        tmids = []
        if blocks is None:
            with open(filenm) as infile:
                blocks = []
                while True:
                    p = Polyco.read(infile)
                    if p is None:
                        break
                    blocks.append(p)
        if not blocks:
            raise PolycoError(f"No polycos in {filenm}!")
        psrname = blocks[0].psr
        self.dataspan = blocks[0].dataspan
        for p in blocks:
            if p.dataspan != self.dataspan:
                raise PolycoError("Data span is changing!\n")
            if p.psr != psrname:
                raise PolycoError("Multiple PSRs in same polycos file!\n")
            self.polycos.append(p)
            tmids.append(p.TMID)
        self.TMIDs = np.asarray(tmids)
        self.validrange = 0.5 * self.dataspan / 1440.0

    def __len__(self):
        return len(self.polycos)

    def select_polyco(self, mjdi, mjdf) -> int:
        goodpoly = int(np.argmin(np.fabs(self.TMIDs - (mjdi + mjdf))))
        if np.fabs(self.TMIDs[goodpoly] - (mjdi + mjdf)) > self.validrange:
            raise PolycoError(f"Cannot find a valid polyco at {mjdi + mjdf:f}!\n")
        return goodpoly

    def get_phase(self, mjdi, mjdf) -> float:
        return self.polycos[self.select_polyco(mjdi, mjdf)].phase(mjdi, mjdf)

    def get_rotation(self, mjdi, mjdf) -> float:
        return self.polycos[self.select_polyco(mjdi, mjdf)].rotation(mjdi, mjdf)

    def get_freq(self, mjdi, mjdf) -> float:
        return self.polycos[self.select_polyco(mjdi, mjdf)].freq(mjdi, mjdf)

    def get_phs_and_freq(self, mjdi, mjdf):
        p = self.polycos[self.select_polyco(mjdi, mjdf)]
        return p.phase(mjdi, mjdf), p.freq(mjdi, mjdf)

    def get_voverc(self, mjdi, mjdf) -> float:
        return self.polycos[self.select_polyco(mjdi, mjdf)].doppler

    def write(self, filenm: str) -> str:
        with open(filenm, "w") as f:
            for p in self.polycos:
                f.write(p.format_block())
        return filenm


# ---------------------------------------------------------------------------
# generation
# ---------------------------------------------------------------------------

def create_polycos_from_spindown(
    par: Union[str, PsrPar],
    start_mjd: float,
    end_mjd: float,
    obs: str = "@",
    obsfreq: float = 0.0,
    span: int = SPAN_DEFAULT,
    numcoeffs: int = NUMCOEFFS_DEFAULT,
) -> Polycos:
    """Synthesize polyco blocks natively from a simple spin-down ephemeris.

    Valid when the apparent spin evolution is the Taylor series
    f(t) = F0 + F1*(t-PEPOCH) + F2/2*(t-PEPOCH)^2 (no binary motion, no
    observatory barycentric correction — i.e. barycentred or artificially
    generated data; this is the regime the reference's test-free pipeline
    exercised via TEMPO).  The rotation polynomial about each block TMID
    is then *exact*:

        N(t) = N(TMID) + f(TMID)*dt + F1/2*dt^2 + F2/6*dt^3,  dt = t-TMID [s]

    mapped onto the polyco convention (DT in minutes):
        RPHASE    = N(TMID) ;  F0_block = f(TMID)
        coeffs[2] = F1/2 * 3600 ;  coeffs[3] = F2/6 * 216000
    """
    if isinstance(par, str):
        par = PsrPar(par)
    f0 = float(par.F0)
    f1 = float(getattr(par, "F1", 0.0) or 0.0)
    f2 = float(getattr(par, "F2", 0.0) or 0.0)
    pepoch = float(getattr(par, "PEPOCH", start_mjd))
    dm = float(getattr(par, "DM", 0.0) or 0.0)
    psrname = par.name.lstrip("BJ")

    def f_at(mjd):
        dt = (mjd - pepoch) * psrmath.SECPERDAY
        return f0 + f1 * dt + 0.5 * f2 * dt * dt

    def n_at(mjd):
        dt = (mjd - pepoch) * psrmath.SECPERDAY
        return f0 * dt + 0.5 * f1 * dt * dt + f2 * dt**3 / 6.0

    blocks = []
    span_days = span / 1440.0
    # center the first block ON start_mjd so the requested range is covered
    # with half-a-span margin at both edges (floating-point-safe; TEMPO
    # similarly over-covers the requested window)
    tmid = float(start_mjd)
    while tmid - 0.5 * span_days <= end_mjd:
        # snap TMID to its serialized split (TMIDi + full-precision
        # fraction) before computing RPHASE so evaluation, which parses
        # tmid_str the same way, is consistent
        tmid_str = f"{tmid:.11f}"
        ipart, _, fpart = tmid_str.partition(".")
        tmid_eval = np.longdouble(int(ipart)) + np.longdouble(
            float("0." + fpart))
        coeffs = np.zeros(numcoeffs)
        # DT is minutes: dt_sec = 60*DT.  The dt^2 coefficient uses the
        # frequency DERIVATIVE AT TMID, f'(TMID) = F1 + F2*(TMID-PEPOCH):
        fdot_tmid = f1 + f2 * (tmid_eval - pepoch) * psrmath.SECPERDAY
        if numcoeffs > 2:
            coeffs[2] = 0.5 * fdot_tmid * 3600.0
        if numcoeffs > 3:
            coeffs[3] = f2 / 6.0 * 216000.0
        mjdi = int(tmid_eval)
        frac_h = (tmid_eval - mjdi) * 24.0
        hh = int(frac_h)
        mm = int((frac_h - hh) * 60)
        ss = (frac_h - hh) * 3600 - mm * 60
        blocks.append(
            Polyco(
                psr=psrname,
                date="DD-MMM-YY",
                utc=f"{hh:02d}{mm:02d}{ss:05.2f}".replace(".", ""),
                tmid_str=tmid_str,
                dm=dm,
                doppler=0.0,
                log10rms=-10.0,
                rphase=float(n_at(tmid_eval)),
                f0=float(f_at(tmid_eval)),
                obs=obs,
                dataspan=span,
                numcoeff=numcoeffs,
                obsfreq=obsfreq,
                coeffs=coeffs,
            )
        )
        tmid += span_days
    return Polycos(filenm="<generated>", blocks=blocks)


def _bt_roemer_delay(mjds: np.ndarray, pb_days: float, a1: float,
                     ecc: float, om_deg: float, t0: float) -> np.ndarray:
    """Blandford-Teukolsky Roemer delay (s) of the pulsar's orbit at the
    given barycentric MJDs: x[sin w (cos E - e) + sqrt(1-e^2) cos w sin E]
    with E from Kepler's equation by Newton iteration."""
    mjds = np.asarray(mjds, dtype=np.longdouble)
    ma = 2.0 * np.pi * np.asarray((mjds - t0) / pb_days, dtype=np.float64)
    ma = np.mod(ma, 2.0 * np.pi)
    E = ma + ecc * np.sin(ma)  # good starting guess for e < 0.8
    for _ in range(25):
        dE = (E - ecc * np.sin(E) - ma) / (1.0 - ecc * np.cos(E))
        E = E - dE
        if np.max(np.abs(dE)) < 1e-14:
            break
    om = np.deg2rad(om_deg)
    return a1 * (np.sin(om) * (np.cos(E) - ecc)
                 + np.sqrt(1.0 - ecc ** 2) * np.cos(om) * np.sin(E))


def create_polycos_from_binary(
    par: Union[str, PsrPar],
    start_mjd: float,
    end_mjd: float,
    obs: str = "@",
    obsfreq: float = 0.0,
    span: int = SPAN_DEFAULT,
    numcoeffs: int = NUMCOEFFS_DEFAULT,
    max_resid_phase: float = 1e-6,
) -> Polycos:
    """Native polyco generation for binary pulsars (BT/ELL1-style Keplerian
    orbits) on barycentred data — the capability the reference delegated to
    the TEMPO binary.

    Per block, the exact rotation count N(t) = f(tau) integrated over the
    orbit-retarded proper time tau = t - Roemer(t) is sampled on Chebyshev
    nodes and least-squares fitted with the polyco polynomial in
    DT = (t - TMID) minutes.  The block span is shrunk (and the fit
    re-done) until the max fit residual is below ``max_resid_phase``
    rotations, so short-period orbits are handled correctly.
    """
    if isinstance(par, str):
        par = PsrPar(par)
    f0 = float(par.F0)
    f1 = float(getattr(par, "F1", 0.0) or 0.0)
    f2 = float(getattr(par, "F2", 0.0) or 0.0)
    pepoch = float(getattr(par, "PEPOCH", start_mjd))
    dm = float(getattr(par, "DM", 0.0) or 0.0)
    pb = float(par.PB)           # days
    a1 = float(par.A1)           # lt-s
    if hasattr(par, "EPS1") or hasattr(par, "EPS2"):
        # ELL1 parameterization: eps1 = e sin w, eps2 = e cos w, epoch is
        # the ascending node; T0 = TASC + (w/2pi) Pb (exact to O(e^2),
        # consistent with the ELL1 small-e regime)
        eps1 = float(getattr(par, "EPS1", 0.0) or 0.0)
        eps2 = float(getattr(par, "EPS2", 0.0) or 0.0)
        ecc = float(np.hypot(eps1, eps2))
        om_rad = float(np.arctan2(eps1, eps2))
        om = np.rad2deg(om_rad)
        t0 = float(par.TASC) + (om_rad % (2 * np.pi)) / (2 * np.pi) * pb
    elif hasattr(par, "T0"):
        ecc = float(getattr(par, "ECC", getattr(par, "E", 0.0)) or 0.0)
        om = float(getattr(par, "OM", 0.0) or 0.0)
        t0 = float(par.T0)
    else:
        raise PolycoError(
            "Binary ephemeris has neither T0/ECC/OM (BT/DD-style) nor "
            "TASC/EPS1/EPS2 (ELL1-style) parameters; cannot generate "
            "native polycos for this model.")
    psrname = par.name.lstrip("BJ")

    def n_at(mjds):
        """Exact rotation count at barycentric MJDs (longdouble)."""
        mjds = np.atleast_1d(np.asarray(mjds, dtype=np.longdouble))
        delay = _bt_roemer_delay(mjds, pb, a1, ecc, om, t0)
        tau = (mjds - pepoch) * psrmath.SECPERDAY - delay
        return f0 * tau + 0.5 * f1 * tau ** 2 + f2 * tau ** 3 / 6.0

    def fit_block(tmid, cur_span):
        """(coeffs, rphase, max_resid) of the polyco polynomial fit on
        Chebyshev nodes over [tmid - span/2, tmid + span/2]."""
        half_min = cur_span / 2.0
        k = np.arange(4 * numcoeffs)
        dts = half_min * np.cos(np.pi * (k + 0.5) / k.size)
        mjds = tmid + np.asarray(dts, dtype=np.longdouble) / 1440.0
        n_tmid = n_at(tmid)[0]
        y = np.asarray(n_at(mjds) - n_tmid, dtype=np.float64)
        # fit in the scaled variable s = DT/half (condition number ~1),
        # then rescale coefficients to the polyco's DT-minutes monomials
        s = dts / half_min
        A = np.vander(s, numcoeffs, increasing=True)
        coeffs_s, *_ = np.linalg.lstsq(A, y, rcond=None)
        resid = float(np.max(np.abs(A @ coeffs_s - y)))
        coeffs = coeffs_s / half_min ** np.arange(numcoeffs)
        return coeffs, float(n_tmid), resid

    # TEMPO polycos require one uniform dataspan; pick the largest span —
    # starting well inside one orbit — whose fit converges at every
    # orbital phase (probe 8 phases across the orbit)
    span = int(min(span, max(4, pb * 1440.0 / 16.0)))
    probes = float(start_mjd) + pb * np.arange(8) / 8.0
    while span > 4:
        if all(fit_block(t, span)[2] <= max_resid_phase for t in probes):
            break
        span = max(4, span // 2)

    while True:
        blocks = []
        span_ok = True
        tmid = float(start_mjd)
        while tmid - 0.5 * (span / 1440.0) <= end_mjd:
            # Fit around TMID exactly as evaluation will see it: Polyco
            # splits tmid_str into TMIDi + TMIDf (the fraction parsed at
            # full float64 precision, which differs from
            # frac(float(tmid_str)) by ~1e-12 days ~ 1e-4 rotations at
            # 200 Hz), so reconstruct that split in longdouble here.
            tmid_str = f"{tmid:.11f}"
            ipart, _, fpart = tmid_str.partition(".")
            tmid_eval = np.longdouble(int(ipart)) + np.longdouble(
                float("0." + fpart))
            coeffs, n_tmid, resid = fit_block(tmid_eval, span)
            if resid > max_resid_phase and span > 4:
                # a production block (e.g. a fast periastron sweep the
                # start-epoch probes missed) needs a finer span; polycos
                # must share one dataspan, so restart smaller
                span_ok = False
                break
            f0_block = coeffs[1] / 60.0
            pcoeffs = coeffs.copy()
            pcoeffs[1] = 0.0  # linear term lives in F0_block
            mjdi = int(tmid_eval)
            frac_h = (tmid_eval - mjdi) * 24.0
            hh = int(frac_h)
            mm = int((frac_h - hh) * 60)
            ss = (frac_h - hh) * 3600 - mm * 60
            blocks.append(
                Polyco(
                    psr=psrname,
                    date="DD-MMM-YY",
                    utc=f"{hh:02d}{mm:02d}{ss:05.2f}".replace(".", ""),
                    tmid_str=tmid_str,
                    dm=dm,
                    doppler=0.0,
                    log10rms=-10.0,
                    rphase=float(n_tmid),
                    f0=f0_block,
                    obs=obs,
                    dataspan=span,
                    numcoeff=numcoeffs,
                    obsfreq=obsfreq,
                    coeffs=pcoeffs,
                )
            )
            tmid += span / 1440.0
        if span_ok:
            return Polycos(filenm="<generated-binary>", blocks=blocks)
        span = max(4, span // 2)


def create_polycos(
    par: Union[str, PsrPar],
    telescope_id: str,
    center_freq: float,
    start_mjd: int,
    end_mjd: int,
    max_hour_angle=None,
    span: int = SPAN_DEFAULT,
    numcoeffs: int = NUMCOEFFS_DEFAULT,
    keep_file: bool = False,
) -> Polycos:
    """Create polycos from a parfile via ``tempo -z`` (reference
    mypolycos.py:213-276).  Falls back to the native spin-down generator
    (or the native Keplerian generator for binary ephemerides) when the
    TEMPO binary is unavailable; topocentric data without TEMPO raises."""
    if isinstance(par, str):
        par = PsrPar(par)

    if shutil.which("tempo") is None:
        if hasattr(par, "BINARY"):
            if telescope_id not in ("@", "0"):
                raise PolycoError(
                    "TEMPO binary not found; native binary polycos are "
                    "only valid for barycentred data (telescope_id '@' "
                    f"or '0', got {telescope_id!r})."
                )
            return create_polycos_from_binary(
                par, float(start_mjd), float(end_mjd), obs=telescope_id,
                obsfreq=center_freq, span=span, numcoeffs=numcoeffs,
            )
        if telescope_id not in ("@", "0"):
            # topocentric data needs Earth-motion corrections only TEMPO
            # provides; a pure spin-down polyco would smear the fold by
            # up to v/c ~ 1e-4 in apparent frequency
            raise PolycoError(
                "TEMPO binary not found; the native spin-down generator is "
                "only valid for barycentred/geocentric data (telescope_id "
                f"'@' or '0', got {telescope_id!r}).  Call "
                "create_polycos_from_spindown directly to override."
            )
        return create_polycos_from_spindown(
            par, float(start_mjd), float(end_mjd), obs=telescope_id,
            obsfreq=center_freq, span=span, numcoeffs=numcoeffs,
        )

    if max_hour_angle is None:
        telescope_name = id_to_telescope[telescope_id]
        max_hour_angle = telescope_to_maxha[telescope_name]

    with open("tz.in", "w") as tzfile:
        tzfile.write(
            f"{telescope_id} {max_hour_angle:d} {span:d} {numcoeffs:d} "
            f"{center_freq:0.5f}\n\n\n"
        )
        psrname = par.name.lstrip("BJ")
        tzfile.write(
            f"{psrname} {span:d} {numcoeffs:d} {max_hour_angle:d} "
            f"{center_freq:0.5f}\n"
        )
    proc = subprocess.Popen(
        ["tempo", "-z", "-f", par.FILE],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )
    out, err = proc.communicate(f"{start_mjd:d} {end_mjd:d}\n")
    try:
        new_polycos = Polycos(filenm="polyco.dat")
    except (OSError, PolycoError) as e:
        raise PolycoError(
            f"Could not read/create polycos!\nTEMPO stdout:\n{out}\n"
            f"TEMPO stderr:\n{err}\nParfile: {par.FILE}"
        ) from e
    finally:
        if os.path.exists("tz.in"):
            os.remove("tz.in")
        if not keep_file and os.path.exists("polyco.dat"):
            os.remove("polyco.dat")
    return new_polycos


def create_polycos_from_inf(par, infdata) -> Polycos:
    """Convenience wrapper using a .inf file's metadata (reference
    mypolycos.py:177-210; fixes the py2 ``type(x)==bytes`` check noted in
    SURVEY.md §2.6)."""
    if isinstance(infdata, str):
        infdata = InfoData(infdata)
    obslength = (infdata.dt * infdata.N) / psrmath.SECPERDAY
    # Barycentred data needs no Earth-motion correction whatever the
    # telescope was — check the flag BEFORE the site lookup so barycentred
    # products from unmapped/synthetic telescopes work, and topocentric
    # data from an unknown site fails loudly instead of folding smeared.
    if getattr(infdata, "bary", 0):
        telescope_id = "@"
    else:
        try:
            telescope_id = telescope_to_id[infdata.telescope]
        except KeyError:
            raise PolycoError(
                f"unknown telescope {infdata.telescope!r}: topocentric "
                "polycos need a TEMPO site id (astro/telescopes.py); "
                "barycentred data should set the .inf 'Barycentered?' flag"
            ) from None
    # '0' = Geocenter, '@' = barycenter (optical/X-ray/gamma-ray data)
    if telescope_id not in ("0", "@"):
        center_freq = infdata.lofreq + (infdata.numchan / 2 - 0.5) * infdata.chan_width
    else:
        center_freq = 0.0
    start_mjd = int(infdata.epoch)
    end_mjd = int(infdata.epoch + obslength) + 1
    return create_polycos(par, telescope_id, center_freq, start_mjd, end_mjd)
