"""Fourier-domain template matching (FFTFIT equivalent) and TOA output.

Replaces the external Fortran ``fftfit`` the reference calls
(bin/dissect.py:339-355, bin/pulses_to_toa.py:198-214) with a NumPy/JAX
implementation of the Taylor (1992, Phil. Trans. R. Soc. A 341, 117)
algorithm: fit observed profile p(i) ~ a + b*s(i - tau) by maximizing the
harmonic cross-correlation, with uncertainties from the curvature of the
chi-square surface.  The ``cprof``/``fftfit``/``measure_phase`` call
signatures mirror the ones the reference uses so tooling ports 1:1.

Also provides ``write_princeton_toa`` (reference's psr_utils dependency;
SURVEY.md §2.5) — the Princeton TOA format:

    columns 1-1   observatory code
            2-15  optional name
            16-24 frequency (MHz)
            25-44 TOA (decimal MJD)
            45-53 TOA uncertainty (us)
            69-78 DM correction (pc cm^-3)
"""

from __future__ import annotations

import sys
from typing import Tuple

import numpy as np

TWOPI = 2.0 * np.pi


class FFTFitError(Exception):
    pass


def cprof(template: np.ndarray):
    """Harmonic decomposition of the template: returns (c, amp, pha) where
    c is the complex rfft, amp/pha the amplitudes/phases of harmonics
    1..N/2 (the Fortran cprof surface used at dissect.py:352)."""
    template = np.asarray(template, dtype=np.float64)
    n = template.size
    nh = n // 2
    c = np.fft.rfft(template)
    amp = np.abs(c[1 : nh + 1])
    pha = np.angle(c[1 : nh + 1])
    return c, amp, pha


def fftfit(profile: np.ndarray, amp: np.ndarray, pha: np.ndarray
           ) -> Tuple[float, float, float, float, float, float, int]:
    """Measure the shift of ``profile`` relative to the template whose
    harmonic amplitudes/phases are (amp, pha).

    Returns (shift, eshift, snr, esnr, b, errb, ngood) with shift/eshift
    in profile bins — the Fortran fftfit surface.  ``shift`` is the
    number of bins the template must be rotated *rightward* (later phase)
    to align with the profile: profile(i) ~ a + b*template(i - shift).
    """
    profile = np.asarray(profile, dtype=np.float64)
    n = profile.size
    nh = len(amp)
    if nh < 1:
        raise FFTFitError("template has no harmonics")
    P = np.fft.rfft(profile)
    p_amp = np.abs(P[1 : nh + 1])
    p_pha = np.angle(P[1 : nh + 1])
    k = np.arange(1, nh + 1, dtype=np.float64)
    s_amp = np.asarray(amp, dtype=np.float64)
    s_pha = np.asarray(pha, dtype=np.float64)

    # template shifted right by tau_rad has harmonic phases
    # s_pha_k - k*tau_rad, so P_k ~ b*S_k*e^{-i k tau} and with
    # dphi = p_pha - s_pha the correlation g(tau) = sum w_k cos(dphi + k*tau)
    # peaks at tau = tau_rad.  Solve g'(tau)=0 by coarse grid + Newton.
    dphi = p_pha - s_pha

    ngrid = max(16 * nh, 64)
    taus = np.linspace(0, TWOPI, ngrid, endpoint=False)
    args = dphi[None, :] + np.outer(taus, k)
    g_grid = np.sum(p_amp * s_amp * np.cos(args), axis=1)
    tau = taus[int(np.argmax(g_grid))]

    w = p_amp * s_amp
    for _ in range(32):
        arg = dphi + k * tau
        dg = -np.sum(w * k * np.sin(arg))
        d2g = -np.sum(w * k * k * np.cos(arg))
        if d2g == 0.0:
            break
        step = -dg / d2g
        tau += step
        if abs(step) < 1e-14:
            break
    arg = dphi + k * tau
    g = np.sum(w * np.cos(arg))

    s2 = np.sum(s_amp**2)
    b = g / s2

    # noise variance per harmonic from the residual chi^2 (Taylor 1992
    # eq. A10 region); dof = 2*nh - 3 fitted params (a, b, tau)
    chi2 = np.sum(p_amp**2) - 2.0 * b * g + b * b * s2
    dof = max(2 * nh - 3, 1)
    sigma2 = max(chi2 / dof, 0.0)

    curv = np.sum(w * k * k * np.cos(arg))  # = -g''(tau)
    if b <= 0 or curv <= 0:
        # degenerate fit: flag the reference's error convention
        # (dissect.py:323-325 checks shift==0.0 and eshift==999.0)
        return 0.0, 999.0, 0.0, 0.0, float(b), 999.0, nh
    etau = np.sqrt(sigma2 / (2.0 * b * curv))
    errb = np.sqrt(sigma2 / (2.0 * s2))

    shift = (tau / TWOPI) * n
    # wrap to [-n/2, n/2)
    shift = (shift + n / 2) % n - n / 2
    eshift = (etau / TWOPI) * n

    snr = b * np.sqrt(2.0 * s2) / np.sqrt(sigma2) if sigma2 > 0 else np.inf
    esnr = errb * np.sqrt(2.0 * s2) / np.sqrt(sigma2) if sigma2 > 0 else 0.0
    return float(shift), float(eshift), float(snr), float(esnr), float(b), float(errb), nh


def measure_phase(profile: np.ndarray, template: np.ndarray):
    """Reference measure_phase surface (bin/dissect.py:339-355): rotate the
    template so its fundamental has zero phase, then fftfit.  Returns
    (shift, eshift, snr, esnr, b, errb, ngood, pha1)."""
    c, amp, pha = cprof(template)
    pha1 = pha[0]
    pha = np.fmod(pha - np.arange(1, len(pha) + 1) * pha1, TWOPI)
    shift, eshift, snr, esnr, b, errb, ngood = fftfit(profile, amp, pha)
    return shift, eshift, snr, esnr, b, errb, ngood, pha1


def presto_freq_offsets(lofreq: float, bw: float, chan_width: float,
                        dm: float):
    """(midfreq, dmdelay_seconds) with PRESTO get_TOAs.py's channel-edge
    conventions: hifreq has no half-channel offset and is one channel below
    the band top (reference bin/dissect.py:290-300)."""
    from pypulsar_tpu.core import psrmath

    hifreq = lofreq + bw - chan_width
    midfreq = lofreq - 0.5 * chan_width + 0.5 * bw
    dmdelay = (psrmath.delay_from_DM(dm, midfreq) -
               psrmath.delay_from_DM(dm, hifreq))
    return midfreq, dmdelay


def emit_princeton_toa(summed_pulse, template_profile, t0i: int, t0f: float,
                       period: float, midfreq: float, dm: float,
                       obs_code: str = "@"):
    """Template-match ``summed_pulse`` and print one Princeton TOA.

    Shared tail of the TOA pipelines (reference bin/dissect.py:308-336 and
    bin/pulses_to_toa.py:167-195): FFTFIT the profile against the
    template, validate the fit, convert the bin shift to time, and write
    the line.  Returns (tau, tphs) — the pulse shift and template
    rotation, both in rotational phase.
    """
    from pypulsar_tpu.core import psrmath

    if template_profile is None:
        raise ValueError("A template profile MUST be provided.")
    shift, eshift, snr, esnr, b, errb, ngood, tphs = measure_phase(
        summed_pulse.profile, template_profile)
    tphs = tphs / TWOPI % 1.0
    tau, tau_err = shift / summed_pulse.N, eshift / summed_pulse.N
    # fftfit's bad-fit sentinel
    if np.fabs(shift) < 1e-7 and np.fabs(eshift - 999.0) < 1e-7:
        raise FFTFitError("Error in FFTFIT. Bad return values.")
    toaf = t0f + tau * period / psrmath.SECPERDAY
    newdays = int(np.floor(toaf))
    write_princeton_toa(t0i + newdays, toaf - newdays,
                        tau_err * period * 1e6, midfreq, dm, obs=obs_code)
    return tau, tphs


def format_princeton_toa(toa_MJDi: int, toa_MJDf: float, toaerr: float,
                         freq: float, dm: float, obs: str = "@",
                         name: str = " " * 13) -> str:
    """Princeton-format TOA line (the psr_utils.write_princeton_toa
    behavior; used at bin/dissect.py:330, bin/pulses_to_toa.py)."""
    # fractional MJD printed to 13 decimal places, no leading zero
    fracstr = f"{toa_MJDf:.13f}"
    if fracstr.startswith("0."):
        fracstr = fracstr[1:]
    elif fracstr.startswith("-0."):
        raise ValueError("fractional MJD must be non-negative")
    toastr = f"{toa_MJDi:5d}{fracstr}"
    line = f"{obs}{name:13s} {freq:8.3f} {toastr} {toaerr:8.2f}"
    if dm != 0.0:
        # line is 52 chars here; 16 spaces put the F10.4 DM at cols 69-78
        line += f"{'':16s}{dm:10.4f}"
    return line


def write_princeton_toa(toa_MJDi, toa_MJDf, toaerr, freq, dm, obs="@",
                        name=" " * 13, file=None):
    print(format_princeton_toa(toa_MJDi, toa_MJDf, toaerr, freq, dm, obs,
                               name), file=file or sys.stdout)
