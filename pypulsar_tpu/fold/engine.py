"""Device fold engine: scatter-add time samples into pulse-phase bins.

The reference folds on the host, one rotation at a time, by cutting the
time series at polyco-predicted period boundaries (formats/datfile.py:231-275
driving bin/dissect.py) — O(pulses) Python iterations.  The TPU-native
design evaluates the phase polynomial for a whole block of samples at once
(float64, host) and folds the block on device with a single segment-sum:

    profile[b] = sum data[i] where floor(phase_i * nbins) % nbins == b

Note the binning convention: bin b collects phases [b/nbins, (b+1)/nbins),
so its representative phase is the bin *center* (b+0.5)/nbins — TOA code
comparing a folded profile against a template sampled at b/nbins must
account for the half-bin offset (as PRESTO's fold does).

1-D series fold with ``jax.ops.segment_sum``; 2-D [chan, time] folds (the
.pfd-style chan x phase archive) as a one-hot matmul on the MXU at
HIGHEST precision — the TPU-native scatter formulation (see fold_bins).
NumPy golden twins live alongside for parity tests (SURVEY.md §4
strategy 1).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pypulsar_tpu.compile import bucket_rows, plane_jit, register_warmer
from pypulsar_tpu.core.psrmath import SECPERDAY
from pypulsar_tpu.obs import telemetry


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _fold_bins_impl(data, bin_idx, nbins: int):
    data = jnp.asarray(data)
    bin_idx = jnp.asarray(bin_idx, jnp.int32)
    counts = jax.ops.segment_sum(
        jnp.ones(bin_idx.shape, jnp.int32), bin_idx, num_segments=nbins
    )
    if data.ndim == 1:
        prof = jax.ops.segment_sum(data, bin_idx, num_segments=nbins)
    else:
        prof, _ = _onehot_fold_2d(data, bin_idx, nbins)
    return prof, counts


_fold_bins_jit = plane_jit(_fold_bins_impl, static_argnames=("nbins",),
                           stage="fold")


def fold_bins(data, bin_idx, nbins: int):
    """Scatter-add ``data`` (1-D [time] or 2-D [chan, time]) into ``nbins``
    phase bins given per-sample bin indices.  Returns (profile, counts).

    The 2-D path is formulated as ``data @ one_hot(bin_idx)`` — a phase-
    bin scatter is a matmul with a 0/1 selection matrix, which runs on
    the MXU instead of XLA's serialized scatter-add (the vmapped
    segment_sum formulation measured ~7 s for a 1024x2^20 fold on v5e;
    the matmul is bandwidth-bound). Counts stay integer (float32 would
    saturate at 2^24 samples/bin)."""
    if telemetry.is_active():
        telemetry.counter("fold.samples", int(np.size(data)))
    with telemetry.span("fold_bins", nbins=nbins):
        return _fold_bins_jit(data, bin_idx, nbins)


_FOLD_BLOCK = 1 << 17  # bounds the live one-hot to ~64 MB at 128 bins


def _onehot_fold_2d(data, bin_idx, nbins: int):
    """``data[C, T] @ one_hot(bin_idx)`` accumulated over time blocks so
    the selection matrix never exceeds _FOLD_BLOCK x nbins (a monolithic
    one-hot is T*nbins*4 bytes — 64 GB for a 2^27-sample fold). The tail
    pads with index ``nbins``, which one_hot maps to an all-zero row.

    Returns (prof[C, nbins], counts_f32[nbins]) — counts are column sums
    of the same one-hot matrices: exact in f32 per block (0/1 sums up to
    _FOLD_BLOCK << 2^24) and across the f32 block accumulation until
    ~2^24 samples/bin. Callers needing exact counts beyond that
    (fold_bins' whole-series totals) use an integer segment_sum instead.
    HIGHEST precision throughout: the default TPU matmul rounds inputs
    to bf16, which visibly degrades fold sums (caught by the bench
    parity check)."""
    C, T = data.shape
    if T <= _FOLD_BLOCK:
        onehot = jax.nn.one_hot(bin_idx, nbins, dtype=data.dtype)
        prof = jnp.dot(data, onehot, preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)
        return prof, onehot.sum(axis=0)
    nblk = -(-T // _FOLD_BLOCK)
    pad = nblk * _FOLD_BLOCK - T
    d = jnp.pad(data, ((0, 0), (0, pad)))
    b = jnp.pad(bin_idx, (0, pad), constant_values=nbins)
    d = d.reshape(C, nblk, _FOLD_BLOCK).transpose(1, 0, 2)
    b = b.reshape(nblk, _FOLD_BLOCK)

    def body(acc, xs):
        dblk, bblk = xs
        acc_p, acc_c = acc
        onehot = jax.nn.one_hot(bblk, nbins, dtype=dblk.dtype)
        prof = jnp.dot(dblk, onehot, preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)
        return (acc_p + prof, acc_c + onehot.sum(axis=0)), None

    (prof, cnt), _ = jax.lax.scan(
        body, (jnp.zeros((C, nbins), jnp.float32),
               jnp.zeros((nbins,), jnp.float32)), (d, b))
    return prof, cnt


def _fold_parts_impl(data, bin_idx, nbins: int, npart: int):
    """Traceable body of :func:`fold_parts` (shared with the fused
    :func:`fold_stats` program, which inlines it in its own trace)."""
    data = jnp.asarray(data)
    bin_idx = jnp.asarray(bin_idx, jnp.int32)
    C, T = data.shape
    part_len = T // npart
    if part_len >= 1 << 24:
        raise ValueError(
            f"part_len={part_len} >= 2^24: f32 one-hot counts would lose "
            f"exactness; use more partitions")
    b = bin_idx[: npart * part_len].reshape(npart, part_len)

    def body(carry, ci):
        dpart = jax.lax.dynamic_slice(
            data, (0, ci * part_len), (C, part_len))
        prof, cnt = _onehot_fold_2d(dpart, b[ci], nbins)
        return carry, (prof, cnt.astype(jnp.int32))

    _, (profs, counts) = jax.lax.scan(body, 0, jnp.arange(npart))
    return profs, counts


_fold_parts_jit = plane_jit(_fold_parts_impl,
                            static_argnames=("nbins", "npart"), stage="fold")


def fold_parts(data, bin_idx, nbins: int, npart: int):
    """Fold into a ``[npart, nchan, nbins]`` sub-integration archive cube
    (the .pfd product) in ONE compiled program.

    ``data[C, T]`` is cut into ``npart`` equal partitions (a trailing
    remainder is dropped, as the reference's whole-rotation cuts drop the
    tail); a lax.scan folds each via the one-hot matmul, holding only one
    partition's selection matrix live. One dispatch for the whole cube —
    the per-partition dispatch loop it replaces paid ~60 ms of remote-
    tunnel latency per partition (bench r3, BENCHNOTES.md).

    Two measured costs are engineered out (v5e A/B, BENCHNOTES): the
    per-partition ``segment_sum`` count scatters (counts come from
    column sums of the SAME one-hot matrix — exact in f32 while
    part_len < 2^24, asserted host-side) and a whole-array pre-transpose
    (partitions slice out of the original layout inside the scan).
    Returns (profiles[npart, C, nbins], counts[npart, nbins])."""
    if telemetry.is_active():
        telemetry.counter("fold.samples", int(np.size(data)))
    with telemetry.span("fold_parts", nbins=nbins, npart=npart):
        return _fold_parts_jit(data, bin_idx, nbins, npart)


@plane_jit(static_argnames=("nbins", "npart"), stage="fold")
def _fold_stats_jit(data, bin_idx, nbins: int, npart: int, dp_offsets):
    """One-dispatch fold + ON-DEVICE profile statistics (VERDICT r3
    item 4): everything pfd_snr-style analysis needs leaves the device as
    KILOBYTES instead of the [npart, C, nbins] archive cube (33 MB at
    bench shapes — through a remote-accelerator link that pull dominated
    the fold end-to-end by up to 10x, BENCHNOTES r3).

    Computed inside the one program, on top of the fold_parts cube:
      - ``part_profs[npart, nbins]``: channel-summed sub-integration
        profiles (the .pfd time-phase plot),
      - ``chan_profs[C, nbins]``: partition-summed channel-phase archive
        (the frequency-phase plot / subband view),
      - ``counts[npart, nbins]``,
      - ``dsum, dsumsq``: folded-data moments for the off-pulse std
        (profile_snr.profile_std / L&K eq. 7.1, reference
        bin/pfd_snr.py:674-718),
      - ``dp_profs[J, nbins]``: bestprof-style period refinement — trial
        ``j`` rotates partition ``i`` by ``dp_offsets[j, i]`` cycles
        (Fourier rotation, exact for band-limited profiles) and sums;
        the host picks the chi2-max trial (reference surface:
        prepfold's .bestprof via bin/pfd_snr.py:151-156
        ``adjust_period``).

    ``dp_offsets[J, npart]`` float32 cycles. The cube itself never
    leaves the device and is freed with the program.
    """
    profs, counts = _fold_parts_impl(data, bin_idx, nbins, npart)
    part_profs = profs.sum(axis=1)  # [npart, nbins]
    chan_profs = profs.sum(axis=0)  # [C, nbins]
    C, T = data.shape
    part_len = T // npart
    used = data[:, : npart * part_len]
    dsum = jnp.sum(used, dtype=jnp.float32)
    dsumsq = jnp.sum(used * used, dtype=jnp.float32)
    # Fourier rotation: shifting a profile by x cycles multiplies rfft
    # bin k by exp(-2i*pi*k*x)
    pf = jnp.fft.rfft(part_profs, axis=1)  # [npart, K]
    k = jnp.arange(pf.shape[1], dtype=jnp.float32)
    ang = -2.0 * jnp.pi * dp_offsets[:, :, None] * k[None, None, :]
    rot = jax.lax.complex(jnp.cos(ang), jnp.sin(ang))  # [J, npart, K]
    # HIGHEST: the default TPU matmul rounds f32 inputs to bf16 (~2e-3
    # relative — the same trap _onehot_fold_2d documents), which would
    # swamp the 2e-4 twin-parity tolerance and noise the chi2 argmax
    dp_f = jnp.einsum("ik,jik->jk", pf, rot,
                      precision=jax.lax.Precision.HIGHEST)
    dp_profs = jnp.fft.irfft(dp_f, n=nbins, axis=1)  # [J, nbins]
    return part_profs, chan_profs, counts, dsum, dsumsq, dp_profs


def fold_stats(data, bin_idx, nbins: int, npart: int, dp_offsets):
    """See :func:`_fold_stats_jit` — this wrapper only adds telemetry
    (folded-sample counter + dispatch span) around the one-dispatch
    program, behind the inactive-is-one-branch check."""
    if telemetry.is_active():
        telemetry.counter("fold.samples", int(np.size(data)))
    with telemetry.span("fold_stats", nbins=nbins, npart=npart):
        return _fold_stats_jit(data, bin_idx, nbins, npart, dp_offsets)


def fold_stats_numpy(data, bin_idx, nbins: int, npart: int, dp_offsets):
    """Golden float64 twin of :func:`fold_stats`."""
    data = np.asarray(data, np.float64)
    C, T = data.shape
    part_len = T // npart
    profs = []
    counts = []
    for i in range(npart):
        p, c = fold_numpy(data[:, i * part_len:(i + 1) * part_len],
                          bin_idx[i * part_len:(i + 1) * part_len], nbins)
        profs.append(p)
        counts.append(c)
    profs = np.stack(profs)  # [npart, C, nbins]
    counts = np.stack(counts)
    part_profs = profs.sum(axis=1)
    chan_profs = profs.sum(axis=0)
    used = data[:, : npart * part_len]
    dsum = used.sum()
    dsumsq = (used * used).sum()
    pf = np.fft.rfft(part_profs, axis=1)
    k = np.arange(pf.shape[1])
    rot = np.exp(-2j * np.pi * np.asarray(dp_offsets)[:, :, None]
                 * k[None, None, :])
    dp_profs = np.fft.irfft(np.einsum("ik,jik->jk", pf, rot), n=nbins,
                            axis=1)
    return part_profs, chan_profs, counts, dsum, dsumsq, dp_profs


def bestprof_offsets(npart: int, T_sec: float, period: float,
                     ntrial: int = 65, max_drift_cycles: float = 2.0):
    """(dp_trials[J] seconds, dp_offsets[J, npart] cycles) for the
    fold_stats period refinement: a fold at period ``P`` of a signal with
    true period ``P + dp`` drifts by ``t * dp / P**2`` cycles at time t;
    trial j rotates partition i (mid-time t_i) by the OPPOSITE so the
    matching trial re-aligns the summed profile. ``max_drift_cycles`` is
    the drift across the whole observation at the largest trial."""
    dp_max = max_drift_cycles * period * period / max(T_sec, 1e-12)
    dps = np.linspace(-dp_max, dp_max, ntrial)
    t_mid = (np.arange(npart) + 0.5) * (T_sec / npart)
    off = -t_mid[None, :] * dps[:, None] / (period * period)
    return dps, off.astype(np.float32)


def fold_snr_stats(data, bin_idx, nbins: int, npart: int, dt: float,
                   period: float, ntrial: int = 65):
    """Device fold + fused statistics, then the host-side (float64, tiny)
    finishing math: off-pulse std from the data moments, L&K eq. 7.1 SNR
    of the summed profile with an auto on-pulse region, and the refined
    period from the chi2-max dp trial. One device dispatch; ~100 KB
    pulled (vs the 33 MB cube).

    Returns a dict with ``snr``, ``best_period``, ``chi2`` [J],
    ``dp_trials`` [J], ``profile`` [nbins], ``part_profs``,
    ``chan_profs``, ``counts``.
    """
    import jax.numpy as jnp

    from pypulsar_tpu.fold.profile_snr import (
        OnPulseError,
        calc_snr,
        onpulse_auto,
        profile_std,
    )

    C, T = np.shape(data)
    part_len = T // npart
    T_sec = npart * part_len * dt
    dps, off = bestprof_offsets(npart, T_sec, period, ntrial=ntrial)
    out = fold_stats(jnp.asarray(data), jnp.asarray(bin_idx), nbins, npart,
                     jnp.asarray(off))
    # one batched pull, then f64 on host: six per-array np.asarray pulls
    # would pay six ~65 ms tunnel roundtrips (ops/transfer.pull_host)
    from pypulsar_tpu.ops.transfer import pull_host

    part_profs, chan_profs, counts, dsum, dsumsq, dp_profs = \
        (np.asarray(x, dtype=np.float64) for x in pull_host(*out))
    n_used = C * npart * part_len
    data_var = dsumsq / n_used - (dsum / n_used) ** 2
    std = profile_std(max(data_var, 0.0), n_used, nbins, 1.0)
    prof = part_profs.sum(axis=0)
    try:
        snr = calc_snr(prof, onpulse_auto(prof), std)[0]
    except OnPulseError:
        snr = 0.0
    chi2 = ((dp_profs - dp_profs.mean(axis=1, keepdims=True)) ** 2).sum(axis=1)
    j = int(np.argmax(chi2))
    return dict(snr=float(snr), best_period=float(period + dps[j]),
                dp_trials=dps, chi2=chi2, profile=prof,
                part_profs=part_profs, chan_profs=chan_profs,
                counts=counts)


# ---------------------------------------------------------------------------
# batched candidate folding (the fold-pipeline kernels)
# ---------------------------------------------------------------------------

def _onehot_fold_1d_batch(data, bin_idx, nbins: int):
    """``[K]``-candidate fold of ONE shared 1-D block: each candidate k
    scatters the same ``data[T]`` into its own bins via
    ``einsum('t,ktb->kb', data, one_hot(bin_idx[k]))`` — the per-candidate
    contraction is the identical length-T f32 gemv the serial 2-D path
    (:func:`_onehot_fold_2d` at C=1) performs, batched on the candidate
    axis. Time blocking at the same ``_FOLD_BLOCK`` seams as the serial
    path, so the f32 accumulation splits match it; the LIVE one-hot is K
    times the serial path's (the candidate axis is the halving_dispatch
    axis on OOM — parallel/foldpipe). Byte-identity with the serial path
    is PINNED on the CPU backend (tests + BENCH_r07_fold.json); on other
    backends XLA may tile the batched contraction differently, where the
    guaranteed contract is the f32/SNR tolerance of the golden twins.
    Returns (prof[K, nbins] f32, counts[K, nbins] f32 — exact while
    block counts < 2^24, the _onehot_fold_2d argument)."""
    K, T = bin_idx.shape
    if T <= _FOLD_BLOCK:
        onehot = jax.nn.one_hot(bin_idx, nbins, dtype=data.dtype)
        prof = jnp.einsum("t,ktb->kb", data, onehot,
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
        return prof, onehot.sum(axis=1)
    nblk = -(-T // _FOLD_BLOCK)
    pad = nblk * _FOLD_BLOCK - T
    d = jnp.pad(data, (0, pad)).reshape(nblk, _FOLD_BLOCK)
    b = jnp.pad(bin_idx, ((0, 0), (0, pad)), constant_values=nbins)
    b = b.reshape(K, nblk, _FOLD_BLOCK).transpose(1, 0, 2)

    def body(acc, xs):
        dblk, bblk = xs
        acc_p, acc_c = acc
        onehot = jax.nn.one_hot(bblk, nbins, dtype=dblk.dtype)
        prof = jnp.einsum("t,ktb->kb", dblk, onehot,
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
        return (acc_p + prof, acc_c + onehot.sum(axis=1)), None

    (prof, cnt), _ = jax.lax.scan(
        body, (jnp.zeros((K, nbins), jnp.float32),
               jnp.zeros((K, nbins), jnp.float32)), (d, b))
    return prof, cnt


def _fold_parts_batch_impl(series, bin_idx, nbins: int, npart: int):
    series = jnp.asarray(series)
    bin_idx = jnp.asarray(bin_idx, jnp.int32)
    K, T = bin_idx.shape
    part_len = T // npart
    if part_len >= 1 << 24:
        raise ValueError(
            f"part_len={part_len} >= 2^24: f32 one-hot counts would lose "
            f"exactness; use more partitions")
    d = series[: npart * part_len].reshape(npart, part_len)
    b = bin_idx[:, : npart * part_len].reshape(
        K, npart, part_len).transpose(1, 0, 2)

    def body(carry, xs):
        dpart, bpart = xs
        prof, cnt = _onehot_fold_1d_batch(dpart, bpart, nbins)
        return carry, (prof, cnt.astype(jnp.int32))

    _, (profs, counts) = jax.lax.scan(body, 0, (d, b))
    return profs.transpose(1, 0, 2), counts.transpose(1, 0, 2)


_fold_parts_batch_jit = plane_jit(_fold_parts_batch_impl,
                                  static_argnames=("nbins", "npart"),
                                  stage="fold")


def fold_parts_batch(series, bin_idx, nbins: int, npart: int):
    """Fold ONE shared dedispersed series at ``K`` candidates' phase
    models in one compiled program: ``series[T]`` float32 is cut into
    ``npart`` partitions (trailing remainder dropped, as
    :func:`fold_parts`) and each partition is folded per candidate via
    the batched one-hot contraction — the fold-pipeline core (candidates
    sharing a DM share the data pass; only the per-candidate bin indices
    differ). Returns (profiles[K, npart, nbins] f32,
    counts[K, npart, nbins] int32)."""
    if telemetry.is_active():
        telemetry.counter("fold.samples",
                          int(np.shape(bin_idx)[0]) * int(np.size(series)))
    with telemetry.span("fold_parts_batch", nbins=nbins, npart=npart,
                        n_cands=int(np.shape(bin_idx)[0])):
        return _fold_parts_batch_jit(series, bin_idx, nbins, npart)


def _onehot_fold_1d_multi(data, bin_idx, nbins: int):
    """Multi-series twin of :func:`_onehot_fold_1d_batch`: candidate k
    folds its OWN ``data[k]`` row (``einsum('kt,ktb->kb')``) instead of
    one shared series. Per candidate the contraction is the identical
    length-T f32 gemv — same ``_FOLD_BLOCK`` seams, same HIGHEST
    precision — so on the CPU backend each row is bit-identical to the
    shared-series kernel fed that row's series (the batch-broker fusion
    contract, pinned by tests/test_broker.py)."""
    K, T = bin_idx.shape
    if T <= _FOLD_BLOCK:
        onehot = jax.nn.one_hot(bin_idx, nbins, dtype=data.dtype)
        prof = jnp.einsum("kt,ktb->kb", data, onehot,
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
        return prof, onehot.sum(axis=1)
    nblk = -(-T // _FOLD_BLOCK)
    pad = nblk * _FOLD_BLOCK - T
    d = jnp.pad(data, ((0, 0), (0, pad))).reshape(
        K, nblk, _FOLD_BLOCK).transpose(1, 0, 2)
    b = jnp.pad(bin_idx, ((0, 0), (0, pad)), constant_values=nbins)
    b = b.reshape(K, nblk, _FOLD_BLOCK).transpose(1, 0, 2)

    def body(acc, xs):
        dblk, bblk = xs
        acc_p, acc_c = acc
        onehot = jax.nn.one_hot(bblk, nbins, dtype=dblk.dtype)
        prof = jnp.einsum("kt,ktb->kb", dblk, onehot,
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
        return (acc_p + prof, acc_c + onehot.sum(axis=1)), None

    (prof, cnt), _ = jax.lax.scan(
        body, (jnp.zeros((K, nbins), jnp.float32),
               jnp.zeros((K, nbins), jnp.float32)), (d, b))
    return prof, cnt


def _fold_parts_multi_impl(stack, series_idx, bin_idx, nbins: int,
                           npart: int):
    stack = jnp.asarray(stack)
    series_idx = jnp.asarray(series_idx, jnp.int32)
    bin_idx = jnp.asarray(bin_idx, jnp.int32)
    K, T = bin_idx.shape
    part_len = T // npart
    if part_len >= 1 << 24:
        raise ValueError(
            f"part_len={part_len} >= 2^24: f32 one-hot counts would lose "
            f"exactness; use more partitions")
    # gather each candidate's series row, then mirror
    # _fold_parts_batch_impl exactly (same partition cut, same scan)
    d = stack[series_idx, : npart * part_len].reshape(
        K, npart, part_len).transpose(1, 0, 2)
    b = bin_idx[:, : npart * part_len].reshape(
        K, npart, part_len).transpose(1, 0, 2)

    def body(carry, xs):
        dpart, bpart = xs
        prof, cnt = _onehot_fold_1d_multi(dpart, bpart, nbins)
        return carry, (prof, cnt.astype(jnp.int32))

    _, (profs, counts) = jax.lax.scan(body, 0, (d, b))
    return profs.transpose(1, 0, 2), counts.transpose(1, 0, 2)


_fold_parts_multi_jit = plane_jit(_fold_parts_multi_impl,
                                  static_argnames=("nbins", "npart"),
                                  stage="fold")


def fold_parts_multi(stack, series_idx, bin_idx, nbins: int, npart: int):
    """Fold ``K`` candidates against ``G`` DIFFERENT equal-length
    series in one compiled program: candidate k folds
    ``stack[series_idx[k]]`` at its own phase model. This is the batch
    broker's fused fold kernel (round 24) — candidates from several
    observations, each with its own dedispersed series, fuse into ONE
    device dispatch. Row k is bit-identical (CPU backend) to
    ``fold_parts_batch(stack[series_idx[k]], bin_idx[k:k+1], ...)``.
    Returns (profiles[K, npart, nbins] f32, counts[K, npart, nbins]
    int32)."""
    if telemetry.is_active():
        telemetry.counter("fold.samples",
                          int(np.shape(bin_idx)[0])
                          * int(np.shape(stack)[-1]))
    with telemetry.span("fold_parts_multi", nbins=nbins, npart=npart,
                        n_cands=int(np.shape(bin_idx)[0]),
                        n_series=int(np.shape(stack)[0])):
        return _fold_parts_multi_jit(stack, series_idx, bin_idx, nbins,
                                     npart)


def fold_parts_batch_numpy(series, bin_idx, nbins: int, npart: int):
    """Golden float64 twin of :func:`fold_parts_batch`: per candidate,
    per partition, the EXACT per-candidate :func:`fold_numpy` bincount —
    bit-identical to folding each candidate alone (the parity contract
    of the batched pipeline)."""
    series = np.asarray(series, np.float64)
    bin_idx = np.asarray(bin_idx)
    K, T = bin_idx.shape
    part_len = T // npart
    profs = np.empty((K, npart, nbins), np.float64)
    counts = np.empty((K, npart, nbins), np.int64)
    for k in range(K):
        for i in range(npart):
            sl = slice(i * part_len, (i + 1) * part_len)
            p, c = fold_numpy(series[sl], bin_idx[k, sl], nbins)
            profs[k, i] = p
            counts[k, i] = c.astype(np.int64)
    return profs, counts


@plane_jit(stage="fold")
def _refine_chi2_jit(part_profs, offsets):
    """chi2[K, J] of every candidate x drift-trial combination: trial j
    rotates candidate k's partition i by ``offsets[j, i]`` cycles
    (Fourier phase ramp — exact for band-limited profiles, the
    fold_stats dp machinery generalized to a shared 2-D (p, pdot) drift
    grid), sums the re-aligned partitions and scores the summed profile
    by its variance about the mean (the chi2-max trial is the
    best-aligned one). ZERO refolds: the data never re-enters — only the
    [npart, nbins] sub-profiles rotate."""
    nbins = part_profs.shape[-1]
    pf = jnp.fft.rfft(part_profs, axis=-1)  # [K, npart, F]
    k = jnp.arange(pf.shape[-1], dtype=jnp.float32)
    ang = -2.0 * jnp.pi * offsets[:, :, None] * k[None, None, :]
    rot = jax.lax.complex(jnp.cos(ang), jnp.sin(ang))  # [J, npart, F]
    # HIGHEST: same bf16-rounding trap _fold_stats_jit documents
    dp_f = jnp.einsum("inf,jnf->ijf", pf, rot,
                      precision=jax.lax.Precision.HIGHEST)  # [K, J, F]
    profs = jnp.fft.irfft(dp_f, n=nbins, axis=-1)  # [K, J, nbins]
    return ((profs - profs.mean(axis=-1, keepdims=True)) ** 2).sum(axis=-1)


def refine_chi2(part_profs, offsets):
    """See :func:`_refine_chi2_jit`; this wrapper adds the dispatch span."""
    with telemetry.span("fold_refine", n_cands=int(np.shape(part_profs)[0]),
                        n_trials=int(np.shape(offsets)[0])):
        return _refine_chi2_jit(jnp.asarray(part_profs),
                                jnp.asarray(offsets, jnp.float32))


def refine_chi2_numpy(part_profs, offsets):
    """Golden float64 twin of :func:`refine_chi2`."""
    part_profs = np.asarray(part_profs, np.float64)
    off = np.asarray(offsets, np.float64)
    pf = np.fft.rfft(part_profs, axis=-1)
    k = np.arange(pf.shape[-1])
    rot = np.exp(-2j * np.pi * off[:, :, None] * k[None, None, :])
    profs = np.fft.irfft(np.einsum("inf,jnf->ijf", pf, rot),
                         n=part_profs.shape[-1], axis=-1)
    return ((profs - profs.mean(axis=-1, keepdims=True)) ** 2).sum(axis=-1)


def refine_drift_grid(ntrial_p: int = 33, ntrial_pd: int = 17,
                      max_drift_cycles: float = 2.0):
    """The candidate-INDEPENDENT (p, pdot) refinement trial grid,
    parametrized in whole-observation drift cycles so one grid (and one
    device rotation tensor) serves every candidate in a batch regardless
    of its period:

    - ``dl``: linear drift over the observation, cycles. A fold at P of
      a signal at P + dp is re-aligned by the trial with
      ``dl = dp * T / P**2`` (the bestprof_offsets relation,
      ``off = -t * dp / P**2`` with u = t/T normalized);
    - ``dq``: quadratic drift, cycles. A pdot error dpd is re-aligned by
      ``dq = dpd * T**2 / (2 P**2)``.

    Returns (dl[J], dq[J]) flattened over the ``ntrial_p x ntrial_pd``
    grid (``ntrial_pd=1`` collapses to the pure-period bestprof grid);
    :func:`drift_offsets` turns them into per-partition rotation offsets
    and :func:`drift_to_p_pd` maps a winning trial back to a candidate's
    (p, pdot)."""
    # a single-trial axis collapses to ZERO drift (np.linspace(-m, m, 1)
    # would return [-m], biasing every refined value by a full -m drift)
    dls = (np.linspace(-max_drift_cycles, max_drift_cycles, ntrial_p)
           if ntrial_p > 1 else np.array([0.0]))
    dqs = (np.linspace(-max_drift_cycles, max_drift_cycles, ntrial_pd)
           if ntrial_pd > 1 else np.array([0.0]))
    DL, DQ = np.meshgrid(dls, dqs, indexing="ij")
    return DL.ravel(), DQ.ravel()


def drift_offsets(dl: np.ndarray, dq: np.ndarray, npart: int) -> np.ndarray:
    """offsets[J, npart] float32 rotation cycles for the drift grid:
    partition i (normalized mid-time u_i) of trial j re-aligns by the
    drift the trial hypothesizes at u_i (the bestprof_offsets sign
    convention, which the fold_stats chi2-argmax machinery pins down)."""
    u = (np.arange(npart) + 0.5) / npart
    off = -(dl[:, None] * u[None, :] + dq[:, None] * u[None, :] ** 2)
    return off.astype(np.float32)


def drift_to_p_pd(dl: float, dq: float, period: float, pdot: float,
                  T_sec: float):
    """Map a winning drift trial back to this candidate's refined
    (p, pdot): inverse of the :func:`refine_drift_grid` relations."""
    dp = dl * period * period / max(T_sec, 1e-12)
    dpd = 2.0 * dq * period * period / max(T_sec * T_sec, 1e-24)
    return period + dp, pdot + dpd


def phase_to_bins(phases: np.ndarray, nbins: int) -> np.ndarray:
    """Fractional rotation counts -> phase bin indices (host, float64)."""
    return (np.floor(np.asarray(phases, np.float64) * nbins).astype(np.int64)
            % nbins).astype(np.int32)


def fold_numpy(data: np.ndarray, bin_idx: np.ndarray, nbins: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Golden twin of fold_bins."""
    data = np.asarray(data)
    bin_idx = np.asarray(bin_idx)
    counts = np.bincount(bin_idx, minlength=nbins).astype(np.float32)
    if data.ndim == 1:
        prof = np.bincount(bin_idx, weights=data, minlength=nbins)
    else:
        prof = np.stack(
            [np.bincount(bin_idx, weights=row, minlength=nbins) for row in data]
        )
    return prof.astype(np.float64), counts


# ---------------------------------------------------------------------------
# phase models
# ---------------------------------------------------------------------------

def phases_constant_period(n: int, dt: float, period: float,
                           start_phase: float = 0.0) -> np.ndarray:
    """Sample phases for a constant period (bin/dissect.py's '-p' mode)."""
    return start_phase + np.arange(n, dtype=np.float64) * (dt / period)


def phases_from_polycos(pcs, mjdstart: float, n: int, dt: float) -> np.ndarray:
    """Absolute rotation counts for n samples starting at mjdstart, from a
    Polycos container.  Evaluated blockwise per valid polyco so each block
    uses one polynomial (float64; the per-sample Horner loop of the
    reference collapses to vectorized polyval)."""
    mjdi = int(mjdstart)
    mjdf0 = mjdstart - mjdi
    tsamp_days = dt / SECPERDAY
    out = np.empty(n, dtype=np.float64)
    i = 0
    while i < n:
        mjdf = mjdf0 + i * tsamp_days
        block_poly = pcs.polycos[pcs.select_polyco(mjdi, mjdf)]
        # samples still covered by this block
        t_end = block_poly.TMID + pcs.validrange
        remaining = int(
            min(n - i, max(1, np.floor((t_end - (mjdi + mjdf)) / tsamp_days)))
        )
        idx = np.arange(i, i + remaining, dtype=np.float64)
        out[i : i + remaining] = block_poly.rotation_batch(
            mjdi, mjdf0 + idx * tsamp_days
        )
        i += remaining
    return out


# ---------------------------------------------------------------------------
# high-level folds
# ---------------------------------------------------------------------------

def _fold_any(data, dt, nbins, n, period, polycos, mjdstart, normalize):
    if period is not None:
        phases = phases_constant_period(n, dt, period)
    elif polycos is not None and mjdstart is not None:
        phases = phases_from_polycos(polycos, mjdstart, n, dt)
    else:
        raise ValueError("need period or (polycos, mjdstart)")
    bin_idx = phase_to_bins(phases, nbins)
    prof, counts = fold_bins(jnp.asarray(np.asarray(data, np.float32)),
                             bin_idx, nbins)
    prof = np.asarray(prof, dtype=np.float64)
    counts = np.asarray(counts, dtype=np.float64)
    if normalize:
        prof = np.where(counts > 0, prof / np.maximum(counts, 1), 0.0)
    return prof, counts


def fold_timeseries(
    data: np.ndarray,
    dt: float,
    nbins: int,
    *,
    period: Optional[float] = None,
    polycos=None,
    mjdstart: Optional[float] = None,
    normalize: bool = False,
):
    """Fold a 1-D time series into an ``nbins`` profile.

    Give either a constant ``period`` or (``polycos``, ``mjdstart``).
    Returns (profile, counts) as numpy arrays; with ``normalize`` the
    profile is divided by per-bin counts (empty bins -> 0).
    """
    return _fold_any(data, dt, nbins, len(data), period, polycos, mjdstart,
                     normalize)


def fold_spectra(
    data: np.ndarray,
    dt: float,
    nbins: int,
    *,
    period: Optional[float] = None,
    polycos=None,
    mjdstart: Optional[float] = None,
    normalize: bool = False,
):
    """Fold 2-D [chan, time] data into a [chan, nbins] archive (the
    .pfd-style product)."""
    return _fold_any(data, dt, nbins, data.shape[1], period, polycos,
                     mjdstart, normalize)


# ---------------------------------------------------------------------------
# warm-pool precompile (round 22)

def _warm_fold(*, n_samples=None, downsamp=1, fold_nbins=64,
               fold_npart=32, fold_batch=32, **_ignored) -> int:
    """Warm-pool planner for the fold stage: AOT-lower the batched
    partition fold at the geometry the fold pipeline will dispatch —
    the downsampled series length and the candidate batch padded to the
    compile plane's bucket ladder (exactly what foldpipe's dispatch
    pads to). Abstract arrays only; nothing is read or dispatched."""
    T = int(n_samples or 0) // max(1, int(downsamp))
    if T <= 0:
        return 0
    K = bucket_rows(max(1, int(fold_batch)))
    series = jax.ShapeDtypeStruct((T,), np.float32)
    bins = jax.ShapeDtypeStruct((K, T), np.int32)
    return int(_fold_parts_batch_jit.warm(series, bins, int(fold_nbins),
                                          int(fold_npart)))


register_warmer("fold", _warm_fold)
