"""Pulse-profile SNR and flux estimation (Lorimer & Kramer eq. 7.1).

Non-interactive core of the reference's pfd_snr tool
(bin/pfd_snr.py:674-718 calc_snr; :34-110 model alignment and the
PRESTO-style Gaussian-components file): given a folded profile, an
on-pulse mask, and the fold statistics, compute

    std  = sqrt(data_var * Nfolded / nbin_eff),
           nbin_eff = proflen * DOF_corr
    SNR  = area / std / sqrt(weq),   weq = area / max(on-pulse)
    Smean = SNR * SEFD / sqrt(npol*T*BW) * sqrt(weq/(proflen-weq))

On-pulse selection modes: explicit (start, end) bin regions, a model
profile aligned by rotation search, or Gaussian components; the
reference's interactive matplotlib picker becomes the CLI's job.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from pypulsar_tpu.core import psrmath


class OnPulseError(Exception):
    pass


# ---------------------------------------------------------------------------
# model alignment (reference :32-67)
# ---------------------------------------------------------------------------

def transform(data: np.ndarray, rot: float, scale: float = 1.0,
              dc: float = 0.0) -> np.ndarray:
    """Rotate (by fraction of a turn), scale, and offset a model profile
    (reference :32-37)."""
    nrot = int(np.round(rot * len(data)))
    return np.asarray(psrmath.rotate(np.asarray(data), nrot)) * scale + dc


def get_rotation(profdata: np.ndarray, modeldata: np.ndarray,
                 scale: float = 1.0, dc: float = 0.0) -> float:
    """Best integer-bin rotation of the model onto the profile by RMS
    search over all phases (reference :39-49)."""
    n = len(profdata)
    prof = np.asarray(profdata, dtype=np.float64)
    model = np.asarray(modeldata, dtype=np.float64) * scale + dc
    # all rotations at once; row r is the model rotated LEFT by r bins,
    # matching transform()'s psrmath.rotate (PRESTO) convention
    idx = (np.arange(n)[None, :] + np.arange(n)[:, None]) % n
    resids = prof[None, :] - model[idx]
    rms = np.sqrt(np.mean(resids**2, axis=1))
    best = int(np.argmin(rms))
    return best / float(n)


def find_scale_and_phase(profdata: np.ndarray, modeldata: np.ndarray):
    """Least-squares (scale, dc) with per-candidate best rotation
    (reference :63-67)."""
    from scipy.optimize import leastsq

    def to_optimize(scale_dc):
        rot = get_rotation(profdata, modeldata, scale_dc[0], scale_dc[1])
        return profdata - transform(modeldata, rot, scale_dc[0], scale_dc[1])

    return leastsq(to_optimize, [1.0, 0.0])


def read_gaussfitfile(gaussfitfile: str, proflen: int):
    """PRESTO pygaussfit.py components file -> ([ncomp, proflen] profiles,
    const) (reference :73-110)."""
    phass, ampls, fwhms = [], [], []
    const = 0.0
    with open(gaussfitfile) as f:
        for line in f:
            ls = line.lstrip()
            if ls.startswith("phas"):
                phass.append(float(line.split()[2]))
            elif ls.startswith("ampl"):
                ampls.append(float(line.split()[2]))
            elif ls.startswith("fwhm"):
                fwhms.append(float(line.split()[2]))
            elif ls.startswith("const"):
                const = float(line.split()[2])
    if not (len(phass) == len(ampls) == len(fwhms)):
        raise OnPulseError(
            f"Number of phases, amplitudes, and FWHMs differ in "
            f"'{gaussfitfile}'!"
        )
    gauss_data = np.zeros((len(ampls), proflen))
    for ii in range(len(ampls)):
        data = ampls[ii] * psrmath.gaussian_profile(proflen, phass[ii],
                                                    fwhms[ii])
        dc = np.min(data)
        const += dc
        gauss_data[ii] = data - dc
    return gauss_data, const


def vonmises_profile(proflen: int, phase: float, concentration: float
                     ) -> np.ndarray:
    """Von Mises pulse component (the reference's injectpsr model dep)."""
    phs = np.arange(proflen, dtype=np.float64) / proflen
    return np.exp(concentration * (np.cos(2 * np.pi * (phs - phase)) - 1.0))


# ---------------------------------------------------------------------------
# on-pulse masks
# ---------------------------------------------------------------------------

def onpulse_from_regions(proflen: int, regions: Sequence[Tuple[int, int]]
                         ) -> np.ndarray:
    """Boolean mask from [start, end) bin regions (the reference's
    interactive selection, reference :675-679)."""
    mask = np.zeros(proflen, dtype=bool)
    for lo, hi in regions:
        mask[int(lo):int(hi)] = True
    if not mask.any():
        raise OnPulseError("No on-pulse region selected!")
    return mask


def onpulse_from_model(prof: np.ndarray, model: np.ndarray,
                       frac: float = 0.05) -> np.ndarray:
    """Align a model to the profile, mark bins where the aligned model
    exceeds ``frac`` of its peak (the ObservationWithModel path)."""
    rot = get_rotation(prof - np.median(prof), model - model.min())
    aligned = transform(model - model.min(), rot)
    mask = aligned > frac * aligned.max()
    if not mask.any():
        raise OnPulseError("Model produced an empty on-pulse region")
    return mask


def onpulse_auto(prof: np.ndarray, thresh_sigma: float = 3.0) -> np.ndarray:
    """Automatic on-pulse: bins above thresh_sigma of a robust (median/MAD)
    baseline, grown to the surrounding half-max region."""
    prof = np.asarray(prof, dtype=np.float64)
    med = np.median(prof)
    mad = np.median(np.abs(prof - med)) * 1.4826
    sigma = mad if mad > 0 else prof.std()  # MAD degenerates on quantized data
    if sigma == 0:
        raise OnPulseError("Flat profile")
    mask = (prof - med) > thresh_sigma * sigma
    if not mask.any():
        raise OnPulseError("No bins above threshold")
    return mask


# ---------------------------------------------------------------------------
# SNR / flux (reference :674-718)
# ---------------------------------------------------------------------------

def profile_std(data_var: float, Nfolded: float, proflen: int,
                dof_corr: float) -> float:
    """Correlation-corrected standard deviation of a folded profile bin
    (reference :685-688)."""
    nbin_eff = proflen * dof_corr
    return float(np.sqrt(data_var * Nfolded / nbin_eff))


def calc_snr(prof: np.ndarray, onpulse: np.ndarray, std: float):
    """L&K eq. 7.1 SNR (reference :690-698).  Returns (snr, weq, area,
    offpulse_mean)."""
    prof = np.asarray(prof, dtype=np.float64)
    onpulse = np.asarray(onpulse, dtype=bool)
    if onpulse.all():
        raise OnPulseError("On-pulse region covers the whole profile; "
                           "no off-pulse baseline left")
    offpulse = prof[~onpulse]
    mean = offpulse.mean()
    scaled = prof - mean
    area = float(np.sum(scaled[onpulse]))
    profmax = float(np.max(scaled[onpulse]))
    if profmax <= 0:
        raise OnPulseError("On-pulse region has no positive signal")
    weq = area / profmax
    if weq <= 0:
        raise OnPulseError("Non-positive equivalent width")
    snr = area / std / np.sqrt(weq)
    return float(snr), float(weq), area, float(mean)


def mean_flux(snr: float, weq: float, proflen: int, sefd: float, T: float,
              bw: float, npol: int = 2) -> float:
    """Mean flux density (mJy) from SNR and SEFD (reference :710-718;
    prepfold data are total-intensity so npol=2)."""
    return float(snr * sefd / np.sqrt(npol * T * bw)
                 * np.sqrt(weq / (proflen - weq)))


def pfd_snr(pfdfile, *, onpulse: Optional[np.ndarray] = None,
            regions: Optional[Sequence[Tuple[int, int]]] = None,
            model: Optional[np.ndarray] = None,
            sefd: Optional[float] = None, dedisperse: bool = True,
            verbose: bool = False):
    """End-to-end pfd -> SNR (the non-interactive pfd_snr main path:
    dedisperse at bestdm with doppler, adjust_period, select on-pulse,
    L&K 7.1).  Returns dict(snr, weq, std, smean)."""
    p = pfdfile
    if dedisperse:
        p.dedisperse(doppler=True)
        p.adjust_period()
    prof = p.sumprof
    if onpulse is None:
        if regions is not None:
            onpulse = onpulse_from_regions(p.proflen, regions)
        elif model is not None:
            onpulse = onpulse_from_model(prof, model)
        else:
            onpulse = onpulse_auto(prof)
    data_avg, data_var = p.stats.sum(axis=1).mean(axis=0)[1:3]
    std = profile_std(data_var, p.Nfolded, p.proflen, p.DOF_corr())
    snr, weq, area, offmean = calc_snr(prof, onpulse, std)
    out = {"snr": snr, "weq": weq, "std": std, "area": area,
           "offpulse_mean": offmean, "smean": None}
    if sefd is not None:
        bw = p.chan_wid * p.numchan
        out["smean"] = mean_flux(snr, weq, p.proflen, sefd, p.T, bw)
    if verbose:
        print(f"SNR: {snr:.2f}  weq: {weq:.2f} bins  std: {std:.3f}")
        if out["smean"] is not None:
            print(f"Mean flux density (mJy): {out['smean']:.4f}")
    return out
