from pypulsar_tpu.fold.pulse import Pulse, SummedPulse, read_pulse_from_file  # noqa: F401
from pypulsar_tpu.fold.polycos import (  # noqa: F401
    Polyco,
    Polycos,
    PolycoError,
    create_polycos,
    create_polycos_from_inf,
    create_polycos_from_spindown,
)
from pypulsar_tpu.fold.toa import (  # noqa: F401
    FFTFitError,
    cprof,
    fftfit,
    measure_phase,
    format_princeton_toa,
    write_princeton_toa,
)
from pypulsar_tpu.fold import profile_snr  # noqa: F401
from pypulsar_tpu.fold.engine import (  # noqa: F401
    fold_bins,
    fold_numpy,
    fold_parts,
    fold_timeseries,
    fold_spectra,
    phases_from_polycos,
    phases_constant_period,
    phase_to_bins,
)
