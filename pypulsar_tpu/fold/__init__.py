from pypulsar_tpu.fold.pulse import Pulse, SummedPulse, read_pulse_from_file  # noqa: F401
