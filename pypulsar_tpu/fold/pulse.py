"""Single-pulse profile objects.

Re-implements reference formats/pulse.py: the ``Pulse`` profile (a slice of a
dedispersed time series covering one rotation), on/off-pulse phase regions,
profile conditioning ops, the pulse text format, and ``SummedPulse``
accumulation with a per-file pulse registry.

Profiles are small (hundreds-thousands of bins) and pipeline logic is
branch-heavy, so this stays NumPy host-side; the batched-folding hot path
lives in ops/fold_ops.py. Py2-era defects fixed (SURVEY.md §2.6): proper
exceptions instead of string raises (pulse.py:189,203,430,440), true division
for bin indices (:107,191).
"""

from __future__ import annotations

import copy
import os.path
import warnings
from typing import List, Optional, Sequence, Tuple

import numpy as np
import scipy.signal


class OnPulseRegionError(Exception):
    """Error when on-pulse region is ill-defined."""

    def __str__(self):
        return f"On-pulse region is ill-defined. {self.args[0] if self.args else ''}"


class PulseIncompatibleError(Exception):
    """Raised when summing pulses with incompatible bin widths."""


class PulseConflictError(Exception):
    """Raised when the same pulse would be summed twice."""


class Pulse:
    """One pulse: profile slice + metadata (reference pulse.py:24-67).

    on_pulse_regions: list of (lo, hi) rotational-phase pairs in [0, 1].
    """

    def __init__(self, number, mjd, time, duration, profile, origfn, dt, dm,
                 telescope, lofreq, chan_width, bw, on_pulse_regions=None):
        self.number = number
        self.mjd = mjd
        self.time = time
        self.duration = duration
        self.profile = np.asarray(profile, dtype=np.float64).flatten()
        self.N = self.profile.size
        self.dt = dt
        self.dm = dm
        self.telescope = telescope
        self.lofreq = lofreq
        self.chan_width = chan_width
        self.bw = bw
        self.origfn = origfn
        if isinstance(on_pulse_regions, (list, np.ndarray)) and len(on_pulse_regions):
            self.set_onoff_pulse_regions(on_pulse_regions)
        else:
            self.on_pulse = None
            self.off_pulse = None

    def __str__(self):
        return (
            f"Pulse #: {self.number}\n\tMJD: {self.mjd:0.15f}\n"
            f"\tTime: {self.time:8.2f} s\n\tDuration: {self.duration:8.4f} s\n"
        )

    def set_onoff_pulse_regions(self, on_pulse_regions: Sequence[Tuple[float, float]]):
        """Validate + store on-pulse regions; derive the complementary
        off-pulse regions (None endpoints = profile edge), per reference
        pulse.py:73-108."""
        on_pulse = np.array(on_pulse_regions).astype("float64")
        on_pulse = on_pulse[on_pulse.argsort(axis=0).transpose()[0]]
        if np.any(on_pulse.flat != np.sort(on_pulse.flatten())):
            raise OnPulseRegionError("On-pulse regions overlap or are inverted")
        self.on_pulse = on_pulse
        off = list(on_pulse.flatten())
        if off[0] == 0.0:
            off = off[1:]
        else:
            off = [None] + off
        if off[-1] == 1.0:
            off = off[:-1]
        else:
            off = off + [None]
        self.off_pulse = np.array(off, dtype=object).reshape(len(off) // 2, 2)

    def get_data(self, regions=None) -> np.ndarray:
        """Concatenate profile data from phase regions (reference :110-131)."""
        if regions is None or len(regions) == 0:
            regions = [(None, None)]
        data = []
        for lo, hi in regions:
            lobin = None if lo is None else int(self.N * lo)
            hibin = None if hi is None else int(self.N * hi)
            if lobin is not None and hibin is not None and hibin <= lobin:
                raise OnPulseRegionError(f"lobin={lobin}, hibin={hibin}")
            data.append(self.profile[lobin:hibin])
        return np.concatenate(data)

    def get_on_pulse(self) -> np.ndarray:
        return self.get_data(self.on_pulse)

    def get_off_pulse(self) -> np.ndarray:
        return self.get_data(self.off_pulse)

    def get_pulse_energies(self) -> Tuple[float, float]:
        """(on-pulse, off-pulse) energies of the scaled profile (:145-157)."""
        c = self.make_copy()
        c.scale()
        return float(np.sum(c.get_on_pulse())), float(np.sum(c.get_off_pulse()))

    def make_copy(self) -> "Pulse":
        return copy.deepcopy(self)

    def scale(self):
        """Subtract off-pulse mean, divide by off-pulse std, in place (:165-175)."""
        off = self.get_off_pulse()
        self.profile = (self.profile - np.mean(off)) / np.std(off)

    def downsample(self, downfactor: int = 1):
        """Co-add ``downfactor`` adjacent bins in place; must divide N (:177-195)."""
        if downfactor > 1:
            if self.N % downfactor != 0:
                raise ValueError(
                    f"downfactor ({downfactor}) is not a factor of profile "
                    f"length ({self.N})"
                )
            self.N = self.N // downfactor
            self.profile = self.profile[: self.N * downfactor].reshape(
                self.N, downfactor
            ).sum(axis=1)
            self.dt *= downfactor

    def downsample_Nbins(self, N: int):
        """Downsample (by averaging) to exactly N bins; leftovers dropped (:197-215)."""
        if N > self.N:
            raise ValueError(
                f"Cannot downsample: new profile ({N}) longer than old ({self.N})"
            )
        downfactor = self.N // N
        numleftover = self.N % N
        prof = self.profile[: self.N - numleftover] if numleftover else self.profile
        self.profile = prof[: N * downfactor].reshape(N, downfactor).mean(axis=1)
        self.N = N
        self.dt *= downfactor

    def smooth(self, smoothfactor: int = 1):
        """RMS-preserving boxcar smooth with wrap padding, in place (:217-241)."""
        if smoothfactor > 1:
            kernel = np.ones(smoothfactor, dtype="float32") / np.sqrt(smoothfactor)
            prof = np.concatenate(
                [self.profile[-smoothfactor:], self.profile, self.profile[:smoothfactor]]
            )
            sm = scipy.signal.convolve(prof, kernel, "same")
            self.profile = sm[smoothfactor:-smoothfactor]

    def detrend(self, numchunks: int = 5):
        """Piecewise-linear detrend in place (:243-250)."""
        bp = np.round(np.linspace(0, self.N, numchunks + 1)).astype(int)
        self.profile = scipy.signal.detrend(self.profile, bp=bp)

    def interpolate(self, numsamples: int):
        """Linear re-interpolation to ``numsamples`` bins, in place (:252-261)."""
        xp = np.arange(self.N)
        x = np.linspace(0, self.N - 1, numsamples)
        self.profile = np.interp(x, xp, self.profile)
        self.dt = self.dt * self.N / float(numsamples)
        self.N = numsamples

    def interp_and_downsamp(self, numsamples: int):
        """Interpolate then downsample to ``numsamples`` bins (:263-279).

        The reference's ``int(N / numsamples) + 1`` is a py2-heritage
        ceil-div that over-downsamples when ``N % numsamples == 0``: at
        an exact multiple it interpolated to a LARGER grid than the
        profile has (resampling distortion for no reason) where the true
        ceiling is the exact factor and the interpolation is the
        identity."""
        downsamp = -(-self.N // numsamples)
        warnings.warn("interp_and_downsamp() may be unreliable")
        self.interpolate(downsamp * numsamples)
        self.downsample(downsamp)

    def is_masked(self, numchunks: int = 5) -> bool:
        """True if any of ``numchunks`` profile sections is flat (:281-294)."""
        edges = np.round(np.linspace(0, self.profile.size, numchunks + 1)).astype(int)
        for i in range(numchunks):
            if np.ptp(self.profile[edges[i] : edges[i + 1]]) == 0:
                return True
        return False

    def get_snr(self) -> float:
        """Max of the scaled on-pulse region (reference bin/dissect.py:358-369)."""
        c = self.make_copy()
        c.scale()
        return float(np.max(c.get_on_pulse() if c.on_pulse is not None else c.profile))

    def plot(self, basefn: Optional[str] = None, downfactor: int = 1,
             smoothfactor: int = 1, shownotes: bool = False,
             decorate: bool = False):
        """Plot the scaled profile to ``<basefn>.prof<number>.ps``
        (reference formats/pulse.py:296-337).  ``decorate`` adds off-pulse
        mean and +1-sigma lines; ``shownotes`` annotates the smoothing."""
        import matplotlib.pyplot as plt

        if basefn is None:
            basefn, _ = os.path.splitext(self.origfn)
        copy = self.make_copy()
        if smoothfactor > 1:
            copy.smooth(smoothfactor)
        copy.scale()
        plt.figure()
        if decorate and copy.on_pulse is not None:
            off = copy.get_off_pulse()
            avg, std = float(np.mean(off)), float(np.std(off))
            plt.axhline(avg, color="k", linestyle="--")
            plt.axhline(avg + std, color="k", linestyle=":")
        if shownotes:
            snrmax = float(np.max(copy.get_on_pulse()
                                  if copy.on_pulse is not None
                                  else copy.profile))
            plt.figtext(0.05, 0.025,
                        "Smooth factor: %d, Downsample factor: %d, "
                        "Max SNR: %f" % (smoothfactor, downfactor, snrmax),
                        size="xx-small")
        if downfactor > 1:
            copy.downsample(downfactor)
        plt.plot(copy.profile, "k-", lw=0.5)
        plt.xlabel("Profile bin")
        plt.title("Pulse #%d" % self.number)
        outfn = "%s.prof%d.ps" % (basefn, self.number)
        plt.savefig(outfn, orientation="landscape")
        plt.close()
        return outfn

    # --- text format (reference :339-374) ---
    def _header_lines(self) -> List[str]:
        lines = [
            f"# Original data file              = {self.origfn}\n",
            f"# Pulse Number                    = {self.number:d}\n",
            f"# MJD of start of pulse           = {self.mjd:0.15f}\n",
            f"# Time into observation (seconds) = {self.time:f}\n",
            f"# Duration of pulse (seconds)     = {self.duration:0.15f}\n",
            f"# Profile bins                    = {self.N:d}\n",
            f"# Width of profile bin (seconds)  = {self.dt:g}\n",
            f"# Dispersion Measure (cm^-3 pc)   = {self.dm:f}\n",
            f"# Telescope                       = {self.telescope}\n",
            f"# Low frequency mid-channel (MHz) = {self.lofreq:0.15f}\n",
            f"# Channel width (MHz)             = {self.chan_width:0.15f}\n",
            f"# Total bandwidth (MHz)           = {self.bw:0.15f}\n",
        ]
        if self.on_pulse is not None:
            for i, (lo, hi) in enumerate(self.on_pulse):
                lines.append(f"# On-pulse region {i:2d} (phase)      = {lo:f}-{hi:f}\n")
        return lines

    def write_to_file(self, basefn: Optional[str] = None):
        if basefn is None:
            basefn, _ = os.path.splitext(self.origfn)
        fn = f"{os.path.split(basefn)[1]}.prof{self.number}"
        with open(fn, "w") as f:
            f.writelines(self._header_lines())
            f.write("###################################\n")
            for i, val in enumerate(self.profile):
                f.write(f"{i:<10d} {val:f}\n")
        return fn

    def to_summed_pulse(self) -> "SummedPulse":
        return SummedPulse(
            self.number, self.mjd, self.time, self.duration, self.profile,
            self.origfn, self.dt, self.dm, self.telescope, self.lofreq,
            self.chan_width, self.bw, self.on_pulse,
        )

    def __add__(self, other):
        if hasattr(other, "pulse_registry"):
            summed = other.make_copy()
        else:
            summed = other.make_copy().to_summed_pulse()
        summed += self
        return summed


class SummedPulse(Pulse):
    """Accumulating pulse sum with a per-file registry of summed pulse
    numbers and double-count detection (reference pulse.py:402-536)."""

    def __init__(self, number, mjd, time, duration, profile, origfn, dt, dm,
                 telescope, lofreq, chan_width, bw, on_pulse_regions=None,
                 init_registry=None, init_count=1):
        super().__init__(number, mjd, time, duration, profile, origfn, dt, dm,
                         telescope, lofreq, chan_width, bw, on_pulse_regions)
        self.pulse_registry = init_registry if init_registry is not None else {origfn: [number]}
        self.count = init_count

    def __iadd__(self, other: Pulse) -> "SummedPulse":
        if self.dt != other.dt:
            raise PulseIncompatibleError(
                f"Incompatible bin widths: {self.dt} vs {other.dt}"
            )
        # validate the whole merge before mutating anything, so a conflict
        # raised mid-merge can't leave the registry out of sync with the profile
        if hasattr(other, "pulse_registry"):
            incoming = other.pulse_registry
            ocount = other.count
        else:
            incoming = {other.origfn: [other.number]}
            ocount = 1
        for fn, nums in incoming.items():
            mine = self.pulse_registry.get(fn, [])
            for num in nums:
                if num in mine:
                    raise PulseConflictError(f"Pulse {fn}:{num} already summed")
        for fn, nums in incoming.items():
            self.pulse_registry.setdefault(fn, []).extend(nums)

        self.N = int(np.min([self.N, other.N]))
        self.duration = float(np.min([self.duration, other.duration]))
        self.profile = self.profile[: self.N] + other.profile[: self.N]
        tot = float(self.count + ocount)
        self.number = (self.count * self.number + ocount * other.number) / tot
        self.time = (self.count * self.time + ocount * other.time) / tot
        self.mjd = (self.count * self.mjd + ocount * other.mjd) / tot
        self.count += ocount
        return self

    def __contains__(self, item) -> bool:
        if hasattr(item, "pulse_registry"):
            for fn, nums in item.pulse_registry.items():
                mine = self.pulse_registry.get(fn, [])
                if any(num in mine for num in nums):
                    return True
            return False
        return (
            item.origfn in self.pulse_registry
            and item.number in self.pulse_registry[item.origfn]
        )

    def write_to_file(self, basefn: Optional[str] = None):
        if basefn is None:
            basefn, _ = os.path.splitext(self.origfn)
        fn = f"{basefn}.summedprof"
        with open(fn, "w") as f:
            f.write(f"# Original data file              = {self.origfn}\n")
            f.write(f"# Pulse Number                    = {int(self.number):d}\n")
            f.write(f"# MJD of start of pulse           = {self.mjd:0.15f}\n")
            f.write(f"# Time into observation (seconds) = {self.time:f}\n")
            f.write(f"# Duration of pulse (seconds)     = {self.duration:0.15f}\n")
            f.write(f"# Profile bins                    = {self.N:d}\n")
            f.write(f"# Width of profile bin (seconds)  = {self.dt:g}\n")
            if self.on_pulse is not None:
                for i, (lo, hi) in enumerate(self.on_pulse):
                    f.write(f"# On-pulse region {i:2d} (phase)      = {lo:f}-{hi:f}\n")
            f.write(f"# Number of profiles summed       = {self.count:d}\n")
            for reg_fn in self.pulse_registry:
                for num in sorted(self.pulse_registry[reg_fn]):
                    f.write(f"# Pulse registry                  = {reg_fn}:{num}\n")
            f.write("###################################\n")
            for i, val in enumerate(self.profile):
                f.write(f"{i:<10d} {val:f}\n")
        return fn


def read_pulse_from_file(filename: str) -> Pulse:
    """Parse the pulse text format back into a Pulse (reference :539-580)."""
    profile = []
    on_pulse_regions = []
    meta = dict(origfn=None, number=0, mjd=0.0, time=0.0, duration=0.0, dt=0.0,
                dm=0.0, telescope=None, lofreq=0.0, chan_width=0.0, bw=0.0)
    with open(filename) as f:
        for line in f:
            if line.startswith("# Original data file"):
                meta["origfn"] = line.split("=")[-1].strip()
            elif line.startswith("# Pulse Number"):
                meta["number"] = int(line.split("=")[-1].strip())
            elif line.startswith("# MJD of start of pulse"):
                meta["mjd"] = float(line.split("=")[-1].strip())
            elif line.startswith("# Time into observation (seconds)"):
                meta["time"] = float(line.split("=")[-1].strip())
            elif line.startswith("# Duration of pulse (seconds)"):
                meta["duration"] = float(line.split("=")[-1].strip())
            elif line.startswith("# Width of profile bin (seconds)"):
                meta["dt"] = float(line.split("=")[-1].strip())
            elif line.startswith("# Dispersion Measure (cm^-3 pc)"):
                meta["dm"] = float(line.split("=")[-1].strip())
            elif line.startswith("# Telescope"):
                meta["telescope"] = line.split("=")[-1].strip()
            elif line.startswith("# Low frequency mid-channel (MHz)"):
                meta["lofreq"] = float(line.split("=")[-1].strip())
            elif line.startswith("# Channel width (MHz)"):
                meta["chan_width"] = float(line.split("=")[-1].strip())
            elif line.startswith("# Total bandwidth (MHz)"):
                meta["bw"] = float(line.split("=")[-1].strip())
            elif line.startswith("# On-pulse region"):
                val = line.split("=")[-1]
                lo, hi = val.split("-")[0].strip(), val.split("-")[1].strip()
                on_pulse_regions.append((float(lo), float(hi)))
            elif line.startswith("#"):
                pass
            else:
                profile.append(float(line.split()[-1].strip()))
    return Pulse(
        meta["number"], meta["mjd"], meta["time"], meta["duration"],
        np.array(profile), meta["origfn"], meta["dt"], meta["dm"],
        meta["telescope"], meta["lofreq"], meta["chan_width"], meta["bw"],
        on_pulse_regions,
    )
