"""Native (C++) codec bindings with transparent NumPy fallback.

The shared library (``codec.cpp``) implements the host-side hot loops of
the IO plane: bit unpacking, PSRFITS scale/offset/weight application,
zero-DM filtering, fused widen+transpose, and boxcar peak detection.  It
is compiled on first use with g++ (cached next to the source); when no
compiler or binary is available every entry point falls back to the NumPy
implementation, so the package works everywhere and accelerates where it
can.

Public surface mirrors the pure-Python codecs:
    unpack_bits(raw, nbits) -> float32[n]
    widen(raw) -> float32[n]
    scale_offset_weight(data, scales, offsets, weights) -> float32 in place
    zero_dm(data) -> float32 in place
    transpose_to_chan_major(raw, nspec, nchan, nbits) -> float32[chan, time]
    boxcar_peak_snr(series, widths) -> float32[nwidths]
    available() -> bool
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import warnings
from typing import Optional, Sequence

import numpy as np
from pypulsar_tpu.tune import knobs

_SRC = os.path.join(os.path.dirname(__file__), "codec.cpp")
_SRC_PREFETCH = os.path.join(os.path.dirname(__file__), "prefetch.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "libpsrcodec.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # compile to a temp path and rename atomically so concurrent
    # importers never dlopen a half-written .so
    tmp = _LIB + ".tmp.%d" % os.getpid()
    srcs = [s for s in (_SRC, _SRC_PREFETCH) if os.path.isfile(s)]
    cmd = (["g++", "-O3", "-std=c++17", "-shared", "-fPIC"] + srcs
           + ["-o", tmp, "-lpthread"])
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if proc.returncode != 0:
        warnings.warn("native codec build failed:\n" + proc.stderr[-2000:])
        return False
    try:
        os.replace(tmp, _LIB)
    except OSError:
        os.unlink(tmp)
        return os.path.isfile(_LIB)
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if knobs.env_str("PYPULSAR_TPU_NO_NATIVE"):
        return None
    stale = not os.path.isfile(_LIB) or any(
        os.path.isfile(s) and os.path.getmtime(s) > os.path.getmtime(_LIB)
        for s in (_SRC, _SRC_PREFETCH))
    if stale:
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    f32p = ctypes.POINTER(ctypes.c_float)
    i32p = ctypes.POINTER(ctypes.c_int32)
    voidp = ctypes.c_void_p
    sz = ctypes.c_size_t
    lib.unpack_bits_f32.argtypes = [u8p, f32p, sz, ctypes.c_int]
    lib.widen_u8_f32.argtypes = [u8p, f32p, sz]
    lib.widen_u16_f32.argtypes = [u16p, f32p, sz]
    lib.scale_offset_weight.argtypes = [f32p, f32p, f32p, f32p, sz, sz]
    lib.zero_dm.argtypes = [f32p, sz, sz]
    lib.transpose_to_chan_major.argtypes = [voidp, f32p, sz, sz,
                                            ctypes.c_int]
    lib.boxcar_peak_snr.argtypes = [f32p, sz, i32p, sz, f32p]
    i64 = ctypes.c_int64
    i64p = ctypes.POINTER(ctypes.c_int64)
    if hasattr(lib, "pf_open"):
        lib.pf_open.argtypes = [ctypes.c_char_p, i64, i64, i64, i64, i64,
                                ctypes.c_int]
        lib.pf_open.restype = voidp
        lib.pf_acquire.argtypes = [voidp, ctypes.POINTER(u8p), i64p, i64p]
        lib.pf_acquire.restype = ctypes.c_int
        lib.pf_release.argtypes = [voidp]
        lib.pf_close.argtypes = [voidp]
    _lib = lib
    return _lib


def available() -> bool:
    """True when the compiled codec is loadable."""
    return _load() is not None


def _f32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


# ---------------------------------------------------------------------------
# entry points (native when possible, NumPy otherwise)
# ---------------------------------------------------------------------------

def unpack_bits(raw: np.ndarray, nbits: int) -> np.ndarray:
    """Packed 1/2/4-bit samples (uint8 buffer) -> float32 values,
    lowest-order bits first."""
    raw = np.ascontiguousarray(raw, dtype=np.uint8)
    if nbits not in (1, 2, 4):
        raise ValueError("nbits must be 1, 2, or 4")
    per = 8 // nbits
    lib = _load()
    if lib is not None:
        out = np.empty(raw.size * per, dtype=np.float32)
        lib.unpack_bits_f32(
            raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            _f32ptr(out), raw.size, nbits)
        return out
    # NumPy fallback: delegate to the canonical unpackers (psrfits only
    # imports this module lazily, so no cycle)
    from pypulsar_tpu.io.psrfits import _UNPACKERS
    return _UNPACKERS[nbits](raw).astype(np.float32)


def widen(raw: np.ndarray) -> np.ndarray:
    """uint8/uint16/float32 buffer -> float32."""
    raw = np.ascontiguousarray(raw)
    lib = _load()
    if lib is not None and raw.dtype == np.uint8:
        out = np.empty(raw.size, dtype=np.float32)
        lib.widen_u8_f32(raw.ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint8)), _f32ptr(out), raw.size)
        return out
    if lib is not None and raw.dtype == np.uint16:
        out = np.empty(raw.size, dtype=np.float32)
        lib.widen_u16_f32(raw.ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint16)), _f32ptr(out), raw.size)
        return out
    return raw.astype(np.float32).ravel()


def scale_offset_weight(data: np.ndarray, scales, offsets,
                        weights) -> np.ndarray:
    """(data*scales+offsets)*weights per channel over [nspec, nchan]
    float32; in place when native, returns the array either way."""
    data = np.ascontiguousarray(data, dtype=np.float32)
    nspec, nchan = data.shape
    scales = np.ascontiguousarray(scales, dtype=np.float32)
    offsets = np.ascontiguousarray(offsets, dtype=np.float32)
    weights = np.ascontiguousarray(weights, dtype=np.float32)
    if not (scales.size == nchan and offsets.size == nchan
            and weights.size == nchan):
        raise ValueError(
            f"per-channel arrays must have size nchan={nchan}; got "
            f"scales {scales.size}, offsets {offsets.size}, "
            f"weights {weights.size}")
    lib = _load()
    if lib is not None:
        lib.scale_offset_weight(_f32ptr(data), _f32ptr(scales),
                                _f32ptr(offsets), _f32ptr(weights),
                                nspec, nchan)
        return data
    # match the native path's in-place semantics
    np.multiply(data, scales, out=data)
    np.add(data, offsets, out=data)
    np.multiply(data, weights, out=data)
    return data


def zero_dm(data: np.ndarray) -> np.ndarray:
    """Subtract each time sample's cross-channel mean over [nspec, nchan]
    float32; in place when native."""
    data = np.ascontiguousarray(data, dtype=np.float32)
    nspec, nchan = data.shape
    lib = _load()
    if lib is not None:
        lib.zero_dm(_f32ptr(data), nspec, nchan)
        return data
    # match the native path's in-place semantics
    data -= data.mean(axis=1, keepdims=True).astype(np.float32)
    return data


def transpose_to_chan_major(raw: np.ndarray, nspec: int, nchan: int
                            ) -> np.ndarray:
    """[time, chan] uint8/uint16/float32 samples -> [chan, time] float32
    (the Spectra layout), fused with the dtype widening."""
    raw = np.ascontiguousarray(raw)
    nbits = {np.dtype(np.uint8): 8, np.dtype(np.uint16): 16,
             np.dtype(np.float32): 32}.get(raw.dtype)
    lib = _load()
    if lib is not None and nbits is not None:
        out = np.empty((nchan, nspec), dtype=np.float32)
        lib.transpose_to_chan_major(
            raw.ctypes.data_as(ctypes.c_void_p), _f32ptr(out),
            nspec, nchan, nbits)
        return out
    return raw.reshape(nspec, nchan).astype(np.float32).T.copy()


def boxcar_peak_snr(series: np.ndarray,
                    widths: Sequence[int]) -> np.ndarray:
    """Peak running-sum/sqrt(w) per boxcar width over a float32 series."""
    series = np.ascontiguousarray(series, dtype=np.float32)
    warr = np.ascontiguousarray(widths, dtype=np.int32)
    lib = _load()
    if lib is not None:
        out = np.empty(warr.size, dtype=np.float32)
        lib.boxcar_peak_snr(_f32ptr(series), series.size,
                            warr.ctypes.data_as(
                                ctypes.POINTER(ctypes.c_int32)),
                            warr.size, _f32ptr(out))
        return out
    out = np.empty(warr.size, dtype=np.float32)
    csum = np.concatenate(([0.0], np.cumsum(series, dtype=np.float64)))
    for i, w in enumerate(warr):
        if w == 0 or w > series.size:
            out[i] = 0.0
            continue
        sums = csum[w:] - csum[:-w]
        out[i] = sums.max() / np.sqrt(float(w))
    return out


class PrefetchReader:
    """Background-thread block reader over a raw sample region of a file
    (native/prefetch.cpp): yields ``(start_spectrum, bytes)`` overlap-save
    blocks while the next ones load off the critical path — the host-side
    analogue of the sweep's dispatch pipeline. Falls back to synchronous
    reads when the native library is unavailable.

    The file region is ``total_spec`` spectra of ``bytes_per_spec`` bytes
    starting at byte ``data_offset``; blocks advance by ``payload`` and
    carry ``overlap`` extra trailing spectra.
    """

    def __init__(self, path: str, data_offset: int, bytes_per_spec: int,
                 total_spec: int, payload: int, overlap: int = 0,
                 depth: int = 3):
        self.path = path
        self.data_offset = int(data_offset)
        self.bytes_per_spec = int(bytes_per_spec)
        self.total_spec = int(total_spec)
        self.payload = int(payload)
        self.overlap = int(overlap)
        self.depth = max(1, int(depth))
        self._lib = _load()
        self._h = None
        if self._lib is not None and hasattr(self._lib, "pf_open"):
            self._h = self._lib.pf_open(
                path.encode(), self.data_offset, self.bytes_per_spec,
                self.total_spec, self.payload, self.overlap, self.depth)
        self.native = self._h is not None

    def __iter__(self):
        if self.native and self._h is not None:
            return self._iter_native()
        # fallback also covers re-iteration after the native handle was
        # consumed/closed (a second pass re-reads synchronously)
        return self._iter_fallback()

    def _iter_native(self):
        lib = self._lib
        buf = ctypes.POINTER(ctypes.c_uint8)()
        start = ctypes.c_int64()
        nspec = ctypes.c_int64()
        try:
            while True:
                rc = lib.pf_acquire(self._h, ctypes.byref(buf),
                                    ctypes.byref(start), ctypes.byref(nspec))
                if rc == 0:
                    return
                if rc < 0:
                    raise IOError(f"prefetch read failed on {self.path}")
                n = int(nspec.value)
                if n > 0:
                    # copy out before release (the slot buffer is reused)
                    raw = np.ctypeslib.as_array(
                        buf, shape=(n * self.bytes_per_spec,)).copy()
                    lib.pf_release(self._h)
                    yield int(start.value), raw
                else:
                    lib.pf_release(self._h)
        finally:
            self.close()

    def _iter_fallback(self):
        with open(self.path, "rb") as f:
            pos = 0
            while pos < self.total_spec:
                n = min(self.payload + self.overlap, self.total_spec - pos)
                f.seek(self.data_offset + pos * self.bytes_per_spec)
                raw = np.fromfile(f, dtype=np.uint8,
                                  count=n * self.bytes_per_spec)
                if raw.size == 0:
                    return
                yield pos, raw
                pos += self.payload

    def close(self):
        if self._h is not None:
            self._lib.pf_close(self._h)
            self._h = None
            self.native = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
