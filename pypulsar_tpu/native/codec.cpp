// pypulsar_tpu native codec: the host-side hot loops of the IO plane.
//
// The reference framework's data plane is pure NumPy; its native
// dependencies (sigproc codec inside PRESTO, psrfits.c) live outside the
// repo.  Here the equivalents are in-tree: branch-free bit unpackers for
// SIGPROC/PSRFITS sample formats, the PSRFITS per-channel
// (data*scale+offset)*weight transform, zero-DM filtering, and a fused
// unpack-transpose for the [time,chan] -> [chan,time] loader boundary.
// Python binds these via ctypes (pypulsar_tpu/native/__init__.py) with a
// NumPy fallback when the shared library is unavailable.
//
// Build: g++ -O3 -march=native -shared -fPIC codec.cpp -o libpsrcodec.so

#include <cstdint>
#include <cstddef>

extern "C" {

// Unpack nbytes of packed samples into float32. nbits in {1, 2, 4}.
// Little-endian within the byte, lowest-order bits first (PRESTO/SIGPROC
// convention: sample 0 occupies the least-significant bits).
void unpack_bits_f32(const uint8_t* in, float* out, size_t nbytes,
                     int nbits) {
    if (nbits == 4) {
        for (size_t i = 0; i < nbytes; ++i) {
            const uint8_t b = in[i];
            out[2 * i]     = static_cast<float>(b & 0x0F);
            out[2 * i + 1] = static_cast<float>(b >> 4);
        }
    } else if (nbits == 2) {
        for (size_t i = 0; i < nbytes; ++i) {
            const uint8_t b = in[i];
            out[4 * i]     = static_cast<float>(b & 0x03);
            out[4 * i + 1] = static_cast<float>((b >> 2) & 0x03);
            out[4 * i + 2] = static_cast<float>((b >> 4) & 0x03);
            out[4 * i + 3] = static_cast<float>(b >> 6);
        }
    } else if (nbits == 1) {
        for (size_t i = 0; i < nbytes; ++i) {
            const uint8_t b = in[i];
            for (int j = 0; j < 8; ++j)
                out[8 * i + j] = static_cast<float>((b >> j) & 1);
        }
    }
}

// uint8 / uint16 -> float32 widening (SIGPROC 8/16-bit formats).
void widen_u8_f32(const uint8_t* in, float* out, size_t n) {
    for (size_t i = 0; i < n; ++i) out[i] = static_cast<float>(in[i]);
}

void widen_u16_f32(const uint16_t* in, float* out, size_t n) {
    for (size_t i = 0; i < n; ++i) out[i] = static_cast<float>(in[i]);
}

// PSRFITS subint transform, in place on [nspec, nchan] float32:
//   data[t, c] = (data[t, c] * scales[c] + offsets[c]) * weights[c]
void scale_offset_weight(float* data, const float* scales,
                         const float* offsets, const float* weights,
                         size_t nspec, size_t nchan) {
    for (size_t t = 0; t < nspec; ++t) {
        float* row = data + t * nchan;
        for (size_t c = 0; c < nchan; ++c)
            row[c] = (row[c] * scales[c] + offsets[c]) * weights[c];
    }
}

// Zero-DM filter, in place on [nspec, nchan] float32: subtract each time
// sample's cross-channel mean (reference bin/zero_dm_filter.py:30-39).
void zero_dm(float* data, size_t nspec, size_t nchan) {
    const float inv = 1.0f / static_cast<float>(nchan);
    for (size_t t = 0; t < nspec; ++t) {
        float* row = data + t * nchan;
        float acc = 0.0f;
        for (size_t c = 0; c < nchan; ++c) acc += row[c];
        const float mean = acc * inv;
        for (size_t c = 0; c < nchan; ++c) row[c] -= mean;
    }
}

// Fused widen + transpose: packed/byte samples laid out [time, chan] on
// disk -> float32 [chan, time] (the Spectra layout), without the
// intermediate [time, chan] float buffer.  nbits in {8, 16, 32}.
void transpose_to_chan_major(const void* in, float* out, size_t nspec,
                             size_t nchan, int nbits) {
    if (nbits == 8) {
        const uint8_t* p = static_cast<const uint8_t*>(in);
        for (size_t t = 0; t < nspec; ++t)
            for (size_t c = 0; c < nchan; ++c)
                out[c * nspec + t] = static_cast<float>(p[t * nchan + c]);
    } else if (nbits == 16) {
        const uint16_t* p = static_cast<const uint16_t*>(in);
        for (size_t t = 0; t < nspec; ++t)
            for (size_t c = 0; c < nchan; ++c)
                out[c * nspec + t] = static_cast<float>(p[t * nchan + c]);
    } else if (nbits == 32) {
        const float* p = static_cast<const float*>(in);
        for (size_t t = 0; t < nspec; ++t)
            for (size_t c = 0; c < nchan; ++c)
                out[c * nspec + t] = p[t * nchan + c];
    }
}

// Boxcar matched filter family on a single float32 series: for each width
// w in widths, out[i] = max over the series of the w-sample running sum
// normalized by sqrt(w).  The host-side twin of the device detection
// kernel, used by host tooling and for parity tests.
void boxcar_peak_snr(const float* series, size_t n, const int* widths,
                     size_t nwidths, float* out_peak) {
    for (size_t wi = 0; wi < nwidths; ++wi) {
        const size_t w = static_cast<size_t>(widths[wi]);
        if (w == 0 || w > n) { out_peak[wi] = 0.0f; continue; }
        double acc = 0.0;
        for (size_t i = 0; i < w; ++i) acc += series[i];
        double best = acc;
        for (size_t i = w; i < n; ++i) {
            acc += series[i] - series[i - w];
            if (acc > best) best = acc;
        }
        out_peak[wi] = static_cast<float>(best / __builtin_sqrt(
            static_cast<double>(w)));
    }
}

}  // extern "C"
