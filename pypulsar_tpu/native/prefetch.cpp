// Async double-buffered block prefetcher: the native IO runtime that keeps
// the device-side sweep fed.
//
// The reference streams files synchronously from Python one block at a
// time (e.g. formats/filterbank.py:109-119 read loops, fbobs cross-file
// reads); at TPU sweep rates the read sits on the critical path.  This
// reader owns a background thread that stays ``depth`` overlap-save blocks
// ahead of the consumer (pread into a ring of reusable buffers), so disk
// latency overlaps device compute — the host analogue of the sweep's
// MAX_PENDING dispatch pipeline (parallel/sweep.py).
//
// C API (ctypes-bound in pypulsar_tpu/native/__init__.py):
//   pf_open(path, data_offset, bytes_per_spec, total_spec,
//           payload_spec, overlap_spec, depth) -> handle (NULL on error)
//   pf_acquire(handle, &buf, &start_spec, &nspec) -> 1 block ready,
//           0 end-of-stream, -1 IO error; blocks until one is ready.
//           The buffer stays valid until the matching pf_release.
//   pf_release(handle)  -- return the oldest acquired buffer to the ring
//   pf_close(handle)
//
// Built into libpsrcodec.so alongside codec.cpp.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct Slot {
    std::vector<uint8_t> buf;
    int64_t start = 0;   // first spectrum index in the block
    int64_t nspec = 0;   // spectra in the block
    bool full = false;
};

struct Prefetcher {
    int fd = -1;
    int64_t data_offset = 0;
    int64_t bytes_per_spec = 0;
    int64_t total_spec = 0;
    int64_t payload = 0;
    int64_t overlap = 0;

    std::vector<Slot> ring;
    size_t prod = 0;     // next slot the reader fills
    size_t cons = 0;     // next slot the consumer acquires
    bool eof = false;
    bool io_error = false;
    bool stop = false;

    std::mutex m;
    std::condition_variable cv_slot_free;
    std::condition_variable cv_slot_full;
    std::thread th;

    void reader_loop() {
        int64_t pos = 0;
        while (true) {
            int64_t n = total_spec - pos;
            if (n <= 0) break;
            if (n > payload + overlap) n = payload + overlap;
            Slot* slot;
            {
                std::unique_lock<std::mutex> lk(m);
                cv_slot_free.wait(lk, [&] {
                    return stop || !ring[prod % ring.size()].full;
                });
                if (stop) return;
                slot = &ring[prod % ring.size()];
            }
            const int64_t want = n * bytes_per_spec;
            slot->buf.resize(static_cast<size_t>(want));
            int64_t got = 0;
            while (got < want) {
                const ssize_t r = pread(fd, slot->buf.data() + got,
                                        static_cast<size_t>(want - got),
                                        data_offset + pos * bytes_per_spec + got);
                if (r < 0) {
                    std::lock_guard<std::mutex> lk(m);
                    io_error = true;
                    cv_slot_full.notify_all();
                    return;
                }
                if (r == 0) break;  // truncated file: surface what we have
                got += r;
            }
            const int64_t nspec_read = got / bytes_per_spec;
            {
                std::lock_guard<std::mutex> lk(m);
                slot->start = pos;
                slot->nspec = nspec_read;
                slot->full = true;
                ++prod;
                cv_slot_full.notify_all();
            }
            if (nspec_read < n) break;  // short read = end of data
            pos += payload;
        }
        std::lock_guard<std::mutex> lk(m);
        eof = true;
        cv_slot_full.notify_all();
    }
};

}  // namespace

extern "C" {

void* pf_open(const char* path, int64_t data_offset, int64_t bytes_per_spec,
              int64_t total_spec, int64_t payload_spec, int64_t overlap_spec,
              int depth) {
    if (bytes_per_spec <= 0 || payload_spec <= 0 || depth < 1) return nullptr;
    const int fd = open(path, O_RDONLY);
    if (fd < 0) return nullptr;
    auto* p = new Prefetcher();
    p->fd = fd;
    p->data_offset = data_offset;
    p->bytes_per_spec = bytes_per_spec;
    p->total_spec = total_spec;
    p->payload = payload_spec;
    p->overlap = overlap_spec;
    p->ring.resize(static_cast<size_t>(depth));
    p->th = std::thread([p] { p->reader_loop(); });
    return p;
}

int pf_acquire(void* handle, uint8_t** buf, int64_t* start, int64_t* nspec) {
    auto* p = static_cast<Prefetcher*>(handle);
    std::unique_lock<std::mutex> lk(p->m);
    p->cv_slot_full.wait(lk, [&] {
        return p->io_error || p->ring[p->cons % p->ring.size()].full ||
               (p->eof && p->cons == p->prod);
    });
    if (p->io_error) return -1;
    Slot& slot = p->ring[p->cons % p->ring.size()];
    if (!slot.full) return 0;  // eof drained
    *buf = slot.buf.data();
    *start = slot.start;
    *nspec = slot.nspec;
    return 1;
}

void pf_release(void* handle) {
    auto* p = static_cast<Prefetcher*>(handle);
    std::lock_guard<std::mutex> lk(p->m);
    Slot& slot = p->ring[p->cons % p->ring.size()];
    if (slot.full) {
        slot.full = false;
        ++p->cons;
        p->cv_slot_free.notify_all();
    }
}

void pf_close(void* handle) {
    auto* p = static_cast<Prefetcher*>(handle);
    {
        std::lock_guard<std::mutex> lk(p->m);
        p->stop = true;
        p->cv_slot_free.notify_all();
    }
    if (p->th.joinable()) p->th.join();
    close(p->fd);
    delete p;
}

}  // extern "C"
