"""Resilience layer: fault injection, OOM-adaptive dispatch, journaled
resume, artifact integrity.

One transient failure must never abort or silently corrupt a multi-hour
survey pass. The submodules divide the problem:

- :mod:`~pypulsar_tpu.resilience.retry` — OOM-adaptive halving of the
  independent dispatch axes (sweep trial groups, accel batches, stage
  chunks), bit-identical recovery by construction;
- :mod:`~pypulsar_tpu.resilience.journal` — the per-run JSONL work-unit
  manifest with size/sha256 validation, plus the atomic-write and
  ``.cand``-completeness helpers every output path shares;
- :mod:`~pypulsar_tpu.resilience.faultinject` — deterministic, named
  fault points (env/CLI-armed) that make every recovery path above
  testable down to byte-identical candidate tables
  (``tests/test_resilience.py``, ``make test-faults``), plus the seeded
  probabilistic chaos mode ``bench.py --chaos`` drives;
- :mod:`~pypulsar_tpu.resilience.health` — the fleet health layer:
  stage heartbeats + deadlines with a watchdog that interrupts wedged
  workers, per-device strike/quarantine accounting, and the
  disk/backpressure admission gate the survey scheduler consults;
- :mod:`~pypulsar_tpu.resilience.locks` — lockdep-instrumented
  Lock/RLock/Condition/Event wrappers (round 19): per-thread held-sets
  (the watchdog's defer-interrupt-while-locked guard), a global
  acquisition-order graph with cycle detection
  (``PYPULSAR_TPU_LOCKDEP`` warn/strict), hold/contention telemetry,
  and the seeded lock-boundary pauses ``bench.py --race`` drives.

The failure model itself (what is retried, what is journaled, what is
fatal) is documented in docs/ARCHITECTURE.md "Failure model & recovery".
"""

from pypulsar_tpu.resilience.faultinject import (  # noqa: F401
    InjectedDeviceFault,
    InjectedFault,
    InjectedIOError,
    InjectedKill,
    InjectedOOM,
    trip,
)
from pypulsar_tpu.resilience.health import (  # noqa: F401
    DeviceHealth,
    HeartbeatRegistry,
    ResourceGuard,
    StageDeadlineExceeded,
    StageStalled,
    StageTimeout,
    Watchdog,
    is_device_fault,
    must_propagate,
    no_degrade,
)
from pypulsar_tpu.resilience.journal import (  # noqa: F401
    RunJournal,
    atomic_open,
    atomic_write_bytes,
    atomic_write_text,
    candfile_complete,
    file_digest,
)
from pypulsar_tpu.resilience.locks import (  # noqa: F401
    LockOrderError,
    TrackedCondition,
    TrackedEvent,
    TrackedLock,
    TrackedRLock,
)
from pypulsar_tpu.resilience.retry import (  # noqa: F401
    halving_dispatch,
    is_oom_error,
)
