"""Deterministic fault injection: the testability half of the resilience
layer.

Every recovery path in this package (OOM-adaptive halving, worker IO
retries, journaled resume) exists because a specific failure was observed
or anticipated in production — and a recovery path that is never executed
is a recovery path that is broken. This module lets a test (or an
operator, via env/CLI) arm a *deterministic* failure at a *named point*
in the pipeline:

- ``oom`` — raise :class:`InjectedOOM` (recognized by
  ``resilience.retry.is_oom_error`` exactly like a device
  ``RESOURCE_EXHAUSTED``) at the Nth hit of a dispatch point;
- ``io`` — raise :class:`InjectedIOError` (an ``OSError``) at the Nth hit
  of a read/produce point;
- ``kill`` — raise :class:`InjectedKill` (a ``BaseException``: ordinary
  ``except Exception`` recovery code cannot swallow it, so it unwinds the
  run like a SIGINT) at the Nth hit of a kill point;
- ``exit`` — ``os._exit(137)``: the true SIGKILL-equivalent (no finally
  blocks, no atexit, no flushing) for subprocess-based tests.

Spec grammar (``PYPULSAR_TPU_FAULTS`` env var or the CLIs'
``--fault-inject``)::

    kind:point[:N][,kind:point[:N]...]

e.g. ``oom:accel.batch_dispatch:2`` injects one OOM on the second batched
accel dispatch. N defaults to 1 and counts 1-based hits of that point;
each armed fault fires exactly once. Instrumented points call
:func:`trip` — a no-op single dict check when nothing is armed, so the
hooks are free in production.

Every firing emits a ``resilience.fault_injected`` telemetry event, so a
fault-injection run's trace shows both the failure and the recovery it
provoked.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

from pypulsar_tpu.obs import telemetry

__all__ = [
    "InjectedFault",
    "InjectedIOError",
    "InjectedKill",
    "InjectedOOM",
    "add_fault_flag",
    "configure",
    "configure_from_env",
    "hits",
    "is_armed",
    "reset",
    "trip",
]

ENV_FAULTS = "PYPULSAR_TPU_FAULTS"

KINDS = ("oom", "io", "kill", "exit")


class InjectedFault:
    """Mixin marking an exception as injected (not a real failure)."""


class InjectedOOM(InjectedFault, RuntimeError):
    """Stands in for the device allocator's failure: the message carries
    RESOURCE_EXHAUSTED so any string-matching classifier (including
    ``resilience.retry.is_oom_error``) treats it like the real thing."""

    def __init__(self, point: str):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected device OOM at {point!r}")


class InjectedIOError(InjectedFault, OSError):
    """A transient read error, as an OSError so the worker retry policy
    (``retry_on=(OSError,)``) catches it like a real EIO/ENETRESET."""

    def __init__(self, point: str):
        super().__init__(f"injected transient IO error at {point!r}")


class InjectedKill(InjectedFault, BaseException):
    """Unwinds the run past every ``except Exception`` recovery handler —
    the in-process stand-in for a kill signal (for the no-cleanup-at-all
    SIGKILL semantics use kind ``exit`` in a subprocess)."""

    def __init__(self, point: str):
        super().__init__(f"injected kill at {point!r}")


# (kind, point) -> 1-based hit index at which to fire (popped once fired)
_armed: Dict[Tuple[str, str], int] = {}
_hits: Dict[str, int] = {}


def parse_spec(spec: str) -> Dict[Tuple[str, str], int]:
    """Parse the fault spec grammar; raises ValueError on malformed
    entries (a typo'd fault spec silently injecting nothing would make a
    green fault test meaningless)."""
    out: Dict[Tuple[str, str], int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) == 2:
            kind, point, n = fields[0], fields[1], 1
        elif len(fields) == 3:
            kind, point = fields[0], fields[1]
            n = int(fields[2])
        else:
            raise ValueError(f"bad fault spec entry {part!r}; expected "
                             f"kind:point[:N]")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; expected one "
                             f"of {KINDS}")
        if n < 1:
            raise ValueError(f"fault hit index must be >= 1; got {n}")
        out[(kind, point)] = n
    return out


def configure(spec: Optional[str]) -> None:
    """Arm the faults in ``spec`` (replacing any armed set); None or an
    empty string clears everything."""
    reset()
    if spec:
        _armed.update(parse_spec(spec))


def configure_from_env() -> None:
    """Arm faults from ``PYPULSAR_TPU_FAULTS`` (the subprocess-test
    channel; unset leaves the armed set alone so a CLI flag survives)."""
    spec = os.environ.get(ENV_FAULTS)
    if spec:
        _armed.update(parse_spec(spec))


def reset() -> None:
    """Clear armed faults and hit counters (test isolation)."""
    _armed.clear()
    _hits.clear()


def is_armed() -> bool:
    return bool(_armed)


def hits(point: str) -> int:
    """How many times ``point`` has tripped (diagnostics/tests)."""
    return _hits.get(point, 0)


def add_fault_flag(parser):
    """Install the shared ``--fault-inject`` CLI option (one definition of
    the flag for every CLI, like telemetry.add_telemetry_flag)."""
    parser.add_argument(
        "--fault-inject", default=None, metavar="SPEC",
        help="arm deterministic faults for resilience testing: "
             "kind:point[:N],... with kinds oom|io|kill|exit (e.g. "
             "oom:accel.batch_dispatch:2 injects a device OOM on the "
             "2nd batched accel dispatch); also via the "
             f"{ENV_FAULTS} env var")
    return parser


def trip(point: str) -> None:
    """Hook call at an instrumented point: fire the armed fault for this
    point when its 1-based hit index is reached, else no-op. The
    nothing-armed fast path is one dict truthiness check."""
    if not _armed:
        return
    n = _hits.get(point, 0) + 1
    _hits[point] = n
    for kind in KINDS:
        key = (kind, point)
        if _armed.get(key) == n:
            del _armed[key]
            telemetry.counter("resilience.faults_injected")
            telemetry.event("resilience.fault_injected", kind=kind,
                            point=point, hit=n)
            if kind == "oom":
                raise InjectedOOM(point)
            if kind == "io":
                raise InjectedIOError(point)
            if kind == "kill":
                raise InjectedKill(point)
            os._exit(137)  # "exit": SIGKILL-equivalent, no cleanup at all
