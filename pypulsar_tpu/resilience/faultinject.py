"""Deterministic fault injection: the testability half of the resilience
layer.

Every recovery path in this package (OOM-adaptive halving, worker IO
retries, journaled resume) exists because a specific failure was observed
or anticipated in production — and a recovery path that is never executed
is a recovery path that is broken. This module lets a test (or an
operator, via env/CLI) arm a *deterministic* failure at a *named point*
in the pipeline:

- ``oom`` — raise :class:`InjectedOOM` (recognized by
  ``resilience.retry.is_oom_error`` exactly like a device
  ``RESOURCE_EXHAUSTED``) at the Nth hit of a dispatch point;
- ``io`` — raise :class:`InjectedIOError` (an ``OSError``) at the Nth hit
  of a read/produce point;
- ``kill`` — raise :class:`InjectedKill` (a ``BaseException``: ordinary
  ``except Exception`` recovery code cannot swallow it, so it unwinds the
  run like a SIGINT) at the Nth hit of a kill point;
- ``exit`` — ``os._exit(137)``: the true SIGKILL-equivalent (no finally
  blocks, no atexit, no flushing) for subprocess-based tests;
- ``hang`` — stop making progress: sleep in small interruptible
  increments (so the survey watchdog's async interrupt can land between
  bytecodes) for up to ``PYPULSAR_TPU_HANG_S`` seconds (default 30 —
  the bound keeps an UNwatched hang from wedging a test run forever);
- ``device`` — raise :class:`InjectedDeviceFault`: a chip-indicting
  failure (``resilience.health.is_device_fault``) that feeds the
  device strike/quarantine accounting;
- ``netstall`` — the coordination-plane sibling of ``hang`` (round 18):
  the same deterministic, ``PYPULSAR_TPU_HANG_S``-bounded interruptible
  stall, armed at the multi-host fleet's plane points
  (``fleet.heartbeat`` / ``fleet.claim`` / ``fleet.fence`` /
  ``fleet.token``) to simulate a slow or partitioned shared filesystem
  without a real network. A netstall parked in the heartbeat renewer
  past ``PYPULSAR_TPU_HOST_LEASE_S`` makes a host adoptable WHILE IT
  STILL RUNS — the split-brain scenario the fencing tokens exist for —
  and it composes with seeded chaos (chaos mode may draw it like any
  other kind).

The streaming daemon (round 23) arms its ingest edges the same way:
``daemon.arrival`` (an arrival is never seen; the watch lane re-sees it
next scan), ``daemon.admit`` (an admission attempt fails; the arrival
goes back to pending and retries next tick) and ``daemon.shed`` (the
shed still happens — the bounded queue may not stay over its bound —
but the fault is counted). All three ride the chaos spray like every
other point, so ``bench.py --daemon-soak`` exercises the admission
plane with the same seeded machinery.

Spec grammar (``PYPULSAR_TPU_FAULTS`` env var or the CLIs'
``--fault-inject``)::

    kind:point[:N][,kind:point[:N]...]

e.g. ``oom:accel.batch_dispatch:2`` injects one OOM on the second batched
accel dispatch. N defaults to 1 and counts 1-based hits of that point;
each armed fault fires exactly once. Instrumented points call
:func:`trip` — a no-op single dict check when nothing is armed, so the
hooks are free in production.

**Chaos mode** (``--fault-chaos`` / ``PYPULSAR_TPU_CHAOS``) is the
probabilistic complement: ``SEED:RATE[:kind+kind...]`` sprays faults
across ALL registered points. Each decision is a pure hash of
``(seed, point, cumulative hit index)`` — deterministic per (point, hit)
no matter how threads interleave, yet different on every retry of the
same point (the hit index keeps counting), so a chaos fleet that
resumes long enough always completes. ``exit`` is excluded from the
chaos kinds: the harness asserting recovery must survive its own
faults. ``bench.py --chaos`` is the committed harness over this mode.

Every firing emits a ``resilience.fault_injected`` telemetry event, so a
fault-injection run's trace shows both the failure and the recovery it
provoked.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Dict, Optional, Tuple

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.tune import knobs

__all__ = [
    "InjectedDeviceFault",
    "InjectedFault",
    "InjectedIOError",
    "InjectedKill",
    "InjectedOOM",
    "add_chaos_flag",
    "add_fault_flag",
    "chaos_active",
    "configure",
    "configure_chaos",
    "configure_from_env",
    "corrupt_array",
    "data_faults_armed",
    "fired_counts",
    "hits",
    "is_armed",
    "reset",
    "trip",
    "trip_data",
]

ENV_FAULTS = "PYPULSAR_TPU_FAULTS"
ENV_CHAOS = "PYPULSAR_TPU_CHAOS"
ENV_HANG_S = "PYPULSAR_TPU_HANG_S"

KINDS = ("oom", "io", "kill", "exit", "hang", "device", "netstall")

# DATA fault kinds (round 13): not exceptions but *mutations* — an armed
# data fault at a read-time point corrupts the block flowing through it
# (``trip_data``), exercising the dataguard scrub + finite-output gates
# the way a real bit-flipped recording would. ``truncate`` at block
# granularity zeroes the block tail (mid-stream shapes are static; the
# file-level truncation lives in resilience.dataguard.corrupt_file).
DATA_KINDS = ("nanburst", "dropblock", "dcjump", "bitflip", "truncate")

# chaos never draws `exit`: os._exit would kill the very harness that
# must resume the fleet and assert parity. `netstall` IS drawable — at
# a coordination-plane point it stalls the plane (the slow-coordinator
# path), anywhere else it degenerates to a bounded hang the watchdog
# already owns.
CHAOS_KINDS = ("oom", "io", "kill", "hang", "device", "netstall")


class InjectedFault:
    """Mixin marking an exception as injected (not a real failure)."""


class InjectedOOM(InjectedFault, RuntimeError):
    """Stands in for the device allocator's failure: the message carries
    RESOURCE_EXHAUSTED so any string-matching classifier (including
    ``resilience.retry.is_oom_error``) treats it like the real thing."""

    def __init__(self, point: str):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected device OOM at {point!r}")


class InjectedIOError(InjectedFault, OSError):
    """A transient read error, as an OSError so the worker retry policy
    (``retry_on=(OSError,)``) catches it like a real EIO/ENETRESET."""

    def __init__(self, point: str):
        super().__init__(f"injected transient IO error at {point!r}")


class InjectedKill(InjectedFault, BaseException):
    """Unwinds the run past every ``except Exception`` recovery handler —
    the in-process stand-in for a kill signal (for the no-cleanup-at-all
    SIGKILL semantics use kind ``exit`` in a subprocess)."""

    def __init__(self, point: str):
        super().__init__(f"injected kill at {point!r}")


class InjectedDeviceFault(InjectedFault, RuntimeError):
    """A chip-indicting failure (dead device, failed collective): the
    message carries DEVICE_FAULT so ``resilience.health.is_device_fault``
    classifies it like the real thing and the survey scheduler charges a
    strike against the leased chip(s)."""

    def __init__(self, point: str):
        super().__init__(
            f"DEVICE_FAULT: injected device failure at {point!r}")


# (kind, point) -> 1-based hit index at which to fire (popped once fired)
_armed: Dict[Tuple[str, str], int] = {}
# same grammar, DATA kinds: fired by trip_data (mutation, not raise)
_armed_data: Dict[Tuple[str, str], int] = {}
_hits: Dict[str, int] = {}

# chaos mode: None, or (seed, rate, kinds tuple)
_chaos: Optional[Tuple[int, float, Tuple[str, ...]]] = None

# kind -> times fired (armed + chaos): the chaos harness's receipt that
# every fault family it claims to have survived actually fired
_fired: Dict[str, int] = {}


def parse_spec(spec: str) -> Dict[Tuple[str, str], int]:
    """Parse the fault spec grammar; raises ValueError on malformed
    entries (a typo'd fault spec silently injecting nothing would make a
    green fault test meaningless)."""
    out: Dict[Tuple[str, str], int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) == 2:
            kind, point, n = fields[0], fields[1], 1
        elif len(fields) == 3:
            kind, point = fields[0], fields[1]
            n = int(fields[2])
        else:
            raise ValueError(f"bad fault spec entry {part!r}; expected "
                             f"kind:point[:N]")
        if kind not in KINDS and kind not in DATA_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; expected one "
                             f"of {KINDS + DATA_KINDS}")
        if n < 1:
            raise ValueError(f"fault hit index must be >= 1; got {n}")
        out[(kind, point)] = n
    return out


def configure(spec: Optional[str]) -> None:
    """Arm the faults in ``spec`` (replacing any armed set and zeroing
    the hit/fired counters); None or an empty string clears the armed
    set. Chaos mode is configured independently (:func:`configure_chaos`)
    and survives — only :func:`reset` clears both, so arming a
    deterministic fault on top of an active chaos spray composes instead
    of silently disarming it."""
    _armed.clear()
    _armed_data.clear()
    _hits.clear()
    _fired.clear()
    if spec:
        _arm(parse_spec(spec))


def _arm(parsed: Dict[Tuple[str, str], int]) -> None:
    """Route parsed spec entries to the exception-armed or data-armed
    set by kind (one grammar, two firing mechanisms)."""
    for (kind, point), n in parsed.items():
        (_armed_data if kind in DATA_KINDS else _armed)[(kind, point)] = n


def parse_chaos_spec(spec: str) -> Tuple[int, float, Tuple[str, ...]]:
    """Parse ``SEED:RATE[:kind+kind...]``; raises ValueError on a
    malformed spec (same loud contract as :func:`parse_spec`)."""
    fields = spec.split(":")
    if len(fields) not in (2, 3):
        raise ValueError(f"bad chaos spec {spec!r}; expected "
                         f"SEED:RATE[:kind+kind...]")
    seed = int(fields[0])
    rate = float(fields[1])
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"chaos rate must be in [0, 1]; got {rate}")
    kinds = CHAOS_KINDS
    if len(fields) == 3 and fields[2]:
        kinds = tuple(k.strip() for k in fields[2].split("+") if k.strip())
        for k in kinds:
            if k not in CHAOS_KINDS:
                raise ValueError(f"unknown chaos kind {k!r}; expected "
                                 f"some of {CHAOS_KINDS}")
    return seed, rate, kinds


def configure_chaos(spec: Optional[str]) -> None:
    """Arm (or, with None/empty, disarm) seeded probabilistic chaos:
    every :func:`trip` point rolls ``hash(seed, point, hit)`` against
    ``rate`` and fires a hash-chosen kind on success. Composes with the
    deterministic armed set (which wins at its exact (point, N))."""
    global _chaos
    _chaos = parse_chaos_spec(spec) if spec else None


def configure_from_env() -> None:
    """Arm faults from ``PYPULSAR_TPU_FAULTS`` and chaos from
    ``PYPULSAR_TPU_CHAOS`` (the subprocess-test channels; unset leaves
    the armed set alone so a CLI flag survives)."""
    spec = knobs.env_str(ENV_FAULTS)
    if spec:
        _arm(parse_spec(spec))
    chaos = knobs.env_str(ENV_CHAOS)
    if chaos:
        configure_chaos(chaos)


def reset() -> None:
    """Clear armed faults, chaos mode, hit and fired counters (test
    isolation)."""
    global _chaos
    _armed.clear()
    _armed_data.clear()
    _hits.clear()
    _fired.clear()
    _chaos = None


def is_armed() -> bool:
    return bool(_armed)


def data_faults_armed() -> bool:
    """True when any DATA fault kind is armed (the dataguard wraps even
    integer sources then, so the injection has somewhere to land)."""
    return bool(_armed_data)


def chaos_active() -> bool:
    return _chaos is not None


def hits(point: str) -> int:
    """How many times ``point`` has tripped (diagnostics/tests)."""
    return _hits.get(point, 0)


def fired_counts() -> Dict[str, int]:
    """``{kind: times fired}`` since the last :func:`reset` — armed and
    chaos firings combined. The chaos harness's receipt: a run that
    claims to have survived kills, OOMs, IO errors, hangs and device
    faults proves each family actually fired."""
    return dict(_fired)


def add_fault_flag(parser):
    """Install the shared ``--fault-inject`` CLI option (one definition of
    the flag for every CLI, like telemetry.add_telemetry_flag)."""
    parser.add_argument(
        "--fault-inject", default=None, metavar="SPEC",
        help="arm deterministic faults for resilience testing: "
             "kind:point[:N],... with kinds "
             "oom|io|kill|exit|hang|device|netstall "
             "(e.g. oom:accel.batch_dispatch:2 injects a device OOM on "
             "the 2nd batched accel dispatch; "
             "netstall:fleet.heartbeat:3 stalls the multi-host "
             "coordination plane) or the DATA kinds "
             "nanburst|dropblock|dcjump|bitflip|truncate, which corrupt "
             "the block at a read-time point (e.g. nanburst:data.block:2) "
             "instead of raising; also via the "
             f"{ENV_FAULTS} env var")
    return parser


def add_chaos_flag(parser):
    """Install the shared ``--fault-chaos`` CLI option (the seeded
    probabilistic mode; see module docstring)."""
    parser.add_argument(
        "--fault-chaos", default=None, metavar="SEED:RATE[:KINDS]",
        help="spray seeded probabilistic faults across every registered "
             "fault point: each (point, hit) rolls hash(seed, point, "
             "hit) against RATE and fires a hash-chosen kind (from "
             "oom|io|kill|hang|device|netstall, or the +-separated "
             "KINDS subset); deterministic per seed, fresh on every "
             "retry; "
             f"also via the {ENV_CHAOS} env var")
    return parser


def _hang(point: str) -> None:
    """Stop making progress, interruptibly: sleep in 50 ms slices so an
    async watchdog interrupt lands between bytecodes (one long
    ``sleep`` would pin the exception until it returned), bounded by
    ``PYPULSAR_TPU_HANG_S`` so an unwatched hang ends on its own."""
    # registry read is typo-tolerant (garbage -> the declared 30.0)
    deadline = time.monotonic() + float(knobs.env_float(ENV_HANG_S))
    while time.monotonic() < deadline:
        time.sleep(0.05)


def _fire(kind: str, point: str, n: int, mode: str) -> None:
    _fired[kind] = _fired.get(kind, 0) + 1
    telemetry.counter("resilience.faults_injected")
    telemetry.event("resilience.fault_injected", kind=kind, point=point,
                    hit=n, mode=mode)
    if kind == "oom":
        raise InjectedOOM(point)
    if kind == "io":
        raise InjectedIOError(point)
    if kind == "kill":
        raise InjectedKill(point)
    if kind == "device":
        raise InjectedDeviceFault(point)
    if kind in ("hang", "netstall"):
        # netstall is semantically a COORDINATION stall (heartbeats /
        # claims / fences stop making progress) but mechanically the
        # same bounded interruptible sleep — what differs is where it
        # is armed, not what it does
        _hang(point)
        return
    os._exit(137)  # "exit": SIGKILL-equivalent, no cleanup at all


def _chaos_roll(point: str, n: int) -> Optional[str]:
    """The chaos decision for the Nth hit of ``point``: None, or the
    kind to fire. A pure function of (seed, point, n) — thread
    interleaving cannot change any individual decision, and the
    cumulative hit index means a REDONE unit re-rolls fresh instead of
    replaying the same fault forever."""
    seed, rate, kinds = _chaos
    h = hashlib.sha256(f"{seed}:{point}:{n}".encode()).digest()
    u = int.from_bytes(h[:8], "big") / float(1 << 64)
    if u >= rate:
        return None
    return kinds[int.from_bytes(h[8:12], "big") % len(kinds)]


def trip(point: str) -> None:
    """Hook call at an instrumented point: fire the armed fault for this
    point when its 1-based hit index is reached (or, in chaos mode, on
    a seeded roll), else no-op. The nothing-armed fast path is two
    truthiness checks."""
    if not _armed and _chaos is None:
        return
    n = _hits.get(point, 0) + 1
    _hits[point] = n
    for kind in KINDS:
        key = (kind, point)
        if _armed.get(key) == n:
            del _armed[key]
            _fire(kind, point, n, "armed")
            return
    if _chaos is not None:
        kind = _chaos_roll(point, n)
        if kind is not None:
            _fire(kind, point, n, "chaos")


def trip_data(point: str, arr):
    """Data-fault hook at a read-time point: return ``arr``, corrupted
    when an armed DATA fault's 1-based hit index is reached, else
    unchanged. Corruption is deterministic — the RNG seeds from
    (kind, point, hit) — so a redone unit replays the identical bytes
    (the recovery-parity contract the exception kinds already honor).
    The nothing-armed fast path is one truthiness check."""
    if not _armed_data:
        return arr
    n = _hits.get(point, 0) + 1
    _hits[point] = n
    for kind in DATA_KINDS:
        key = (kind, point)
        if _armed_data.get(key) == n:
            del _armed_data[key]
            _fired[kind] = _fired.get(kind, 0) + 1
            telemetry.counter("resilience.faults_injected")
            telemetry.event("resilience.fault_injected", kind=kind,
                            point=point, hit=n, mode="armed")
            return corrupt_array(arr, kind, _data_rng(kind, point, n))
    return arr


def _data_rng(kind: str, point: str, n: int):
    import numpy as np

    h = hashlib.sha256(f"data:{kind}:{point}:{n}".encode()).digest()
    return np.random.Generator(np.random.SFC64(
        list(h[:16])))


def corrupt_array(arr, kind: str, rng):
    """Apply one DATA fault kind to a block (any array; returns a host
    numpy copy — the dataguard scrub downstream re-ships it). Spans are
    ~5%% of the last axis at a seeded offset."""
    import numpy as np

    a = np.array(arr)  # host copy (syncs a device block; faults are rare)
    flat = a.reshape(-1)
    size = flat.size
    if size == 0:
        return a
    span = max(1, size // 20)
    start = int(rng.integers(0, max(size - span, 1)))
    if kind == "nanburst":
        if not np.issubdtype(a.dtype, np.floating):
            a = a.astype(np.float32)
            flat = a.reshape(-1)
        flat[start:start + span] = np.nan
        flat[start] = np.inf
    elif kind == "dropblock":
        flat[start:start + span] = 0
    elif kind == "truncate":
        flat[size - span:] = 0  # block tails are static-shaped: zero them
    elif kind == "dcjump":
        if np.issubdtype(a.dtype, np.floating):
            flat[start:start + span] += np.float32(1e4)
        else:
            info = np.iinfo(a.dtype)
            seg = flat[start:start + span].astype(np.int64) + info.max // 2
            flat[start:start + span] = np.clip(seg, info.min,
                                               info.max).astype(a.dtype)
    elif kind == "bitflip":
        view = a.view(np.uint8).reshape(-1)
        offs = rng.integers(0, view.size, size=min(64, view.size))
        bits = rng.integers(0, 8, size=offs.size)
        view[offs] ^= (np.uint8(1) << bits.astype(np.uint8))
    else:
        raise ValueError(f"unknown data fault kind {kind!r}")
    return a
