"""OOM-adaptive dispatch: halve the batch, back off, re-run.

The survey-scale accel stage has already crashed a TPU worker at
(B=32, N=2^21, zmax=200) — fourier/accelsearch.py budgets HBM up front
precisely because the axon backend hard-crashes instead of raising. But
budgets are estimates: an XLA fusion holding one extra temporary, a
neighbour process on a shared device, or a conservative-enough-but-wrong
bytes-per-cell model can still produce a recoverable
``RESOURCE_EXHAUSTED`` — and on backends that DO raise it, aborting a
multi-hour survey over one oversized dispatch is the wrong trade. The
real-time dedispersion literature treats adaptive reconfiguration as a
first-class runtime concern (Sclocco et al., arXiv:1601.01165,
1601.05052); this module is that policy for the dispatch axis every hot
path already has:

- the sweep's trial-group axis (``parallel/sweep.py`` chunk dispatch),
- the accel handoff's spectrum batches (``parallel/accelpipe.py``),
- the batched stage runner's HBM chunks (``fourier/accelsearch.py``).

All three axes are *embarrassingly independent* — per-group scans and
per-spectrum searches share no state — so halving a failed dispatch and
re-running the halves is bit-identical to the original dispatch, which is
what lets the fault-injection suite pin recovery down to byte-equal
candidate tables.

Every halving emits a ``resilience.oom_backoff`` telemetry event and
bumps the ``resilience.oom_backoffs`` counter, so ``tlmsum`` shows how a
degraded run survived.
"""

from __future__ import annotations

import random
import time
from typing import Callable, List, Optional, Tuple

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import faultinject

__all__ = ["backoff_delay", "halving_dispatch", "is_oom_error",
           "retry_transient"]

# bounded backoff before re-dispatching after an OOM: gives the allocator
# (and any neighbour briefly holding the memory) time to settle, without
# ever stalling a survey for more than ~seconds per halving
BACKOFF_BASE_S = 0.05
BACKOFF_MAX_S = 2.0

# bound on the transient-IO retry backoff (shared with the prefetch
# worker policy): an NFS hiccup gets seconds to clear, a real outage
# still fails within ~retries * 5 s
RETRY_BACKOFF_MAX_S = 5.0

# OSError subclasses that are configuration errors, not IO weather: a
# typo'd path or bad permission fails identically on every attempt —
# retrying it only delays the real error and mislabels it as transient
NON_TRANSIENT_OS_ERRORS = (FileNotFoundError, PermissionError,
                           IsADirectoryError, NotADirectoryError)

# process-default jitter source for backoff delays; tests inject their
# own seeded random.Random for determinism
_JITTER_RNG = random.Random()


def backoff_delay(base: float, attempt: int, cap: float,
                  rng: Optional[random.Random] = None) -> float:
    """Jittered bounded exponential backoff: ``base * 2^(attempt-1)``
    (capped at ``cap``) scaled by a uniform factor in [0.5, 1.0).

    The jitter is the point, not a refinement: the pure deterministic
    schedule retries *in lockstep* — N leases that fail together (one
    flaky chip, one NFS blip) all come back at exactly base, 2*base,
    4*base and collide again, the classic thundering-herd retry storm.
    ``rng`` is injectable so tests stay deterministic
    (``random.Random(seed)``); None uses the process-default source."""
    delay = min(base * (2 ** (max(1, attempt) - 1)), cap)
    r = rng if rng is not None else _JITTER_RNG
    return delay * (0.5 + 0.5 * r.random())


def retry_transient(fn, *, retries: int = 2, backoff: float = 0.1,
                    retry_on: Tuple[type, ...] = (OSError,),
                    what: str = "io",
                    rng: Optional[random.Random] = None):
    """Run ``fn()`` retrying ``retry_on`` failures with bounded
    exponential backoff — the transient-IO policy of the prefetch
    workers, usable at any read site (a survey pass must not abort over
    one NFS hiccup). Permanent OSError subclasses
    (``NON_TRANSIENT_OS_ERRORS``) are never retried. Each retry emits a
    ``resilience.worker_retry`` event; exhaustion re-raises the last
    error."""
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            if isinstance(e, NON_TRANSIENT_OS_ERRORS):
                raise
            if attempt >= retries:
                raise
            attempt += 1
            delay = backoff_delay(backoff, attempt, RETRY_BACKOFF_MAX_S,
                                  rng)
            telemetry.counter("resilience.worker_retries")
            telemetry.event("resilience.worker_retry", pipeline=what,
                            attempt=attempt, error=type(e).__name__,
                            delay_s=round(delay, 3))
            print(f"# {what}: transient {type(e).__name__} ({e}); "
                  f"retry {attempt}/{retries} in {delay:.2f}s")
            time.sleep(delay)


def is_oom_error(e: BaseException) -> bool:
    """True for a device out-of-memory failure: an XlaRuntimeError-style
    RESOURCE_EXHAUSTED (matched on the message — jaxlib's exception types
    move between versions, the status string does not) or an injected
    OOM. Never true for KeyboardInterrupt-class BaseExceptions."""
    if isinstance(e, faultinject.InjectedOOM):
        return True
    if not isinstance(e, Exception):
        return False
    msg = str(e)
    return ("RESOURCE_EXHAUSTED" in msg
            or "out of memory" in msg.lower()
            or "OutOfMemory" in type(e).__name__)


def halving_dispatch(
    run: Callable[[int, int], object],
    n: int,
    *,
    min_size: int = 1,
    what: str = "dispatch",
    max_halvings: int = 16,
) -> List[Tuple[int, int, object]]:
    """Run ``run(lo, hi)`` over ``[0, n)``, halving any slice whose
    dispatch raises a device OOM (``is_oom_error``) until slices reach
    ``min_size``; returns ``[(lo, hi, result), ...]`` in index order.

    ``run`` must be a pure function of its slice (each item's result
    independent of the slicing) — the property that makes the recovery
    bit-identical. ``min_size`` > 1 keeps slices on a required multiple
    (e.g. a sharded batch axis must stay divisible by the mesh); an OOM
    at ``min_size`` re-raises, as does any non-OOM error.
    ``max_halvings`` bounds pathological retry storms (a "successful"
    dispatch that OOMs every time at every size is a real failure)."""
    if n <= 0:
        return []
    min_size = max(1, int(min_size))
    halvings = 0
    out: List[Tuple[int, int, object]] = []
    stack = [(0, n)]  # LIFO with right half pushed first -> index order
    while stack:
        lo, hi = stack.pop()
        try:
            out.append((lo, hi, run(lo, hi)))
            continue
        except Exception as e:  # noqa: BLE001 - classified below
            if not is_oom_error(e) or hi - lo <= min_size:
                raise
            if halvings >= max_halvings:
                raise
            err = e
        halvings += 1
        size = hi - lo
        # split on a min_size multiple so constrained axes stay legal
        half = max(min_size, ((size // 2) // min_size) * min_size)
        mid = lo + half
        telemetry.counter("resilience.oom_backoffs")
        telemetry.event("resilience.oom_backoff", what=what, size=size,
                        new_size=half, error=type(err).__name__)
        delay = backoff_delay(BACKOFF_BASE_S, halvings, BACKOFF_MAX_S)
        print(f"# {what}: device OOM at size {size}; backing off "
              f"{delay:.2f}s and retrying as {half} + {size - half}")
        time.sleep(delay)
        stack.append((mid, hi))
        stack.append((lo, mid))
    return out
