"""Data-integrity layer: validity through the device chain, finite
gates, corruption tooling.

The resilience layer so far hardens the *runtime* (OOM/IO/kill recovery,
watchdogs, device quarantine) but trusted its *input bytes*. Real
telescope recordings are dirty — dropped packets, truncated tails,
saturated or zeroed blocks are the norm for live transient surveys
(PAPERS.md 1601.01165), which is why the reference pipeline carries the
whole rfifind/mask machinery. This module is the data-plane counterpart
of :mod:`.health`:

- **Stream scrub** (:func:`guard_source` / :class:`GuardedSource`) —
  decorates the staged block sources so every float chunk passes a
  cheap fused ``isfinite`` reduction ON DEVICE: non-finite cells are
  zero-filled (rfifind-mask semantics: flagged data contributes
  nothing) and accounted in the ``data.*`` telemetry counters, so a NaN
  born in one chunk can never silently propagate into SNRs. Integer
  sources (uint filterbanks) cannot hold non-finite values and pass
  through unwrapped — the guard costs the hot 8-bit path nothing.
- **Finite-output gates** (:func:`finite_rows` / :func:`finite_cands`)
  — the candidate and SNR writers filter non-finite rows (counted as
  ``data.nonfinite_cands_dropped``), so a non-finite value provably
  cannot reach a ``.cands``/``.cand``/``.txtcand`` file or a SNR row.
- **Ingest validation** (:func:`validate_input`) — the survey DAG's
  admission check: recognized formats get a cheap header + size
  cross-check and return a data-quality report (salvaged span, masked
  fraction denominators); a recognized-but-broken file raises
  :class:`~pypulsar_tpu.io.errors.DataFormatError` and the scheduler
  quarantines the observation with reason ``"data"`` (distinct from
  runtime quarantine) instead of burning retries on it.
- **Corruption tooling** (:func:`corrupt_file`, :func:`fuzz_mutate`,
  :func:`run_reader_fuzz`) — seeded deterministic file corruption (the
  one code path ``tools/make_synthetic_fil.py --corrupt`` and
  ``bench.py --corruption`` share) and the structure-aware reader fuzz
  harness whose contract is: every reader, fed mutated bytes, parses
  (possibly salvaging a prefix) or raises ``DataFormatError`` — never a
  hang, never a crash.

Knobs: ``PYPULSAR_TPU_DATAGUARD=0`` disables the stream scrub (the
gates and validation stay on — they are correctness, not policy);
``PYPULSAR_TPU_MAX_BAD_FRAC`` sets the survey's degrade-vs-quarantine
threshold (default 0.5: an observation reporting more than half its
samples missing/invalid at ingest is data-quarantined).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pypulsar_tpu.io.errors import DataFormatError
from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.tune import knobs

__all__ = [
    "CORRUPT_KINDS",
    "DataFormatError",
    "GuardedSource",
    "StreamQuality",
    "corrupt_file",
    "finite_cands",
    "finite_rows",
    "fuzz_mutate",
    "guard_enabled",
    "guard_source",
    "max_bad_frac_default",
    "reader_quality",
    "run_reader_fuzz",
    "validate_input",
]

ENV_GUARD = "PYPULSAR_TPU_DATAGUARD"
ENV_MAX_BAD_FRAC = "PYPULSAR_TPU_MAX_BAD_FRAC"
DEFAULT_MAX_BAD_FRAC = 0.5


def guard_enabled() -> bool:
    return knobs.env_str(ENV_GUARD) != "0"


def max_bad_frac_default() -> float:
    # registry read is typo-tolerant (bad value -> declared default)
    return float(knobs.env_float(ENV_MAX_BAD_FRAC))


# ---------------------------------------------------------------------------
# stream scrub
# ---------------------------------------------------------------------------

_scrub_jit = None


def _device_scrub(block):
    """(clean block, n_nonfinite, n_zero) on device — one fused
    elementwise pass + two scalar reductions, compiled once per shape."""
    global _scrub_jit
    if _scrub_jit is None:
        import jax.numpy as jnp

        from pypulsar_tpu.compile import plane_jit

        @plane_jit(stage="data")
        def f(b):
            finite = jnp.isfinite(b)
            clean = jnp.where(finite, b, jnp.zeros((), b.dtype))
            return (clean,
                    jnp.sum(~finite, dtype=jnp.int32),
                    jnp.sum(clean == 0, dtype=jnp.int32))

        _scrub_jit = f
    return _scrub_jit(block)


@dataclasses.dataclass
class StreamQuality:
    """Running per-stream account of what the scrub saw/did. Shared
    across reroots of the same source (resume must not double-zero the
    telemetry story, but totals may legitimately re-count replayed
    chunks — the counters are diagnostics, not science)."""

    cells: int = 0
    nonfinite_cells: int = 0
    zero_cells: int = 0
    chunks: int = 0

    def fraction_bad(self) -> float:
        return self.nonfinite_cells / self.cells if self.cells else 0.0

    def to_dict(self) -> Dict:
        return {"cells": self.cells,
                "nonfinite_cells": self.nonfinite_cells,
                "zero_cells": self.zero_cells,
                "chunks": self.chunks,
                "fraction_bad": round(self.fraction_bad(), 6)}


class GuardedSource:
    """Decorates a staged block source (``frequencies``/``tsamp``/
    ``nsamples``/``chan_major_blocks``) with the data-integrity scrub.

    Sits INSIDE any rfifind mask wrapper: the mask fill computes channel
    medians, and a NaN reaching that reduction would poison the whole
    channel — scrub first, mask second. Device blocks scrub on device
    (counts accumulate as lazy device scalars; ONE host sync when the
    stream ends), host blocks scrub in numpy. Every completed iteration
    flushes its deltas to the ``data.*`` telemetry counters.
    """

    FAULT_POINT = "data.block"

    def __init__(self, src, stats: Optional[StreamQuality] = None):
        self._src = src
        self.frequencies = src.frequencies
        self.tsamp = src.tsamp
        self.nsamples = src.nsamples
        self.stats = stats if stats is not None else StreamQuality()

    def chan_major_blocks(self, payload: int, overlap: int):
        try:
            import jax
        except Exception:  # noqa: BLE001 - backend-less: host scrub only
            jax = None
        dev_bad = dev_zero = None
        host_bad = host_zero = 0
        cells = chunks = 0
        try:
            for pos, block in self._src.chan_major_blocks(payload,
                                                          overlap):
                block = faultinject.trip_data(self.FAULT_POINT, block)
                chunks += 1
                cells += int(np.prod(np.shape(block)))
                if jax is not None and isinstance(block, jax.Array):
                    block, n_bad, n_zero = _device_scrub(block)
                    dev_bad = n_bad if dev_bad is None else dev_bad + n_bad
                    dev_zero = (n_zero if dev_zero is None
                                else dev_zero + n_zero)
                else:
                    a = np.asarray(block)
                    if np.issubdtype(a.dtype, np.floating):
                        finite = np.isfinite(a)
                        n_bad = int(a.size - np.count_nonzero(finite))
                        if n_bad:
                            a = np.where(finite, a,
                                         np.zeros((), a.dtype))
                            host_bad += n_bad
                            block = a
                        host_zero += int(np.count_nonzero(a == 0))
                yield pos, block
        finally:
            n_bad = host_bad + (int(dev_bad) if dev_bad is not None else 0)
            n_zero = host_zero + (int(dev_zero)
                                  if dev_zero is not None else 0)
            self.stats.cells += cells
            self.stats.nonfinite_cells += n_bad
            self.stats.zero_cells += n_zero
            self.stats.chunks += chunks
            if chunks:
                telemetry.counter("data.chunks", chunks)
                telemetry.counter("data.cells", cells)
            if n_zero:
                telemetry.counter("data.zero_cells", n_zero)
            if n_bad:
                telemetry.counter("data.nonfinite_cells", n_bad)
                telemetry.event(
                    "data.nonfinite_scrubbed", cells=n_bad,
                    frac=round(n_bad / max(cells, 1), 6))


def _source_is_float(src) -> bool:
    """True when the source's delivered blocks are float-typed (can
    carry non-finite values): in-memory Spectra, PSRFITS (scale/offset/
    weight make f32), and 32-bit filterbanks. uint filterbanks cannot
    hold a NaN and skip the guard (which also preserves their exact-
    integer host-downsample fast path)."""
    r = getattr(src, "reader", None)
    if r is None:
        return True  # _SpectraSource: float payload
    nbits = getattr(r, "nbits", None)
    if nbits is None:
        return True  # psrfits & friends deliver float32
    return int(nbits) >= 32


def guard_source(src):
    """Wrap a staged block source with :class:`GuardedSource` when it
    can carry non-finite values — or unconditionally when a DATA fault
    is armed (the injection needs somewhere to land). Identity when
    ``PYPULSAR_TPU_DATAGUARD=0`` or the source is integer-typed."""
    if isinstance(src, GuardedSource):
        return src
    if not guard_enabled():
        return src
    if not (faultinject.data_faults_armed() or _source_is_float(src)):
        return src
    return GuardedSource(src)


# ---------------------------------------------------------------------------
# finite-output gates
# ---------------------------------------------------------------------------

def _finite(v) -> bool:
    try:
        return bool(np.isfinite(v))
    except TypeError:
        return True  # non-numeric fields pass


def finite_rows(rows: Sequence[dict], keys: Sequence[str],
                what: str = "cands") -> List[dict]:
    """Filter dict rows whose ``keys`` are all finite; count drops in
    ``data.nonfinite_cands_dropped``. The gate every text-table writer
    calls so a non-finite value can never reach a published row."""
    good = [r for r in rows
            if all(_finite(r.get(k)) for k in keys)]
    dropped = len(rows) - len(good)
    if dropped:
        telemetry.counter("data.nonfinite_cands_dropped", dropped)
        telemetry.event("data.nonfinite_rows_dropped", what=what,
                        dropped=dropped)
        print(f"# dataguard: dropped {dropped} non-finite {what} "
              f"row(s) at the output gate")
    return good


def finite_cands(cands, T: float, what: str = "accel") -> list:
    """The accel-candidate form of the gate: sigma/power/r/z finite AND
    a usable frequency (r=0 debris would divide by zero in the period
    column)."""
    cands = list(cands)
    good = []
    for c in cands:
        vals = (c.sigma, c.power, c.r, c.z)
        if all(_finite(v) for v in vals):
            freq = c.freq(T) if T else 0.0
            if np.isfinite(freq) and freq > 0:
                good.append(c)
    dropped = len(cands) - len(good)
    if dropped:
        telemetry.counter("data.nonfinite_cands_dropped", dropped)
        telemetry.event("data.nonfinite_rows_dropped", what=what,
                        dropped=dropped)
        print(f"# dataguard: dropped {dropped} non-finite {what} "
              f"candidate(s) at the output gate")
    return good


# ---------------------------------------------------------------------------
# ingest validation + data-quality reports
# ---------------------------------------------------------------------------

def reader_quality(reader) -> Optional[Dict]:
    """The salvage half of a reader's data-quality story (None when the
    file read back whole)."""
    return getattr(reader, "salvage", None)


def validate_input(path: str) -> Optional[Dict]:
    """Cheap ingest-time validation of one observation input.

    Returns a data-quality report dict for recognized formats
    (``format``, geometry, ``salvage``, ``bad_frac`` — the fraction of
    expected samples missing), None for missing/unrecognized files (the
    stage itself will fail with a proper error — synthetic test DAGs
    use dummy paths), and raises :class:`DataFormatError` for a file
    that *claims* a recognized format but violates it — the signal the
    survey scheduler turns into a reason-``"data"`` quarantine."""
    if not os.path.isfile(path):
        return None
    try:
        with open(path, "rb") as f:
            magic = f.read(16)
    except OSError:
        return None
    if magic.startswith(b"SIMPLE"):
        return _validate_psrfits(path)
    if _sniff_sigproc(magic):
        return _validate_filterbank(path)
    return None


def _sniff_sigproc(magic: bytes) -> bool:
    """True when the leading bytes carry a SIGPROC HEADER_START marker —
    the cheap is-it-claiming-to-be-ours test (a failing parse after a
    positive sniff is a data error, not an unrecognized format)."""
    return magic[4:16] == b"HEADER_START"


def _validate_filterbank(path: str) -> Dict:
    from pypulsar_tpu.io.filterbank import FilterbankFile

    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # salvage warns; we REPORT it
        fb = FilterbankFile(path)
    try:
        salvage = fb.salvage
        nsamp = int(fb.number_of_samples)
        report = {
            "format": "filterbank",
            "nsamples": nsamp,
            "nchan": int(fb.nchans),
            "nbits": int(fb.nbits),
            "salvage": salvage,
        }
    finally:
        fb.close()
    bad = 0.0
    if nsamp == 0:
        bad = 1.0  # a header with no payload is all-bad
    elif salvage and salvage.get("expected_samples"):
        bad = salvage["missing_samples"] / salvage["expected_samples"]
    report["bad_frac"] = round(float(bad), 6)
    return report


def _validate_psrfits(path: str) -> Dict:
    from pypulsar_tpu.io.psrfits import PsrfitsFile

    pf = PsrfitsFile(path)
    try:
        report = {
            "format": "psrfits",
            "nsamples": int(pf.nspec),
            "nchan": int(pf.nchan),
            "nbits": int(pf.nbits),
            "salvage": None,
            "bad_frac": 1.0 if int(pf.nspec) == 0 else 0.0,
        }
    finally:
        pf.close()
    return report


# ---------------------------------------------------------------------------
# deterministic file corruption (ONE code path for tools + bench + tests)
# ---------------------------------------------------------------------------

CORRUPT_KINDS = ("truncate", "bitflip", "dropblock", "nanburst",
                 "dcjump", "header")


def _rng(seed: int, tag: str):
    h = hashlib.sha256(f"{tag}:{seed}".encode()).digest()
    return np.random.Generator(np.random.SFC64(list(h[:16])))


def _sigproc_header_size(path: str) -> int:
    from pypulsar_tpu.io import sigproc

    try:
        with open(path, "rb") as f:
            _, _, hsize = sigproc.read_header(f, path=path)
        return hsize
    except (DataFormatError, OSError):
        return 0


def corrupt_file(path: str, kind: str, seed: int = 0) -> Dict:
    """Deterministically corrupt ``path`` in place with one data-fault
    kind (see :data:`CORRUPT_KINDS`) — the shared recipe behind
    ``make_synthetic_fil --corrupt`` and ``bench.py --corruption``, so
    tests, bench and tooling can never drift apart on what "a truncated
    file" means. Returns a description of what was done.

    Payload-relative kinds locate the SIGPROC header first (header_size
    0 for non-SIGPROC files: the whole file is payload). ``nanburst``
    and ``dcjump`` interpret the payload as float32 — the depth the
    synthetic survey inputs use."""
    if kind not in CORRUPT_KINDS:
        raise ValueError(f"unknown corruption kind {kind!r}; expected "
                         f"one of {CORRUPT_KINDS}")
    size = os.path.getsize(path)
    rng = _rng(seed, f"{kind}:{os.path.basename(path)}")
    desc: Dict = {"kind": kind, "seed": seed, "path": path}
    if kind == "header":
        # scribble over the keyword stream right after HEADER_START:
        # parses must fail loudly (DataFormatError), never wander
        with open(path, "r+b") as f:
            f.seek(min(16, size))
            f.write(rng.integers(0, 256, size=32,
                                 dtype=np.uint8).tobytes())
        desc["span"] = (16, 48)
        return desc
    hsize = _sigproc_header_size(path)
    payload = size - hsize
    if payload <= 0:
        raise ValueError(f"{path}: no payload to corrupt")
    if kind == "truncate":
        # drop the tail 40%, deliberately landing mid-spectrum so the
        # reader's partial-tail salvage path is the one exercised
        keep = hsize + int(payload * 0.6) + 1
        os.truncate(path, min(keep, size))
        desc["truncated_to"] = keep
        return desc
    if kind == "bitflip":
        with open(path, "r+b") as f:
            offs = sorted(int(o) for o in
                          rng.integers(0, payload, size=64))
            for o in offs:
                f.seek(hsize + o)
                b = f.read(1)
                f.seek(hsize + o)
                f.write(bytes([b[0] ^ (1 << int(rng.integers(0, 8)))]))
        desc["flips"] = 64
        return desc
    # span/offset are 4-byte aligned RELATIVE TO THE PAYLOAD (not the
    # file): float32 cells start at hsize, so a file-aligned offset on
    # an odd-size header would write the NaN pattern straddling cell
    # boundaries — denormal soup instead of NaNs
    span = max(4, (payload // 20) & ~3)  # ~5% of the payload
    off = int(rng.integers(0, max(payload - span, 1))) & ~3
    start = hsize + off
    desc["span"] = (start, start + span)
    if kind == "dropblock":
        with open(path, "r+b") as f:
            f.seek(start)
            f.write(b"\x00" * span)
        return desc
    if kind == "nanburst":
        burst = np.full(span // 4, np.nan, dtype=np.float32)
        burst[0] = np.inf
        with open(path, "r+b") as f:
            f.seek(start)
            f.write(burst.tobytes())
        return desc
    # dcjump: add a large offset to the span's float32 values
    with open(path, "r+b") as f:
        f.seek(start)
        vals = np.frombuffer(f.read(span), dtype=np.float32).copy()
        vals += np.float32(1e4)
        f.seek(start)
        f.write(vals.tobytes())
    return desc


# ---------------------------------------------------------------------------
# structure-aware reader fuzz
# ---------------------------------------------------------------------------

def fuzz_mutate(data: bytes, rng) -> bytes:
    """One seeded structural mutation of a file image: truncation at a
    random offset, byte flips, a zeroed span, a garbage-overwritten
    span, or a duplicated span — the shapes real corruption takes
    (dropped packets, torn copies, bit rot)."""
    if not data:
        return data
    op = int(rng.integers(0, 5))
    n = len(data)
    if op == 0:  # truncate
        return data[: int(rng.integers(0, n))]
    buf = bytearray(data)
    if op == 1:  # flip 1-8 random bytes
        for _ in range(int(rng.integers(1, 9))):
            i = int(rng.integers(0, n))
            buf[i] ^= 1 << int(rng.integers(0, 8))
    elif op == 2:  # zero a span
        span = int(rng.integers(1, max(n // 4, 2)))
        i = int(rng.integers(0, max(n - span, 1)))
        buf[i:i + span] = b"\x00" * span
    elif op == 3:  # garbage a span
        span = int(rng.integers(1, max(n // 8, 2)))
        i = int(rng.integers(0, max(n - span, 1)))
        buf[i:i + span] = rng.integers(0, 256, size=span,
                                       dtype=np.uint8).tobytes()
    else:  # duplicate a span over another (framing slip)
        span = int(rng.integers(1, max(n // 8, 2)))
        i = int(rng.integers(0, max(n - span, 1)))
        j = int(rng.integers(0, max(n - span, 1)))
        buf[j:j + span] = buf[i:i + span]
    return bytes(buf)


def run_reader_fuzz(fmt: str, n: int, seed: int,
                    workdir: str) -> Tuple[Dict[str, int], List]:
    """Fuzz one reader with ``n`` seeded mutations of a small valid
    file. Returns ``(outcome counts, failures)`` where outcomes are
    ``ok`` (parsed whole), ``salvage`` (parsed a reported prefix) and
    ``error`` (clean :class:`DataFormatError`); ``failures`` lists any
    mutation that escaped the contract (raw exception) — the fuzz tests
    assert it empty. ``fmt``: ``filterbank`` | ``psrfits`` | ``dat``."""
    os.makedirs(workdir, exist_ok=True)
    base = _fuzz_base(fmt, workdir)
    rng = _rng(seed, f"fuzz:{fmt}")
    counts = {"ok": 0, "salvage": 0, "error": 0}
    failures: List = []
    for i in range(n):
        mutated = fuzz_mutate(base, rng)
        try:
            outcome = _fuzz_open(fmt, workdir, mutated)
        except DataFormatError:
            counts["error"] += 1
        except Exception as e:  # noqa: BLE001 - the contract violation
            failures.append((i, f"{type(e).__name__}: {e}"))
        else:
            counts[outcome] += 1
    return counts, failures


def _fuzz_base(fmt: str, workdir: str) -> bytes:
    """A small VALID file image of ``fmt`` (plus sidecars on disk where
    the format needs them)."""
    rng = np.random.default_rng(7)
    if fmt == "filterbank":
        from pypulsar_tpu.io.filterbank import write_filterbank

        fn = os.path.join(workdir, "base.fil")
        data = rng.standard_normal((64, 16)).astype(np.float32)
        write_filterbank(fn, dict(nchans=16, tsamp=1e-3, fch1=1500.0,
                                  foff=-1.0, nbits=32), data)
    elif fmt == "psrfits":
        from pypulsar_tpu.io.psrfits import write_psrfits

        fn = os.path.join(workdir, "base.fits")
        data = rng.integers(0, 40, size=(8, 64)).astype(np.float32)
        write_psrfits(fn, data, 1500.0 - np.arange(8.0), 1e-3,
                      nsamp_per_subint=16, nbits=8)
    elif fmt == "dat":
        from pypulsar_tpu.io.datfile import write_dat
        from pypulsar_tpu.io.infodata import InfoData

        base = os.path.join(workdir, "base")
        inf = InfoData()
        inf.epoch = 55000.0
        inf.dt = 1e-3
        inf.DM = 10.0
        write_dat(base, rng.standard_normal(256).astype(np.float32), inf)
        fn = base + ".dat"
        # the .inf sidecar stays valid on disk; the .dat bytes mutate
    else:
        raise ValueError(f"unknown fuzz format {fmt!r}")
    with open(fn, "rb") as f:
        return f.read()


def _fuzz_open(fmt: str, workdir: str, mutated: bytes) -> str:
    """Open + exercise one mutated image; returns ``ok``/``salvage`` or
    raises (DataFormatError = clean outcome, anything else = contract
    violation recorded by the caller)."""
    if fmt == "filterbank":
        from pypulsar_tpu.io.filterbank import FilterbankFile

        fn = os.path.join(workdir, "mut.fil")
        with open(fn, "wb") as f:
            f.write(mutated)
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            fb = FilterbankFile(fn)
        try:
            n = min(int(fb.number_of_samples), 8)
            if n > 0:
                fb.get_samples(0, n)
            return "salvage" if fb.salvage else "ok"
        finally:
            fb.close()
    if fmt == "psrfits":
        from pypulsar_tpu.io.psrfits import PsrfitsFile, is_PSRFITS

        fn = os.path.join(workdir, "mut.fits")
        with open(fn, "wb") as f:
            f.write(mutated)
        if not is_PSRFITS(fn):
            raise DataFormatError(fn, "no longer sniffs as PSRFITS")
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            pf = PsrfitsFile(fn)
            try:
                n = min(int(pf.nspec), 4)
                if n > 0:
                    pf.get_spectra(0, n)
                return "ok"
            finally:
                pf.close()
    if fmt == "dat":
        from pypulsar_tpu.io.datfile import Datfile

        fn = os.path.join(workdir, "base.dat")  # .inf sidecar lives here
        with open(fn, "wb") as f:
            f.write(mutated)
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            d = Datfile(fn)
        try:
            d.read_all()
            return "salvage" if d.salvage else "ok"
        finally:
            d.close()
    raise ValueError(f"unknown fuzz format {fmt!r}")
