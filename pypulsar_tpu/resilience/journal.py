"""Journaled, integrity-checked resume: one manifest for the whole chain.

Restartability in this package grew ad hoc — the sweep checkpoints its
accumulator to ``.npz``, the accel stage keys resume on ``.cand``
existence (``--skip-existing``), sift has nothing — and the weakest link
defined the whole chain's behavior: a zero-byte ``.cand`` from a killed
run was "done", a truncated ``.dat`` tee was trusted forever. This module
generalizes all of it into one per-run JSONL **work-unit journal**:

- every completed unit appends one ``done`` record naming its output
  artifacts with their **size and sha256** (atomic append: single
  ``write`` + ``flush`` + ``fsync``, so a kill leaves at most one
  truncated trailing line, which the loader tolerates);
- a header record fingerprints the run configuration — resuming under
  different parameters starts from scratch instead of trusting stale
  artifacts (the same contract SweepCheckpoint enforces for the sweep);
- on resume, :meth:`RunJournal.completed` re-validates every recorded
  artifact on disk (exists, size matches, checksum matches) — a
  journal entry whose artifact was truncated, deleted or overwritten is
  *redone*, not trusted, and emits a ``resilience.journal_invalid``
  telemetry event saying why.

The module also holds the artifact-integrity helpers the satellite fixes
use standalone: :func:`candfile_complete` (the validated form of
``--skip-existing``) and :func:`atomic_write_text`/``bytes`` (tmp +
``os.replace``, the sweep checkpoints' discipline applied to every
pipeline output).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pypulsar_tpu.obs import telemetry

__all__ = [
    "RunJournal",
    "atomic_open",
    "atomic_write_bytes",
    "atomic_write_text",
    "candfile_complete",
    "file_digest",
]

TMP_SUFFIX = ".tmp"
JOURNAL_VERSION = 1


def atomic_write_bytes(path: str, data: bytes) -> str:
    """Write ``path`` atomically (tmp + os.replace): readers see either
    the old complete file or the new complete file, never a truncation.
    The tmp lives next to the target so the replace stays one-filesystem."""
    tmp = path + TMP_SUFFIX
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path


def atomic_write_text(path: str, text: str) -> str:
    return atomic_write_bytes(path, text.encode())


@contextlib.contextmanager
def atomic_open(path: str, mode: str = "wb"):
    """Streaming sibling of :func:`atomic_write_bytes`: yields a file
    handle on ``path + '.tmp'`` and renames it into place only when the
    block exits cleanly.  On ANY exception (including injected kills)
    the tmp is removed and ``path`` is untouched — so a torn stream can
    never pose as the finished artifact, and no `.tmp` debris outlives
    the failure.

    Fresh-write modes only: with append/read/update modes the final
    rename would REPLACE the artifact with just the tmp's bytes —
    silent data loss, so the entry point refuses them."""
    if "a" in mode or "r" in mode or "+" in mode or not (
            "w" in mode or "x" in mode):
        raise ValueError(
            f"atomic_open mode {mode!r} is not a fresh write; the "
            f"tmp+replace idiom would clobber the existing artifact")
    tmp = path + TMP_SUFFIX
    f = open(tmp, mode)
    try:
        yield f
    except BaseException:
        f.close()
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    f.close()
    os.replace(tmp, path)


def file_digest(path: str) -> Tuple[int, str]:
    """(size_bytes, sha256 hex) of a file's current content."""
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            size += len(block)
            h.update(block)
    return size, h.hexdigest()


def _fourierprops_bytes() -> int:
    """The on-disk fourierprops record size, from the ONE definition
    (io.prestocand.FOURIERPROPS_DTYPE) — a hardcoded 88 here would
    silently diverge if the dtype ever changed, classifying every valid
    .cand as truncation debris. Imported lazily: the journal itself has
    no numpy dependency."""
    from pypulsar_tpu.io.prestocand import FOURIERPROPS_DTYPE

    return FOURIERPROPS_DTYPE.itemsize


def candfile_complete(candfn: str, txtfn: Optional[str] = None) -> bool:
    """True when a ``.cand`` file is a COMPLETE artifact, not debris from
    a killed run: it exists, its size is a whole number of fourierprops
    records, and (when the sibling ``.txtcand`` path is given) the
    human-readable twin exists with a parseable header and a row count
    equal to the binary record count.

    The pair check is what disambiguates the zero-byte case: a
    legitimately empty result is a 0-record ``.cand`` PLUS a
    header-only ``.txtcand`` (the txt is written first, the cand last —
    the completion marker order), while a killed run leaves the
    zero-byte ``.cand`` alone."""
    try:
        size = os.path.getsize(candfn)
    except OSError:
        return False
    rec = _fourierprops_bytes()
    if size % rec:
        return False
    n_cands = size // rec
    if txtfn is None:
        return size > 0
    try:
        with open(txtfn) as f:
            lines = f.read().splitlines()
    except OSError:
        return False
    if not lines or not lines[0].startswith("#"):
        return False
    n_rows = sum(1 for ln in lines[1:] if ln.strip())
    return n_rows == n_cands


class RunJournal:
    """Append-only JSONL manifest of completed work units (see module
    docstring). ``fingerprint`` identifies the run configuration: opening
    an existing journal whose header fingerprint differs archives nothing
    — the file is restarted (the old journal described a different run,
    the same contract as a SweepCheckpoint mismatch). ``tool`` guards the
    restart: a journal whose header was written by a DIFFERENT tool is
    never restarted — the first write raises instead, so pointing one
    stage's CLI at another stage's manifest cannot silently erase it.

    ``shared=True`` is the multi-host discipline (round 18): the journal
    may be appended to by SEVERAL processes over its lifetime (one at a
    time — the survey fleet's fencing tokens serialize ownership, and
    every append is fenced first), so

    - appends go through an ``"a"``-mode handle (``O_APPEND``: every
      write lands at the REAL end of file, never at a stale offset a
      previous owner remembered), each record framed by a leading
      newline so a predecessor's torn tail glues onto a blank-skipped
      fragment instead of corrupting the next record, and
    - the loader skips malformed interior lines instead of declaring
      the whole file foreign — a fenced-off writer's one torn line must
      not erase every other host's recorded progress.
    """

    def __init__(self, path: str, fingerprint: str = "",
                 tool: str = "run", shared: bool = False):
        self.path = path
        self.fingerprint = fingerprint
        self.tool = tool
        self.shared = bool(shared)
        self._fh = None
        self._records: List[dict] = []
        self._keep_bytes = 0  # byte offset after the last VALID line
        self._foreign = False  # header written by a different tool
        self._completed_cache: Optional[Set[str]] = None
        self._load()
        if self._foreign:
            # fail FAST, before any work is done against the wrong
            # manifest — proceeding would end in a refused write anyway
            raise ValueError(
                f"journal {path!r} belongs to a different tool; refusing "
                f"to overwrite it — give {tool!r} its own journal file")

    # -- read side -----------------------------------------------------------

    def _load(self) -> None:
        """Parse existing records, tolerating a truncated trailing line
        (the one artifact a kill mid-append can leave; ``_keep_bytes``
        marks where valid content ends so appends truncate the torn tail
        instead of gluing the next record onto it)."""
        self._records = []
        self._keep_bytes = 0
        self._foreign = False
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return
        header_ok = False
        offset = 0
        lines = raw.decode(errors="replace").splitlines(keepends=True)
        for i, line in enumerate(lines):
            nbytes = len(line.encode())
            stripped = line.strip()
            if not stripped:
                offset += nbytes
                continue
            try:
                rec = json.loads(stripped)
            except ValueError:
                # only the LAST line may legitimately be torn; malformed
                # interior lines mean the file is not ours — start over.
                # A SHARED journal instead skips them: a fenced-off
                # previous owner's one torn line (each owner's appends
                # are newline-framed) must not erase the progress every
                # other host recorded after it.
                if i == len(lines) - 1:
                    break
                if self.shared and self._records:
                    offset += nbytes
                    continue
                self._records = []
                self._keep_bytes = 0
                return
            if not self._records:
                if rec.get("type") != "journal":
                    self._keep_bytes = 0
                    return  # not a journal: nothing usable
                if rec.get("tool", "run") != self.tool:
                    # another tool's manifest: refuse to ever restart it
                    self._foreign = True
                    self._keep_bytes = 0
                    return
                if rec.get("fingerprint") != self.fingerprint:
                    self._keep_bytes = 0
                    return  # same tool, different run: restartable
                header_ok = True
            offset += nbytes
            self._records.append(rec)
            self._keep_bytes = offset
        if not header_ok:
            self._records = []
            self._keep_bytes = 0

    def is_fresh(self) -> bool:
        """True when no prior usable records were loaded — a new journal
        file, or a restart after a fingerprint mismatch/corruption. What
        callers key start-over side effects on (e.g. the survey
        scheduler scrubbing stale artifacts a reconfigured rerun must
        not glob up)."""
        return not self._records

    def completed(self, validate: bool = True) -> Set[str]:
        """Unit ids recorded done whose artifacts (still) validate:
        every output exists with the recorded size and sha256. A unit
        whose artifacts fail validation is excluded — the caller redoes
        it — and the reason is surfaced as telemetry. The validated set
        is cached per instance (several pipeline stages consult the one
        shared journal; re-hashing every artifact per stage would
        duplicate both the IO and the journal_invalid events)."""
        if validate and self._completed_cache is not None:
            return set(self._completed_cache)
        done: Set[str] = set()
        for rec in self._records:
            if rec.get("type") != "done" or "unit" not in rec:
                continue
            unit = rec["unit"]
            if not validate:
                done.add(unit)
                continue
            ok = True
            for out in rec.get("outputs", []):
                reason = self._validate_output(out)
                if reason is not None:
                    ok = False
                    telemetry.counter("resilience.journal_invalid")
                    telemetry.event("resilience.journal_invalid",
                                    unit=unit, path=out.get("path", "?"),
                                    reason=reason)
                    break
            if ok:
                done.add(unit)
            else:
                done.discard(unit)  # a later invalid entry wins
        if validate:
            self._completed_cache = set(done)
        return done

    @staticmethod
    def _validate_output(out: dict) -> Optional[str]:
        """None when the artifact matches its journal record, else a
        short reason string."""
        path = out.get("path")
        if not path or not os.path.exists(path):
            return "missing"
        try:
            size, digest = file_digest(path)
        except OSError:
            return "unreadable"
        if size != out.get("bytes"):
            return "size_mismatch"
        if out.get("sha256") and digest != out["sha256"]:
            return "checksum_mismatch"
        return None

    # -- write side ----------------------------------------------------------

    def _open(self):
        if self._fh is not None:
            return self._fh
        if self._foreign:
            raise ValueError(
                f"journal {self.path!r} belongs to a different tool; "
                f"refusing to overwrite it — give {self.tool!r} its own "
                f"journal file")
        fresh = not self._records
        if fresh:
            # a journal from a different run (or corrupt) restarts the file
            self._fh = open(self.path, "w")
            self._append({"type": "journal", "version": JOURNAL_VERSION,
                          "tool": self.tool,
                          "fingerprint": self.fingerprint})
        elif self.shared:
            # multi-host append discipline: O_APPEND puts every write at
            # the REAL end of file (a previous owner may have appended
            # since we loaded); torn tails are NOT truncated — the
            # newline framing in _append renders them skippable blanks
            self._fh = open(self.path, "a")
        else:
            # matching run: append — after truncating any torn trailing
            # line so the next record starts on its own line
            self._fh = open(self.path, "r+")
            self._fh.seek(self._keep_bytes)
            self._fh.truncate()
        return self._fh

    def _append(self, rec: dict) -> None:
        fh = self._open()
        line = json.dumps(rec) + "\n"
        if self.shared:
            # leading newline: if the predecessor died mid-append, its
            # torn fragment ends here as a blank-skipped line instead of
            # gluing onto this record
            line = "\n" + line
        fh.write(line)
        fh.flush()
        os.fsync(fh.fileno())  # a recorded unit must survive the next kill
        self._records.append(rec)

    def done(self, unit: str, outputs: Iterable[str], **extra) -> None:
        """Record ``unit`` complete with the current size + sha256 of each
        of its output artifacts (digested NOW, after the atomic writes —
        the journal describes what is actually on disk). ``extra`` attrs
        ride along on the record (the survey fleet stamps its fencing
        ``token``); :meth:`completed` ignores them."""
        outs: List[Dict] = []
        for path in outputs:
            size, digest = file_digest(path)
            outs.append({"path": path, "bytes": size, "sha256": digest})
        self._append({"type": "done", "unit": unit, "outputs": outs,
                      **extra})
        if self._completed_cache is not None:
            self._completed_cache.add(unit)
        telemetry.counter("resilience.journal_units")

    def note(self, **attrs) -> None:
        """Free-form journal record (run milestones; ignored by
        :meth:`completed`)."""
        self._append({"type": "note", **attrs})

    def notes(self, event: Optional[str] = None) -> List[dict]:
        """Note records loaded from this journal (optionally filtered by
        their ``event`` attr) — the channel pipelines use to persist
        small per-unit RESULTS (e.g. refined fold parameters) across
        kills: the artifacts themselves validate via :meth:`completed`,
        but derived numbers that live only in a summary file would
        otherwise be lost with it."""
        out = [r for r in self._records if r.get("type") == "note"]
        if event is not None:
            out = [r for r in out if r.get("event") == event]
        return out

    def inode(self) -> Optional[Tuple[int, int]]:
        """``(st_dev, st_ino)`` of the open append handle, or None when
        nothing has been written yet.  A shared journal's file can be
        renamed or unlinked under a live writer by another host (e.g. a
        candstore compaction retiring a segment); comparing this
        against ``os.stat(path)`` tells the writer whether its records
        still live at the path it thinks they do."""
        if self._fh is None:
            return None
        st = os.fstat(self._fh.fileno())
        return (st.st_dev, st.st_ino)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
