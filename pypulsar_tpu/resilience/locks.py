"""psrrace's dynamic half: lockdep-instrumented synchronization wrappers.

PRs 5-13 grew a threaded fleet runtime — scheduler worker/claim threads
sharing a Condition, a watchdog that interrupts stages via
``PyThreadState_SetAsyncExc``, heartbeat renewers, prefetch producers —
and every one of those threads acquires locks that nothing checked.
The static rules (PL012-PL016) lock the *source shapes* in; this module
is the RUNTIME check: every :class:`TrackedLock` / :class:`TrackedRLock`
/ :class:`TrackedCondition` acquisition maintains

- a **per-thread held-set** (queryable cross-thread:
  :func:`thread_holds_lock` is how the watchdog defers an async
  interrupt that would otherwise strand a held lock — see
  ``resilience.health.interrupt_thread``), and
- a **global acquisition-order graph** keyed by lock NAME (instances
  come and go per fleet; the ordering discipline is per name). Acquiring
  K while holding H adds edge H->K; a new edge that closes a cycle is an
  **order violation**: under ``PYPULSAR_TPU_LOCKDEP=strict`` it raises
  :class:`LockOrderError` BEFORE the offending acquire (the lock is
  never taken, so nothing is stranded), under the default ``warn`` it
  emits a ``lockdep.order_violation`` telemetry event and continues,
  and ``off`` disables tracking entirely.

Non-``quiet`` locks also feed the tlmsum "lock health" roll-up:
``lock.<name>.hold_ms`` / ``lock.<name>.wait_ms`` gauges and a
``lock.<name>.contended`` counter. The telemetry session's own lock and
the knob registry's overlay lock are adopted ``quiet`` (tracking only,
no emission) — they sit on the hot path of the very telemetry calls a
non-quiet lock would make, and a leaf emitting about itself would
recurse.

**Async-exception safety.** The held-set entry is pushed BEFORE the
underlying acquire and popped AFTER the underlying release, so the
watchdog's defer-while-locked check covers the entire window in which
an async exception could otherwise land between ``__enter__``'s acquire
and the ``with`` block's protection (CPython delivers the exception at
the next bytecode boundary; a hit inside ``__enter__`` after the raw
acquire would strand the lock forever — the exact hazard PR 7's
watchdog introduced and this round closes).

**Seeded interleaving (the ``bench.py --race`` harness).** With race
mode armed (:func:`configure_race`, or the ``PYPULSAR_TPU_RACE_SEED`` /
``PYPULSAR_TPU_RACE_PAUSE_US`` knobs), every tracked acquire/release
first fires the ``lock.<name>`` faultinject point (so deterministic
faults and seeded chaos can land exactly at lock boundaries) and then
sleeps a deterministic ``hash(seed, name, hit)``-derived pause, widening
the race windows the interleaving stress asserts across.

Import discipline: stdlib-only at module level (the knob registry and
``resilience.health`` import this module from bootstrap-adjacent
paths); telemetry/knobs/faultinject are imported lazily at call time.
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "LockOrderError",
    "TrackedCondition",
    "TrackedEvent",
    "TrackedLock",
    "TrackedRLock",
    "configure_race",
    "edges",
    "race_pauses",
    "reset",
    "snapshot",
    "thread_holds_lock",
    "violations",
]

ENV_LOCKDEP = "PYPULSAR_TPU_LOCKDEP"
ENV_RACE_SEED = "PYPULSAR_TPU_RACE_SEED"
ENV_RACE_PAUSE_US = "PYPULSAR_TPU_RACE_PAUSE_US"


class LockOrderError(RuntimeError):
    """A tracked acquisition would close a cycle in the global lock
    acquisition-order graph (raised under ``PYPULSAR_TPU_LOCKDEP=strict``
    BEFORE the lock is taken; the ``warn`` mode records the same verdict
    as a ``lockdep.order_violation`` telemetry event instead)."""


# -- module registry ---------------------------------------------------------
# One RAW lock guards all bookkeeping: it is a leaf by construction
# (nothing is acquired under it, no telemetry is emitted under it), so
# it can never participate in the cycles it exists to detect.
_registry_lock = threading.Lock()

# thread ident -> [[lock_id, name, count, t_acquired], ...] (a stack);
# keyed globally (not threading.local) so the watchdog can ask about
# OTHER threads before delivering an async interrupt
_held: Dict[int, List[list]] = {}

# acquisition-order graph: name -> {names acquired while holding it},
# plus the first site observed for each edge (for the violation report)
_edges: Dict[str, Set[str]] = {}
_edge_first: Dict[Tuple[str, str], str] = {}

# recorded order violations (never trimmed; a fleet with ANY is broken)
_violations: List[dict] = []

# name -> [acquires, contentions, hold_total_s, hold_max_s, wait_max_s]
_stats: Dict[str, list] = {}

# lazy tracking switch: None = not resolved yet ("off" disables all
# bookkeeping; warn/strict differ only at violation time, read then)
_enabled: Optional[bool] = None

# race mode: None, or (seed, pause_seconds); _race_hits counts pauses
_race: Optional[Tuple[int, float]] = None
_race_env_checked = False
_race_hits = [0]

# thread-local reentrancy guard around telemetry emission: a gauge about
# lock N must not recurse through the (tracked) telemetry session lock
_tls = threading.local()


def _knob_raw(name: str) -> Optional[str]:
    """The lockdep knobs resolve through ``knobs.env_raw`` — the
    registry's ONE raw read (PL011) — and never through ``env_value``:
    the full read path takes the tuned-overlay lock, which is itself a
    tracked lock, and bookkeeping that re-enters the lock it is
    bookkeeping for deadlocks on the spot. All three knobs are declared
    ``invariant=False`` with no search domain, so env-or-default IS
    their full precedence chain."""
    from pypulsar_tpu.tune import knobs

    return knobs.env_raw(name)


def _tracking_enabled() -> bool:
    global _enabled
    if _enabled is None:
        mode = (_knob_raw(ENV_LOCKDEP) or "warn").strip().lower()
        _enabled = mode not in ("off", "0", "none")
    return _enabled


def _strict() -> bool:
    """Mode resolved at VIOLATION time (rare), so a test can flip
    strict/warn via the environment without restarting the process."""
    return (_knob_raw(ENV_LOCKDEP) or "warn").strip().lower() == "strict"


def configure_race(seed: Optional[int], pause_us: float = 100.0) -> None:
    """Arm (seed is not None) or disarm seeded lock-boundary pauses.
    Also resolves the tracking switch so a race run is always tracked."""
    global _race, _enabled
    if seed is None:
        _race = None
        return
    _race = (int(seed), max(0.0, float(pause_us)) * 1e-6)
    _enabled = True
    _race_hits[0] = 0


def _race_from_env() -> None:
    """One-shot env arm for subprocess harnesses (the CLI children a
    race run spawns cannot call :func:`configure_race` directly)."""
    global _race_env_checked
    if _race_env_checked:
        return
    _race_env_checked = True
    if _race is not None:
        return
    try:
        pause = float(_knob_raw(ENV_RACE_PAUSE_US) or 0.0)
        seed = int(float(_knob_raw(ENV_RACE_SEED) or 0))
    except ValueError:
        return  # a typo'd race knob must never abort (knob contract)
    if pause > 0:
        configure_race(seed, pause)


def _maybe_pause(name: str, where: str) -> None:
    """The seeded interleaving perturbation: fire the lock-boundary
    fault point, then sleep a deterministic hash-derived sliver. Only
    reached when race mode is armed — production acquires never pay."""
    armed = _race
    if armed is None:  # disarmed under us: a pause is best-effort
        return
    from pypulsar_tpu.resilience import faultinject

    faultinject.trip(f"lock.{name}.{where}")
    seed, pause = armed
    if pause <= 0:
        return
    with _registry_lock:
        _race_hits[0] += 1
        n = _race_hits[0]
    h = hashlib.sha256(f"{seed}:{name}:{where}:{n}".encode()).digest()
    frac = int.from_bytes(h[:4], "big") / float(1 << 32)
    time.sleep(pause * frac)


def race_pauses() -> int:
    """Pauses injected since race mode was armed (the harness receipt
    that the interleaving stress actually perturbed something)."""
    return _race_hits[0]


def thread_holds_lock(thread_id: int) -> bool:
    """Does ``thread_id`` currently hold ANY tracked lock? The watchdog's
    pre-interrupt check: an async exception delivered into a held-lock
    window can strand the lock or tear a locked invariant, so delivery
    is deferred to the next tick instead (resilience.health)."""
    with _registry_lock:
        return bool(_held.get(thread_id))


def _emit_guarded(fn, *args, **kw) -> None:
    """Run one telemetry emission under the reentrancy guard (the
    emission itself acquires the — tracked, quiet — session lock)."""
    if getattr(_tls, "emitting", False):
        return
    _tls.emitting = True
    try:
        fn(*args, **kw)
    finally:
        _tls.emitting = False


def _record_violation(held_name: str, name: str, path: List[str],
                      tid: int) -> None:
    # path walks the EXISTING edges name -> ... -> held_name; the new
    # edge held_name -> name closes the loop
    cycle = path + [name]
    rec = {"acquiring": name, "held": held_name, "cycle": cycle,
           "thread": tid,
           "first_sites": {f"{a}->{b}": _edge_first.get((a, b), "?")
                           for a, b in zip(cycle, cycle[1:])}}
    with _registry_lock:
        _violations.append(rec)
    from pypulsar_tpu.obs import telemetry

    _emit_guarded(telemetry.counter, "lockdep.order_violations")
    _emit_guarded(telemetry.event, "lockdep.order_violation",
                  acquiring=name, held=held_name,
                  cycle="->".join(cycle))
    if _strict():
        raise LockOrderError(
            f"lock order violation: acquiring {name!r} while holding "
            f"{held_name!r} closes the cycle {'->'.join(cycle)} "
            f"(first sites: {rec['first_sites']}); the canonical "
            f"hierarchy is documented in docs/ARCHITECTURE.md "
            f"'Concurrency model'")


def _path_between(graph: Dict[str, Set[str]], src: str,
                  dst: str) -> Optional[List[str]]:
    """BFS path src -> dst (graph is tiny: one node per lock NAME)."""
    if src == dst:
        return [src]
    seen = {src}
    frontier: List[List[str]] = [[src]]
    while frontier:
        nxt: List[List[str]] = []
        for path in frontier:
            for peer in sorted(graph.get(path[-1], ())):
                if peer == dst:
                    return path + [dst]
                if peer not in seen:
                    seen.add(peer)
                    nxt.append(path + [peer])
        frontier = nxt
    return None


def _caller_site() -> str:
    """First stack frame outside this module — the edge's provenance
    for the violation report's first-sites table. Paid only when a NEW
    edge (or a violation) is recorded, never on the steady-state
    acquire path."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:  # pragma: no cover - interpreter teardown
        return "?"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def _before_acquire(lock_id: int, name: str,
                    reentrant: bool) -> Optional[list]:
    """Order-graph update + held-set push, BEFORE the raw acquire (see
    the async-exception note in the module docstring). Returns the held
    entry to finish in ``_after_release`` (None when tracking is off or
    this is a reentrant re-acquire that only bumps its count).

    An edge that CLOSES a cycle is never persisted into the graph: a
    persisted inversion edge would make every later identical inversion
    look like a known-good ordering and skip the check — strict mode
    must raise (and warn mode must record) on EVERY occurrence, because
    the fleet's retry machinery survives the first raise and re-runs
    the same code path."""
    if not _tracking_enabled():
        return None
    _race_from_env()
    tid = threading.get_ident()
    pending: List[Tuple[str, List[str]]] = []
    with _registry_lock:
        stack = _held.setdefault(tid, [])
        if reentrant:
            for ent in stack:
                if ent[0] == lock_id:
                    ent[2] += 1
                    return None
        new_edges = []
        for ent in stack:
            held_name = ent[1]
            if held_name == name:
                continue  # same-name sibling (two manifests): no edge
            if name not in _edges.get(held_name, ()):
                new_edges.append(held_name)
        site = _caller_site() if new_edges else ""
        for held_name in new_edges:
            path = _path_between(_edges, name, held_name)
            _edge_first.setdefault((held_name, name), site)
            if path is not None:
                pending.append((held_name, path))
            else:
                _edges.setdefault(held_name, set()).add(name)
        entry = [lock_id, name, 1, time.monotonic()]
        stack.append(entry)
    for held_name, path in pending:
        try:
            _record_violation(held_name, name, path, tid)
        except LockOrderError:
            _drop_entry(tid, entry)
            raise
    return entry


def _drop_entry(tid: int, entry: list) -> None:
    with _registry_lock:
        stack = _held.get(tid)
        if stack and entry in stack:
            stack.remove(entry)
            if not stack:
                del _held[tid]


def _after_release(name: str, entry: Optional[list], quiet: bool) -> None:
    if entry is None:
        return
    tid = threading.get_ident()
    hold = time.monotonic() - entry[3]
    _drop_entry(tid, entry)
    with _registry_lock:
        st = _stats.setdefault(name, [0, 0, 0.0, 0.0, 0.0])
        st[0] += 1
        st[2] += hold
        st[3] = max(st[3], hold)
    if not quiet:
        from pypulsar_tpu.obs import telemetry

        if telemetry.is_active():
            _emit_guarded(telemetry.gauge, f"lock.{name}.hold_ms",
                          round(hold * 1e3, 4))


def _note_contention(name: str, waited: float, quiet: bool) -> None:
    with _registry_lock:
        st = _stats.setdefault(name, [0, 0, 0.0, 0.0, 0.0])
        st[1] += 1
        st[4] = max(st[4], waited)
    if not quiet:
        from pypulsar_tpu.obs import telemetry

        if telemetry.is_active():
            _emit_guarded(telemetry.counter, f"lock.{name}.contended")
            _emit_guarded(telemetry.gauge, f"lock.{name}.wait_ms",
                          round(waited * 1e3, 4))


class TrackedLock:
    """A ``threading.Lock`` with lockdep bookkeeping (module docstring).
    Drop-in for the ``with``/``acquire``/``release`` protocol, including
    use as a :class:`threading.Condition`'s lock (it provides the
    ``_is_owned`` hook from its own held-set, so the Condition's
    ownership asserts are exact instead of the probe-acquire guess)."""

    _reentrant = False

    def __init__(self, name: str, quiet: bool = False):
        self.name = name
        self.quiet = quiet
        self._inner = self._make_inner()
        self._entry_tls = threading.local()

    @staticmethod
    def _make_inner():
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(False)
        waited = 0.0
        if not got:
            if not blocking:
                return False
            t0 = time.monotonic()
            got = self._inner.acquire(True, timeout)
            if not got:
                return False
            waited = time.monotonic() - t0
        # the raw lock is held; bookkeeping happens "inside" it so the
        # held-set covers the full critical section. A strict-mode
        # violation must release before raising — the offending lock is
        # never left taken.
        try:
            entry = _before_acquire(id(self), self.name,
                                    self._reentrant)
        except LockOrderError:
            self._inner.release()
            raise
        self._entry_tls.entry = entry
        if waited > 0:
            _note_contention(self.name, waited, self.quiet)
        if _race is not None:
            _maybe_pause(self.name, "acquired")
        return True

    def release(self) -> None:
        entry = getattr(self._entry_tls, "entry", None)
        self._entry_tls.entry = None
        if _race is not None:
            _maybe_pause(self.name, "release")
        self._inner.release()
        _after_release(self.name, entry, self.quiet)

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:
        """Condition's ownership hook: exact, from the held-set."""
        if not _tracking_enabled():
            return self._inner.locked()
        tid = threading.get_ident()
        with _registry_lock:
            return any(ent[0] == id(self)
                       for ent in _held.get(tid, ()))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"<{type(self).__name__} {self.name!r} "
                f"locked={self.locked()}>")


class TrackedRLock(TrackedLock):
    """Reentrant flavor: a re-acquire by the owning thread bumps the
    held entry's count instead of adding edges (no self-cycle false
    positives), and the Condition save/restore hooks keep the held-set
    consistent across ``cv.wait``'s full release."""

    _reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._is_owned():
            # reentrant fast path: no contention possible, count bump
            self._inner.acquire()
            _before_acquire(id(self), self.name, True)
            return True
        got = self._inner.acquire(False)
        waited = 0.0
        if not got:
            if not blocking:
                return False
            t0 = time.monotonic()
            got = self._inner.acquire(True, timeout)
            if not got:
                return False
            waited = time.monotonic() - t0
        try:
            entry = _before_acquire(id(self), self.name, True)
        except LockOrderError:
            self._inner.release()
            raise
        self._entry_tls.entry = entry
        if waited > 0:
            _note_contention(self.name, waited, self.quiet)
        if _race is not None:
            _maybe_pause(self.name, "acquired")
        return True

    def release(self) -> None:
        tid = threading.get_ident()
        dropped = None
        if _tracking_enabled():
            with _registry_lock:
                stack = _held.get(tid, [])
                for ent in stack:
                    if ent[0] == id(self):
                        ent[2] -= 1
                        if ent[2] <= 0:
                            dropped = ent
                        break
        self._inner.release()
        if dropped is not None:
            _after_release(self.name, dropped, self.quiet)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        """Condition.wait's full release: drop the held entry entirely
        (the waiter holds nothing while parked — the watchdog may
        interrupt it) and save the inner recursion state."""
        tid = threading.get_ident()
        if _tracking_enabled():
            with _registry_lock:
                stack = _held.get(tid, [])
                for ent in list(stack):
                    if ent[0] == id(self):
                        stack.remove(ent)
                        if not stack:
                            del _held[tid]
                        break
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        ent = _before_acquire(id(self), self.name, False)
        if ent is not None and state and isinstance(state, tuple):
            ent[2] = state[0] if isinstance(state[0], int) else 1


class TrackedCondition(threading.Condition):
    """A ``threading.Condition`` over a tracked lock. Pass the shared
    :class:`TrackedLock` when several guards alias one mutex (the
    scheduler's ``_lock``/``_cv`` pair); default is a private
    :class:`TrackedRLock`, matching ``threading.Condition()``.

    ``wait`` releases through the tracked lock's own hooks, so the
    held-set is empty while parked — a waiting thread is interruptible,
    a working one is protected."""

    def __init__(self, name: str, lock: Optional[TrackedLock] = None):
        self.name = name
        super().__init__(lock if lock is not None
                         else TrackedRLock(name))


class TrackedEvent:
    """A ``threading.Event`` with a race-pause hook on ``set()`` (the
    signal edge is where interleaving bugs hide; holding-state tracking
    does not apply — events are level-triggered, never 'held')."""

    def __init__(self, name: str):
        self.name = name
        self._inner = threading.Event()

    def set(self) -> None:
        if _race is not None:
            _maybe_pause(self.name, "set")
        self._inner.set()

    def clear(self) -> None:
        self._inner.clear()

    def is_set(self) -> bool:
        return self._inner.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._inner.wait(timeout)


# -- introspection -----------------------------------------------------------


def violations() -> List[dict]:
    """Order violations recorded since the last :func:`reset` (the race
    harness asserts this is empty across every seed)."""
    with _registry_lock:
        return [dict(v) for v in _violations]


def edges() -> Dict[str, List[str]]:
    """The observed acquisition-order graph (name -> sorted names
    acquired while holding it) — the runtime counterpart of PL012's
    static graph, and what the ARCHITECTURE hierarchy documents."""
    with _registry_lock:
        return {k: sorted(v) for k, v in sorted(_edges.items())}


def snapshot() -> Dict[str, dict]:
    """Per-lock stats: acquires, contentions, hold totals/maxima."""
    with _registry_lock:
        return {name: {"acquires": st[0], "contentions": st[1],
                       "hold_total_s": round(st[2], 6),
                       "hold_max_s": round(st[3], 6),
                       "wait_max_s": round(st[4], 6)}
                for name, st in sorted(_stats.items())}


def reset() -> None:
    """Clear the order graph, violations, stats, race arming and the
    cached mode (test isolation). Held-sets of LIVE threads are kept —
    wiping them under a running fleet would blind the watchdog
    deferral."""
    global _enabled, _race, _race_env_checked
    with _registry_lock:
        _edges.clear()
        _edge_first.clear()
        _violations.clear()
        _stats.clear()
    _enabled = None
    _race = None
    _race_env_checked = False
    _race_hits[0] = 0


# -- bootstrap adoption ------------------------------------------------------


def _adopt_bootstrap_locks() -> None:
    """The knob registry is imported from bootstrap paths and must stay
    stdlib-only, so it cannot import this module; adopt its tuned-overlay
    lock from THIS side instead, the first time the resilience layer
    loads. The overlay lock is a leaf (nothing is acquired under it) and
    quiet (it guards the read path of the very knobs a telemetry
    emission would consult)."""
    try:
        from pypulsar_tpu.tune import knobs as _knobs

        if not isinstance(_knobs._tuned_lock, TrackedLock):
            _knobs._tuned_lock = TrackedLock("knobs.tuned", quiet=True)
    except Exception:  # noqa: BLE001 - half-initialized bootstrap
        # import: the registry keeps its plain stdlib lock, losing only
        # lockdep coverage of one leaf, never correctness
        pass
    try:
        from pypulsar_tpu.obs import flightrec as _flightrec

        if not isinstance(_flightrec._lock, TrackedLock):
            _flightrec._lock = TrackedLock("obs.flightrec", quiet=True)
    except Exception:  # noqa: BLE001 - same contract: the flight
        # recorder keeps its plain bootstrap lock, a quiet leaf
        pass


_adopt_bootstrap_locks()
