"""Fleet health primitives: heartbeats, deadlines, watchdog interrupts,
device strike accounting, resource admission.

PRs 3/5/6 made single *failures* survivable; this module covers the
failures that never raise at all — the ones a long-running survey daemon
(ROADMAP "service mode") meets first:

- a **wedged stage** holds its device lease forever. Stages emit
  *heartbeats* as a side effect of the telemetry they already record
  (``obs.telemetry`` activity hooks, see
  :func:`telemetry.add_activity_hook`): every span entry, counter bump
  or event fired on the stage's thread refreshes its
  :class:`HeartbeatRegistry` entry. A scheduler-side :class:`Watchdog`
  thread interrupts the stage worker — via
  :func:`interrupt_thread`, the async-exception channel, raising
  :class:`StageDeadlineExceeded` / :class:`StageStalled` (ordinary
  Exceptions) — when the stage outruns its declared deadline or stops
  heartbeating, so a hung stage becomes just another retryable fault
  for the existing retry -> quarantine policy;
- a **flaky chip** fails gang after gang with no memory of its strikes.
  :class:`DeviceHealth` counts strikes per device (OOMs, collective
  failures, injected device faults — :func:`is_device_fault`) and
  quarantines a device past ``PYPULSAR_TPU_DEVICE_STRIKES`` (default
  3); the survey scheduler evicts it from the lease pool mid-fleet and
  retries in-flight gangs shrunk to the surviving chips (placement is
  excluded from fingerprints, so artifacts stay byte-identical at the
  new width);
- a **full disk / saturated pipeline** crashes mid-write instead of
  waiting. :class:`ResourceGuard` is the admission gate the scheduler
  consults before launching new work: low free disk under the artifact
  root (``PYPULSAR_TPU_MIN_FREE_MB``) or a ship-ahead
  ``*.pending_depth`` gauge past its bound pauses *scheduling*, never
  the work already in flight.

Everything here is dependency-light (no jax import): the survey
scheduler, ``parallel/mesh.py`` and the tests share one implementation.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import locks
# the historical typo-tolerant helper is now a re-export of the knob
# registry's read path (round 17): registered knobs resolve env > tuned
# cache > declared default; unregistered names keep the old
# (raw env, ``default`` argument) behavior — including every caller's
# garbage-tolerant contract (a typo'd knob must never abort a fleet)
from pypulsar_tpu.tune.knobs import env_float  # noqa: F401

__all__ = [
    "DEFERRED",
    "DeviceHealth",
    "HeartbeatEntry",
    "HeartbeatRegistry",
    "HostHealth",
    "ResourceGuard",
    "StageDeadlineExceeded",
    "StageStalled",
    "StageTimeout",
    "Watchdog",
    "interrupt_thread",
    "is_device_fault",
    "must_propagate",
    "no_degrade",
]

# strikes before a device is quarantined out of the lease pool
ENV_DEVICE_STRIKES = "PYPULSAR_TPU_DEVICE_STRIKES"
DEFAULT_DEVICE_STRIKES = 3

# heartbeat-silence timeout (seconds) applied to every survey stage when
# the CLI/env does not set one explicitly; unset = stall detection off
ENV_STALL_S = "PYPULSAR_TPU_STALL_S"

# admission-gate floor for free disk under the artifact root, in MB
# (0 disables the check)
ENV_MIN_FREE_MB = "PYPULSAR_TPU_MIN_FREE_MB"
DEFAULT_MIN_FREE_MB = 32.0

# admission hysteresis (round 23): once the gate pauses, it resumes only
# past the floor/bound by this fractional margin — a fleet hovering AT
# the threshold must not flap paused/resumed event pairs every poll
ENV_ADMIT_RESUME_MARGIN = "PYPULSAR_TPU_ADMIT_RESUME_MARGIN"
DEFAULT_ADMIT_RESUME_MARGIN = 0.25




class StageTimeout(RuntimeError):
    """Base of the watchdog's interrupts. An ordinary Exception BY
    DESIGN: the scheduler's bounded retry -> quarantine policy owns a
    hung stage exactly like any other stage failure."""


class StageDeadlineExceeded(StageTimeout):
    """The stage outran its declared wall-clock deadline."""


class StageStalled(StageTimeout):
    """The stage stopped heartbeating for longer than the stall bound."""


# interrupt_thread's third verdict (round 19): the target currently
# holds a lockdep-tracked lock, so delivery is withheld — the caller
# retries next tick. Truthy ON PURPOSE: legacy ``assert
# interrupt_thread(...)`` call sites read deferral as "the thread is
# being handled", never as "the thread is gone".
DEFERRED = "deferred"


def interrupt_thread(thread_id: int, exc_type: type, *,
                     force: bool = False):
    """Raise ``exc_type`` asynchronously in the thread ``thread_id``
    (CPython's ``PyThreadState_SetAsyncExc``). The exception lands at
    the thread's next bytecode boundary — which is why the injected
    ``hang`` fault sleeps in small increments instead of one long
    ``sleep``. Returns False when the thread is gone (raced with
    completion); a result > 1 means the interpreter refused and the
    request is withdrawn.

    Async-interrupt safety (round 19): when the target thread holds any
    lockdep-tracked lock (``resilience.locks.thread_holds_lock``), the
    exception is NOT delivered and :data:`DEFERRED` is returned instead
    — an exception landing inside a held-lock window can strand the
    lock (the ``with`` protocol never runs ``__exit__`` for an acquire
    it never returned from) or tear a locked invariant mid-update.
    Callers poll (the watchdog re-arms the entry and retries next tick;
    the claim loop's zombie check re-fires every poll), so delivery
    lands at the first unlocked boundary. ``force=True`` bypasses the
    guard — last-resort teardown only."""
    if not force and locks.thread_holds_lock(thread_id):
        telemetry.counter("lockdep.interrupts_deferred")
        return DEFERRED
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_id), ctypes.py_object(exc_type))
    if res > 1:  # pragma: no cover - interpreter refused: undo
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_id), None)
        return False
    return res == 1


class HeartbeatEntry:
    """One running stage's liveness record (created by
    :meth:`HeartbeatRegistry.start`). ``obs``/``stage``/``trace_id``
    carry the causal identity of the work being watched (round 21):
    the watchdog's verdicts, the postmortem capsules, and the stitched
    trace all attribute through them."""

    __slots__ = ("label", "thread_id", "started", "deadline_s",
                 "stall_s", "last_beat", "fired", "payload",
                 "obs", "stage", "trace_id")

    def __init__(self, label: str, thread_id: int,
                 deadline_s: Optional[float], stall_s: Optional[float],
                 payload=None, obs: Optional[str] = None,
                 stage: Optional[str] = None,
                 trace_id: Optional[str] = None):
        now = time.monotonic()
        self.label = label
        self.thread_id = thread_id
        self.deadline_s = deadline_s
        self.stall_s = stall_s
        self.started = now
        self.last_beat = now
        self.fired = False  # the watchdog interrupts an entry ONCE
        self.payload = payload
        self.obs = obs
        self.stage = stage
        self.trace_id = trace_id


class HeartbeatRegistry:
    """Thread-safe registry of running stages. ``beat`` is the hot path
    (called from the telemetry activity hook on every span entry /
    counter bump): one or two dict gets + one float store, no lock —
    heartbeats may be arbitrarily slightly stale, the watchdog's poll
    interval dwarfs any race window.

    Liveness is attributed PER TRACE first, per thread second
    (round 21): telemetry carries the active trace context's
    ``trace_id`` into the hook, so work recorded by a stage's helper
    threads (prefetch producers running under the adopted context)
    beats the STAGE's entry, not the helper's thread. Only contextless
    telemetry falls back to thread attribution — and jit compilation
    still records nothing at all, so a stall bound must exceed the
    stage's longest legitimately silent window. A false stall costs one
    retry (ordinary Exception into the retry -> quarantine policy),
    never artifacts."""

    def __init__(self):
        self._lock = locks.TrackedLock("health.heartbeats")
        self._entries: Dict[int, HeartbeatEntry] = {}  # id(entry) keyed
        self._by_thread: Dict[int, HeartbeatEntry] = {}
        self._by_trace: Dict[str, HeartbeatEntry] = {}

    def start(self, label: str, *, thread_id: Optional[int] = None,
              deadline_s: Optional[float] = None,
              stall_s: Optional[float] = None,
              payload=None, obs: Optional[str] = None,
              stage: Optional[str] = None,
              trace_id: Optional[str] = None) -> HeartbeatEntry:
        tid = thread_id if thread_id is not None else threading.get_ident()
        entry = HeartbeatEntry(label, tid, deadline_s, stall_s, payload,
                               obs=obs, stage=stage, trace_id=trace_id)
        with self._lock:
            self._entries[id(entry)] = entry
            self._by_thread[tid] = entry
            if trace_id is not None:
                self._by_trace[trace_id] = entry
        return entry

    def beat(self, trace_id: Optional[str] = None) -> None:
        """The telemetry activity hook (one positional arg: the active
        trace context's id, or None). Trace attribution wins — a helper
        thread working under an adopted context beats the stage that
        owns the trace; contextless telemetry beats whatever entry this
        thread started."""
        entry = None
        if trace_id is not None:
            entry = self._by_trace.get(trace_id)
        if entry is None:
            entry = self._by_thread.get(threading.get_ident())
        if entry is not None:
            entry.last_beat = time.monotonic()

    def beat_thread(self, thread_id: Optional[int] = None) -> None:
        """Thread-attributed beat (the pre-round-21 hook shape); kept
        for direct callers that watch a specific worker thread."""
        tid = thread_id if thread_id is not None else threading.get_ident()
        entry = self._by_thread.get(tid)
        if entry is not None:
            entry.last_beat = time.monotonic()

    def finish(self, entry: HeartbeatEntry) -> None:
        with self._lock:
            self._entries.pop(id(entry), None)
            if self._by_thread.get(entry.thread_id) is entry:
                del self._by_thread[entry.thread_id]
            if entry.trace_id is not None \
                    and self._by_trace.get(entry.trace_id) is entry:
                del self._by_trace[entry.trace_id]

    def active(self) -> List[HeartbeatEntry]:
        with self._lock:
            return list(self._entries.values())

    def is_active(self, entry: HeartbeatEntry) -> bool:
        """True while ``entry`` has not been finished — the check a
        watchdog must make immediately before an async interrupt, so a
        stage that completed since :meth:`expired` is never shot at."""
        with self._lock:
            return id(entry) in self._entries

    def rearm(self, entry: HeartbeatEntry) -> None:
        """Put a fired entry back on the watchdog's radar — the
        deferred-interrupt retry path: :meth:`expired` marks an entry
        fired exactly once, so a verdict whose delivery was withheld
        (the target held a tracked lock) must be re-armed to be
        re-returned on the next poll tick."""
        with self._lock:
            if id(entry) in self._entries:
                entry.fired = False

    def expired(self, now: Optional[float] = None) \
            -> List[Tuple[HeartbeatEntry, str]]:
        """Entries past their deadline ('deadline') or heartbeat-silent
        past their stall bound ('stall'), each returned AT MOST ONCE
        (marked fired) — the watchdog must not re-interrupt a stage
        that is already unwinding."""
        now = time.monotonic() if now is None else now
        out: List[Tuple[HeartbeatEntry, str]] = []
        with self._lock:
            for entry in self._entries.values():
                if entry.fired:
                    continue
                if entry.deadline_s is not None \
                        and now - entry.started > entry.deadline_s:
                    entry.fired = True
                    out.append((entry, "deadline"))
                elif entry.stall_s is not None \
                        and now - entry.last_beat > entry.stall_s:
                    entry.fired = True
                    out.append((entry, "stall"))
        return out


class Watchdog:
    """Scheduler-side liveness poller: every ``interval`` seconds, hand
    each newly expired :class:`HeartbeatRegistry` entry to
    ``on_expire(entry, reason)`` (the scheduler's callback emits the
    telemetry verdict and interrupts the stage's worker thread). A
    daemon thread: a fleet that unwinds abruptly must not block on
    it."""

    def __init__(self, registry: HeartbeatRegistry,
                 on_expire: Callable[[HeartbeatEntry, str], None],
                 interval: float = 0.05):
        self.registry = registry
        self.interval = interval
        self._on_expire = on_expire
        self._stop = locks.TrackedEvent("health.watchdog_stop")
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="survey-watchdog",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            for entry, reason in self.registry.expired():
                try:
                    self._on_expire(entry, reason)
                except Exception:  # noqa: BLE001 - watchdog never dies
                    pass


# -- device health -----------------------------------------------------------


def is_device_fault(e: BaseException) -> bool:
    """True for a failure that indicts the DEVICE rather than the work:
    an injected device fault, or an XLA/runtime message that names a
    dead chip, a failed collective or a wedged transfer. Deliberately
    narrow — an OOM is accounted separately (``retry.is_oom_error``),
    and an ordinary pipeline exception must never cost a chip a
    strike."""
    from pypulsar_tpu.resilience import faultinject

    if isinstance(e, faultinject.InjectedDeviceFault):
        return True
    if not isinstance(e, Exception):
        return False
    msg = str(e)
    return any(pat in msg for pat in (
        "DEVICE_FAULT", "device failure", "failed to execute replicated",
        "collective operation", "NCCL", "slice_index",
        "failed to enqueue", "Device or resource busy"))


def must_propagate(e: BaseException) -> bool:
    """True for failures that in-pipeline degradation handlers (serial
    fallbacks, NumPy twins, skip-this-item loops) must RE-RAISE instead
    of absorbing:

    - a :class:`StageTimeout` — the watchdog already charged the
      verdict and the scheduler is reclaiming the lease; a handler that
      swallows the interrupt leaves a condemned stage running (and a
      per-item handler would silently drop the item's artifacts from a
      stage then recorded done);
    - a chip-indicting fault (:func:`is_device_fault`) — degrading
      in-place hides the strike from the device-health accounting and
      keeps dispatching to a chip that should be quarantined.

    Ordinary failures still degrade locally, exactly as before."""
    return isinstance(e, StageTimeout) or is_device_fault(e)


def no_degrade(e: BaseException) -> bool:
    """:func:`must_propagate` plus ANY injected fault: handlers whose
    degraded path is not byte-identical to the healthy one (a NumPy
    twin, a skip-this-item loop that drops artifacts) must re-raise
    these instead of degrading. An injected fault is retryable BY
    CONSTRUCTION (armed faults fire once; chaos re-rolls each hit), so
    escalating it to the stage-level retry recovers through the exact
    same bytes — which is precisely what the chaos harness asserts.
    Genuine environmental failures keep the degrade paths: approximate
    science still beats no science on a real broken night."""
    from pypulsar_tpu.resilience import faultinject

    return must_propagate(e) or isinstance(e, faultinject.InjectedFault)


class DeviceHealth:
    """Per-device strike accounting with quarantine past ``limit``
    strikes (``PYPULSAR_TPU_DEVICE_STRIKES``, default 3). Ids are the
    caller's device axis — the survey scheduler counts LEASE ids (the
    operator's ``--devices`` pool), ``parallel.mesh`` mirrors real jax
    device ids. Thread-safe; every strike/quarantine lands in telemetry
    as ``mesh.device_strike`` / ``mesh.device_quarantined`` events plus
    ``device{N}.strikes`` counters, so ``tlmsum``'s per-device roll-up
    shows chip health next to chip utilization."""

    def __init__(self, limit: Optional[int] = None):
        if limit is None:
            limit = int(env_float(ENV_DEVICE_STRIKES,
                                  DEFAULT_DEVICE_STRIKES))
        self.limit = max(1, int(limit))
        self._lock = locks.TrackedLock("health.devices")
        self._strikes: Dict[int, int] = {}
        self._quarantined: set = set()
        self._last_error: Dict[int, str] = {}

    def strike(self, dev_id: int, kind: str = "device", error: str = "",
               allow_quarantine: bool = True) -> bool:
        """Record one strike against ``dev_id``; returns True when this
        strike NEWLY quarantines the device. ``allow_quarantine=False``
        counts the strike but defers the verdict — how the scheduler
        protects the last healthy lease (an empty pool is a hung fleet,
        strictly worse than a flaky one)."""
        dev_id = int(dev_id)
        with self._lock:
            n = self._strikes.get(dev_id, 0) + 1
            self._strikes[dev_id] = n
            if error:
                self._last_error[dev_id] = error[:200]
            newly = (allow_quarantine and n >= self.limit
                     and dev_id not in self._quarantined)
            if newly:
                self._quarantined.add(dev_id)
        telemetry.counter(f"device{dev_id}.strikes")
        telemetry.event("mesh.device_strike", dev=dev_id, kind=kind,
                        strikes=n)
        if newly:
            telemetry.counter(f"device{dev_id}.quarantined")
            telemetry.event("mesh.device_quarantined", dev=dev_id,
                            strikes=n, kind=kind)
        return newly

    def is_quarantined(self, dev_id: int) -> bool:
        with self._lock:
            return int(dev_id) in self._quarantined

    def quarantined(self) -> set:
        with self._lock:
            return set(self._quarantined)

    def strikes(self, dev_id: int) -> int:
        with self._lock:
            return self._strikes.get(int(dev_id), 0)

    def snapshot(self) -> Dict[int, dict]:
        """Per-device view for ``survey --status`` / fleet-health JSON:
        ``{id: {strikes, quarantined, last_error}}``."""
        with self._lock:
            ids = set(self._strikes) | self._quarantined
            return {i: {"strikes": self._strikes.get(i, 0),
                        "quarantined": i in self._quarantined,
                        "last_error": self._last_error.get(i, "")}
                    for i in sorted(ids)}

    def reset(self) -> None:
        with self._lock:
            self._strikes.clear()
            self._quarantined.clear()
            self._last_error.clear()


class HostHealth:
    """Host-level strike accounting for the multi-host survey fleet
    (round 18) — the :class:`DeviceHealth` idea one level up. Ids are
    host-lease strings; strikes are charged when a host's death is
    OBSERVED (an adoption: its heartbeat went silent with observations
    in flight) or when a host CEDES its own observation to a higher
    fencing token (it was stalled long enough to be presumed dead —
    flappy, even if alive). Past ``PYPULSAR_TPU_HOST_STRIKES`` (default
    3) the host is quarantined: the claim loop stops it taking NEW
    observations, and the verdict renders next to device health in the
    fleet-health JSON and ``survey --status``. Unlike a device, a
    quarantined host is never 'evicted' — it simply drains its in-flight
    work and idles; the fencing tokens already make its stale writes
    harmless."""

    ENV_HOST_STRIKES = "PYPULSAR_TPU_HOST_STRIKES"

    def __init__(self, limit: Optional[int] = None):
        if limit is None:
            limit = int(env_float(self.ENV_HOST_STRIKES, 3))
        self.limit = max(1, int(limit))
        self._lock = locks.TrackedLock("health.hosts")
        self._strikes: Dict[str, int] = {}
        self._quarantined: set = set()
        self._last_error: Dict[str, str] = {}

    def strike(self, host: str, kind: str = "adopted",
               error: str = "") -> bool:
        """One strike against ``host``; True when this strike NEWLY
        quarantines it."""
        host = str(host)
        with self._lock:
            n = self._strikes.get(host, 0) + 1
            self._strikes[host] = n
            if error:
                self._last_error[host] = error[:200]
            newly = n >= self.limit and host not in self._quarantined
            if newly:
                self._quarantined.add(host)
        telemetry.event("survey.host_strike", host=host, kind=kind,
                        strikes=n)
        if newly:
            telemetry.event("survey.host_quarantined", host=host,
                            strikes=n, kind=kind)
        return newly

    def is_quarantined(self, host: str) -> bool:
        with self._lock:
            return str(host) in self._quarantined

    def strikes(self, host: str) -> int:
        with self._lock:
            return self._strikes.get(str(host), 0)

    def snapshot(self) -> Dict[str, dict]:
        """Per-host view for the fleet-health JSON / ``--status``."""
        with self._lock:
            ids = set(self._strikes) | self._quarantined
            return {h: {"strikes": self._strikes.get(h, 0),
                        "quarantined": h in self._quarantined,
                        "last_error": self._last_error.get(h, "")}
                    for h in sorted(ids)}


# -- resource admission ------------------------------------------------------


class ResourceGuard:
    """The scheduler's admission gate: ``admit()`` returns None when new
    work may launch, else a short reason string. Checks, in order:

    - free disk under ``path`` >= ``min_free_bytes``
      (``PYPULSAR_TPU_MIN_FREE_MB``, default 32 MB; 0 disables) — the
      preflight that turns a mid-write ENOSPC crash into a pause;
    - no live ``*.pending_depth`` gauge above ``max_pending`` (when
      set) — the ship-ahead depth gauges the prefetch pipelines
      already publish double as the backpressure signal: a consumer
      that stopped draining means admitting more observations only
      deepens the pile.

    The gate pauses *scheduling*; stages already running always
    continue (they are what frees the resource).

    Admission is *hysteretic* (round 23): once paused, the gate demands
    a ``resume_margin`` of slack past the threshold before admitting
    again (free disk >= floor * (1 + margin), pending depth <= bound /
    (1 + margin); ``PYPULSAR_TPU_ADMIT_RESUME_MARGIN``, default 0.25).
    A gauge hovering exactly at the threshold therefore produces ONE
    paused/resumed episode, not one pair per oscillation — the
    flapping the scheduler's per-episode events would otherwise
    faithfully amplify into the trace."""

    def __init__(self, path: str,
                 min_free_bytes: Optional[float] = None,
                 max_pending: Optional[float] = None,
                 resume_margin: Optional[float] = None):
        if min_free_bytes is None:
            mb = env_float(ENV_MIN_FREE_MB, DEFAULT_MIN_FREE_MB)
            min_free_bytes = (mb or 0.0) * 1e6
        if resume_margin is None:
            resume_margin = env_float(ENV_ADMIT_RESUME_MARGIN,
                                      DEFAULT_ADMIT_RESUME_MARGIN)
        self.path = path
        self.min_free_bytes = float(min_free_bytes)
        self.max_pending = max_pending
        self.resume_margin = max(0.0, float(resume_margin or 0.0))
        # the hysteresis latch; quiet — the guard is consulted on the
        # scheduler's launch path and must not emit about itself
        self._lock = locks.TrackedLock("health.guard", quiet=True)
        self._paused = False

    def free_bytes(self) -> Optional[float]:
        try:
            return float(shutil.disk_usage(self.path).free)
        except OSError:
            return None  # an unstatable root is not a reason to pause

    def _check(self, paused: bool) -> Optional[str]:
        """One stateless evaluation at the thresholds the latch state
        selects: strict (margin-widened) while paused, base otherwise."""
        widen = 1.0 + (self.resume_margin if paused else 0.0)
        if self.min_free_bytes > 0:
            floor = self.min_free_bytes * widen
            free = self.free_bytes()
            if free is not None and free < floor:
                return (f"low disk: {free / 1e6:.0f} MB free under "
                        f"{self.path!r} < {floor / 1e6:.0f}"
                        f" MB floor"
                        + (" (resume margin)" if paused else ""))
        if self.max_pending is not None:
            bound = self.max_pending / widen
            s = telemetry.current()
            if s is not None:
                for name, g in s.gauge_values().items():
                    if name.endswith(".pending_depth") \
                            and g.get("last", 0) > bound:
                        return (f"backpressure: {name} = "
                                f"{g.get('last', 0):.0f} > "
                                f"{bound:.0f}"
                                + (" (resume margin)" if paused else ""))
        return None

    def admit(self) -> Optional[str]:
        with self._lock:
            paused = self._paused
        reason = self._check(paused)
        with self._lock:
            self._paused = reason is not None
        return reason
