"""Survey orchestrator: run the full search chain over fleets of
observations.

PRs 1-4 built the per-observation pieces (telemetry, the streamed
sweep->accel handoff, journaled resume + fault injection, batched
folding); this package composes them into one fleet-level runtime:

- :mod:`.dag` — the per-observation stage DAG (rfifind-mask ->
  ``sweep --accel-search`` -> sift -> foldbatch -> pfd_snr), each stage
  declaring its inputs/outputs and running the SAME in-process CLI entry
  point the serial chain uses (artifacts stay byte-identical);
- :mod:`.scheduler` — the fleet scheduler: device-bound stages take an
  exclusive device lease (priority + FIFO), host-bound stages run on a
  bounded worker pool so observation B's prep/post overlaps observation
  A's device time;
- :mod:`.state` — fingerprinted per-observation manifests
  (``resilience.journal`` underneath): kill -9 mid-fleet and
  ``survey --resume`` replans, skips validated stages, and re-runs only
  torn ones; persistent per-stage failure quarantines the observation
  instead of aborting the fleet;
- :mod:`.fleet` — the multi-host coordination plane (round 18):
  fsync'd heartbeat-renewed host leases with monotonic fencing tokens
  in a shared directory, atomic observation claims, orphan adoption
  when a host goes silent, and stale-token write rejection so a dead
  host's late writes are no-ops. ``survey --hosts M`` runs one survey
  across M host processes on it.

Surfaced as ``python -m pypulsar_tpu.cli survey`` (cli/survey.py).
"""

from pypulsar_tpu.survey.dag import StageExit, SurveyConfig, build_dag
from pypulsar_tpu.survey.fleet import FleetPlane, StaleLeaseError
from pypulsar_tpu.survey.scheduler import FleetResult, FleetScheduler
from pypulsar_tpu.survey.state import Observation, ObsManifest

__all__ = [
    "FleetPlane",
    "FleetResult",
    "FleetScheduler",
    "Observation",
    "ObsManifest",
    "StageExit",
    "StaleLeaseError",
    "SurveyConfig",
    "build_dag",
]
