"""Multi-host coordination plane: host leases, fencing tokens, adoption.

One survey can now span M host *processes* (one per machine, or M on one
machine for testing) with no coordinator service at all: the shared
artifact directory IS the control plane, exactly the way real-time
transient surveys run always-on multi-node pipelines that must tolerate
node loss without losing observations (PAPERS.md 1601.01165). Everything
here is plain fsync'd files under ``<outdir>/_fleet/``, written with the
PR 3 atomic idiom (tmp + ``os.replace``), so the plane inherits the same
kill-anywhere guarantees as the artifacts it coordinates::

    <outdir>/_fleet/
      hosts/<host>.json   heartbeat-renewed HOST LEASE (atomic replace +
                          fsync): {host, pid, token, started, beat}.  A
                          host whose beat goes silent past
                          PYPULSAR_TPU_HOST_LEASE_S is DEAD; a clean
                          shutdown marks it LEFT.
      tok/<NNNNNNNNNN>    fencing-token allocations: empty files created
                          O_CREAT|O_EXCL, so the namespace itself is the
                          monotonic counter — whoever creates N owns
                          token N, and no two claims can ever share one.
      claims/<obs>.json   observation CLAIM: {obs, host, token, state}.
                          Written atomically; ownership is decided by
                          the token *in the file*, never by who wrote
                          last into a log.

**Fencing.** Every claim (initial or adoption) allocates a FRESH token,
strictly greater than every token ever issued. The owner stamps its
token into every manifest append and re-reads the claim file immediately
before each append (:meth:`FleetPlane.fence`): if the claim now carries
a higher token — a survivor adopted the observation while this host was
stalled, partitioned, or presumed dead — the append raises
:class:`StaleLeaseError` instead of writing. A dead host's late
*manifest* writes are therefore no-ops by construction: it cannot hold
the highest token, because adoption always allocates a newer one.
Artifact files are covered by three complementary layers rather than a
per-write fence: (1) the zombie's own claim loop detects the lost claim
within one poll tick and async-interrupts the running stage with
``StaleLeaseError`` (the same channel the watchdog uses), (2) stages
are deterministic, so writes that DO land in the residual window carry
the same bytes the adopter writes, and (3) the manifest records
size+sha256 digested at ``done`` time — an artifact torn by a truly
simultaneous same-tmp write fails validation and is redone, never
trusted.

**Adoption.** Survivors watch the host leases; an observation whose
claim is held by a dead (or cleanly-left) host is an *orphan*, and any
live host may adopt it: allocate a new token, replace the claim, settle
(``PYPULSAR_TPU_HOST_SETTLE_S``), re-read, and proceed only if still the
holder. Two racing adopters thus resolve to ONE winner: ``os.replace``
leaves exactly one claim in the file, the settle re-read catches the
common race, and the per-append fence catches the rest — the loser's
first manifest append raises and it cedes. The adopted observation then
resumes from its journal/manifest exactly as a single-host ``--resume``
does: validated stages skip, torn ones redo, bytes identical.

**Faults.** The plane's own steps are instrumented fault points
(``fleet.token`` / ``fleet.claim`` / ``fleet.heartbeat`` /
``fleet.fence``) so the ``netstall`` kind can stall the coordination
plane deterministically — a heartbeat renewer parked in a netstall past
the lease bound makes THIS host adoptable while it still runs, which is
precisely the split-brain scenario the fencing tokens exist for.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.resilience.locks import TrackedEvent
from pypulsar_tpu.tune import knobs

__all__ = [
    "FleetPlane",
    "StaleLeaseError",
    "default_host_id",
    "plane_dir",
    "read_plane_status",
]

# heartbeat-silence bound (seconds) past which a host lease is DEAD and
# its in-flight observations become adoptable
ENV_HOST_LEASE_S = "PYPULSAR_TPU_HOST_LEASE_S"
# renewal cadence; 0/unset = lease_s / 4
ENV_HOST_HEARTBEAT_S = "PYPULSAR_TPU_HOST_HEARTBEAT_S"
# claim settle window: write -> re-read delay that resolves the common
# double-adoption race before any stage work starts
ENV_HOST_SETTLE_S = "PYPULSAR_TPU_HOST_SETTLE_S"
# host identity override (the --hosts launcher sets per-child ids)
ENV_HOST_ID = "PYPULSAR_TPU_HOST_ID"

PLANE_DIR = "_fleet"


class StaleLeaseError(RuntimeError):
    """This host's claim on an observation was superseded by a higher
    fencing token (a survivor adopted it): the write that consulted the
    fence must NOT happen, and the local scheduler cedes the
    observation instead of retrying or quarantining it — the new owner
    is already running it."""


def plane_dir(outdir: str) -> str:
    return os.path.join(outdir, PLANE_DIR)


def default_host_id() -> str:
    """This process's host identity: the explicit override, else the
    launcher's rank (``host<rank>`` whenever a multi-process grid is
    declared), else hostname+pid — unique per process, stable within
    one process lifetime."""
    hid = knobs.env_str(ENV_HOST_ID)
    if hid:
        return str(hid)
    from pypulsar_tpu.parallel import distributed

    if distributed.local_count() > 1:
        return f"host{distributed.local_rank()}"
    return f"{socket.gethostname()}-{os.getpid()}"


def _atomic_write_json(path: str, payload: dict, tag: str) -> None:
    """tmp + os.replace with an owner-unique tmp name (two hosts writing
    the same target must never interleave inside one shared tmp), fsync
    before the rename so the record survives the next power cut."""
    tmp = f"{path}.{tag}.tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(payload, sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    """A record written by :func:`_atomic_write_json`, or None (missing
    or torn — torn means not ours, the writer is atomic)."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


class FleetPlane:
    """One host's handle on the shared coordination plane (see module
    docstring). Construct with the fleet's artifact ``outdir``; call
    :meth:`register` before claiming and :meth:`close` on the way out."""

    def __init__(self, outdir: str, host_id: Optional[str] = None,
                 lease_s: Optional[float] = None,
                 heartbeat_s: Optional[float] = None,
                 settle_s: Optional[float] = None):
        self.root = plane_dir(outdir)
        self.host_id = host_id or default_host_id()
        if "/" in self.host_id or self.host_id in (".", ".."):
            raise ValueError(f"host id {self.host_id!r} must be a plain "
                             f"filename component")
        self.lease_s = float(lease_s if lease_s is not None
                             else knobs.env_float(ENV_HOST_LEASE_S))
        hb = (heartbeat_s if heartbeat_s is not None
              else knobs.env_float(ENV_HOST_HEARTBEAT_S))
        self.heartbeat_s = float(hb) if hb else max(self.lease_s / 4.0,
                                                    0.05)
        self.settle_s = float(settle_s if settle_s is not None
                              else knobs.env_float(ENV_HOST_SETTLE_S))
        self._hosts_dir = os.path.join(self.root, "hosts")
        self._tok_dir = os.path.join(self.root, "tok")
        self._claims_dir = os.path.join(self.root, "claims")
        for d in (self._hosts_dir, self._tok_dir, self._claims_dir):
            os.makedirs(d, exist_ok=True)
        self.token: Optional[int] = None  # the HOST lease's token
        self._renew: Optional[threading.Thread] = None
        self._stop = TrackedEvent("fleet.renew_stop")

    # -- fencing tokens ------------------------------------------------------

    # tokens older than this may be compacted away: deletion is only
    # safe when no allocator can still be probing that low — a live
    # allocation's scan-to-create window is milliseconds, and the hint
    # file keeps fresh allocators probing at the top, so an hour is a
    # deep safety margin (NEVER compact by count: deleting a recent
    # token lets a stale-scanned racer re-create — re-ISSUE — it, the
    # exact duplicate the monotonicity stress test guards against)
    TOKEN_COMPACT_AGE_S = 3600.0
    _HINT = ".hi"

    def _token_hint(self) -> Optional[int]:
        try:
            with open(os.path.join(self._tok_dir, self._HINT)) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return None

    def next_token(self) -> int:
        """Allocate the next fencing token: strictly greater than every
        token ever issued under this plane. O_CREAT|O_EXCL on the
        zero-padded token file makes the allocation atomic — two racing
        allocators get two distinct integers, never one. A best-effort
        hint file makes the common allocation O(1) (probe up from the
        hint instead of listing the directory), and age-based
        compaction keeps ``tok/`` bounded on an always-on survey: only
        entries old enough that no in-flight probe can reach them are
        removed, so a token can never be re-issued."""
        faultinject.trip("fleet.token")
        hint = self._token_hint()
        if hint is None:
            try:
                hint = max((int(x) for x in os.listdir(self._tok_dir)
                            if x.isdigit()), default=0)
            except OSError:
                hint = 0
        n = max(hint, getattr(self, "_last_token", 0))
        while True:
            n += 1
            try:
                fd = os.open(os.path.join(self._tok_dir, f"{n:010d}"),
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                continue  # taken: probe one higher
            os.close(fd)
            break
        self._last_token = n
        # best-effort hint + compaction; failures cost speed, never
        # correctness (the probe loop works from any starting point)
        try:
            hint_tmp = os.path.join(self._tok_dir,
                                    f"{self._HINT}.{self.host_id}.tmp")
            with open(hint_tmp, "w") as f:
                f.write(str(n))
            os.replace(hint_tmp,
                       os.path.join(self._tok_dir, self._HINT))
            cutoff = time.time() - self.TOKEN_COMPACT_AGE_S
            for name in os.listdir(self._tok_dir):
                if not name.isdigit() or int(name) >= n:
                    continue
                path = os.path.join(self._tok_dir, name)
                if os.stat(path).st_mtime < cutoff:
                    os.remove(path)
        except OSError:
            pass
        return n

    # -- the shared clock ----------------------------------------------------

    def _fs_now(self) -> float:
        """The shared FILESYSTEM's idea of now. Host liveness must not
        compare one machine's wall clock against another's ``beat``
        timestamp (README ships one-process-per-machine fleets; 12 s of
        NTP drift would falsely kill — or immortalize — a host): the
        one clock every fleet member shares is the filesystem's, so age
        is measured mtime-against-mtime. Touch a per-host probe and
        read its mtime; local time is only the no-plane-IO fallback."""
        probe = os.path.join(self.root, f".now.{self.host_id}")
        try:
            with open(probe, "w"):
                pass
            return os.stat(probe).st_mtime
        except OSError:
            return time.time()

    # -- host leases ---------------------------------------------------------

    def _host_path(self, host: Optional[str] = None) -> str:
        return os.path.join(self._hosts_dir, f"{host or self.host_id}.json")

    def register(self) -> int:
        """Join the fleet: allocate this host's fencing token, write the
        lease, start the renewal thread. Returns the host token."""
        self.token = self.next_token()
        self.heartbeat()
        telemetry.event("survey.host_registered", host=self.host_id,
                        token=self.token, lease_s=self.lease_s)
        self._stop.clear()
        self._renew = threading.Thread(target=self._renew_loop,
                                       name=f"fleet-heartbeat-"
                                            f"{self.host_id}",
                                       daemon=True)
        self._renew.start()
        return self.token

    def heartbeat(self, left: bool = False) -> None:
        """Renew (or, with ``left``, retire) this host's lease. The
        ``fleet.heartbeat`` fault point sits BEFORE the write: a
        netstall here is a host that is alive but silent — the exact
        failure adoption + fencing must survive."""
        faultinject.trip("fleet.heartbeat")
        rec = {"host": self.host_id, "pid": os.getpid(),
               "token": self.token, "beat": time.time(),
               "lease_s": self.lease_s}
        if left:
            rec["left"] = True
        _atomic_write_json(self._host_path(), rec, self.host_id)

    def _renew_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self.heartbeat()
            except Exception:  # noqa: BLE001 - a failed renewal must not
                # kill the renewer: one missed beat is recoverable, a
                # dead renew thread silently forfeits the lease (the
                # next iteration retries; persistent failure = the host
                # goes dead, which adoption handles)
                pass

    def close(self) -> None:
        """Clean shutdown: stop renewing and mark the lease LEFT so
        other hosts read an exit, not a death (status renders the
        difference; orphan adoption treats both as adoptable)."""
        self._stop.set()
        if self._renew is not None:
            self._renew.join(timeout=5.0)
            self._renew = None
        try:
            self.heartbeat(left=True)
        except OSError:
            pass  # an unwritable plane at exit changes nothing

    def hosts(self) -> Dict[str, dict]:
        """Every registered host's last lease record, keyed by id. Each
        record is stamped with the lease FILE's mtime (``_mtime``) —
        the liveness clock (see :meth:`_fs_now`)."""
        out: Dict[str, dict] = {}
        try:
            names = sorted(os.listdir(self._hosts_dir))
        except OSError:
            return out
        for fn in names:
            if not fn.endswith(".json"):
                continue
            path = os.path.join(self._hosts_dir, fn)
            rec = _read_json(path)
            if rec and rec.get("host"):
                try:
                    rec["_mtime"] = os.stat(path).st_mtime
                except OSError:
                    pass  # replaced between read and stat: beat stands in
                out[str(rec["host"])] = rec
        return out

    def is_live(self, rec: Optional[dict],
                now: Optional[float] = None) -> bool:
        """A host is live while its lease renews within ITS declared
        bound (each record carries lease_s: hosts may join with
        different bounds) and it has not retired the lease. Age is the
        lease file's mtime against the filesystem's now — never one
        machine's wall clock against another's (cross-machine skew
        bigger than the lease bound would otherwise falsely kill, or
        immortalize, a live host)."""
        if not rec or rec.get("left"):
            return False
        now = self._fs_now() if now is None else now
        bound = float(rec.get("lease_s") or self.lease_s)
        beat = float(rec.get("_mtime", rec.get("beat", 0.0)))
        return (now - beat) <= bound

    def live_hosts(self) -> List[str]:
        return sorted(h for h, rec in self.hosts().items()
                      if self.is_live(rec))

    # -- observation claims --------------------------------------------------

    def _claim_path(self, obs: str) -> str:
        return os.path.join(self._claims_dir, f"{obs}.json")

    def read_claim(self, obs: str) -> Optional[dict]:
        return _read_json(self._claim_path(obs))

    def claims(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        try:
            names = sorted(os.listdir(self._claims_dir))
        except OSError:
            return out
        for fn in names:
            if fn.endswith(".json"):
                rec = _read_json(os.path.join(self._claims_dir, fn))
                if rec and rec.get("obs"):
                    out[str(rec["obs"])] = rec
        return out

    def claim(self, obs: str,
              allow_terminal: bool = False) -> Optional[int]:
        """Try to take (or adopt) ``obs``; returns the fencing token on
        success, None when the observation is someone else's (live
        holder), already terminal, or lost to a racing claimant.
        ``allow_terminal`` re-opens a done/quarantined claim — the
        caller's reconfigured-rerun path, which has verified the
        terminal verdict belongs to a DIFFERENT run configuration.

        An adoption — the previous claim's holder is dead or left while
        the observation was still running — records where the work came
        from and emits the ``survey.obs_adopted`` event the traces and
        the host-strike accounting key on."""
        cur = self.read_claim(obs)
        adopted_from = None
        if cur is not None:
            state = cur.get("state", "running")
            if state in ("done", "quarantined") and not allow_terminal:
                return None  # terminal: nothing to run
            holder = str(cur.get("host", ""))
            if holder == self.host_id:
                # our own live claim (a resumed host process re-claims
                # with a FRESH token — the old one may be stale)
                pass
            elif state == "running" \
                    and self.is_live(self.hosts().get(holder)):
                return None  # a live host owns it
            adopted_from = (holder if holder != self.host_id
                            and state == "running" else None)
        token = self.next_token()
        faultinject.trip("fleet.claim")
        # re-read immediately before the replace: a racing adopter that
        # allocated a HIGHER token and already wrote must not be
        # regressed by our slower, lower-token write (the claim file's
        # token may only go up — the invariant fencing rests on)
        cur2 = self.read_claim(obs)
        if cur2 is not None and int(cur2.get("token") or 0) > token:
            telemetry.event("survey.claim_lost", host=self.host_id,
                            obs=obs, token=token,
                            current_token=cur2.get("token"))
            return None
        rec = {"obs": obs, "host": self.host_id, "token": token,
               "state": "running", "t": time.time()}
        if adopted_from:
            rec["adopted_from"] = adopted_from
        _atomic_write_json(self._claim_path(obs), rec, self.host_id)
        if self.settle_s > 0:
            # settle: let a racing claimant's replace land, then check
            # who actually holds the file — the fast path that resolves
            # double adoption before any stage work starts (the
            # per-append fence is the backstop for the residual race)
            time.sleep(self.settle_s)
        after = self.read_claim(obs)
        if not after or after.get("token") != token:
            telemetry.event("survey.claim_lost", host=self.host_id,
                            obs=obs, token=token)
            return None
        if adopted_from:
            telemetry.counter("survey.adoptions")
            telemetry.event("survey.obs_adopted", host=self.host_id,
                            obs=obs, token=token,
                            adopted_from=adopted_from)
        return token

    def fence(self, obs: str, token: int) -> None:
        """Raise :class:`StaleLeaseError` unless ``token`` still holds
        the claim on ``obs`` — the check every manifest append makes
        immediately before writing. A dead host waking from a stall
        fails here on its FIRST write, before it can tear anything."""
        faultinject.trip("fleet.fence")
        cur = self.read_claim(obs)
        if cur is None or cur.get("token") != token:
            held = cur.get("token") if cur else None
            holder = cur.get("host") if cur else None
            telemetry.counter("survey.stale_writes_rejected")
            telemetry.event("survey.stale_write_rejected",
                            host=self.host_id, obs=obs, token=token,
                            current_token=held, current_host=holder)
            raise StaleLeaseError(
                f"host {self.host_id!r} token {token} no longer holds "
                f"{obs!r} (claim now {holder!r} token {held}): write "
                f"rejected, observation ceded to the adopter")

    def mark_terminal(self, obs: str, token: int,
                      state: str = "done",
                      trace_id: Optional[str] = None) -> None:
        """Record ``obs`` terminal (``done`` / ``quarantined``) under a
        still-held claim — fenced, so only the real owner can close an
        observation out. ``trace_id`` (round 21) links the terminal
        claim record to the observation's causal trace, so ``--status``
        and the stitched timeline agree on WHICH story ended here."""
        self.fence(obs, token)
        cur = self.read_claim(obs) or {}
        cur.update({"obs": obs, "host": self.host_id, "token": token,
                    "state": state, "t": time.time()})
        if trace_id is not None:
            cur["trace_id"] = trace_id
        _atomic_write_json(self._claim_path(obs), cur, self.host_id)
        telemetry.event("survey.claim_terminal", host=self.host_id,
                        obs=obs, state=state, trace_id=trace_id)


def read_plane_status(outdir: str) -> Optional[dict]:
    """Read-only plane view for ``survey --status`` (works without
    registering a host): ``{"hosts": {...}, "claims": {...}}``, or None
    when the fleet never ran multi-host."""
    root = plane_dir(outdir)
    if not os.path.isdir(root):
        return None
    # a throwaway un-registered handle: pure reader, writes nothing
    plane = FleetPlane.__new__(FleetPlane)
    plane.root = root
    plane.host_id = "?"
    plane.lease_s = float(knobs.env_float(ENV_HOST_LEASE_S))
    plane._hosts_dir = os.path.join(root, "hosts")
    plane._tok_dir = os.path.join(root, "tok")
    plane._claims_dir = os.path.join(root, "claims")
    hosts = plane.hosts()
    now = time.time()
    for rec in hosts.values():
        rec["live"] = plane.is_live(rec, now)
        rec["beat_age_s"] = round(now - float(rec.get("beat", 0.0)), 1)
    return {"hosts": hosts, "claims": plane.claims(),
            "lease_s": plane.lease_s}
