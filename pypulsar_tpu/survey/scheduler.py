"""Fleet scheduler: device leases + a bounded host pool over the obs DAG.

Survey-scale pipelines are throughput systems (arXiv:1601.01165 frames
dedispersion surveys exactly this way): the accelerator must stay
saturated while host-side IO, prep and post-processing for OTHER beams
proceed concurrently. The serial per-tool chain leaves the device idle
during every sift and pfd_snr; this scheduler runs the per-observation
stage DAG (:mod:`.dag`) over the whole fleet with two execution lanes:

- **device lane** — ``device_bound`` stages queue for one of N exclusive
  device leases (default 1: one device-bound stage at a time per
  device). The queue is priority + FIFO: deeper stages first (drain
  observations toward completion, bounding in-flight intermediate
  artifacts), submission order breaking ties.
- **host lane** — host-bound stages (sift, pfd_snr summaries) run on a
  bounded worker pool (``max_host_workers``), overlapping the device
  lane.

Failure policy: a stage that raises an ordinary Exception (including a
nonzero CLI exit, an injected IO fault, an OOM that escaped the in-stage
halving) retries up to ``retries`` times with bounded exponential
backoff; past that the OBSERVATION is quarantined — recorded in its
manifest, its remaining stages cancelled, the fleet continues — instead
of aborting the run. A BaseException (``faultinject.InjectedKill``,
KeyboardInterrupt) unwinds the whole fleet like a signal: nothing is
marked done that did not finish, and a ``--resume`` replans from the
manifests.

Fault points (``--fault-inject`` / PYPULSAR_TPU_FAULTS), armed at stage
boundaries: ``survey.stage_start`` / ``survey.stage_done`` (any stage,
Nth hit) and the per-stage ``survey.stage_start.<name>`` /
``survey.stage_done.<name>``. ``stage_done`` trips AFTER the artifacts
are written but BEFORE the manifest records them — the torn-stage window
a resume must redo.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.survey.dag import StageSpec, SurveyConfig, build_dag, stage_names
from pypulsar_tpu.survey.state import (
    Observation,
    ObsManifest,
    ObsTrace,
    fleet_fingerprint,
)

__all__ = ["FleetResult", "FleetScheduler"]

# bounded backoff between retries of a failed stage (base * 2^attempt,
# capped): the delay runs on a timer thread, NOT the lane worker, so a
# backing-off observation never stalls the device lease or a host slot
RETRY_BACKOFF_BASE_S = 0.25
RETRY_BACKOFF_MAX_S = 5.0

_PENDING, _QUEUED, _RUNNING, _DONE, _QUARANTINED = range(5)


@dataclass
class FleetResult:
    """What one scheduler run did: ``ran`` (executed this run, in
    completion order), ``skipped`` (validated complete from the
    manifests — the resume contract's receipt), ``quarantined``
    (obs -> failing stage + error), ``retried`` stage-retry count."""

    ran: List[Tuple[str, str]] = field(default_factory=list)
    skipped: List[Tuple[str, str]] = field(default_factory=list)
    quarantined: Dict[str, Dict[str, str]] = field(default_factory=dict)
    retried: int = 0
    wall: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.quarantined


class _Task:
    __slots__ = ("obs_i", "stage", "state", "attempts", "seq")

    def __init__(self, obs_i: int, stage: StageSpec):
        self.obs_i = obs_i
        self.stage = stage
        self.state = _PENDING
        self.attempts = 0
        self.seq = -1


class FleetScheduler:
    """See module docstring. ``stages`` defaults to the standard five-
    stage DAG (:func:`build_dag`); tests inject synthetic DAGs."""

    def __init__(self, observations: Sequence[Observation],
                 cfg: Optional[SurveyConfig] = None, *,
                 stages: Optional[Sequence[StageSpec]] = None,
                 max_host_workers: int = 2, devices: int = 1,
                 retries: int = 1, resume: bool = False,
                 telemetry_dir: Optional[str] = None,
                 verbose: bool = False):
        self.cfg = cfg if cfg is not None else SurveyConfig()
        self.stages = list(stages) if stages is not None \
            else build_dag(self.cfg)
        self._by_name = {s.name: s for s in self.stages}
        self._depth = {s.name: i for i, s in enumerate(self.stages)}
        for s in self.stages:
            for d in s.deps:
                if d not in self._by_name:
                    raise ValueError(f"stage {s.name!r} depends on "
                                     f"unknown stage {d!r}")
        self.obs = list(observations)
        names = [o.name for o in self.obs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate observation names: {names}")
        self.max_host_workers = max(1, int(max_host_workers))
        self.devices = max(1, int(devices))
        self.retries = max(0, int(retries))
        self.resume = resume
        self.telemetry_dir = telemetry_dir
        self.verbose = verbose

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._device_q: "queue.PriorityQueue" = queue.PriorityQueue()
        self._host_q: "queue.PriorityQueue" = queue.PriorityQueue()
        self._seq = 0
        self._stop = False
        self._fatal: Optional[BaseException] = None
        self._tasks: Dict[Tuple[int, str], _Task] = {
            (i, s.name): _Task(i, s)
            for i in range(len(self.obs)) for s in self.stages}
        self.result = FleetResult()
        self._manifests: List[ObsManifest] = []
        self._traces: List[Optional[ObsTrace]] = []
        self._t0 = 0.0

    # -- manifests ----------------------------------------------------------

    def _clean_stale_outputs(self, obs: Observation) -> None:
        """Scrub every artifact the stages would enumerate for this
        observation (plus the sweep's chain journal). Runs only when the
        manifest is FRESH — a reconfigured rerun into the same outdir
        must not let the previous grid's files leak into the glob-driven
        stage inputs/outputs (sift would cluster old-grid .cand trails,
        snr would summarize orphaned archives), which would diverge from
        a clean-dir serial chain."""
        stale = [f"{obs.outbase}.chain.jsonl"]
        for s in self.stages:
            stale += s.outputs(obs, self.cfg)
        for path in stale:
            try:
                os.remove(path)
            except OSError:
                pass

    def _open_manifests(self) -> None:
        snames = stage_names(self.stages)
        for obs in self.obs:
            if not self.resume and os.path.exists(obs.manifest):
                # a fresh (non-resume) fleet starts from scratch — the
                # same contract as `sweep --checkpoint` without --resume
                os.remove(obs.manifest)
            m = ObsManifest(obs.manifest,
                            fleet_fingerprint(obs, self.cfg, snames))
            if m.fresh:
                # new manifest OR a restart after changed params/input:
                # nothing will be skipped, so nothing stale may linger
                self._clean_stale_outputs(obs)
            m.plan(obs, snames)
            self._manifests.append(m)
            trace = None
            if self.telemetry_dir:
                trace = ObsTrace(
                    os.path.join(self.telemetry_dir, f"{obs.name}.jsonl"),
                    obs.name, append=self.resume)
            self._traces.append(trace)

    # -- scheduling core ----------------------------------------------------

    def _enqueue_locked(self, task: _Task) -> None:
        task.state = _QUEUED
        self._seq += 1
        task.seq = self._seq
        # deeper stages first (finish observations, free their
        # intermediates), FIFO within a depth
        entry = (-self._depth[task.stage.name], task.seq, task)
        (self._device_q if task.stage.device_bound
         else self._host_q).put(entry)

    def _promote_locked(self, obs_i: int) -> None:
        for s in self.stages:
            task = self._tasks[(obs_i, s.name)]
            if task.state != _PENDING:
                continue
            if all(self._tasks[(obs_i, d)].state == _DONE for d in s.deps):
                self._enqueue_locked(task)

    def _finished_locked(self) -> bool:
        return all(t.state in (_DONE, _QUARANTINED)
                   for t in self._tasks.values())

    # -- execution ----------------------------------------------------------

    def _execute(self, task: _Task) -> None:
        obs = self.obs[task.obs_i]
        stage = task.stage
        faultinject.trip("survey.stage_start")
        faultinject.trip(f"survey.stage_start.{stage.name}")
        telemetry.counter("survey.stages_run")
        t_rel = time.perf_counter() - self._t0
        t0 = time.perf_counter()
        with telemetry.span(f"survey.stage.{stage.name}", obs=obs.name):
            stage.execute(obs, self.cfg)
        dur = time.perf_counter() - t0
        faultinject.trip("survey.stage_done")
        faultinject.trip(f"survey.stage_done.{stage.name}")
        outputs = stage.outputs(obs, self.cfg)
        self._manifests[task.obs_i].mark_done(stage.name, outputs)
        trace = self._traces[task.obs_i]
        if trace is not None:
            trace.span(f"survey.stage.{stage.name}", t_rel, dur,
                       outputs=len(outputs))
        if self.verbose:
            print(f"# survey: {obs.name}: {stage.name} done "
                  f"({dur:.2f}s, {len(outputs)} artifacts)")
        with self._cv:
            task.state = _DONE
            self.result.ran.append((obs.name, stage.name))
            self._promote_locked(task.obs_i)
            if self._finished_locked():
                self._stop = True
            self._cv.notify_all()

    def _requeue_retry(self, task: _Task) -> None:
        """Timer callback re-enqueuing a backing-off task — unless its
        observation was quarantined (or the fleet stopped) while it
        waited: a retry must not resurrect a cancelled stage."""
        with self._cv:
            if not self._stop and task.state != _QUARANTINED:
                self._enqueue_locked(task)
                self._cv.notify_all()

    def _handle_failure(self, task: _Task, err: Exception) -> None:
        obs = self.obs[task.obs_i]
        stage = task.stage
        with self._lock:
            if task.state == _QUARANTINED:
                # another stage of this observation quarantined it while
                # this one was running: its failure is already verdict
                return
        telemetry.counter("survey.stage_failures")
        telemetry.event("survey.stage_failed", obs=obs.name,
                        stage=stage.name, error=type(err).__name__)
        if task.attempts < self.retries:
            task.attempts += 1
            self.result.retried += 1
            delay = min(RETRY_BACKOFF_BASE_S * (2 ** (task.attempts - 1)),
                        RETRY_BACKOFF_MAX_S)
            telemetry.event("survey.stage_retry", obs=obs.name,
                            stage=stage.name, attempt=task.attempts)
            if self.verbose:
                print(f"# survey: {obs.name}: {stage.name} failed "
                      f"({type(err).__name__}: {err}); retry "
                      f"{task.attempts}/{self.retries} in {delay:.2f}s")
            # re-enqueue from a timer, not this worker: the backoff must
            # not hold the device lease / host slot idle. The fleet
            # cannot finish early — the task stays non-terminal until
            # the timer fires and the retry settles.
            timer = threading.Timer(delay, self._requeue_retry, (task,))
            timer.daemon = True
            timer.start()
            return
        # bounded retries exhausted: quarantine the OBSERVATION — the
        # fleet continues, the verdict is recorded, and a later resume
        # may try again (the operator explicitly asked)
        error = f"{type(err).__name__}: {err}"
        self._manifests[task.obs_i].quarantine(stage.name, error)
        telemetry.event("survey.quarantine", obs=obs.name,
                        stage=stage.name, error=type(err).__name__)
        trace = self._traces[task.obs_i]
        if trace is not None:
            trace.event("survey.quarantine", stage=stage.name)
        print(f"# survey: QUARANTINED {obs.name} at {stage.name}: {error} "
              f"(fleet continues)")
        with self._cv:
            for s in self.stages:
                t = self._tasks[(task.obs_i, s.name)]
                if t.state != _DONE:
                    t.state = _QUARANTINED
            self.result.quarantined[obs.name] = {"stage": stage.name,
                                                 "error": error}
            if self._finished_locked():
                self._stop = True
            self._cv.notify_all()

    def _lease_device(self, lease: Optional[int]):
        """The JAX device backing lease ``lease``, or None when no
        binding is needed. With one lease (the default) the process
        default device already IS the lease; with several, each device
        worker pins its stages via ``jax.default_device`` (thread-local)
        so N leases really are N chips, not N-fold oversubscription of
        device 0. Guarded: a jax-less run (stub DAGs) just skips the
        binding."""
        if lease is None or self.devices <= 1:
            return None
        try:
            import jax

            devs = jax.local_devices()
        except Exception:  # noqa: BLE001 - no backend: nothing to pin
            return None
        return devs[lease % len(devs)]

    def _worker(self, q: "queue.PriorityQueue",
                lease: Optional[int] = None) -> None:
        device = self._lease_device(lease)
        while True:
            try:
                _, _, task = q.get(timeout=0.05)
            except queue.Empty:
                if self._stop:
                    return
                continue
            with self._lock:
                if self._stop and self._fatal is not None:
                    continue  # fleet is unwinding: drop queued work
                if task.state == _QUARANTINED:
                    continue  # cancelled while queued
                task.state = _RUNNING
            try:
                if device is not None:
                    import jax

                    with jax.default_device(device):
                        self._execute(task)
                else:
                    self._execute(task)
            except Exception as e:  # noqa: BLE001 - retry/quarantine policy
                self._handle_failure(task, e)
            except BaseException as e:  # injected kill / interrupt
                with self._cv:
                    if self._fatal is None:
                        self._fatal = e
                    self._stop = True
                    self._cv.notify_all()
                return

    # -- entry point --------------------------------------------------------

    def run(self) -> FleetResult:
        """Run the fleet to completion (or first fatal error). Returns
        the :class:`FleetResult`; re-raises a BaseException (injected
        kill, KeyboardInterrupt) after the in-flight stages settle."""
        self._t0 = time.perf_counter()
        self._open_manifests()
        try:
            with self._cv:
                for i in range(len(self.obs)):
                    done = (self._manifests[i].done_stages()
                            if self.resume else set())
                    for s in self.stages:
                        if s.name in done:
                            self._tasks[(i, s.name)].state = _DONE
                            self.result.skipped.append(
                                (self.obs[i].name, s.name))
                            telemetry.counter("survey.stages_skipped")
                    self._promote_locked(i)
                if self._finished_locked():
                    self._stop = True
            workers = (
                [threading.Thread(target=self._worker,
                                  args=(self._device_q, d),
                                  name=f"survey-device{d}")
                 for d in range(self.devices)]
                + [threading.Thread(target=self._worker,
                                    args=(self._host_q,),
                                    name=f"survey-host{h}")
                   for h in range(self.max_host_workers)])
            for w in workers:
                w.start()
            with self._cv:
                while not self._stop:
                    self._cv.wait(0.1)
            for w in workers:
                w.join()
        finally:
            self.result.wall = time.perf_counter() - self._t0
            for m in self._manifests:
                m.close()
            for t in self._traces:
                if t is not None:
                    t.close()
        if self._fatal is not None:
            raise self._fatal
        return self.result
