"""Fleet scheduler: device leases + a bounded host pool over the obs DAG.

Survey-scale pipelines are throughput systems (arXiv:1601.01165 frames
dedispersion surveys exactly this way): the accelerator must stay
saturated while host-side IO, prep and post-processing for OTHER beams
proceed concurrently. The serial per-tool chain leaves the device idle
during every sift and pfd_snr; this scheduler runs the per-observation
stage DAG (:mod:`.dag`) over the whole fleet with two execution lanes:

- **device lane** — ``device_bound`` stages queue for exclusive device
  leases drawn from a pool of N chips (default 1: one device-bound
  stage at a time). The queue is priority + FIFO: deeper stages first
  (drain observations toward completion, bounding in-flight
  intermediate artifacts), submission order breaking ties. A stage
  whose spec declares ``devices_max > 1`` may be **gang-leased**: one
  execution holds k chips at once (the stage's ``gang_argv`` spans
  them, e.g. ``sweep --mesh k``), the alternative placement to
  fleet-parallel k-obs-x-1-chip. ``gang`` picks the shape — a fixed k,
  or ``"auto"``: fleet-parallel while enough ready device stages exist
  to fill the chips, widening gangs (scaled by the measured per-stage
  cost share from this run's completed stages — the numbers the obs
  traces record) when chips would otherwise idle. Every placement
  decision lands in the fleet trace as a ``survey.gang_decision`` event
  (k, chips, reason) and in the observation's trace. Gang acquisition
  is FIFO with full reservation (an older waiting claim reserves freed
  chips), so a wide gang can never starve behind a stream of 1-chip
  stages. Leased chips publish thread-locally
  (``parallel.mesh.device_lease``), which is where ``cli/sweep
  --mesh`` resolves its mesh devices — two concurrent gangs can never
  both address chips 0..k-1.
- **host lane** — host-bound stages (sift, pfd_snr summaries) run on a
  bounded worker pool (``max_host_workers``), overlapping the device
  lane.

Failure policy: a stage that raises an ordinary Exception (including a
nonzero CLI exit, an injected IO fault, an OOM that escaped the in-stage
halving) retries up to ``retries`` times with bounded, seeded-jitter
exponential backoff (lockstep retries of leases that failed together
would collide again; ``resilience.retry.backoff_delay``); past that the
OBSERVATION is quarantined — recorded in its manifest, its remaining
stages cancelled, the fleet continues — instead of aborting the run. A
BaseException (``faultinject.InjectedKill``, KeyboardInterrupt) unwinds
the whole fleet like a signal: nothing is marked done that did not
finish, and a ``--resume`` replans from the manifests.

Fleet health (round 12, ``resilience.health``): stages heartbeat
through the telemetry they already record (activity hooks); a watchdog
thread interrupts a stage that outruns its declared deadline
(``StageSpec.deadline_s``/``deadline_per_mb``, or the uniform
``stage_deadline`` override) or stops heartbeating for ``stall_s``
(``--stall-timeout`` / ``PYPULSAR_TPU_STALL_S``) — the interrupt is an
ordinary Exception, so a hung stage lands in the same retry ->
quarantine path, with ``survey.deadline_exceeded`` /
``survey.stage_stalled`` events in the fleet and obs traces and its
lease(s) reclaimed. Device-fault/OOM failures charge strikes against
the leased chips (``parallel.mesh.device_health``); a chip past K
strikes is evicted from the pool mid-fleet (never the last healthy
one) and retried gangs shrink to the survivors — placement is excluded
from fingerprints, so the shrunk retry's artifacts stay byte-identical.
Before launching new work the scheduler consults the
``resilience.health.ResourceGuard`` admission gate (free disk under the
artifact root, ship-ahead ``*.pending_depth`` backpressure): a failing
gate pauses *scheduling* (``survey.admission_paused``), never the
stages in flight. Per-device verdicts are mirrored to
``<outdir>/_fleet_health.json`` for ``survey --status``.

Fault points (``--fault-inject`` / PYPULSAR_TPU_FAULTS), armed at stage
boundaries: ``survey.stage_start`` / ``survey.stage_done`` (any stage,
Nth hit) and the per-stage ``survey.stage_start.<name>`` /
``survey.stage_done.<name>``. ``stage_done`` trips AFTER the artifacts
are written but BEFORE the manifest records them — the torn-stage window
a resume must redo.

Multi-host fleet (round 18, ``survey.fleet``): pass a registered
:class:`~pypulsar_tpu.survey.fleet.FleetPlane` and this scheduler
becomes ONE HOST of an M-host fleet sharing the artifact directory.
Observations are then not pre-assigned: a claim/adopt loop takes them
one at a time through the plane's fenced lease files (at most
``devices`` in flight per host, so a slow host never hoards the queue),
opens the per-obs manifest lazily UNDER the held claim (token-stamped,
fence-checked on every append), and resumes an adopted observation from
its journal exactly as a single-host ``--resume`` would — validated
stages skip, torn ones redo, bytes identical. A host whose heartbeat
goes silent past ``PYPULSAR_TPU_HOST_LEASE_S`` has its in-flight
observations adopted by survivors; if it was merely stalled (netstall,
paused VM) and wakes, its next manifest append raises ``StaleLeaseError``
and the observation is CEDED — not retried, not quarantined: the adopter
owns it now (host-aware failure policy). Hosts charge
:class:`~pypulsar_tpu.resilience.health.HostHealth` strikes on the
deaths they observe (and on their own cedes); a host past the strike
limit stops claiming new work and drains out. Each host's stage spans
and fleet events are stamped ``host=<id>`` so ``tlmsum`` renders the
per-host roll-up.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from pypulsar_tpu.obs import flightrec, telemetry, tracing
from pypulsar_tpu.parallel import broker as broker_mod
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.resilience import health as health_mod
from pypulsar_tpu.resilience import locks as locks_mod
from pypulsar_tpu.resilience.retry import backoff_delay, is_oom_error
from pypulsar_tpu.survey import fleet as fleet_mod
from pypulsar_tpu.survey.dag import StageSpec, SurveyConfig, build_dag, stage_names
from pypulsar_tpu.tune import knobs as knobs_mod
from pypulsar_tpu.survey.state import (
    Observation,
    ObsManifest,
    ObsTrace,
    fleet_fingerprint,
    write_fleet_health,
)

__all__ = ["FleetResult", "FleetScheduler"]

# bounded, jittered backoff between retries of a failed stage (base *
# 2^attempt capped, then scaled by seeded jitter — see
# resilience.retry.backoff_delay): the delay runs on a timer thread,
# NOT the lane worker, so a backing-off observation never stalls the
# device lease or a host slot
RETRY_BACKOFF_BASE_S = 0.25
RETRY_BACKOFF_MAX_S = 5.0

# auto-gang cost gate: a gang-able stage whose measured mean cost is
# under this share of the whole device chain runs 1-chip even when
# chips idle — k chips on a minor stage buys k x the lease churn for a
# sliver of wall time (env-overridable: a fleet of near-equal stages
# may want a lower bar)
GANG_COST_MIN_FRAC = health_mod.env_float(
    "PYPULSAR_TPU_GANG_COST_MIN_FRAC", 0.25)

_UNSET = object()  # _n_jax_devices cache sentinel (None = no backend)

_PENDING, _QUEUED, _RUNNING, _DONE, _QUARANTINED, _REMOTE = range(6)

# Stages whose device work submits typed units to the batch broker
# (round 24), and the broker party kind each stage registers as.  Only
# these stages are eligible for batch-lane claims.
_BROKER_UNITS = {"sweep": "accel", "fold": "fold"}


@dataclass
class FleetResult:
    """What one scheduler run did: ``ran`` (executed this run, in
    completion order), ``skipped`` (validated complete from the
    manifests — the resume contract's receipt), ``quarantined``
    (obs -> failing stage + error), ``retried`` stage-retry count."""

    ran: List[Tuple[str, str]] = field(default_factory=list)
    skipped: List[Tuple[str, str]] = field(default_factory=list)
    quarantined: Dict[str, Dict[str, str]] = field(default_factory=dict)
    retried: int = 0
    timeouts: int = 0  # watchdog interrupts (deadline + stall)
    evicted_devices: List[int] = field(default_factory=list)
    wall: float = 0.0
    # multi-host bookkeeping (empty without a plane): observations this
    # host ADOPTED from a dead/left host, observations it CEDED to a
    # higher fencing token, and observations other live hosts finished
    adopted: List[str] = field(default_factory=list)
    ceded: List[str] = field(default_factory=list)
    remote_done: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.quarantined


class _Task:
    __slots__ = ("obs_i", "stage", "state", "attempts", "seq",
                 "last_dev_ids", "last_real_dev_ids", "last_error",
                 "done_recorded", "lane_seq")

    def __init__(self, obs_i: int, stage: StageSpec):
        self.obs_i = obs_i
        self.stage = stage
        self.state = _PENDING
        self.attempts = 0
        self.seq = -1
        self.last_dev_ids: Optional[List[int]] = None
        self.last_real_dev_ids: Optional[List[int]] = None
        self.last_error = ""
        # set the instant the manifest records this execution done: a
        # watchdog interrupt landing after that point must finish the
        # task, not retry it
        self.done_recorded = False
        # queue seq this task was batch-lane-claimed at (round 24): the
        # lane runs the task out-of-band, so its original queue entry
        # goes stale; a worker popping THAT seq consumes it silently. A
        # retry re-enqueue gets a new seq and runs normally.
        self.lane_seq: Optional[int] = None


class FleetScheduler:
    """See module docstring. ``stages`` defaults to the standard five-
    stage DAG (:func:`build_dag`); tests inject synthetic DAGs."""

    def __init__(self, observations: Sequence[Observation],
                 cfg: Optional[SurveyConfig] = None, *,
                 stages: Optional[Sequence[StageSpec]] = None,
                 max_host_workers: int = 2, devices: int = 1,
                 retries: int = 1, resume: bool = False,
                 telemetry_dir: Optional[str] = None,
                 gang="auto",
                 stall_s: Optional[float] = None,
                 stage_deadline: Optional[float] = None,
                 strike_limit: Optional[int] = None,
                 min_free_mb: Optional[float] = None,
                 max_pending: Optional[float] = None,
                 max_bad_frac: Optional[float] = None,
                 jitter_rng=None,
                 plane: Optional["fleet_mod.FleetPlane"] = None,
                 verbose: bool = False,
                 service: bool = False):
        self.cfg = cfg if cfg is not None else SurveyConfig()
        self.stages = list(stages) if stages is not None \
            else build_dag(self.cfg)
        self._by_name = {s.name: s for s in self.stages}
        self._depth = {s.name: i for i, s in enumerate(self.stages)}
        for s in self.stages:
            for d in s.deps:
                if d not in self._by_name:
                    raise ValueError(f"stage {s.name!r} depends on "
                                     f"unknown stage {d!r}")
        self.obs = list(observations)
        names = [o.name for o in self.obs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate observation names: {names}")
        self.max_host_workers = max(1, int(max_host_workers))
        self.devices = max(1, int(devices))
        self._njax: object = _UNSET
        self.retries = max(0, int(retries))
        self.resume = resume
        self.telemetry_dir = telemetry_dir
        if telemetry_dir:
            # ObsTrace silently disables itself on an unopenable path
            # (observability is a passenger) — a missing directory would
            # drop every trace, so create it here for library callers,
            # not just the CLI
            try:
                os.makedirs(telemetry_dir, exist_ok=True)
            except OSError:
                pass
        if gang != "auto":
            gang = max(1, int(gang))
            if gang > self.devices:
                raise ValueError(f"--gang {gang} exceeds the "
                                 f"{self.devices} device leases")
        self.gang = gang
        self.verbose = verbose

        # fleet health: heartbeats + watchdog, device strikes, admission
        if stall_s is None:
            stall_s = health_mod.env_float(health_mod.ENV_STALL_S, None)
        self.stall_s = stall_s
        self.stage_deadline = stage_deadline
        self.jitter_rng = jitter_rng
        self._hb = health_mod.HeartbeatRegistry()
        self._watchdog: Optional[health_mod.Watchdog] = None
        self._health = self._make_device_health(strike_limit)
        root = (os.path.dirname(self.obs[0].outbase) or "."
                if self.obs else ".")
        self._health_dir = root if self.obs else None
        self._guard = health_mod.ResourceGuard(
            root,
            min_free_bytes=(min_free_mb * 1e6
                            if min_free_mb is not None else None),
            max_pending=max_pending)
        # degrade-vs-quarantine threshold for the INGEST data-quality
        # verdict (resilience.dataguard): an observation whose input
        # reports more than this fraction of its samples missing/invalid
        # is data-quarantined before burning any device time
        if max_bad_frac is None:
            from pypulsar_tpu.resilience import dataguard

            max_bad_frac = dataguard.max_bad_frac_default()
        self.max_bad_frac = float(max_bad_frac)
        self._admission_blocked = False  # one event per pause episode

        # ONE mutex behind two guards (the bare lock for state peeks,
        # the condition for wait/notify) — lockdep-tracked under a
        # single name, so the order graph sees them as the one lock
        # they are (docs/ARCHITECTURE.md "Concurrency model")
        self._lock = locks_mod.TrackedLock("survey.sched")
        self._cv = locks_mod.TrackedCondition("survey.sched",
                                              lock=self._lock)
        self._device_q: "queue.PriorityQueue" = queue.PriorityQueue()
        self._host_q: "queue.PriorityQueue" = queue.PriorityQueue()
        self._seq = 0
        self._stop = False
        self._fatal: Optional[BaseException] = None
        self._tasks: Dict[Tuple[int, str], _Task] = {
            (i, s.name): _Task(i, s)
            for i in range(len(self.obs)) for s in self.stages}
        # the device POOL gangs draw from (lease ids 0..devices-1) and
        # the FIFO claim line that keeps wide gangs starvation-free
        self._free_ids = set(range(self.devices))
        self._claims: List[Tuple[object, List[int]]] = []
        self._stage_cost: Dict[str, List[float]] = {}  # name -> [s, n]
        self.result = FleetResult()
        self._manifests: List[Optional[ObsManifest]] = []
        self._traces: List[Optional[ObsTrace]] = []
        # per-obs causal trace ids (round 21): minted once in each
        # manifest, so kill+resume and adoption continue the SAME trace
        self._trace_ids: List[Optional[str]] = []
        # obs index -> dead host it was adopted from; consumed by the
        # FIRST stage span after adoption (the lane-handover link the
        # stitched trace renders)
        self._adopted_from: Dict[int, str] = {}
        # a stage that consumed more than this fraction of its watchdog
        # budget without tripping it emits survey.slo_burn — the
        # early-warning margin tlmsum's SLO section accounts
        self._slo_frac = knobs_mod.env_float("PYPULSAR_TPU_OBS_SLO_FRAC")
        self._t0 = 0.0

        # multi-host plane (round 18): observations are CLAIMED, not
        # pre-assigned — the claim/adopt loop owns admission, manifests
        # open lazily under a held claim, and every manifest append is
        # fenced by the claim's token
        self.plane = plane
        self.host_id = plane.host_id if plane is not None else None
        self._owned: set = set()            # obs indices we hold claims on
        self._obs_tokens: Dict[int, int] = {}
        self._terminal_remote: set = set()  # obs another host finished
        # at most `devices` claimed-but-unfinished obs per host: a host
        # must not hoard the queue it cannot drain (the surplus-host /
        # idle-adopter contract rides on unclaimed obs staying visible)
        self._claim_ahead = max(1, self.devices)
        self._host_health = (health_mod.HostHealth()
                             if plane is not None else None)
        self._claim_thread: Optional[threading.Thread] = None
        self._warm_thread: Optional[threading.Thread] = None
        self._plane_owned_here = False  # register()ed by this run()

        # service mode (round 23): the fleet does NOT exit when every
        # task is terminal — the daemon keeps submit()ing observations
        # into the running DAG, and only request_drain() restores the
        # batch run-to-completion exit contract
        self._service = bool(service)
        self._draining = False
        # obs indices whose input file existence is re-verified at every
        # stage launch (daemon submissions: a source that vanishes
        # between admit and stage start is a LOUD data-quarantine, not a
        # crash or a retry loop). Batch obs are exempt — stub-stage
        # fleets legitimately run against paths that never exist.
        self._verify_input: set = set()
        # optional terminal-edge hook (obs_name, state) the daemon uses
        # for tenant accounting; failures are swallowed (a passenger)
        self.on_obs_terminal = None
        # optional obs_name -> tenant resolver for the candidate-store
        # ingest edge (the daemon points this at its admission books)
        self.tenant_of = None
        # set once run() has opened the initial manifests and promoted
        # the initial obs: submit() before this point would race the
        # startup manifest pass (the daemon waits on it)
        self._ready = locks_mod.TrackedEvent("survey.sched.ready")

    # -- manifests ----------------------------------------------------------

    def _clean_stale_outputs(self, obs: Observation) -> None:
        """Scrub every artifact the stages would enumerate for this
        observation (plus the sweep's chain journal). Runs only when the
        manifest is FRESH — a reconfigured rerun into the same outdir
        must not let the previous grid's files leak into the glob-driven
        stage inputs/outputs (sift would cluster old-grid .cand trails,
        snr would summarize orphaned archives), which would diverge from
        a clean-dir serial chain."""
        stale = [f"{obs.outbase}.chain.jsonl"]
        for s in self.stages:
            stale += s.outputs(obs, self.cfg)
        for path in stale:
            try:
                os.remove(path)
            except OSError:
                pass

    def _open_manifests(self) -> None:
        if self.plane is not None:
            # multi-host mode: manifests open LAZILY in _claim_obs,
            # under the held claim — three hosts eagerly opening (and
            # fresh-scrubbing) every manifest at startup would race each
            # other over observations none of them own yet
            self._manifests = [None] * len(self.obs)
            self._traces = [None] * len(self.obs)
            self._trace_ids = [None] * len(self.obs)
            return
        snames = stage_names(self.stages)
        for obs in self.obs:
            if not self.resume and os.path.exists(obs.manifest):
                # a fresh (non-resume) fleet starts from scratch — the
                # same contract as `sweep --checkpoint` without --resume
                os.remove(obs.manifest)
            m = ObsManifest(obs.manifest,
                            fleet_fingerprint(obs, self.cfg, snames))
            if m.fresh:
                # new manifest OR a restart after changed params/input:
                # nothing will be skipped, so nothing stale may linger
                self._clean_stale_outputs(obs)
            m.plan(obs, snames)
            self._manifests.append(m)
            tid = self._mint_trace(m)
            self._trace_ids.append(tid)
            trace = None
            if self.telemetry_dir:
                trace = ObsTrace(
                    os.path.join(self.telemetry_dir, f"{obs.name}.jsonl"),
                    obs.name, append=self.resume, trace_id=tid)
            self._traces.append(trace)

    def _mint_trace(self, m: ObsManifest) -> Optional[str]:
        """The observation's causal trace_id (minted once, persisted in
        the manifest — see ObsManifest.ensure_trace). Observability is a
        passenger: a failure here runs the observation untraced."""
        try:
            return m.ensure_trace(tracing.new_trace_id)
        except (fleet_mod.StaleLeaseError, OSError):
            return None

    # -- ingest data validation ---------------------------------------------

    def _validate_ingest(self) -> None:
        """Validate every observation's INPUT before any stage runs
        (resilience.dataguard.validate_input): a recognized-but-broken
        file, or one whose data-quality report exceeds --max-bad-frac,
        is quarantined with reason ``"data"`` — distinct from runtime
        quarantine, because the fix is a re-transfer, not a retry.
        Salvageable inputs record their report in the manifest (the
        --status / tlmsum denominators) and DEGRADE: the readers carry
        the valid prefix through the chain. In multi-host mode each obs
        is validated at CLAIM time instead (``_claim_obs``): only the
        claim holder may write the verdict into the manifest."""
        for i in range(len(self.obs)):
            self._validate_ingest_one(i)

    def _validate_ingest_one(self, i: int) -> bool:
        """Ingest-validate one observation; returns False when it was
        data-quarantined (the claim holder records the verdict)."""
        from pypulsar_tpu.io.errors import DataFormatError
        from pypulsar_tpu.resilience import dataguard

        obs = self.obs[i]
        try:
            report = dataguard.validate_input(obs.infile)
        except DataFormatError as e:
            self._quarantine_data(i, f"{type(e).__name__}: {e}")
            return False
        except Exception as e:  # noqa: BLE001 - see below
            # an unexpected validation failure (OSError on a flaky
            # mount, a codec corner the wrappers missed) must not
            # abort the WHOLE fleet at startup — admit the obs and
            # let the stage machinery's retry->quarantine own it
            print(f"# survey: {obs.name}: ingest validation failed "
                  f"({type(e).__name__}: {e}); admitting unchecked")
            return True
        if report is None:
            return True  # unrecognized/missing: the stage reports it
        self._manifests[i].note_data_quality(report)
        bad = float(report.get("bad_frac", 0.0) or 0.0)
        if bad > self.max_bad_frac:
            self._quarantine_data(
                i, f"data-quality bad_frac {bad:.3f} exceeds "
                   f"--max-bad-frac {self.max_bad_frac:.3f}")
            return False
        if bad and self.verbose:
            print(f"# survey: {obs.name}: degraded input admitted "
                  f"(bad_frac {bad:.3f} <= {self.max_bad_frac:.3f})")
        return True

    def _quarantine_data(self, obs_i: int, error: str) -> None:
        obs = self.obs[obs_i]
        self._manifests[obs_i].quarantine("ingest", error, reason="data")
        telemetry.counter("survey.data_quarantines")
        telemetry.event("survey.quarantine", obs=obs.name,
                        stage="ingest", reason="data")
        trace = self._traces[obs_i]
        if trace is not None:
            trace.event("survey.quarantine", stage="ingest",
                        reason="data")
        print(f"# survey: DATA-QUARANTINED {obs.name} at ingest: {error} "
              f"(fleet continues)")
        self._postmortem("data_quarantine", obs_i,
                         extra={"error": error})
        with self._cv:
            for s in self.stages:
                t = self._tasks[(obs_i, s.name)]
                if t.state != _DONE:
                    t.state = _QUARANTINED
            self.result.quarantined[obs.name] = {
                "stage": "ingest", "error": error, "reason": "data"}
            self._maybe_stop_locked()
            self._cv.notify_all()
        self._plane_mark_terminal(obs_i, "quarantined")

    # -- scheduling core ----------------------------------------------------

    def _enqueue_locked(self, task: _Task) -> None:
        task.state = _QUEUED
        self._seq += 1
        task.seq = self._seq
        # deeper stages first (finish observations, free their
        # intermediates), FIFO within a depth
        entry = (-self._depth[task.stage.name], task.seq, task)
        (self._device_q if task.stage.device_bound
         else self._host_q).put(entry)

    def _promote_locked(self, obs_i: int) -> None:
        for s in self.stages:
            task = self._tasks[(obs_i, s.name)]
            if task.state != _PENDING:
                continue
            if all(self._tasks[(obs_i, d)].state == _DONE for d in s.deps):
                self._enqueue_locked(task)

    def _finished_locked(self) -> bool:
        return all(t.state in (_DONE, _QUARANTINED, _REMOTE)
                   for t in self._tasks.values())

    def _maybe_stop_locked(self) -> None:
        """Stop the fleet when every task is terminal — unless service
        mode holds it open for future :meth:`submit` calls (only a
        :meth:`request_drain` restores the batch exit contract). Every
        terminal edge funnels through here so the service-mode liveness
        rule lives in exactly one place."""
        if self._finished_locked() \
                and not (self._service and not self._draining):
            self._stop = True

    # -- service mode (round 23) --------------------------------------------

    def submit(self, obs: Observation, *, resume: bool = True,
               verify_input: bool = True) -> int:
        """Register ONE new observation with a RUNNING service-mode
        fleet and promote its ready stages. The daemon's ingest edge:
        the manifest is opened and planned immediately (the accepted-
        work durability contract — an accepted observation survives
        kill+restart exactly like a batch obs), journal-validated
        stages are skipped (``resume=True``, the default, makes a
        daemon-restart resubmission idempotent: zero re-runs of
        validated stages), and ingest validation may data-quarantine
        the observation before any stage runs. Returns the obs index.

        Thread-safe against the workers: the manifest/trace open runs
        outside the scheduler lock (it blocks on disk), registration
        appends under the lock (list appends — existing indices never
        move), and the tasks become visible to workers only at the
        final promote."""
        if not self._service:
            raise RuntimeError("submit() requires service=True")
        with self._lock:
            if any(o.name == obs.name for o in self.obs):
                raise ValueError(f"duplicate observation name "
                                 f"{obs.name!r}")
        snames = stage_names(self.stages)
        if not resume and os.path.exists(obs.manifest):
            os.remove(obs.manifest)
        m = ObsManifest(obs.manifest,
                        fleet_fingerprint(obs, self.cfg, snames))
        if m.fresh:
            self._clean_stale_outputs(obs)
        m.plan(obs, snames)
        tid = self._mint_trace(m)
        trace = None
        if self.telemetry_dir:
            trace = ObsTrace(
                os.path.join(self.telemetry_dir, f"{obs.name}.jsonl"),
                obs.name, append=resume, trace_id=tid)
        with self._cv:
            i = len(self.obs)
            self.obs.append(obs)
            self._manifests.append(m)
            self._trace_ids.append(tid)
            self._traces.append(trace)
            for s in self.stages:
                self._tasks[(i, s.name)] = _Task(i, s)
            if verify_input:
                self._verify_input.add(i)
        if not self._validate_ingest_one(i):
            return i  # data-quarantined before any stage ran
        done = m.done_stages() if resume else set()
        with self._cv:
            for s in self.stages:
                if s.name in done:
                    self._tasks[(i, s.name)].state = _DONE
                    self.result.skipped.append((obs.name, s.name))
                    telemetry.counter("survey.stages_skipped")
            self._promote_locked(i)
            obs_complete = all(
                self._tasks[(i, s.name)].state == _DONE
                for s in self.stages)
            self._cv.notify_all()
        if obs_complete:
            # every stage already journal-validated: terminal on arrival
            self._plane_mark_terminal(i, "done")
        return i

    def request_drain(self) -> None:
        """End service mode: finish everything submitted so far, then
        exit :meth:`run` with the ordinary batch verdict (the SIGTERM
        half of the daemon's overload contract)."""
        with self._cv:
            self._draining = True
            self._maybe_stop_locked()
            self._cv.notify_all()

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until :meth:`run` has finished its startup manifest
        pass (service mode: the point after which :meth:`submit` is
        safe)."""
        return self._ready.wait(timeout)

    # -- multi-host claim / adopt loop --------------------------------------

    def _manifest_current(self, obs_i: int) -> bool:
        """Does the observation's on-disk manifest carry THIS run's
        fingerprint? A terminal plane claim is only trustworthy
        together with a matching manifest — a claim left 'done' by a
        PREVIOUS configuration's fleet must be re-opened and re-run,
        exactly as a single-host rerun restarts a mismatched manifest
        (finding: stale terminal claims must not short-circuit a
        reconfigured rerun)."""
        obs = self.obs[obs_i]
        want = fleet_fingerprint(obs, self.cfg,
                                 stage_names(self.stages))
        import json

        try:
            with open(obs.manifest) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    return (rec.get("type") == "journal"
                            and rec.get("fingerprint") == want)
        except (OSError, ValueError):
            pass
        return False

    def _interrupt_lost_stages_locked(self, obs_i: int) -> None:
        """Our claim on ``obs_i`` is gone (a survivor adopted it while
        we were presumed dead): async-interrupt any stage of it still
        RUNNING with StaleLeaseError so its artifact writes stop within
        one poll tick — waiting for the stage's next manifest append
        could leave a zombie writer racing the adopter for minutes.
        A DEFERRED delivery (the stage holds a tracked lock right now)
        is fine: the claim loop calls this every poll tick, so the
        interrupt retries until it lands at an unlocked boundary."""
        for entry in self._hb.active():
            task = entry.payload
            if getattr(task, "obs_i", None) == obs_i:
                health_mod.interrupt_thread(entry.thread_id,
                                            fleet_mod.StaleLeaseError)

    def _plane_mark_terminal(self, obs_i: int, state: str) -> None:
        """Best-effort claim closeout (done/quarantined). Losing the
        fence here means a survivor adopted the observation while its
        last write was in flight — the adopter revalidates and closes
        it out itself, so the local verdict simply stands down.

        Every obs-terminal edge (done / quarantined / data-quarantined)
        funnels through here, which is why the service-mode terminal
        hook also rides it: the daemon's tenant books settle on the
        same edges the multi-host plane does."""
        cb = self.on_obs_terminal
        if cb is not None:
            try:
                cb(self.obs[obs_i].name, state)
            except Exception:  # noqa: BLE001 - accounting is a passenger
                pass
        if state == "done":
            # publish to the candidate store UNDER the still-held claim
            # (round 25) — the fenced append is what makes a dead
            # host's late publish a no-op
            self._publish_candidates(obs_i)
        if self.plane is None:
            return
        token = self._obs_tokens.get(obs_i)
        if token is None:
            return
        try:
            self.plane.mark_terminal(
                self.obs[obs_i].name, token, state,
                trace_id=self._trace_ids[obs_i])
        except fleet_mod.StaleLeaseError:
            self._cede_obs(obs_i, already_terminal=True)

    def _publish_candidates(self, obs_i: int) -> None:
        """Candidate-store ingest (round 25): normalize this done
        observation's terminal artifacts and publish them, fenced under
        the obs claim when a plane is live.  A passenger like the
        terminal hook — it only READS stage outputs and writes only
        under ``_fleet/candstore/``, so per-obs artifacts stay
        byte-identical and a store failure never fails the obs.
        ``PYPULSAR_TPU_CANDSTORE=0`` restores the store-less fleet."""
        from pypulsar_tpu import candstore as candstore_mod

        if not candstore_mod.enabled():
            return
        obs = self.obs[obs_i]
        outdir = os.path.dirname(obs.outbase) or "."
        token = self._obs_tokens.get(obs_i)
        fence = None
        if self.plane is not None and token is not None:
            fence = (lambda o=obs.name, t=token:
                     self.plane.fence(o, t))
        tenant = "default"
        resolver = self.tenant_of
        if resolver is not None:
            try:
                tenant = str(resolver(obs.name) or "default")
            except Exception:  # noqa: BLE001 - accounting passenger
                tenant = "default"
        try:
            candstore_mod.publish_obs(
                outdir, obs.name, obs.outbase, obs.infile,
                tenant=tenant, trace_id=self._trace_ids[obs_i],
                fence=fence, token=token)
        except fleet_mod.StaleLeaseError:
            pass  # adopter owns the obs now; it will publish
        except Exception:  # noqa: BLE001 - the store is a passenger
            pass

    def _claim_obs(self, i: int, token: int, adopted_from=None) -> None:
        """Take ownership of one claimed observation: open its manifest
        UNDER the held claim (token-stamped, fenced), scrub stale
        artifacts only when the manifest is fresh, validate ingest, mark
        journal-validated stages done (an adopted obs resumes exactly
        like a single-host ``--resume``) and promote the rest."""
        obs = self.obs[i]
        snames = stage_names(self.stages)
        m = ObsManifest(
            obs.manifest, fleet_fingerprint(obs, self.cfg, snames),
            token=token,
            fence=lambda o=obs.name, t=token: self.plane.fence(o, t))
        # re-verify the claim BEFORE the destructive scrub: a residual
        # double-claim loser (both racers passed the settle re-read)
        # must not delete the winner's freshly written artifacts — the
        # fence raises here, before anything is touched
        self.plane.fence(obs.name, token)
        if m.fresh:
            self._clean_stale_outputs(obs)
        m.plan(obs, snames)
        self._manifests[i] = m
        # SAME trace_id the previous owner minted (the manifest is the
        # shared source of truth): the adopter's spans continue the
        # observation's causal story, they don't start a new one
        self._trace_ids[i] = self._mint_trace(m)
        if self.telemetry_dir and self._traces[i] is None:
            # append: an adopted observation's trace keeps the dead
            # host's recorded spans — exactly the forensics worth having
            self._traces[i] = ObsTrace(
                os.path.join(self.telemetry_dir, f"{obs.name}.jsonl"),
                obs.name, append=True, trace_id=self._trace_ids[i])
        with self._cv:
            self._owned.add(i)
            self._obs_tokens[i] = token
        if adopted_from:
            self.result.adopted.append(obs.name)
            # the lane-handover link: the first stage span this host
            # runs for the adopted obs carries adopted_from, so the
            # stitched trace shows WHERE the trace hopped hosts
            self._adopted_from[i] = adopted_from
            trace = self._traces[i]
            if trace is not None:
                # no `host` attr here: the adopter's fleet trace already
                # carries the host-keyed event (the plane emits it), and
                # summarizing both traces together must not double-count
                # the adoption in the per-host roll-up
                trace.event("survey.obs_adopted",
                            adopted_from=adopted_from, token=token)
            if self.verbose:
                print(f"# survey[{self.host_id}]: ADOPTED {obs.name} "
                      f"from silent host {adopted_from!r} "
                      f"(token {token}); resuming from its manifest")
        if not self._validate_ingest_one(i):
            return  # data-quarantined under our claim
        done = m.done_stages()
        with self._cv:
            for s in self.stages:
                task = self._tasks[(i, s.name)]
                if s.name in done:
                    task.state = _DONE
                    self.result.skipped.append((obs.name, s.name))
                    telemetry.counter("survey.stages_skipped")
                else:
                    task.state = _PENDING
                    task.attempts = 0  # a fresh owner gets fresh retries
            self._promote_locked(i)
            self._maybe_stop_locked()
            self._cv.notify_all()

    def _claim_failed(self, i: int, token: int, e: Exception) -> None:
        """A claim we won but cannot act on (foreign-tool manifest,
        unreadable outdir): close it out as quarantined so the fleet
        sees a verdict instead of a wedge held by a silent owner."""
        obs = self.obs[i]
        self._owned.discard(i)
        self._obs_tokens.pop(i, None)
        err = f"{type(e).__name__}: {e}"
        print(f"# survey[{self.host_id}]: cannot open {obs.name}: "
              f"{err}; quarantining the claim")
        with self._cv:
            # terminal HERE: later poll ticks must not re-read our own
            # quarantined claim as another host's verdict and report it
            # 'finished remotely'
            self._terminal_remote.add(i)
            for s in self.stages:
                t = self._tasks[(i, s.name)]
                if t.state != _DONE:
                    t.state = _QUARANTINED
            self.result.quarantined[obs.name] = {
                "stage": "claim", "error": err}
            self._cv.notify_all()
        try:
            self.plane.mark_terminal(obs.name, token, "quarantined")
        except (fleet_mod.StaleLeaseError, OSError):
            pass
        self._postmortem("claim_quarantined", i, extra={"error": err})

    def _cede_obs(self, i: int, already_terminal: bool = False) -> None:
        """This host's claim on obs ``i`` was superseded (a survivor
        adopted it while we were stalled/presumed dead): stand down
        WITHOUT retry or quarantine — the adopter owns the observation
        now, and the fencing token has already made our late writes
        no-ops. Non-done tasks return to _PENDING so the claim loop can
        re-adopt if the new owner dies in turn."""
        obs = self.obs[i]
        with self._cv:
            if i not in self._owned:
                return
            self._owned.discard(i)
            self._obs_tokens.pop(i, None)
            for s in self.stages:
                t = self._tasks[(i, s.name)]
                if t.state not in (_DONE, _REMOTE):
                    t.state = _PENDING
            self._cv.notify_all()
        m, self._manifests[i] = self._manifests[i], None
        if m is not None:
            m.close()
        self.result.ceded.append(obs.name)
        telemetry.counter("survey.obs_ceded")
        telemetry.event("survey.obs_ceded", host=self.host_id,
                        obs=obs.name)
        if self._host_health is not None and not already_terminal:
            # repeated losses mean THIS host keeps going silent under
            # work (flaky node): past the strike limit it stops
            # claiming and drains out
            self._host_health.strike(self.host_id, kind="ceded",
                                     error=f"lost {obs.name} to a "
                                           f"higher fencing token")
        if self.verbose:
            print(f"# survey[{self.host_id}]: CEDED {obs.name} to its "
                  f"adopter (stale fencing token); fleet continues")
        self._postmortem("obs_ceded", i)

    def _plane_poll(self) -> None:
        """One claim-loop tick: claim unowned observations (orphans
        first — adoption is the liveness path), observe terminal states
        other hosts recorded, and stop the fleet when every observation
        is globally terminal."""
        hosts = self.plane.hosts()
        claims = self.plane.claims()
        with self._lock:
            owned_open = sum(
                1 for i in self._owned
                if any(self._tasks[(i, s.name)].state
                       not in (_DONE, _QUARANTINED, _REMOTE)
                       for s in self.stages))
            owned_now = set(self._owned)
        # zombie self-check FIRST: if any claim we think we hold now
        # carries someone else's token, we were adopted away (netstall,
        # long GC, partition) — interrupt the running stage NOW instead
        # of letting it race the adopter's writes until its next
        # manifest append
        for i in owned_now:
            tok = self._obs_tokens.get(i)
            c = claims.get(self.obs[i].name)
            if tok is not None and (c is None
                                    or c.get("token") != tok):
                with self._lock:
                    self._interrupt_lost_stages_locked(i)
        barred = (self._host_health is not None
                  and self._host_health.is_quarantined(self.host_id))
        for i, obs in enumerate(self.obs):
            with self._lock:
                if i in self._owned or i in self._terminal_remote:
                    continue
            c = claims.get(obs.name)
            state = c.get("state", "running") if c else None
            holder = str(c.get("host", "")) if c else None
            if c is not None and state in ("done", "quarantined"):
                reopen = not self._manifest_current(i)
                if not reopen and self.resume and state == "done":
                    # an EXPLICIT --resume in plane mode re-validates a
                    # done claim's artifacts (size+sha256, the single-
                    # host resume contract): a corrupted artifact
                    # re-opens the claim instead of being trusted
                    try:
                        m = ObsManifest(self.obs[i].manifest,
                                        fleet_fingerprint(
                                            self.obs[i], self.cfg,
                                            stage_names(self.stages)))
                        done = m.done_stages()
                        m.close()
                        reopen = any(s.name not in done
                                     for s in self.stages)
                    except Exception:  # noqa: BLE001 - unreadable
                        reopen = True  # manifest: redo, never trust
                if reopen:
                    # terminal under a DIFFERENT configuration (or the
                    # manifest is gone / fails validation): the verdict
                    # does not apply to THIS run — re-open the claim
                    # and re-run, the plane-mode form of the restart-
                    # on-fingerprint-mismatch contract
                    if not barred and owned_open < self._claim_ahead:
                        token = self.plane.claim(obs.name,
                                                 allow_terminal=True)
                        if token is not None:
                            try:
                                self._claim_obs(i, token)
                            except fleet_mod.StaleLeaseError:
                                continue
                            except Exception as e:  # noqa: BLE001 - same
                                # contract as the claim handler below
                                self._claim_failed(i, token, e)
                                continue
                            owned_open += 1
                    continue
                # another host closed it out: record the remote verdict
                # and mark the tasks terminal locally
                with self._cv:
                    self._terminal_remote.add(i)
                    for s in self.stages:
                        t = self._tasks[(i, s.name)]
                        if t.state != _DONE:
                            t.state = _REMOTE
                    if state == "quarantined" \
                            and obs.name not in self.result.quarantined:
                        self.result.quarantined[obs.name] = {
                            "stage": "?", "error":
                                f"quarantined by host {holder!r}",
                            "host": holder}
                    self.result.remote_done.append(obs.name)
                    self._cv.notify_all()
                continue
            if barred or owned_open >= self._claim_ahead:
                continue
            holder_live = (c is not None and holder != self.host_id
                           and self.plane.is_live(hosts.get(holder)))
            if holder_live:
                continue  # a live host is on it
            adopted_from = (holder if c is not None
                            and holder != self.host_id else None)
            token = self.plane.claim(obs.name)
            if token is None:
                continue  # lost the race (or it went terminal meanwhile)
            if adopted_from and self._host_health is not None:
                # charge the death we just observed: the account the
                # fleet-health JSON and --status render per host
                self._host_health.strike(
                    adopted_from, kind="adopted",
                    error=f"{obs.name} orphaned (heartbeat silent)")
            try:
                self._claim_obs(i, token, adopted_from=adopted_from)
            except fleet_mod.StaleLeaseError:
                continue  # out-adopted during setup: theirs now
            except Exception as e:  # noqa: BLE001 - a claim we cannot
                # act on must not be held forever: _claim_failed closes
                # it out as quarantined (a verdict, not a wedge)
                self._claim_failed(i, token, e)
                continue
            owned_open += 1
        with self._cv:
            self._maybe_stop_locked()
            if self._stop:
                self._cv.notify_all()

    def _plane_loop(self) -> None:
        """The claim/adopt daemon: poll fast enough that adoption lands
        within ~one heartbeat of the lease expiring, slow enough that M
        idle hosts do not hammer the shared directory."""
        poll = max(0.05, min(self.plane.heartbeat_s, 0.5))
        while not self._stop:
            try:
                self._plane_poll()
            except Exception as e:  # noqa: BLE001 - the claim loop must
                # outlive transient plane IO errors (shared-FS hiccup):
                # a dead claim loop would strand every unclaimed obs
                telemetry.event("survey.claim_loop_error",
                                error=type(e).__name__)
            time.sleep(poll)

    # -- fleet health -------------------------------------------------------

    @staticmethod
    def _make_device_health(strike_limit):
        """The process-global mesh registry when jax is importable (so
        mesh-building code and the scheduler share one account), a
        local one otherwise — either way FRESH per fleet: strikes are
        runtime state, not survey state, and a resumed fleet gives
        every chip a clean slate."""
        try:
            from pypulsar_tpu.parallel import mesh as mesh_mod

            return mesh_mod.reset_device_health(strike_limit)
        except Exception:  # noqa: BLE001 - no jax backend: local account
            return health_mod.DeviceHealth(strike_limit)

    def _lease_real(self, i: int) -> int:
        """The REAL jax device id lease ``i`` pins by default (leases
        wrap modulo the chip count on an oversubscribed pool). Strikes
        are charged against real chips — the account `parallel.mesh`
        reads — so health checks must translate lease ids the same
        way."""
        n = self._n_jax_devices()
        return i % n if n else i

    def _healthy_ids(self) -> List[int]:
        return [i for i in range(self.devices)
                if not self._health.is_quarantined(self._lease_real(i))]

    def _deadline_for(self, stage: StageSpec, obs: Observation):
        if self.stage_deadline is not None:
            return self.stage_deadline
        return stage.deadline_for(obs)

    def _needs_watchdog(self) -> bool:
        return (self.stall_s is not None
                or self.stage_deadline is not None
                or any(s.deadline_s is not None
                       or s.deadline_per_mb is not None
                       for s in self.stages))

    def _on_stage_expired(self, entry, reason: str) -> None:
        """Watchdog callback: record the verdict, then interrupt the
        stage's worker thread (StageDeadlineExceeded / StageStalled are
        ordinary Exceptions — the worker's retry/quarantine policy owns
        the rest, and its finally blocks release the lease)."""
        task = entry.payload
        obs = self.obs[task.obs_i]
        now = time.monotonic()
        if reason == "deadline":
            name = "survey.deadline_exceeded"
            after = now - entry.started
            exc = health_mod.StageDeadlineExceeded
        else:
            name = "survey.stage_stalled"
            after = now - entry.last_beat
            exc = health_mod.StageStalled
        # interrupt FIRST, and only while the entry is still live: if
        # the stage finished between expired() and here, the async
        # exception would land wherever that worker thread is NEXT —
        # outside _execute's try, killing the worker and hanging the
        # fleet. (The remaining finish-vs-raise race is closed by the
        # worker loop's StageTimeout catch and the done_recorded
        # guard in _handle_failure.)
        if not self._hb.is_active(entry):
            telemetry.event("survey.late_interrupt", obs=obs.name,
                            stage=task.stage.name)
            return
        res = health_mod.interrupt_thread(entry.thread_id, exc)
        if res is health_mod.DEFERRED:
            # the stage currently holds a lockdep-tracked lock: an
            # async exception landing there could strand the lock or
            # tear a locked invariant. The verdict STANDS — re-arm the
            # entry so the next watchdog tick retries; delivery lands
            # at the first unlocked boundary (round 19 contract;
            # regression: tests/test_lockdep.py)
            self._hb.rearm(entry)
            telemetry.event("survey.interrupt_deferred", obs=obs.name,
                            stage=task.stage.name, reason=reason)
            return
        if not res:
            telemetry.event("survey.late_interrupt", obs=obs.name,
                            stage=task.stage.name)
            return
        with self._lock:
            self.result.timeouts += 1
        telemetry.counter("survey.watchdog_interrupts")
        telemetry.event(name, obs=obs.name, stage=task.stage.name,
                        after_s=round(after, 3))
        trace = self._traces[task.obs_i]
        if trace is not None:
            trace.event(name, stage=task.stage.name,
                        after_s=round(after, 3))
        if self.verbose:
            print(f"# survey: WATCHDOG {obs.name}: {task.stage.name} "
                  f"{reason} after {after:.1f}s; interrupting worker")
        self._postmortem(f"watchdog_{reason}", task.obs_i,
                         extra={"stage": task.stage.name,
                                "after_s": round(after, 3)})

    def _strike_leases(self, task: "_Task", err: Exception) -> None:
        """Charge the failed execution's leased chips when the error
        indicts the DEVICE (OOM that escaped in-stage halving, dead
        chip, failed collective, injected device fault). Eviction
        spares the last healthy lease — an empty pool is a hung fleet
        — and every verdict lands in the fleet-health JSON."""
        ids = task.last_dev_ids
        if not ids:
            return
        oom = is_oom_error(err)
        if not oom and not health_mod.is_device_fault(err):
            return
        kind = "oom" if oom else "device"
        # charge the REAL chips the execution was pinned to (the id
        # space `parallel.mesh` filters by); on an oversubscribed pool
        # a quarantined chip takes EVERY lease that maps to it
        reals = task.last_real_dev_ids \
            or [self._lease_real(i) for i in ids]
        evicted: List[int] = []
        for r in reals:
            allow = len(self._healthy_ids()) > 1
            if self._health.strike(r, kind=kind, error=str(err)[:200],
                                   allow_quarantine=allow):
                evicted.extend(i for i in range(self.devices)
                               if self._lease_real(i) == r)
        if evicted:
            with self._cv:
                self._free_ids.difference_update(evicted)
                self.result.evicted_devices.extend(evicted)
                self._cv.notify_all()
            telemetry.event("survey.device_evicted", devs=evicted,
                            stage=task.stage.name,
                            obs=self.obs[task.obs_i].name,
                            healthy=len(self._healthy_ids()))
            print(f"# survey: QUARANTINED device lease(s) {evicted} "
                  f"after {self._health.limit} strikes "
                  f"({type(err).__name__}); pool shrinks to "
                  f"{len(self._healthy_ids())} chips, gangs retry "
                  f"shrunk")
            self._postmortem("device_evicted", task.obs_i,
                             extra={"devices": evicted,
                                    "stage": task.stage.name})
        self._write_health_json()

    def _postmortem(self, reason: str, obs_i: Optional[int] = None,
                    extra: Optional[dict] = None) -> None:
        """Freeze the flight recorder into a capsule at a failure edge
        (quarantine, watchdog verdict, eviction, cede, crash): the last
        N telemetry records land under ``<outdir>/_fleet/postmortem/``
        so every QUARANTINED ``--status`` row has its explanation on
        disk even when ``--telemetry`` was off. Best-effort by
        construction (flightrec.dump never raises)."""
        if self._health_dir is None:
            return
        path = flightrec.dump(
            os.path.join(fleet_mod.plane_dir(self._health_dir),
                         "postmortem"),
            reason, host=self.host_id,
            obs=self.obs[obs_i].name if obs_i is not None else None,
            extra=extra)
        if path is not None and self.verbose:
            print(f"# survey: postmortem capsule {path}")

    def _write_health_json(self) -> None:
        """Mirror the per-device verdicts next to the manifests so
        ``survey --status`` (a different process, maybe much later)
        can render chip health alongside observation progress."""
        if self._health_dir is None:
            return
        snap = self._health.snapshot()
        hosts = (self._host_health.snapshot()
                 if self._host_health is not None else {})
        if not snap and not self.result.evicted_devices and not hosts:
            return
        payload = {
            "pool": self.devices,
            "strike_limit": self._health.limit,
            "devices": {str(i): v for i, v in snap.items()},
        }
        if hosts:
            payload["hosts"] = hosts
            payload["host_strike_limit"] = self._host_health.limit
        write_fleet_health(self._health_dir, payload)

    def _wait_admission(self) -> None:
        """Block until the resource gate admits new work (or the fleet
        stops). Pauses are episodes: one ``survey.admission_paused``
        event when the gate first refuses, one ``..._resumed`` when it
        clears — not one per poll."""
        reason = self._guard.admit()
        if reason is None:
            return
        with self._lock:
            first = not self._admission_blocked
            self._admission_blocked = True
        if first:
            telemetry.counter("survey.admission_pauses")
            telemetry.event("survey.admission_paused", reason=reason)
            print(f"# survey: admission paused ({reason}); in-flight "
                  f"stages continue, new launches wait")
        while not self._stop:
            time.sleep(0.2)
            reason = self._guard.admit()
            if reason is None:
                with self._lock:
                    self._admission_blocked = False
                telemetry.event("survey.admission_resumed")
                return

    # -- execution ----------------------------------------------------------

    def _execute(self, task: _Task, gang: int = 1,
                 dev_ids: Optional[List[int]] = None) -> None:
        obs = self.obs[task.obs_i]
        stage = task.stage
        if task.obs_i in self._verify_input \
                and not os.path.exists(obs.infile):
            # a daemon-accepted source that vanished between admit and
            # stage start (mover rolled it back, tenant deleted it): a
            # LOUD data-quarantine — re-transfer territory, not a crash
            # and not a retry loop burning attempts on ENOENT
            self._quarantine_data(
                task.obs_i,
                f"input file vanished after admission: {obs.infile}")
            return
        tid = (self._trace_ids[task.obs_i]
               if task.obs_i < len(self._trace_ids) else None)
        budget = self._deadline_for(stage, obs)
        span_attrs = {"obs": obs.name}
        if self.host_id is not None:
            span_attrs["host"] = self.host_id
        if dev_ids is not None:
            span_attrs["dev"] = dev_ids
        if gang > 1:
            span_attrs["gang"] = gang
        if budget is not None:
            # the SLO denominator, carried ON the span so tlmsum can
            # account burn from the trace alone
            span_attrs["budget_s"] = round(float(budget), 3)
        adopted_src = self._adopted_from.pop(task.obs_i, None)
        if adopted_src is not None:
            span_attrs["adopted_from"] = adopted_src
        t_rel = time.perf_counter() - self._t0
        t0 = time.perf_counter()
        # liveness entry: the watchdog interrupts this thread on
        # deadline/stall; any telemetry the stage records (spans,
        # counters — chunk cadence on every hot path) beats it. The
        # entry covers the stage_start/stage_done fault boundaries and
        # the manifest append too — a hang at a boundary must not sleep
        # in a window the watchdog cannot see (it holds the lease).
        task.done_recorded = False
        hb = self._hb.start(f"{obs.name}:{stage.name}",
                            deadline_s=budget,
                            stall_s=self.stall_s, payload=task,
                            obs=obs.name, stage=stage.name,
                            trace_id=tid)
        sp_sid = None
        try:
            faultinject.trip("survey.stage_start")
            faultinject.trip(f"survey.stage_start.{stage.name}")
            # the stage span is its trace's ROOT (parent_id unset): every
            # span the stage's kernels record nests under it, and helper
            # threads adopt the context so their beats land on this
            # heartbeat entry (the round-21 attribution fix)
            with telemetry.trace_context(trace_id=tid, obs=obs.name,
                                         stage=stage.name):
                telemetry.counter("survey.stages_run")
                with telemetry.span(f"survey.stage.{stage.name}",
                                    **span_attrs) as sp:
                    stage.execute(obs, self.cfg, gang=gang)
                if sp is not None:
                    sp_sid = getattr(sp, "sid", None)
            dur = time.perf_counter() - t0
            faultinject.trip("survey.stage_done")
            faultinject.trip(f"survey.stage_done.{stage.name}")
            outputs = stage.outputs(obs, self.cfg)
            self._manifests[task.obs_i].mark_done(stage.name, outputs)
            task.done_recorded = True
        finally:
            self._hb.finish(hb)
        slo_burn = (budget is not None and budget > 0
                    and dur > self._slo_frac * float(budget))
        if slo_burn:
            # consumed most of the watchdog budget WITHOUT tripping it:
            # the early warning that a deadline is about to start
            # costing retries
            telemetry.counter("survey.slo_burns")
            telemetry.event("survey.slo_burn", obs=obs.name,
                            stage=stage.name,
                            budget_s=round(float(budget), 3),
                            frac=round(dur / float(budget), 3))
            # SLO burn gates batching: collapse the broker's coalesce
            # window so latency-critical work dispatches immediately
            # instead of widening batches (round 24)
            broker_mod.note_pressure(f"slo_burn:{stage.name}")
        trace = self._traces[task.obs_i]
        if trace is not None:
            tr_attrs = {"outputs": len(outputs)}
            if self.host_id is not None:
                tr_attrs["host"] = self.host_id
            if dev_ids is not None:
                tr_attrs["dev"] = dev_ids
            if gang > 1:
                tr_attrs["gang"] = gang
            if budget is not None:
                tr_attrs["budget_s"] = round(float(budget), 3)
            if adopted_src is not None:
                tr_attrs["adopted_from"] = adopted_src
            trace.span(f"survey.stage.{stage.name}", t_rel, dur,
                       span_id=sp_sid, **tr_attrs)
            if slo_burn:
                trace.event("survey.slo_burn", stage=stage.name,
                            frac=round(dur / float(budget), 3))
        if self.verbose:
            print(f"# survey: {obs.name}: {stage.name} done "
                  f"({dur:.2f}s, {len(outputs)} artifacts"
                  + (f", gang x{gang} on chips {dev_ids}"
                     if gang > 1 else "") + ")")
        with self._cv:
            task.state = _DONE
            if stage.device_bound:
                # the measured per-stage cost the auto-gang policy
                # consults (same numbers the obs trace records)
                ent = self._stage_cost.setdefault(stage.name, [0.0, 0])
                ent[0] += dur
                ent[1] += 1
            self.result.ran.append((obs.name, stage.name))
            self._promote_locked(task.obs_i)
            obs_complete = all(
                self._tasks[(task.obs_i, s.name)].state == _DONE
                for s in self.stages)
            self._maybe_stop_locked()
            self._cv.notify_all()
        if obs_complete:
            # close the claim out so other hosts read this observation
            # terminal instead of waiting on our heartbeat forever
            self._plane_mark_terminal(task.obs_i, "done")

    def _requeue_retry(self, task: _Task) -> None:
        """Timer callback re-enqueuing a backing-off task — unless its
        observation was quarantined, ceded to an adopter, or the fleet
        stopped while it waited: a retry must not resurrect a stage
        this host no longer owns."""
        with self._cv:
            if self._stop or task.state in (_QUARANTINED, _REMOTE):
                return
            if self.plane is not None and task.obs_i not in self._owned:
                return
            self._enqueue_locked(task)
            self._cv.notify_all()

    def _handle_failure(self, task: _Task, err: Exception) -> None:
        obs = self.obs[task.obs_i]
        stage = task.stage
        if self.plane is not None \
                and isinstance(err, fleet_mod.StaleLeaseError):
            # host-aware failure policy: a stale fencing token is not a
            # stage failure — a survivor adopted the observation while
            # this host was stalled/presumed dead. Cede it: no retry
            # (the adopter is already running it), no quarantine (the
            # observation is healthy), no device strike (the chip did
            # nothing wrong).
            self._cede_obs(task.obs_i)
            return
        with self._lock:
            if task.state == _QUARANTINED:
                # another stage of this observation quarantined it while
                # this one was running: its failure is already verdict
                return
            if task.state == _DONE:
                # a watchdog interrupt that landed AFTER the stage
                # completed (the unavoidable async-exc race window):
                # the work is done and recorded; nothing to retry
                telemetry.event("survey.late_interrupt", obs=obs.name,
                                stage=stage.name)
                return
        if task.done_recorded:
            # the interrupt landed between the manifest's done record
            # and the task-state update in _execute's tail: the work
            # IS complete — finish the task instead of re-running (or
            # phantom-quarantining) a stage whose artifacts validate
            telemetry.event("survey.late_interrupt", obs=obs.name,
                            stage=stage.name)
            with self._cv:
                if task.state != _DONE:
                    task.state = _DONE
                    self.result.ran.append((obs.name, stage.name))
                    self._promote_locked(task.obs_i)
                    self._maybe_stop_locked()
                    self._cv.notify_all()
            return
        self._strike_leases(task, err)
        error = f"{type(err).__name__}: {err}"
        task.last_error = error
        telemetry.counter("survey.stage_failures")
        telemetry.event("survey.stage_failed", obs=obs.name,
                        stage=stage.name, error=type(err).__name__)
        if task.attempts < self.retries:
            task.attempts += 1
            self.result.retried += 1
            delay = backoff_delay(RETRY_BACKOFF_BASE_S, task.attempts,
                                  RETRY_BACKOFF_MAX_S, self.jitter_rng)
            # the attempt + error excerpt land in the manifest so
            # --status (any process, any time) can show WHY a stage is
            # retrying, not just that it is slow
            try:
                self._manifests[task.obs_i].note_retry(
                    stage.name, task.attempts, error)
            except fleet_mod.StaleLeaseError:
                # adopted away between the failure and its verdict:
                # the retry belongs to the new owner
                self._cede_obs(task.obs_i)
                return
            telemetry.event("survey.stage_retry", obs=obs.name,
                            stage=stage.name, attempt=task.attempts)
            if self.verbose:
                print(f"# survey: {obs.name}: {stage.name} failed "
                      f"({type(err).__name__}: {err}); retry "
                      f"{task.attempts}/{self.retries} in {delay:.2f}s")
            # re-enqueue from a timer, not this worker: the backoff must
            # not hold the device lease / host slot idle. The fleet
            # cannot finish early — the task stays non-terminal until
            # the timer fires and the retry settles.
            timer = threading.Timer(delay, self._requeue_retry, (task,))
            timer.daemon = True
            timer.start()
            return
        # bounded retries exhausted: quarantine the OBSERVATION — the
        # fleet continues, the verdict is recorded, and a later resume
        # may try again (the operator explicitly asked)
        try:
            self._manifests[task.obs_i].quarantine(stage.name, error)
        except fleet_mod.StaleLeaseError:
            # the adopter owns the observation (and its verdicts) now
            self._cede_obs(task.obs_i)
            return
        telemetry.event("survey.quarantine", obs=obs.name,
                        stage=stage.name, error=type(err).__name__)
        trace = self._traces[task.obs_i]
        if trace is not None:
            trace.event("survey.quarantine", stage=stage.name)
        print(f"# survey: QUARANTINED {obs.name} at {stage.name}: {error} "
              f"(fleet continues)")
        self._postmortem("quarantine", task.obs_i,
                         extra={"stage": stage.name, "error": error})
        with self._cv:
            for s in self.stages:
                t = self._tasks[(task.obs_i, s.name)]
                if t.state != _DONE:
                    t.state = _QUARANTINED
            self.result.quarantined[obs.name] = {"stage": stage.name,
                                                 "error": error}
            self._maybe_stop_locked()
            self._cv.notify_all()
        self._plane_mark_terminal(task.obs_i, "quarantined")

    # -- gang leases --------------------------------------------------------

    def _gang_size(self, task: _Task) -> Tuple[int, str]:
        """(k, reason) — how many chips THIS execution gets. Fixed
        ``gang`` pins k; ``"auto"`` picks fleet-parallel while enough
        ready device-bound stages exist to fill the chips and widens a
        gang-able stage onto idle chips otherwise, gated by the
        measured per-stage cost share (see GANG_COST_MIN_FRAC)."""
        stage = task.stage
        gmax = min(int(getattr(stage, "devices_max", 1)), self.devices)
        njax = self._n_jax_devices()
        if njax is not None:
            # a gang mesh needs k DISTINCT chips; an oversubscribed
            # lease pool (--devices > real devices) may only widen up
            # to the real count
            gmax = min(gmax, njax)
        # a quarantined chip is out of the pool: gangs SHRINK to the
        # surviving leases (placement is not science — artifacts stay
        # byte-identical at the new width)
        healthy = len(self._healthy_ids())
        if healthy < self.devices:
            gmax = min(gmax, max(1, healthy))
        if gmax <= 1:
            return 1, ("single-device stage" if healthy >= self.devices
                       else f"shrunk to {healthy} healthy chip(s)")
        if self.gang != "auto":
            k = min(int(self.gang), gmax)
            reason = f"fixed --gang {self.gang}"
            if k < int(self.gang):
                reason += f" shrunk to {k} ({healthy} healthy chips)"
            return k, reason
        with self._lock:
            other_ready = sum(
                1 for t in self._tasks.values()
                if t is not task and t.stage.device_bound
                and t.state in (_QUEUED, _RUNNING))
            cost = {n: c[0] / max(c[1], 1)
                    for n, c in self._stage_cost.items() if c[1]}
        idle = self.devices - 1 - other_ready
        if idle <= 0:
            return 1, (f"fleet-parallel: {other_ready} other ready "
                       f"device stages fill the {self.devices} chips")
        k = min(gmax, 1 + idle)
        total = sum(cost.values())
        mine = cost.get(stage.name)
        if mine is not None and total > 0:
            frac = mine / total
            if frac < GANG_COST_MIN_FRAC:
                return 1, (f"measured {stage.name} cost share "
                           f"{frac:.0%} < {GANG_COST_MIN_FRAC:.0%} of "
                           f"the device chain: gang not worth it")
            return k, (f"gang x{k}: {idle} idle chips and "
                       f"{stage.name} owns {frac:.0%} of the measured "
                       f"device chain")
        return k, f"gang x{k}: {idle} idle chips, cost unmeasured yet"

    def _acquire_devices(self, k: int) -> Optional[List[int]]:
        """Block until k lease ids are free and claim them. FIFO with
        full reservation: an older waiting claim reserves freed chips
        (up to its need) before any younger claim may take them, so a
        wide gang cannot starve behind 1-chip traffic. Returns None
        when the fleet is unwinding (fatal).

        The claim SHRINKS if devices are quarantined while it waits —
        a gang asking for chips that no longer exist must retry at the
        surviving width, not park forever (``need`` is a mutable cell
        so older claims' reservations shrink with them)."""
        ticket = object()
        need = [k]
        with self._cv:
            self._claims.append((ticket, need))
            try:
                while True:
                    if self._stop and self._fatal is not None:
                        return None
                    need[0] = min(need[0],
                                  max(1, len(self._healthy_ids())))
                    rem = len(self._free_ids)
                    grant = False
                    for t, n in self._claims:
                        if t is ticket:
                            grant = rem >= need[0]
                            break
                        rem -= min(n[0], rem)  # older claims reserve
                    if grant:
                        ids = sorted(self._free_ids)[:need[0]]
                        self._free_ids.difference_update(ids)
                        return ids
                    self._cv.wait(0.1)
            finally:
                self._claims.remove((ticket, need))

    def _release_devices(self, ids: List[int]) -> None:
        with self._cv:
            # a lease quarantined while this execution held it never
            # returns to the pool
            self._free_ids.update(
                i for i in ids
                if not self._health.is_quarantined(self._lease_real(i)))
            self._cv.notify_all()

    def _n_jax_devices(self) -> Optional[int]:
        """Real JAX device count, cached; None without a backend."""
        if self._njax is _UNSET:
            try:
                import jax

                self._njax = len(jax.local_devices())
            except Exception:  # noqa: BLE001 - no backend
                self._njax = None
        return self._njax

    def _jax_gang(self, ids: List[int]) -> Optional[list]:
        """The JAX devices backing lease ids, or None when no binding
        is needed. With one lease (the default) the process default
        device already IS the lease; with several, the stage pins via
        ``jax.default_device`` + ``parallel.mesh.device_lease`` so k
        leases really are k chips, not k-fold oversubscription of
        device 0. Guarded: a jax-less run (stub DAGs) skips binding.

        Lease ids wrap modulo the real device count (an oversubscribed
        pool is legal for 1-chip fleet placement), but a GANG mesh must
        hold distinct chips — colliding ids are bumped to the next free
        device; ``_gang_size`` caps k at the real count so a solution
        always exists."""
        if self.devices <= 1:
            return None
        try:
            import jax

            devs = jax.local_devices()
        except Exception:  # noqa: BLE001 - no backend: nothing to pin
            return None
        n = len(devs)
        if len(ids) > 1:
            if len(ids) > n:
                raise ValueError(
                    f"gang of {len(ids)} leases needs {len(ids)} distinct "
                    f"devices but only {n} exist")
            picked: List[int] = []
            used: set = set()
            for i in ids:
                j = i % n
                while j in used:
                    j = (j + 1) % n
                used.add(j)
                picked.append(j)
            return [devs[j] for j in picked]
        return [devs[i % n] for i in ids]

    def _run_device_task(self, task: _Task) -> None:
        """One device-lane execution: decide the gang shape, take the
        lease(s), record the placement decision, run pinned."""
        obs = self.obs[task.obs_i]
        k, reason = self._gang_size(task)
        ids = self._acquire_devices(k)
        if ids is None:  # fleet unwinding while we waited
            return
        if len(ids) < k:  # pool shrank while waiting: gang shrinks too
            k = len(ids)
            reason += f"; shrunk to {k} while waiting"
        task.last_dev_ids = list(ids)
        task.last_real_dev_ids = None
        try:
            telemetry.event("survey.gang_decision", obs=obs.name,
                            stage=task.stage.name, k=k, chips=ids,
                            reason=reason)
            trace = self._traces[task.obs_i]
            if trace is not None:
                trace.event("survey.gang_decision", stage=task.stage.name,
                            k=k, chips=ids, reason=reason)
            gang_devs = self._jax_gang(ids)
            if gang_devs is not None:
                task.last_real_dev_ids = [
                    int(getattr(d, "id", i))
                    for i, d in zip(ids, gang_devs)]
            mates = self._claim_lane_mates(task, k)
            if gang_devs is not None:
                import jax

                from pypulsar_tpu.parallel.mesh import device_lease

                with jax.default_device(gang_devs[0]), \
                        device_lease(gang_devs):
                    self._run_lane(task, mates, k, ids, pinned=True)
            else:
                self._run_lane(task, mates, k, ids, pinned=False)
        finally:
            self._release_devices(ids)

    def _claim_lane_mates(self, task: _Task, k: int) -> List[_Task]:
        """Round 24 batch lanes.  A single-chip lease taken for a
        broker-submitting stage widens into a *batch lane*: it claims up
        to ``PYPULSAR_TPU_BROKER_LANE - 1`` queued same-stage tasks and
        runs them concurrently UNDER THIS LEASE, so their device
        dispatches meet in the batch broker and fuse instead of
        serializing on separate exclusive leases.  Claims are skipped
        for gangs (k > 1), non-broker stages, when the broker/lanes are
        off, and whenever the resource guard is refusing launches."""
        if k != 1 or task.stage.name not in _BROKER_UNITS:
            return []
        if not broker_mod.enabled() or broker_mod.lane_width() <= 1:
            return []
        if self._guard.admit() is not None:
            return []  # under resource pressure: no extra tenants
        width = broker_mod.lane_width()
        mates: List[_Task] = []
        with self._lock:
            if self._stop:
                return []
            for t in self._tasks.values():
                if len(mates) >= width - 1:
                    break
                if t is task or t.state != _QUEUED:
                    continue
                if t.stage.name != task.stage.name:
                    continue
                if self.plane is not None and t.obs_i not in self._owned:
                    continue
                # claim: run out of band, leave a stale queue entry
                # that _worker_step consumes by seq match
                t.state = _RUNNING
                t.lane_seq = t.seq
                mates.append(t)
        return mates

    def _run_lane(self, task: _Task, mates: List[_Task], k: int,
                  ids: List[int], *, pinned: bool) -> None:
        """Execute the leader task, plus any lane mates in sibling
        threads that re-enter the leader's device pin + lease.  All
        lane members register as broker parties for the stage's unit
        kind *before* any of them runs, so the first submitter's batch
        window knows how many peers to wait for; each member withdraws
        its party as it finishes so trailing uneven batches never stall
        on departed peers."""
        dev_ids = ids if pinned else None
        if not mates:
            self._execute(task, gang=k, dev_ids=dev_ids)
            return
        # scope must be computed inside the pinned context so leader
        # and mates (which re-enter the same lease) key identically
        party = (_BROKER_UNITS[task.stage.name], broker_mod.device_scope())
        bk = broker_mod.get_broker()
        names = [self.obs[t.obs_i].name for t in mates]
        telemetry.counter("broker.lane_grants", len(mates))
        telemetry.event("survey.lane_decision", stage=task.stage.name,
                        leader=self.obs[task.obs_i].name, mates=names,
                        width=1 + len(mates), chips=ids)
        # pre-register every member (leader included) before anything
        # executes: closes the race where the leader submits before a
        # mate thread has spun up and the batch dispatches solo
        for _ in range(1 + len(mates)):
            bk._party_enter(party)

        def _mate_body(t: _Task) -> None:
            try:
                try:
                    if pinned:
                        import jax

                        from pypulsar_tpu.parallel.mesh import device_lease

                        gang_devs = self._jax_gang(ids)
                        with jax.default_device(gang_devs[0]), \
                                device_lease(gang_devs):
                            self._execute(t, gang=k, dev_ids=dev_ids)
                    else:
                        self._execute(t, gang=k)
                finally:
                    bk._party_exit(party)
            except Exception as e:  # stage failure: normal retry path
                self._handle_failure(t, e)
            except BaseException as e:  # injected kill etc: fleet-fatal
                with self._cv:
                    if self._fatal is None:
                        self._fatal = e
                    self._stop = True
                    self._cv.notify_all()

        threads = []
        for t in mates:
            th = threading.Thread(
                target=_mate_body, args=(t,), daemon=True,
                name=f"lane-{self.obs[t.obs_i].name}-{t.stage.name}")
            th.start()
            threads.append(th)
        try:
            try:
                self._execute(task, gang=k, dev_ids=dev_ids)
            finally:
                bk._party_exit(party)
        finally:
            for th in threads:
                th.join()

    def _worker(self, q: "queue.PriorityQueue",
                device_lane: bool = False) -> None:
        while True:
            try:
                self._worker_step(q, device_lane)
            except StopIteration:
                return
            except health_mod.StageTimeout:
                # an async watchdog interrupt that lost the race with
                # stage completion and landed between tasks: the
                # verdict was already withdrawn (late_interrupt); the
                # worker must survive, or its queue lane dies and the
                # fleet hangs
                telemetry.event("survey.late_interrupt")

    def _worker_step(self, q: "queue.PriorityQueue",
                     device_lane: bool) -> None:
        """One take-a-task-and-run-it iteration; raises StopIteration
        to shut the worker down."""
        try:
            _, seq, task = q.get(timeout=0.05)
        except queue.Empty:
            if self._stop:
                raise StopIteration
            return
        # resource preflight: low disk / backpressure pauses the
        # LAUNCH of this stage (in-flight work keeps running and is
        # what frees the resource); re-checked after the pause
        self._wait_admission()
        with self._lock:
            if self._stop and self._fatal is not None:
                return  # fleet is unwinding: drop queued work
            if task.seq != seq:
                # the task was re-enqueued since this entry was put
                # (lane-claimed then retried): a younger entry owns it
                return
            if task.lane_seq == seq:
                # a batch lane ran (or is running) this task out of
                # band: this is its stale queue entry — consume it
                task.lane_seq = None
                return
            if task.state in (_QUARANTINED, _REMOTE):
                return  # cancelled / finished remotely while queued
            if self.plane is not None \
                    and task.obs_i not in self._owned:
                return  # ceded while queued: the adopter runs it
            task.state = _RUNNING
        try:
            if device_lane:
                self._run_device_task(task)
            else:
                self._execute(task)
        except Exception as e:  # noqa: BLE001 - retry/quarantine policy
            self._handle_failure(task, e)
        except BaseException as e:  # injected kill / interrupt
            with self._cv:
                if self._fatal is None:
                    self._fatal = e
                self._stop = True
                self._cv.notify_all()
            raise StopIteration

    # -- warm-pool precompile (round 22) ------------------------------------

    def _obs_geometry(self, i: int) -> Optional[dict]:
        """One observation's stage geometry for the compile plane's
        warmers: the raw header (channel table, sample time, length)
        plus the fleet config's grid — everything a warmer needs to
        rebuild the shapes its stage will dispatch. None when the
        header cannot be read (the stage machinery owns that error)."""
        from pypulsar_tpu.cli.sweep import _open_reader

        import numpy as np

        cfg = self.cfg
        try:
            r = _open_reader(self.obs[i].infile)
            try:
                freqs = np.asarray(r.frequencies, dtype=np.float64)
                tsamp = float(r.tsamp)
                nsamp = int(getattr(r, "number_of_samples", 0)
                            or getattr(r, "nsamples", 0) or 0)
            finally:
                close = getattr(r, "close", None)
                if close is not None:
                    close()
        except Exception:  # noqa: BLE001 - warm pool never fails a fleet
            return None
        return dict(
            dms=cfg.lodm + cfg.dmstep * np.arange(max(1, cfg.numdms)),
            freqs=freqs, dt=tsamp, n_samples=nsamp,
            downsamp=max(1, cfg.downsamp), nsub=cfg.nsub,
            group_size=cfg.group_size, chunk_payload=cfg.chunk,
            fold_nbins=cfg.fold_nbins, fold_npart=cfg.fold_npart,
            fold_batch=cfg.fold_batch)

    def _warmpool_loop(self) -> None:
        """Host-pool precompile daemon: while the devices chew on the
        current observations, AOT-compile the next ready observation's
        (stage, geometry) set through the compile plane's registered
        warmers, so its first dispatch finds a ready executable instead
        of a trace+compile stall on the critical path. Purely an
        optimization: every failure is swallowed (counted by the plane
        as ``compile.warm_error``) and the loop exits once every
        observation is warmed or already running."""
        import pypulsar_tpu.fold.engine  # noqa: F401 - registers warmers
        import pypulsar_tpu.parallel.sweep  # noqa: F401
        from pypulsar_tpu.compile import warm_stage, warmable_stages

        warmed: set = set()
        while not self._stop:
            target = None
            with self._lock:
                for i in range(len(self.obs)):
                    if i in warmed:
                        continue
                    states = [self._tasks[(i, s.name)].state
                              for s in self.stages]
                    if all(st in (_DONE, _QUARANTINED, _REMOTE)
                           for st in states):
                        warmed.add(i)  # nothing left to warm for
                        continue
                    if any(st == _RUNNING for st in states):
                        warmed.add(i)  # too late: already on a device
                        continue
                    target = i
                    break
            if target is None:
                return  # every observation warmed or started
            warmed.add(target)
            geo = self._obs_geometry(target)
            if geo is None:
                continue
            obs = self.obs[target]
            t_rel = time.perf_counter() - self._t0
            t0 = time.perf_counter()
            n = 0
            with telemetry.span("survey.precompile", obs=obs.name):
                for stage in warmable_stages():
                    if self._stop:
                        break
                    n += warm_stage(stage, **geo)
            dur = time.perf_counter() - t0
            telemetry.counter("survey.precompiled", n)
            trace = self._traces[target]
            if trace is not None:
                trace.span("survey.precompile", t_rel, dur, compiled=n)
            if self.verbose and n:
                print(f"# survey: {obs.name}: warm pool precompiled "
                      f"{n} executable(s) in {dur:.2f}s")

    # -- entry point --------------------------------------------------------

    def run(self) -> FleetResult:
        """Run the fleet to completion (or first fatal error). Returns
        the :class:`FleetResult`; re-raises a BaseException (injected
        kill, KeyboardInterrupt) after the in-flight stages settle."""
        self._t0 = time.perf_counter()
        self._open_manifests()
        if self.plane is not None:
            if self.plane.token is None:
                self.plane.register()
                self._plane_owned_here = True
        else:
            self._validate_ingest()
        if self._needs_watchdog():
            # heartbeats ride the telemetry the stages already record;
            # the hook is process-global, so it is installed only for
            # the run and removed in the finally below
            telemetry.add_activity_hook(self._hb.beat)
            self._watchdog = health_mod.Watchdog(self._hb,
                                                 self._on_stage_expired)
            self._watchdog.start()
        try:
            if self.plane is not None:
                # multi-host: nothing is pre-assigned — the claim loop
                # admits observations as it wins their leases (and
                # adopts orphans as hosts die); an initial tick before
                # the workers start gives them something to chew on
                self._plane_poll()
                self._claim_thread = threading.Thread(
                    target=self._plane_loop,
                    name=f"survey-claims-{self.host_id}", daemon=True)
                self._claim_thread.start()
            else:
                with self._cv:
                    for i in range(len(self.obs)):
                        done = (self._manifests[i].done_stages()
                                if self.resume else set())
                        for s in self.stages:
                            if s.name in done:
                                self._tasks[(i, s.name)].state = _DONE
                                self.result.skipped.append(
                                    (self.obs[i].name, s.name))
                                telemetry.counter("survey.stages_skipped")
                        self._promote_locked(i)
                    self._maybe_stop_locked()
            self._ready.set()
            if knobs_mod.env_str("PYPULSAR_TPU_COMPILE_WARMPOOL") \
                    not in ("0", "off", "none"):
                # warm-pool precompile rides the host pool's spare
                # cycles; a daemon so a hung compile cannot block exit
                self._warm_thread = threading.Thread(
                    target=self._warmpool_loop, name="survey-warmpool",
                    daemon=True)
                self._warm_thread.start()
            workers = (
                [threading.Thread(target=self._worker,
                                  args=(self._device_q, True),
                                  name=f"survey-device{d}")
                 for d in range(self.devices)]
                + [threading.Thread(target=self._worker,
                                    args=(self._host_q,),
                                    name=f"survey-host{h}")
                   for h in range(self.max_host_workers)])
            for w in workers:
                w.start()
            try:
                with self._cv:
                    while not self._stop:
                        self._cv.wait(0.1)
            except BaseException as e:  # Ctrl+C lands HERE, not in a worker
                # stop + fatal so workers drop queued work (and an
                # admission-paused worker wakes) instead of polling
                # forever under a join() that never returns
                with self._cv:
                    if self._fatal is None:
                        self._fatal = e
                    self._stop = True
                    self._cv.notify_all()
            for w in workers:
                w.join()
        finally:
            self._ready.set()  # never leave a service waiter hanging
            if self._watchdog is not None:
                self._watchdog.stop()
                self._watchdog = None
                telemetry.remove_activity_hook(self._hb.beat)
            if self._claim_thread is not None:
                self._claim_thread.join(timeout=5.0)
                self._claim_thread = None
            if self._warm_thread is not None:
                self._warm_thread.join(timeout=5.0)
                self._warm_thread = None
            self._write_health_json()
            self.result.wall = time.perf_counter() - self._t0
            for m in self._manifests:
                if m is not None:
                    m.close()
            for t in self._traces:
                if t is not None:
                    t.close()
            if self.plane is not None and self._plane_owned_here:
                # retire the host lease (LEFT, not DEAD). An InjectedKill
                # unwinds through here too — its lease reads LEFT with
                # claims still running, which is equally adoptable; only
                # a true SIGKILL/os._exit skips this and leaves the
                # lease to go silent (DEAD after the lease bound)
                self.plane.close()
        if self._fatal is not None:
            # the capsule for the run that ended in a bang: the last N
            # telemetry records before the unhandled crash/interrupt
            self._postmortem(
                "crash",
                extra={"error": f"{type(self._fatal).__name__}: "
                                f"{self._fatal}"})
            raise self._fatal
        return self.result
