"""Fleet state: per-observation manifests, status views, obs traces.

One observation's progress through the stage DAG is a fingerprinted
``resilience.journal.RunJournal`` (tool ``"survey"``) living next to its
artifacts: every completed stage appends one ``done`` record naming its
output artifacts with size + sha256 (fsync'd, torn-tail tolerant), so a
``kill -9`` mid-fleet followed by ``survey --resume`` replans from what
actually validates on disk — a stage whose artifacts were truncated,
deleted or half-written is redone, never trusted. Rerunning under
different stage parameters changes the fingerprint and restarts the
manifest instead of skipping against stale artifacts (the same contract
the sweep chain journal enforces).

The module also holds the read-only views the ``survey --status`` table
renders (raw, fingerprint-agnostic manifest parsing: status must work on
a manifest written by a run with parameters this process does not know)
and :class:`ObsTrace`, the per-observation JSONL trace writer whose
records use the telemetry schema so ``tlmsum`` — including its fleet
roll-up mode — summarizes obs traces and the fleet trace alike.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

from pypulsar_tpu.resilience.journal import RunJournal, atomic_write_text
from pypulsar_tpu.resilience.locks import TrackedLock

__all__ = [
    "ObsManifest",
    "ObsTrace",
    "Observation",
    "fleet_fingerprint",
    "fleet_health_path",
    "format_status",
    "load_manifest_records",
    "manifest_path",
    "read_fleet_health",
    "status_rows",
    "write_fleet_health",
]

MANIFEST_SUFFIX = ".survey.jsonl"

# per-device health mirror next to the manifests (see write_fleet_health)
FLEET_HEALTH_NAME = "_fleet_health.json"

# --status truncates last-error excerpts to this many characters: the
# table must stay a table, the full string is in the manifest
ERROR_EXCERPT_LEN = 60


def fleet_health_path(outdir: str) -> str:
    return os.path.join(outdir, FLEET_HEALTH_NAME)


def write_fleet_health(outdir: str, payload: Dict) -> None:
    """Atomically mirror the scheduler's per-device strike/quarantine
    verdicts to ``<outdir>/_fleet_health.json`` so ``survey --status``
    (a different process, maybe much later) renders chip health next to
    observation progress. Observability is a passenger: an unwritable
    outdir drops the mirror, never the fleet."""
    try:
        atomic_write_text(fleet_health_path(outdir),
                          json.dumps(payload, indent=2, sort_keys=True)
                          + "\n")
    except OSError:
        pass


def read_fleet_health(outdir: str) -> Optional[Dict]:
    """The last fleet-health mirror under ``outdir``, or None (no file,
    torn file — the writer is atomic, so torn means not ours)."""
    try:
        with open(fleet_health_path(outdir)) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


@dataclass(frozen=True)
class Observation:
    """One fleet member: a raw file plus the basename its whole artifact
    chain (mask, .cands, .dat/.cand trails, .accelcands, .pfd, SNR
    summary, manifest) is rooted at."""

    name: str
    infile: str
    outbase: str

    @property
    def manifest(self) -> str:
        return manifest_path(self.outbase)


def manifest_path(outbase: str) -> str:
    return outbase + MANIFEST_SUFFIX


def fleet_fingerprint(obs: Observation, cfg, stage_names: Sequence[str]) -> str:
    """Hash of everything that determines one observation's artifacts:
    the input file (path + size + mtime — a replaced raw file, even a
    same-size regeneration, must redo, not skip), the stage list, and
    the full stage configuration. Matches the sweep-journal contract: a
    manifest written under other parameters is restarted, never
    resumed."""
    h = hashlib.sha256()
    h.update(obs.infile.encode() + b"\0" + obs.outbase.encode() + b"\0")
    try:
        st = os.stat(obs.infile)
        h.update(f"{st.st_size}:{st.st_mtime_ns}".encode())
    except OSError:
        h.update(b"missing")
    h.update(("|".join(stage_names)).encode())
    if cfg is not None:
        for key in sorted(vars(cfg)):
            h.update(f"{key}={vars(cfg)[key]!r};".encode())
    return h.hexdigest()


class ObsManifest:
    """One observation's stage journal (see module docstring). Unit ids
    are ``stage:<name>``; free-form notes record the plan (for --status)
    and quarantine verdicts.

    Multi-host fleets (round 18) open the manifest with a fencing
    ``token`` and a ``fence`` callable: every append consults the fence
    FIRST (it raises ``survey.fleet.StaleLeaseError`` when a survivor
    adopted the observation — the dead host's late write becomes a
    no-op), records carry the token, and the underlying journal runs in
    its shared/append-only discipline so successive owners append to one
    file without stepping on each other's offsets."""

    def __init__(self, path: str, fingerprint: str,
                 token: Optional[int] = None, fence=None):
        # ALWAYS the shared/append-tolerant journal discipline, not just
        # under a plane: a single-host `--resume` must be able to read a
        # manifest a multi-host fleet wrote (interior torn line from a
        # SIGKILL'd owner, later owners appended past it) — the reader
        # cannot know who wrote the file
        self._journal = RunJournal(path, fingerprint, tool="survey",
                                   shared=True)
        self._lock = TrackedLock("survey.manifest")
        self.path = path
        self.token = token
        self._fence = fence
        # captured BEFORE any write: a fresh manifest (new file, or a
        # restart after a parameter/input change) means the chain starts
        # over and stale artifacts must be scrubbed, not globbed up
        self.fresh = self._journal.is_fresh()

    def _check_fence(self) -> None:
        """The write gate: a stale fencing token must be rejected BEFORE
        the append touches the file (outside the manifest lock — the
        fence reads the claim file and may raise)."""
        if self._fence is not None:
            self._fence()

    def plan(self, obs: Observation, stage_names: Sequence[str]) -> None:
        """Record the planned stage list once per fresh manifest — the
        denominator the --status table renders without re-deriving the
        DAG (a resumed manifest already carries it)."""
        self._check_fence()
        with self._lock:
            if not self._journal.notes(event="plan"):
                self._journal.note(event="plan", obs=obs.name,
                                   infile=obs.infile,
                                   stages=list(stage_names))

    def done_stages(self, validate: bool = True) -> set:
        """Stage names recorded done whose artifacts (still) validate."""
        with self._lock:
            units = self._journal.completed(validate=validate)
        return {u.split(":", 1)[1] for u in units if u.startswith("stage:")}

    def mark_done(self, stage: str, outputs: Iterable[str]) -> None:
        self._check_fence()
        extra = {"token": self.token} if self.token is not None else {}
        with self._lock:
            self._journal.done(f"stage:{stage}", outputs, **extra)

    def quarantine(self, stage: str, error: str,
                   reason: Optional[str] = None) -> None:
        """``reason="data"`` marks an INPUT verdict (ingest validation,
        --max-bad-frac) as distinct from a runtime quarantine — the
        operator's fix is a re-transfer, not a retry."""
        self._check_fence()
        with self._lock:
            rec = {"event": "quarantine", "stage": stage, "error": error}
            if reason:
                rec["reason"] = reason
            if self.token is not None:
                rec["token"] = self.token
            self._journal.note(**rec)

    def note_data_quality(self, report: Dict) -> None:
        """Record the ingest data-quality report once per manifest (the
        denominators --status and the tlmsum roll-up render: fraction
        masked/missing, salvaged span, fault kinds seen)."""
        self._check_fence()
        with self._lock:
            if not self._journal.notes(event="data_quality"):
                self._journal.note(event="data_quality", **report)

    def ensure_trace(self, trace_id_factory) -> str:
        """The observation's causal trace_id (round 21): minted once
        per manifest on first claim, re-read by every later owner —
        kill+resume and cross-host adoption both continue the SAME
        trace, which is what lets tlmtrace stitch one causal story
        across M hosts' files."""
        self._check_fence()
        with self._lock:
            for note in self._journal.notes(event="trace"):
                tid = note.get("trace_id")
                if tid:
                    return str(tid)
            tid = str(trace_id_factory())
            self._journal.note(event="trace", trace_id=tid)
            return tid

    def note_retry(self, stage: str, attempt: int, error: str) -> None:
        """Record one retry verdict (attempt number + the error that
        provoked it) so ``--status`` can show WHY a stage is retrying,
        not just that it is slow. Watchdog interrupts land here too —
        a deadline/stall verdict reads like any other stage error."""
        self._check_fence()
        with self._lock:
            rec = {"event": "retry", "stage": stage,
                   "attempt": int(attempt), "error": error}
            if self.token is not None:
                rec["token"] = self.token
            self._journal.note(**rec)

    def close(self) -> None:
        self._journal.close()


def load_manifest_records(path: str) -> List[dict]:
    """Raw manifest records, fingerprint-agnostic and torn-tail tolerant
    — the --status reader (RunJournal itself discards records whose
    fingerprint it cannot re-derive, which status cannot)."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn trailing line from a kill
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def status_rows(manifest_paths: Sequence[str]) -> List[Dict]:
    """One status dict per manifest: observation, planned stages, stages
    recorded done, and any quarantine verdict. Artifact validation is
    NOT re-run here (status is a cheap read-only view; ``--resume`` does
    the hashing)."""
    rows: List[Dict] = []
    for path in sorted(manifest_paths):
        recs = load_manifest_records(path)
        obs = os.path.basename(path)
        if obs.endswith(MANIFEST_SUFFIX):
            obs = obs[: -len(MANIFEST_SUFFIX)]
        stages: List[str] = []
        done: List[str] = []
        quarantine = None
        data_quality = None
        trace_id = None
        retries: Dict[str, Dict] = {}
        for rec in recs:
            if rec.get("type") == "note" and rec.get("event") == "plan":
                stages = list(rec.get("stages", []))
                obs = rec.get("obs", obs)
            elif rec.get("type") == "done":
                unit = rec.get("unit", "")
                if unit.startswith("stage:"):
                    name = unit.split(":", 1)[1]
                    if name not in done:
                        done.append(name)
                    if quarantine is not None \
                            and quarantine["stage"] == name:
                        # a LATER done record for the quarantined stage
                        # means a resume got past it — the verdict is
                        # superseded, not the observation's fate
                        quarantine = None
            elif rec.get("type") == "note" and rec.get("event") == "quarantine":
                quarantine = {"stage": rec.get("stage", "?"),
                              "error": rec.get("error", "?")}
                if rec.get("reason"):
                    quarantine["reason"] = rec["reason"]
            elif (rec.get("type") == "note"
                  and rec.get("event") == "data_quality"):
                data_quality = {k: rec.get(k) for k in
                                ("format", "nsamples", "bad_frac",
                                 "salvage") if k in rec}
            elif rec.get("type") == "note" and rec.get("event") == "retry":
                # last verdict per stage wins: attempts is the running
                # count, the error excerpt is the freshest reason
                retries[rec.get("stage", "?")] = {
                    "attempts": int(rec.get("attempt", 0) or 0),
                    "error": str(rec.get("error", ""))}
            elif rec.get("type") == "note" and rec.get("event") == "trace":
                trace_id = rec.get("trace_id")
        rows.append({"obs": obs, "manifest": path, "stages": stages,
                     "done": done, "quarantine": quarantine,
                     "data_quality": data_quality, "retries": retries,
                     "trace_id": trace_id})
    return rows


def _excerpt(error: str, limit: int = ERROR_EXCERPT_LEN) -> str:
    error = " ".join(str(error).split())  # tracebacks flatten to one line
    return error if len(error) <= limit else error[: limit - 1] + "…"


def format_status(rows: Sequence[Dict],
                  health: Optional[Dict] = None,
                  plane: Optional[Dict] = None,
                  capsules: Optional[Dict[str, List[str]]] = None,
                  tenants: Optional[Dict] = None) -> str:
    """Render the --status progress table (plus, with a fleet-health
    mirror, the per-device strike/quarantine block, and, with a
    multi-host plane snapshot from ``fleet.read_plane_status``, the
    host-liveness block and a per-observation owner column).
    ``capsules`` maps observation name -> postmortem capsule paths
    (obs/flightrec) so a QUARANTINED row points at its explanation;
    ``tenants`` is the streaming daemon's admission snapshot
    (``daemon.read_tenant_status``), rendered as a per-tenant
    quota/books block when a daemon runs (or ran) here."""
    claims = (plane or {}).get("claims", {})
    capsules = capsules or {}
    host_col = bool(plane)
    lines = [f"# {'observation':<20s} {'progress':<10s} {'retries':<8s} "
             + (f"{'host':<12s} " if host_col else "") + "state"]
    for r in rows:
        total = len(r["stages"]) or "?"
        done = r["done"]
        prog = f"{len(done)}/{total}"
        retries = r.get("retries", {})
        n_retries = sum(v.get("attempts", 0) for v in retries.values())
        if r["quarantine"] is not None:
            q = r["quarantine"]
            tag = ("DATA-QUARANTINED" if q.get("reason") == "data"
                   else "QUARANTINED")
            state = (f"{tag} at {q['stage']} "
                     f"({_excerpt(q['error'])})")
            caps = capsules.get(r["obs"], [])
            if caps:
                state += f" [capsule: {os.path.basename(caps[-1])}]"
        elif r["stages"] and len(done) == len(r["stages"]):
            state = "complete"
        else:
            pend = [s for s in r["stages"] if s not in done]
            state = ("next: " + pend[0]) if pend else \
                ("done: " + ",".join(done) if done else "pending")
        # surviving retry verdicts annotate an otherwise-bare state:
        # "WHY is this stage still pending" is the question --status
        # exists to answer
        if retries and r["quarantine"] is None:
            worst = max(retries.items(),
                        key=lambda kv: kv[1].get("attempts", 0))
            state += (f" [retried {worst[0]} x{worst[1]['attempts']}: "
                      f"{_excerpt(worst[1].get('error', ''))}]")
        dq = r.get("data_quality")
        if dq:
            bits = []
            if dq.get("bad_frac"):
                bits.append(f"bad {100.0 * dq['bad_frac']:.1f}%")
            salv = dq.get("salvage")
            if salv and salv.get("missing_samples"):
                bits.append(f"salvaged {salv.get('read_samples', '?')}"
                            f"/{salv.get('expected_samples', '?')} "
                            f"samples")
            if bits:
                state += " [data: " + ", ".join(bits) + "]"
        owner = ""
        if host_col:
            c = claims.get(r["obs"])
            owner = f"{c.get('host', '?')}" if c else "-"
            if c and c.get("adopted_from"):
                state += (f" [adopted from {c['adopted_from']} "
                          f"(token {c.get('token', '?')})]")
        lines.append(f"# {r['obs']:<20s} {prog:<10s} {n_retries:<8d} "
                     + (f"{owner:<12s} " if host_col else "") + state)
    if plane and plane.get("hosts"):
        hosts = plane["hosts"]
        lines.append(f"# hosts (lease bound "
                     f"{plane.get('lease_s', '?')}s):")
        owned: Dict[str, List[str]] = {}
        for obs_name, c in claims.items():
            if c.get("state", "running") == "running":
                owned.setdefault(str(c.get("host", "?")),
                                 []).append(obs_name)
        for hid in sorted(hosts):
            h = hosts[hid]
            if h.get("left"):
                verdict = "LEFT"
            elif h.get("live"):
                verdict = "LIVE"
            else:
                verdict = "DEAD"
            own = ",".join(sorted(owned.get(hid, []))) or "-"
            lines.append(f"#   {hid:<18s} token {h.get('token', '?'):<6} "
                         f"{verdict:<5s} beat "
                         f"{h.get('beat_age_s', '?')}s ago  "
                         f"owns: {own}")
    if health:
        devices = health.get("devices", {})
        if devices:
            lines.append(f"# devices (pool {health.get('pool', '?')}, "
                         f"quarantine at "
                         f"{health.get('strike_limit', '?')} strikes):")
            for dev_id in sorted(devices, key=lambda s: int(s)):
                d = devices[dev_id]
                verdict = "QUARANTINED" if d.get("quarantined") else "ok"
                err = d.get("last_error", "")
                tail = f" ({_excerpt(err)})" if err else ""
                lines.append(f"#   device {dev_id}: "
                             f"{d.get('strikes', 0)} strike(s), "
                             f"{verdict}{tail}")
        host_strikes = health.get("hosts", {})
        if host_strikes:
            lines.append(f"# host strikes (claim bar at "
                         f"{health.get('host_strike_limit', '?')}):")
            for hid in sorted(host_strikes):
                h = host_strikes[hid]
                verdict = ("BARRED from new claims"
                           if h.get("quarantined") else "ok")
                err = h.get("last_error", "")
                tail = f" ({_excerpt(err)})" if err else ""
                lines.append(f"#   {hid}: {h.get('strikes', 0)} "
                             f"strike(s), {verdict}{tail}")
    if tenants and tenants.get("tenants"):
        drain = " DRAINING" if tenants.get("draining") else ""
        lines.append(
            f"# tenants (accept queue "
            f"{tenants.get('queue_depth', '?')}/"
            f"{tenants.get('queue_bound', '?')}, "
            f"{tenants.get('accepted_open', '?')} accepted in "
            f"flight{drain}):")
        for name in sorted(tenants["tenants"]):
            t = tenants["tenants"][name]
            rate = t.get("rate", 0) or 0
            quota = (f"{t.get('tokens', '?')}/{t.get('burst', '?')} "
                     f"tokens @ {rate:g}/s" if rate
                     else "unmetered")
            lines.append(
                f"#   {name:<14s} prio {t.get('priority', 0):<3d} "
                f"{quota:<26s} "
                f"{t.get('submitted', 0)} submitted / "
                f"{t.get('accepted', 0)} accepted / "
                f"{t.get('shed', 0)} shed / "
                f"{t.get('quarantined', 0)} quarantined / "
                f"{t.get('completed', 0)} completed")
    return "\n".join(lines)


class ObsTrace:
    """Per-observation JSONL trace in the telemetry schema (``meta`` /
    ``span`` / ``event`` / ``end`` records), append-per-record flushed so
    a killed fleet keeps every finished stage's timing. Thread-safe: the
    scheduler records a stage span from whichever worker ran it. Written
    directly (not via obs.telemetry) because that module is one
    process-global session — which the fleet trace owns."""

    def __init__(self, path: str, obs: str, append: bool = False,
                 trace_id: Optional[str] = None):
        self._lock = TrackedLock("survey.obstrace")
        self._t0 = time.perf_counter()
        self._fh: Optional[object] = None
        # the observation's causal trace (round 21): stamped on every
        # span/event so tlmtrace can stitch this file into the fleet
        # timeline; survives append-mode reopens (each owner re-reads
        # the id from the manifest)
        self.trace_id = trace_id
        # a resumed fleet APPENDS: the killed run's recorded stage spans
        # are exactly the forensics worth keeping (tlmsum aggregates
        # spans across the whole file; later end/meta records win)
        fresh = not (append and os.path.exists(path)
                     and os.path.getsize(path) > 0)
        try:
            self._fh = open(path, "w" if fresh else "a")
        except OSError:
            return  # observability is a passenger, never the payload
        if fresh:
            meta = {"type": "meta", "tool": "survey-obs", "obs": obs,
                    "t_unix": time.time()}
            if trace_id:
                meta["trace_id"] = trace_id
            self._write(meta)

    def _write(self, rec: dict) -> None:
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.write(json.dumps(rec) + "\n")
                self._fh.flush()
            except OSError:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def span(self, name: str, t_start: float, dur: float,
             span_id: Optional[str] = None,
             parent_id: Optional[str] = None, **attrs) -> None:
        rec = {"type": "span", "name": name, "t": round(t_start, 6),
               "dur": round(dur, 6)}
        if self.trace_id:
            rec["trace_id"] = self.trace_id
        if span_id:
            # echo spans share the fleet-trace span's id (they ARE the
            # same execution); tlmtrace dedups by (trace_id, span_id)
            rec["span_id"] = span_id
        if parent_id:
            rec["parent_id"] = parent_id
        if attrs:
            rec["attrs"] = attrs
        self._write(rec)

    def event(self, name: str, **attrs) -> None:
        rec = {"type": "event", "name": name,
               "t": round(time.perf_counter() - self._t0, 6)}
        if self.trace_id:
            rec["trace_id"] = self.trace_id
        if attrs:
            rec["attrs"] = attrs
        self._write(rec)

    def close(self) -> None:
        self._write({"type": "end",
                     "wall": round(time.perf_counter() - self._t0, 6)})
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
