"""The per-observation stage DAG: what one fleet member runs, declared.

Five stages close the raw -> science chain in-tree::

    mask (device)  rfifind-compatible RFI mask from the data
      └─ sweep (device)  DM sweep + streamed accel handoff
           (``sweep --accel-search --write-dats --journal``: single-pulse
           .cands, per-DM .dat/.inf tee, per-trial .cand/.txtcand)
           └─ sift (host)  cluster per-DM candidates -> .accelcands
                └─ fold (device)  batched candidate folding -> .pfd
                     └─ snr (host)  pfd_snr --json fleet summary

Each :class:`StageSpec` declares whether it needs the device (the
scheduler's lease axis), which stages it depends on, the argv of the
EXACT in-process CLI entry point the serial per-tool chain would run
(artifact bytes therefore cannot diverge from the serial chain — the
orchestrator adds concurrency, not a second implementation), and an
output enumerator resolved AFTER the run (fold archives are named by the
sifted candidates, so the set is dynamic). Outputs feed the manifest's
validate-or-redo hook: ``resilience.journal`` records size + sha256 per
artifact and a resumed fleet re-runs any stage whose outputs no longer
validate.

Stage failure granularity: a stage that exits nonzero raises
:class:`StageExit` — an ordinary Exception, so the scheduler's bounded
retry/quarantine policy owns it; injected kills (BaseException) unwind
the fleet like a signal.
"""

from __future__ import annotations

import glob
import importlib
import os
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from pypulsar_tpu.survey.state import Observation

__all__ = [
    "StageExit",
    "StageSpec",
    "SurveyConfig",
    "build_dag",
    "stage_names",
]


class StageExit(RuntimeError):
    """A stage's CLI entry point returned a nonzero exit code."""


@dataclass
class SurveyConfig:
    """Every knob the five stages take, with the individual tools'
    defaults. One config per fleet: the manifest fingerprint hashes all
    of it, so changing any knob restarts (never resumes) the affected
    manifests."""

    # mask (rfifind)
    mask: bool = True
    mask_time: float = 1.0
    # sweep (flat grid; the DDplan path stays a per-tool workflow)
    lodm: float = 0.0
    dmstep: float = 1.0
    numdms: int = 32
    nsub: int = 64
    group_size: int = 0
    downsamp: int = 1
    chunk: Optional[int] = None
    threshold: float = 6.0
    # accel handoff
    accel_zmax: float = 200.0
    accel_dz: float = 2.0
    accel_numharm: int = 8
    accel_sigma: float = 2.0
    # None = the tuned registry default (PYPULSAR_TPU_ACCEL_BATCH: env
    # > auto-tuning cache > 32), resolved inside the sweep CLI at the
    # stage's own geometry; an explicit value pins it (round 17)
    accel_batch: Optional[int] = None
    # spectral fusion (round 15): the sweep stage hands the accel
    # search device-resident fused spectra (`sweep --spectral`) instead
    # of teeing per-DM .dat series; the fold stage then streams the RAW
    # file (its own one-pass dedispersion) since no .dats exist
    accel_spectral: bool = False
    # sift
    sift_sigma: float = 4.0
    sift_min_hits: int = 2
    sift_min_dm: Optional[float] = None
    # fold
    fold_nbins: int = 64
    fold_npart: int = 32
    fold_batch: int = 32


@dataclass(frozen=True)
class StageSpec:
    """One DAG node. ``run`` defaults to dispatching ``argv`` to the
    ``tool`` CLI's in-process ``main``; stages with pre/post logic that
    is not a plain CLI call (snr's empty-fleet guard) override it.

    ``devices_max`` declares the stage's device-count range [1, max]:
    the scheduler may gang-lease up to that many chips to ONE execution
    of this stage (vs the default fleet-parallel 1-chip placement), and
    ``gang_argv(obs, cfg, k)`` builds the argv that actually spans k
    chips (the sweep stage adds ``--mesh k``). Gang size is a PLACEMENT
    choice, never science: a gang-aware stage must produce byte-
    identical artifacts at any k, so manifests resume across gang
    changes (the fingerprint deliberately excludes placement).

    ``deadline_s`` / ``deadline_per_mb`` declare the stage's wall-clock
    budget for the fleet watchdog: a flat bound, a bound scaled by the
    observation's input size in MB, or their sum (both set). None/None
    (the default) means no deadline — heartbeat stall detection
    (``--stall-timeout``) still covers the truly wedged case. Like
    placement, deadlines are runtime policy, not science: they are NOT
    part of the manifest fingerprint."""

    name: str
    tool: str
    device_bound: bool
    deps: Tuple[str, ...]
    argv: Callable[[Observation, SurveyConfig], List[str]]
    outputs: Callable[[Observation, SurveyConfig], List[str]]
    run: Optional[Callable[[Observation, SurveyConfig], int]] = field(
        default=None)
    devices_max: int = 1
    gang_argv: Optional[Callable[[Observation, SurveyConfig, int],
                                 List[str]]] = field(default=None)
    deadline_s: Optional[float] = None
    deadline_per_mb: Optional[float] = None

    def deadline_for(self, obs: Observation) -> Optional[float]:
        """This stage's wall-clock deadline for ``obs`` in seconds, or
        None when the spec declares no bound. The size-derived term
        uses the INPUT file (the one size known before the stage runs);
        an unstatable input contributes nothing rather than failing —
        the stage itself will report the missing file properly."""
        if self.deadline_s is None and self.deadline_per_mb is None:
            return None
        total = self.deadline_s or 0.0
        if self.deadline_per_mb:
            try:
                mb = os.path.getsize(obs.infile) / 1e6
            except OSError:
                mb = 0.0
            total += self.deadline_per_mb * mb
        return total if total > 0 else None

    def execute(self, obs: Observation, cfg: SurveyConfig,
                gang: int = 1) -> None:
        if self.run is not None:
            rc = self.run(obs, cfg)
        else:
            argv = (self.gang_argv(obs, cfg, gang)
                    if gang > 1 and self.gang_argv is not None
                    else self.argv(obs, cfg))
            rc = run_cli_tool(self.tool, argv)
        if rc:
            raise StageExit(f"stage {self.name!r} ({self.tool}) exited "
                            f"{rc} for observation {obs.name!r}")


def run_cli_tool(tool: str, argv: List[str]) -> int:
    """Invoke a CLI tool's ``main`` in-process (a library call, not a
    subprocess — the readers, jit caches and telemetry session are
    shared with the fleet). argparse errors (SystemExit) become exit
    codes so the scheduler's retry/quarantine policy sees them instead
    of a fleet-fatal BaseException."""
    mod = importlib.import_module(f"pypulsar_tpu.cli.{tool}")
    try:
        return int(mod.main(argv) or 0)
    except SystemExit as e:  # argparse .error() inside a worker thread
        code = e.code
        return code if isinstance(code, int) else 1


def _sorted_glob(pattern: str) -> List[str]:
    return sorted(glob.glob(pattern))


def _mask_file(obs: Observation) -> str:
    return f"{obs.outbase}_rfifind.mask"


def _mask_argv(obs: Observation, cfg: SurveyConfig) -> List[str]:
    return [obs.infile, "-o", obs.outbase, "-t", str(cfg.mask_time)]


def _mask_outputs(obs: Observation, cfg: SurveyConfig) -> List[str]:
    outs = [_mask_file(obs)]
    stats = f"{obs.outbase}_rfifind.stats.npz"
    if os.path.exists(stats):
        outs.append(stats)
    return outs


# widest gang one sweep stage may hold (chips, not a science knob — NOT
# in SurveyConfig, so changing it can never restart a manifest)
SWEEP_GANG_MAX = 8


def _sweep_argv(obs: Observation, cfg: SurveyConfig) -> List[str]:
    # spectral fusion drops the .dat tee (there is no time series to
    # tee); the fold stage compensates by streaming the raw file
    series = (["--spectral"] if cfg.accel_spectral else ["--write-dats"])
    argv = [obs.infile, "-o", obs.outbase,
            "--lodm", str(cfg.lodm), "--dmstep", str(cfg.dmstep),
            "--numdms", str(cfg.numdms), "-s", str(cfg.nsub),
            "--group-size", str(cfg.group_size),
            "--threshold", str(cfg.threshold),
            *series, "--accel-search",
            "--accel-zmax", str(cfg.accel_zmax),
            "--accel-dz", str(cfg.accel_dz),
            "--accel-numharm", str(cfg.accel_numharm),
            "--accel-sigma", str(cfg.accel_sigma),
            *(["--accel-batch", str(cfg.accel_batch)]
              if cfg.accel_batch is not None else []),
            # the chain journal gives the (long) sweep stage its own
            # intra-stage resume: a redone stage skips validated units
            "--journal", f"{obs.outbase}.chain.jsonl"]
    if cfg.downsamp != 1:
        argv += ["--downsamp", str(cfg.downsamp)]
    if cfg.chunk is not None:
        argv += ["--chunk", str(cfg.chunk)]
    if cfg.mask:
        argv += ["--mask", _mask_file(obs)]
    return argv


def _sweep_gang_argv(obs: Observation, cfg: SurveyConfig,
                     k: int) -> List[str]:
    """The k-chip form of the sweep stage: the SAME argv plus ``--mesh
    k`` — the sweep pass shards its trial groups and the accel handoff
    shards (dm x spectrum) over the k leased chips (cli/sweep builds the
    mesh from the thread's gang lease). Artifacts are byte-identical to
    the 1-chip argv, the contract the multi-chip bench asserts."""
    return _sweep_argv(obs, cfg) + ["--mesh", str(k)]


def _sweep_outputs(obs: Observation, cfg: SurveyConfig) -> List[str]:
    return ([f"{obs.outbase}.cands"]
            + _sorted_glob(f"{obs.outbase}_DM*.dat")
            + _sorted_glob(f"{obs.outbase}_DM*.inf")
            + _sorted_glob(f"{obs.outbase}_DM*_ACCEL_*.cand")
            + _sorted_glob(f"{obs.outbase}_DM*_ACCEL_*.txtcand"))


def _sift_argv(obs: Observation, cfg: SurveyConfig) -> List[str]:
    argv = (_sorted_glob(f"{obs.outbase}_DM*_ACCEL_*.cand")
            + ["-s", str(cfg.sift_sigma),
               "--min-hits", str(cfg.sift_min_hits),
               "-o", f"{obs.outbase}.accelcands"])
    if cfg.sift_min_dm is not None:
        argv += ["--min-dm", str(cfg.sift_min_dm)]
    return argv


def _sift_outputs(obs: Observation, cfg: SurveyConfig) -> List[str]:
    return [f"{obs.outbase}.accelcands"]


def _fold_argv(obs: Observation, cfg: SurveyConfig) -> List[str]:
    argv = ["--cands", f"{obs.outbase}.accelcands", "-o", obs.outbase,
            "-n", str(cfg.fold_nbins), "--npart", str(cfg.fold_npart),
            "--batch", str(cfg.fold_batch)]
    if cfg.accel_spectral:
        # no .dat tee exists under spectral fusion: fold from the RAW
        # file (foldbatch's one streamed dedispersion pass), with the
        # sweep's own series geometry AND mask so the folded series
        # match what the candidates were found in (a maskless fold
        # would reintroduce the RFI the search excluded)
        return ([obs.infile, *argv, "-s", str(cfg.nsub),
                 "--group-size", str(cfg.group_size)]
                + (["--downsamp", str(cfg.downsamp)]
                   if cfg.downsamp != 1 else [])
                + (["--mask", _mask_file(obs)] if cfg.mask else []))
    return argv + ["--datbase", obs.outbase]


def _fold_outputs(obs: Observation, cfg: SurveyConfig) -> List[str]:
    outs = _sorted_glob(f"{obs.outbase}_cand*.pfd")
    summary = f"{obs.outbase}_foldbatch.json"
    if os.path.exists(summary):
        outs.append(summary)
    return outs


def _snr_json(obs: Observation) -> str:
    return f"{obs.outbase}_snr.json"


def _snr_argv(obs: Observation, cfg: SurveyConfig) -> List[str]:
    return (_sorted_glob(f"{obs.outbase}_cand*.pfd")
            + ["--json", _snr_json(obs)])


def _snr_run(obs: Observation, cfg: SurveyConfig) -> int:
    """pfd_snr over the folded archives; an observation whose sift kept
    nothing (no archives) is a legitimate empty survey row, not an
    error — pfd_snr requires at least one input, so write the empty
    summary directly."""
    argv = _snr_argv(obs, cfg)
    if not _sorted_glob(f"{obs.outbase}_cand*.pfd"):
        from pypulsar_tpu.resilience.journal import atomic_write_text

        atomic_write_text(_snr_json(obs), "[]")
        return 0
    return run_cli_tool("pfd_snr", argv)


def _snr_outputs(obs: Observation, cfg: SurveyConfig) -> List[str]:
    return [_snr_json(obs)]


def build_dag(cfg: SurveyConfig) -> List[StageSpec]:
    """The stage list in topological order (the chain above; ``mask``
    drops out — and the sweep drops ``--mask`` — under
    ``cfg.mask=False``)."""
    stages: List[StageSpec] = []
    sweep_deps: Tuple[str, ...] = ()
    if cfg.mask:
        stages.append(StageSpec("mask", "rfifind", True, (),
                                _mask_argv, _mask_outputs))
        sweep_deps = ("mask",)
    stages += [
        StageSpec("sweep", "sweep", True, sweep_deps,
                  _sweep_argv, _sweep_outputs,
                  devices_max=SWEEP_GANG_MAX,
                  gang_argv=_sweep_gang_argv),
        StageSpec("sift", "sift", False, ("sweep",),
                  _sift_argv, _sift_outputs),
        StageSpec("fold", "foldbatch", True, ("sift",),
                  _fold_argv, _fold_outputs),
        StageSpec("snr", "pfd_snr", False, ("fold",),
                  _snr_argv, _snr_outputs, run=_snr_run),
    ]
    return stages


def stage_names(stages: Sequence[StageSpec]) -> List[str]:
    return [s.name for s in stages]
