"""Streaming survey daemon (round 23): multi-tenant admission,
quota-aware overload shedding, graceful degradation under sustained
overload.

Everything before this round is batch-over-files: ``survey`` takes a
fixed observation list, runs the DAG to completion, exits. The heavy-
traffic scenario the north star names — real-time transient surveys
whose recorders never stop producing — needs the inverse contract: a
process that never exits, fed by watch directories and socket
submissions, that must *degrade deliberately* under overload instead of
OOMing, wedging, or silently dropping work it promised to run.

The admission state machine (one arrival moves left to right, landing
in exactly ONE terminal column)::

    arrival --> pending --> ACCEPTED --> done
      |            |           |
      |            |           +------> quarantined   (ingest verdict /
      |            |                     vanished input / stage failure)
      |            +--------> SHED      (queue bound; lowest priority,
      |                                  thinnest quota first)
      +----------> (retry)              (injected fault at the edge:
                                         the arrival is simply re-seen)

- **pending** arrivals are *unaccepted*: they wait on their tenant's
  token bucket and on the composed :class:`ResourceGuard` (free-disk
  floor + pending-depth backpressure, now hysteretic). The pending
  queue is BOUNDED (``PYPULSAR_TPU_DAEMON_QUEUE_BOUND``): past the
  bound the daemon sheds the lowest-priority entry — over-quota
  (fewest bucket tokens) first within a priority — with a
  ``daemon.shed`` event carrying tenant/reason/queue_depth, so the
  decision trail reconstructs from the fleet trace alone.
- **accepted** work is sacred: acceptance *is* the manifest plan
  (:meth:`FleetScheduler.submit` journals it immediately), so an
  accepted observation survives kill -9 + restart like any batch obs —
  the daemon's own ``daemon.jsonl`` journal replays accepted-minus-
  terminal records on startup and resubmits them with ``resume=True``
  (zero re-runs of journal-validated stages). Shedding NEVER touches
  accepted work.
- **half-written files are never ingested**: a watch-dir arrival is
  admitted only after its size has been stable for the quiesce window
  (``PYPULSAR_TPU_DAEMON_QUIESCE_S``).
- **bad tenant data cannot charge healthy tenants**: ingest validation
  (round 13) quarantines inside the bad tenant's own books; token
  buckets are per-tenant, so one tenant's garbage burns only its own
  quota.

Fault points ``daemon.arrival`` / ``daemon.admit`` / ``daemon.shed``
are armed like every other point (``--fault-inject``, chaos spray):
the ingest edge is the daemon's own supervisor, so an injected fault
there degrades to a retry at the next scan tick — the books stay
balanced because the arrival is only counted once it gets past the
trip.

Tenant accounting is mirrored to ``<outdir>/_fleet/tenants.json``
(atomic) for ``survey --status`` / ``/status.json``; per-tenant
telemetry events feed tlmsum's per-tenant roll-up.
"""

from __future__ import annotations

import json
import os
import socketserver
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.parallel import broker as broker_mod
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.resilience import health as health_mod
from pypulsar_tpu.resilience import locks as locks_mod
from pypulsar_tpu.resilience.journal import atomic_write_text
from pypulsar_tpu.survey import fleet as fleet_mod
from pypulsar_tpu.survey.scheduler import FleetScheduler
from pypulsar_tpu.survey.state import Observation
from pypulsar_tpu.tune import knobs

__all__ = ["SurveyDaemon", "TenantSpec", "parse_tenant_spec",
           "read_tenant_status", "tenants_json_path"]

ENV_QUEUE_BOUND = "PYPULSAR_TPU_DAEMON_QUEUE_BOUND"
ENV_QUIESCE_S = "PYPULSAR_TPU_DAEMON_QUIESCE_S"
ENV_POLL_S = "PYPULSAR_TPU_DAEMON_POLL_S"
ENV_TENANT_RATE = "PYPULSAR_TPU_DAEMON_TENANT_RATE"
ENV_TENANT_BURST = "PYPULSAR_TPU_DAEMON_TENANT_BURST"
ENV_IDLE_EXIT_S = "PYPULSAR_TPU_DAEMON_IDLE_EXIT_S"

TENANTS_JSON = "tenants.json"
DAEMON_JOURNAL = "daemon.jsonl"

# watch-dir extensions worth scanning for (filterbank + raw voltages)
WATCH_EXTS = (".fil", ".sf", ".raw")


def tenants_json_path(outdir: str) -> str:
    return os.path.join(fleet_mod.plane_dir(outdir), TENANTS_JSON)


def journal_path(outdir: str) -> str:
    return os.path.join(fleet_mod.plane_dir(outdir), DAEMON_JOURNAL)


def read_tenant_status(outdir: str) -> Optional[dict]:
    """The daemon's tenant snapshot (``--status`` / ``/status.json``
    consumer side); None when no daemon ever ran here or the file is
    torn mid-replace (the next write heals it)."""
    try:
        with open(tenants_json_path(outdir)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


class TenantSpec:
    """One tenant's admission contract: scheduling ``priority`` (higher
    wins; sheds last) and a token bucket (``rate`` admissions/second
    refill, ``burst`` depth; rate 0 = unmetered)."""

    def __init__(self, name: str, priority: int = 0,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None):
        if rate is None:
            rate = knobs.env_float(ENV_TENANT_RATE)
        if burst is None:
            burst = knobs.env_float(ENV_TENANT_BURST)
        self.name = str(name)
        self.priority = int(priority)
        self.rate = max(0.0, float(rate or 0.0))
        self.burst = max(1.0, float(burst or 1.0))
        self.tokens = self.burst
        self._t_refill = time.monotonic()

    def refill(self, now: Optional[float] = None) -> None:
        if self.rate <= 0:
            return
        now = time.monotonic() if now is None else now
        dt = max(0.0, now - self._t_refill)
        self._t_refill = now
        self.tokens = min(self.burst, self.tokens + dt * self.rate)

    def try_take(self) -> bool:
        """One admission's worth of quota; False = over quota for now
        (the arrival stays pending until the bucket refills)."""
        self.refill()
        if self.rate <= 0:
            return True  # unmetered tenant
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


def parse_tenant_spec(spec: str) -> TenantSpec:
    """CLI grammar ``NAME[:PRIORITY[:RATE[:BURST]]]`` — loud on
    malformed fields (a typo'd quota silently defaulting would make the
    overload contract meaningless)."""
    fields = spec.split(":")
    if not fields[0]:
        raise ValueError(f"bad tenant spec {spec!r}: empty name")
    if len(fields) > 4:
        raise ValueError(f"bad tenant spec {spec!r}; expected "
                         f"NAME[:PRIORITY[:RATE[:BURST]]]")
    try:
        prio = int(fields[1]) if len(fields) > 1 and fields[1] else 0
        rate = (float(fields[2])
                if len(fields) > 2 and fields[2] else None)
        burst = (float(fields[3])
                 if len(fields) > 3 and fields[3] else None)
    except ValueError as e:
        raise ValueError(f"bad tenant spec {spec!r}: {e}") from None
    return TenantSpec(fields[0], prio, rate, burst)


class _Arrival:
    """One unaccepted submission waiting in the bounded pending queue."""

    __slots__ = ("tenant", "path", "seq", "t_arrived")

    def __init__(self, tenant: str, path: str, seq: int):
        self.tenant = tenant
        self.path = path
        self.seq = seq
        self.t_arrived = time.time()


class _TenantBooks:
    """Per-tenant admission accounting (monotonic counters; the
    in-process half of the books the soak harness balances)."""

    __slots__ = ("submitted", "accepted", "shed", "quarantined",
                 "completed")

    def __init__(self):
        self.submitted = 0
        self.accepted = 0
        self.shed = 0
        self.quarantined = 0
        self.completed = 0

    def as_dict(self) -> dict:
        return {"submitted": self.submitted, "accepted": self.accepted,
                "shed": self.shed, "quarantined": self.quarantined,
                "completed": self.completed}


class _SubmitServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class _SubmitHandler(socketserver.StreamRequestHandler):
    """Line protocol: ``<tenant> <path>\\n`` per request, one verdict
    line back (``accepted <obs>`` / ``shed <reason>`` /
    ``quarantined <reason>`` / ``error <msg>``) — the submitter learns
    the admission decision synchronously, which is the whole point of
    a socket lane next to the fire-and-forget watch directory."""

    def handle(self):
        daemon = self.server.survey_daemon
        try:
            line = self.rfile.readline().decode(errors="replace").strip()
        except OSError:
            return
        if not line:
            return
        parts = line.split(None, 1)
        if len(parts) != 2:
            self._reply("error expected '<tenant> <path>'")
            return
        tenant, path = parts
        try:
            verdict, detail = daemon.submit(tenant, path)
        except Exception as e:  # noqa: BLE001 - one bad submission must
            # not kill the handler thread pool; the verdict IS the error
            verdict, detail = "error", f"{type(e).__name__}: {e}"
        self._reply(f"{verdict} {detail}")

    def _reply(self, text: str) -> None:
        try:
            self.wfile.write((text + "\n").encode())
        except OSError:
            pass  # submitter hung up: the journal still has the verdict


class SurveyDaemon:
    """The streaming ingest service around a ``service=True``
    :class:`FleetScheduler`. Construct, then :meth:`run` (blocks until
    :meth:`request_drain` — typically wired to SIGTERM — or the idle-
    exit knob fires); ``result`` carries the drained fleet's verdict."""

    def __init__(self, outdir: str, cfg, *,
                 stages=None,
                 tenants: Sequence[TenantSpec] = (),
                 watch: Sequence[Tuple[str, str]] = (),
                 initial: Sequence[Tuple[str, str]] = (),
                 port: Optional[int] = None,
                 queue_bound: Optional[int] = None,
                 quiesce_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 idle_exit_s: Optional[float] = None,
                 min_free_mb: Optional[float] = None,
                 max_pending: Optional[float] = None,
                 verbose: bool = False,
                 **scheduler_kw):
        self.outdir = outdir
        os.makedirs(fleet_mod.plane_dir(outdir), exist_ok=True)
        self.verbose = verbose
        self.queue_bound = int(queue_bound
                               if queue_bound is not None
                               else knobs.env_int(ENV_QUEUE_BOUND))
        self.quiesce_s = float(quiesce_s if quiesce_s is not None
                               else knobs.env_float(ENV_QUIESCE_S))
        self.poll_s = max(0.05, float(
            poll_s if poll_s is not None
            else knobs.env_float(ENV_POLL_S)))
        self.idle_exit_s = float(
            idle_exit_s if idle_exit_s is not None
            else knobs.env_float(ENV_IDLE_EXIT_S) or 0.0)
        # (directory, tenant) watch lanes
        self.watch = [(os.path.abspath(d), t) for d, t in watch]
        # (tenant, path) submissions fed through the admission path at
        # startup (the CLI's positional observations)
        self._initial = [(t, os.path.abspath(p)) for t, p in initial]
        # the daemon's OWN admission gate, composed in FRONT of the
        # scheduler's (which still pauses stage launches): refusing at
        # the door keeps the pending queue — and therefore the shed
        # pressure — honest about what the node can actually take
        self._guard = health_mod.ResourceGuard(
            outdir,
            min_free_bytes=(min_free_mb * 1e6
                            if min_free_mb is not None else None),
            max_pending=max_pending)
        self._sched = FleetScheduler(
            [], cfg, stages=stages, service=True, resume=True,
            min_free_mb=min_free_mb, max_pending=max_pending,
            verbose=verbose, **scheduler_kw)
        self._sched.on_obs_terminal = self._on_obs_terminal
        # candidate-store ingest (round 25) stamps records with the
        # admitting tenant, so /candidates?tenant= queries are real
        self._sched.tenant_of = lambda name: self._obs_tenant.get(
            name, "default")

        # reentrant: scheduler.submit() fires _on_obs_terminal
        # synchronously when ingest validation quarantines the arrival,
        # and the books for both edges live under this one lock
        self._lock = locks_mod.TrackedRLock("survey.daemon")
        self._tenants: Dict[str, TenantSpec] = {}
        for t in tenants:
            self._tenants[t.name] = t
        self._books: Dict[str, _TenantBooks] = {}
        self._pending: List[_Arrival] = []
        self._seq = 0
        self._seen_paths: set = set()
        self._obs_tenant: Dict[str, str] = {}   # obs name -> tenant
        self._obs_infile: Dict[str, str] = {}   # obs name -> source path
        self._obs_state: Dict[str, str] = {}    # obs name -> state
        self._accepted_open = 0                 # accepted, not terminal
        self._names_used: set = set()
        self._draining = locks_mod.TrackedEvent("survey.daemon.drain")
        self._t_last_arrival = time.monotonic()
        # watch-dir quiesce ledger: path -> (size, t_first_stable)
        self._quiesce: Dict[str, Tuple[int, float]] = {}
        self._journal_fh = None
        self._fleet_crash: Optional[BaseException] = None
        self._server: Optional[_SubmitServer] = None
        self.port: Optional[int] = None
        if port is not None:
            self._server = _SubmitServer(("127.0.0.1", int(port)),
                                         _SubmitHandler)
            self._server.survey_daemon = self
            self.port = int(self._server.server_address[1])
        self.result = None

    # -- tenant plumbing ----------------------------------------------------

    def _tenant(self, name: str) -> TenantSpec:
        t = self._tenants.get(name)
        if t is None:
            # an unconfigured tenant gets the knob-default contract —
            # the daemon serves whoever shows up, operators pin quotas
            # for the tenants they care about
            t = TenantSpec(name)
            self._tenants[name] = t
        return t

    def _book(self, name: str) -> _TenantBooks:
        b = self._books.get(name)
        if b is None:
            b = _TenantBooks()
            self._books[name] = b
        return b

    # -- journal ------------------------------------------------------------

    def _journal(self, rec: dict) -> None:
        """Append-per-record fsync'd admission journal: the restart
        replay's source of truth. A torn tail (kill -9 mid-append) is
        tolerated at read time like every other journal here."""
        if self._journal_fh is None:
            self._journal_fh = open(journal_path(self.outdir), "a")
        self._journal_fh.write(json.dumps(rec) + "\n")
        self._journal_fh.flush()
        os.fsync(self._journal_fh.fileno())

    def _replay_journal(self) -> List[dict]:
        """Rebuild books + the accepted-minus-terminal resubmission
        list from ``daemon.jsonl`` (torn-tail tolerant)."""
        recs: List[dict] = []
        try:
            with open(journal_path(self.outdir)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail: the record never happened
                    if isinstance(rec, dict):
                        recs.append(rec)
        except OSError:
            return []
        return recs

    def recover(self) -> int:
        """Startup replay: every journaled accept without a terminal
        record is resubmitted with ``resume=True`` — journal-validated
        stages are skipped, so a kill -9 + restart re-runs ONLY the
        work that never completed. Returns the resubmission count."""
        recs = self._replay_journal()
        accepted: Dict[str, dict] = {}
        terminal: Dict[str, str] = {}
        for rec in recs:
            typ = rec.get("type")
            if typ == "accept":
                accepted[str(rec.get("obs"))] = rec
            elif typ == "terminal":
                terminal[str(rec.get("obs"))] = str(rec.get("state"))
            with self._lock:
                t = str(rec.get("tenant", "?"))
                b = self._book(t)
                if typ == "accept":
                    b.submitted += 1
                    b.accepted += 1
                elif typ == "shed":
                    b.submitted += 1
                    b.shed += 1
                elif typ == "terminal":
                    pass  # settled below, once per obs
        n = 0
        for name, rec in accepted.items():
            tenant = str(rec.get("tenant", "?"))
            with self._lock:
                self._names_used.add(name)
                self._obs_tenant[name] = tenant
                self._obs_infile[name] = str(rec.get("infile"))
                self._seen_paths.add(str(rec.get("infile")))
            state = terminal.get(name)
            if state is not None:
                # already settled in a previous life: fold the verdict
                # into the books without resubmitting
                with self._lock:
                    b = self._book(tenant)
                    if state == "done":
                        b.completed += 1
                    else:
                        b.quarantined += 1
                    self._obs_state[name] = state
                continue
            obs = Observation(name, str(rec.get("infile")),
                              str(rec.get("outbase")))
            with self._lock:
                self._obs_state[name] = "accepted"
                self._accepted_open += 1
            try:
                self._sched.submit(obs, resume=True, verify_input=True)
            except ValueError:
                pass  # duplicate accept records: already registered
            n += 1
            if self.verbose:
                print(f"# daemon: recovered accepted {name} "
                      f"(tenant {tenant}); resuming from its manifest")
        return n

    # -- arrival / admission ------------------------------------------------

    def submit(self, tenant: str, path: str) -> Tuple[str, str]:
        """One socket-lane submission: synchronous verdict. The file
        must exist (a socket submitter asserts the transfer is done —
        the quiesce window is the watch lane's job)."""
        if not os.path.exists(path):
            return "error", f"no such file: {path}"
        return self._arrive(tenant, path, lane="socket")

    def _arrive(self, tenant: str, path: str,
                lane: str) -> Tuple[str, str]:
        """Admission for one arrival. Counts the arrival, takes the
        fault trip, then either admits now, parks it pending, or sheds
        past the queue bound — exactly one verdict per arrival."""
        try:
            faultinject.trip("daemon.arrival")
        except Exception as e:  # noqa: BLE001 - injected-only (guarded
            # by the isinstance below); a kill stays a BaseException
            if not isinstance(e, faultinject.InjectedFault):
                raise
            # the ingest edge is its own supervisor: an injected fault
            # here means the arrival was never seen — the watch lane
            # re-sees the file next scan, the socket lane reports it
            telemetry.counter("daemon.arrival_faults")
            return "error", f"transient ingest fault: {e}"
        if self._draining.is_set():
            return "error", "daemon draining"
        with self._lock:
            if path in self._seen_paths:
                return "error", f"already submitted: {path}"
            self._seen_paths.add(path)
            self._t_last_arrival = time.monotonic()
            self._seq += 1
            arr = _Arrival(tenant, path, self._seq)
            self._book(tenant).submitted += 1
            telemetry.counter("daemon.arrivals")
            telemetry.event("daemon.arrival", tenant=tenant,
                            path=os.path.basename(path), lane=lane)
            self._pending.append(arr)
            shed_verdict = self._enforce_bound_locked()
            if shed_verdict is not None and shed_verdict[0] is arr:
                return "shed", shed_verdict[1]
        verdict = self._pump_locked_entry(arr)
        return verdict

    def _enforce_bound_locked(self):
        """Shed down to the queue bound: lowest priority first, and
        within a priority the tenant with the THINNEST bucket (most
        over quota) first; newest arrival breaks remaining ties. The
        caller holds the lock. Returns (victim, reason) for the last
        victim (so an arrival that shed ITSELF gets its own verdict)."""
        last = None
        while len(self._pending) > self.queue_bound:
            depth = len(self._pending)

            def shed_key(a: _Arrival):
                t = self._tenant(a.tenant)
                t.refill()
                return (t.priority, t.tokens, -a.seq)

            victim = min(self._pending, key=shed_key)
            self._pending.remove(victim)
            t = self._tenant(victim.tenant)
            reason = (f"queue full: depth {depth} > bound "
                      f"{self.queue_bound}; lowest priority "
                      f"{t.priority} (tenant {victim.tenant}, "
                      f"{t.tokens:.1f} tokens)")
            try:
                faultinject.trip("daemon.shed")
            except Exception as e:  # noqa: BLE001 - injected-only
                if not isinstance(e, faultinject.InjectedFault):
                    raise
                # the shed MUST still happen — an injected fault at
                # this point may not leave the queue over its bound
                telemetry.counter("daemon.shed_faults")
            self._book(victim.tenant).shed += 1
            # overload shedding means the fleet is behind: collapse the
            # batch broker's coalesce window so in-flight work stops
            # trading latency for batch width (round 24)
            broker_mod.note_pressure("daemon.shed")
            telemetry.counter("daemon.shed_total")
            telemetry.event("daemon.shed", tenant=victim.tenant,
                            reason=reason, queue_depth=depth,
                            path=os.path.basename(victim.path))
            self._journal({"type": "shed", "tenant": victim.tenant,
                           "path": victim.path, "reason": reason,
                           "queue_depth": depth,
                           "t_unix": time.time()})
            if self.verbose:
                print(f"# daemon: SHED {os.path.basename(victim.path)} "
                      f"(tenant {victim.tenant}): {reason}")
            last = (victim, reason)
        return last

    def _pump_locked_entry(self, arr: _Arrival) -> Tuple[str, str]:
        """Run one admission pass, then report what happened to ONE
        specific arrival (the socket lane's synchronous answer)."""
        self._pump()
        with self._lock:
            if arr in self._pending:
                return "pending", os.path.basename(arr.path)
            # settled during the pump: the name map has its verdict
            # (only the queue bound sheds, and that was reported by
            # the caller — so here it is accepted or quarantined)
            for name, infile in self._obs_infile.items():
                if infile == arr.path:
                    st = self._obs_state.get(name, "accepted")
                    if st == "quarantined":
                        return "quarantined", name
                    return "accepted", name
        return "error", f"arrival lost: {os.path.basename(arr.path)}"

    def _pump(self) -> None:
        """One admission pass over the pending queue, highest priority
        first: composed guard -> tenant token bucket -> accept. An
        arrival that cannot be admitted THIS pass stays pending (only
        the queue bound sheds)."""
        reason = self._guard.admit()
        if reason is not None:
            # the node is the bottleneck, not any tenant: everything
            # stays pending; the bounded queue (and its shed policy)
            # absorbs the overflow while the guard's hysteresis decides
            # when the node is genuinely healthy again
            telemetry.counter("daemon.guard_refusals")
            return
        while True:
            with self._lock:
                if not self._pending:
                    return
                # highest priority first; FIFO within a priority
                arr = max(self._pending,
                          key=lambda a: (self._tenant(a.tenant).priority,
                                         -a.seq))
                t = self._tenant(arr.tenant)
                if not t.try_take():
                    # over quota: the arrival waits for the refill. Try
                    # the OTHER tenants — a starved low-quota tenant
                    # must not stall a high-priority one behind it.
                    others = [a for a in self._pending
                              if a.tenant != arr.tenant]
                    picked = None
                    for cand in sorted(
                            others,
                            key=lambda a: (
                                -self._tenant(a.tenant).priority,
                                a.seq)):
                        if self._tenant(cand.tenant).try_take():
                            picked = cand
                            break
                    if picked is None:
                        return
                    arr = picked
                self._pending.remove(arr)
            self._admit(arr)

    def _admit(self, arr: _Arrival) -> None:
        """Accept one arrival into the running fleet: fault trip,
        journal, scheduler.submit (which plans the manifest — the
        durability edge), books."""
        try:
            faultinject.trip("daemon.admit")
        except Exception as e:  # noqa: BLE001 - injected-only
            if not isinstance(e, faultinject.InjectedFault):
                raise
            # supervised edge: put it back, retry next tick — the
            # arrival was counted, but not yet accepted or shed, so
            # the books still balance when it settles later
            telemetry.counter("daemon.admit_faults")
            with self._lock:
                self._pending.append(arr)
            return
        with self._lock:
            name = self._unique_name(arr.path)
            outbase = os.path.join(self.outdir, name)
            self._names_used.add(name)
            self._obs_tenant[name] = arr.tenant
            self._obs_infile[name] = arr.path
            self._obs_state[name] = "accepted"
            self._accepted_open += 1
            self._book(arr.tenant).accepted += 1
            telemetry.counter("daemon.accepted")
            telemetry.event("daemon.accept", tenant=arr.tenant,
                            obs=name, queue_depth=len(self._pending))
            self._journal({"type": "accept", "tenant": arr.tenant,
                           "obs": name, "infile": arr.path,
                           "outbase": outbase, "t_unix": time.time()})
        obs = Observation(name, arr.path, outbase)
        try:
            self._sched.submit(obs, resume=True, verify_input=True)
        except Exception as e:  # noqa: BLE001 - an unsubmittable accept
            # must settle, not wedge: quarantine it in the books so
            # accepted == completed + quarantined still balances
            with self._lock:
                if self._obs_state.get(name) == "accepted":
                    self._settle_locked(name, "quarantined")
            print(f"# daemon: accepted {name} failed to submit "
                  f"({type(e).__name__}: {e}); quarantined")
        if self.verbose:
            print(f"# daemon: ACCEPTED {name} (tenant {arr.tenant})")

    def _unique_name(self, path: str) -> str:
        stem = os.path.splitext(os.path.basename(path))[0] or "obs"
        name, k = stem, 1
        while name in self._names_used:
            k += 1
            name = f"{stem}-{k}"
        return name

    def _settle_locked(self, name: str, state: str) -> None:
        """Fold one accepted observation's terminal verdict into the
        books (caller holds the lock; idempotent per obs)."""
        prev = self._obs_state.get(name)
        if prev in ("done", "quarantined"):
            return  # already settled (idempotent terminal edges)
        self._obs_state[name] = state
        self._accepted_open = max(0, self._accepted_open - 1)
        tenant = self._obs_tenant.get(name, "?")
        b = self._book(tenant)
        if state == "done":
            b.completed += 1
        else:
            b.quarantined += 1
            telemetry.counter("daemon.quarantined")
        telemetry.event("daemon.terminal", tenant=tenant, obs=name,
                        state=state)
        self._journal({"type": "terminal", "obs": name, "state": state,
                       "tenant": tenant, "t_unix": time.time()})

    def _on_obs_terminal(self, name: str, state: str) -> None:
        """Scheduler terminal-edge hook (worker threads): settle the
        tenant books on the same edges the coordination plane uses."""
        with self._lock:
            if name not in self._obs_tenant:
                return  # a batch obs (not daemon-submitted)
            self._settle_locked(
                name, "done" if state == "done" else "quarantined")

    # -- watch-dir scanning --------------------------------------------------

    def _scan_watch(self) -> None:
        """One pass over the watch lanes: a file is an arrival only
        once its size has been stable for the quiesce window (a
        recorder mid-write grows; a mover's rename is atomic and lands
        already-stable)."""
        now = time.monotonic()
        for d, tenant in self.watch:
            try:
                entries = sorted(os.listdir(d))
            except OSError:
                continue  # unreadable watch dir: retry next tick
            for fn in entries:
                if not fn.lower().endswith(WATCH_EXTS):
                    continue
                path = os.path.join(d, fn)
                with self._lock:
                    if path in self._seen_paths:
                        continue
                try:
                    size = os.path.getsize(path)
                except OSError:
                    self._quiesce.pop(path, None)
                    continue  # vanished mid-scan: never an arrival
                prev = self._quiesce.get(path)
                if prev is None or prev[0] != size:
                    self._quiesce[path] = (size, now)
                    continue  # still growing (or first sighting)
                if now - prev[1] < self.quiesce_s:
                    continue  # stable, but not for long enough yet
                self._quiesce.pop(path, None)
                self._arrive(tenant, path, lane="watch")

    # -- status mirror -------------------------------------------------------

    def tenant_snapshot(self) -> dict:
        """The tenants block (``--status`` / ``/status.json`` /
        ``tenants.json``): contract + books per tenant, plus the
        queue's live shape."""
        with self._lock:
            tenants = {}
            for name in sorted(set(self._tenants) | set(self._books)):
                t = self._tenant(name)
                t.refill()
                b = self._book(name)
                tenants[name] = dict(
                    priority=t.priority, rate=t.rate, burst=t.burst,
                    tokens=round(t.tokens, 2), **b.as_dict())
            return {"t_unix": time.time(),
                    "queue_depth": len(self._pending),
                    "queue_bound": self.queue_bound,
                    "accepted_open": self._accepted_open,
                    "draining": self._draining.is_set(),
                    "tenants": tenants}

    def _write_tenants_json(self) -> None:
        try:
            atomic_write_text(
                tenants_json_path(self.outdir),
                json.dumps(self.tenant_snapshot(), indent=1,
                           sort_keys=True))
        except OSError:
            pass  # status mirror is a passenger

    # -- lifecycle ----------------------------------------------------------

    def request_drain(self) -> None:
        """SIGTERM semantics: stop accepting, finish everything
        accepted, exit :meth:`run` with the fleet verdict. Safe from
        signal handlers and any thread (event + scheduler drain are
        both idempotent)."""
        self._draining.set()

    def stats(self) -> dict:
        """Aggregate books (the in-process soak assertion's input)."""
        with self._lock:
            agg = _TenantBooks()
            for b in self._books.values():
                agg.submitted += b.submitted
                agg.accepted += b.accepted
                agg.shed += b.shed
                agg.quarantined += b.quarantined
                agg.completed += b.completed
            out = agg.as_dict()
            out["pending"] = len(self._pending)
            out["accepted_open"] = self._accepted_open
            return out

    def _idle(self) -> bool:
        if self.idle_exit_s <= 0:
            return False
        with self._lock:
            if self._pending or self._accepted_open:
                return False
            return (time.monotonic() - self._t_last_arrival
                    >= self.idle_exit_s)

    def run(self):
        """The service loop. Blocks until a drain request (or idle
        exit) and the fleet settles; returns the FleetResult."""
        sched_thread = threading.Thread(
            target=self._run_sched, name="survey-daemon-fleet",
            daemon=True)  # joined on the drain path; daemon so a
        # wedged service never blocks interpreter exit
        sched_thread.start()
        # submit() before the scheduler's startup manifest pass would
        # race it (the initial-promote loop walks self.obs): wait for
        # the ready edge before replaying the admission journal
        self._sched.wait_ready(30.0)
        n = self.recover()
        if n and self.verbose:
            print(f"# daemon: recovered {n} accepted observation(s) "
                  f"from the admission journal")
        for tenant, path in self._initial:
            self._arrive(tenant, path, lane="cli")
        if self._server is not None:
            srv_thread = threading.Thread(
                target=self._server.serve_forever,
                name="survey-daemon-submit", daemon=True)
            srv_thread.start()
            if self.verbose:
                print(f"# daemon: submissions on 127.0.0.1:{self.port} "
                      f"('<tenant> <path>' per line)")
        try:
            while not self._draining.is_set():
                self._scan_watch()
                self._pump()
                self._write_tenants_json()
                if self._idle():
                    if self.verbose:
                        print(f"# daemon: idle for "
                              f"{self.idle_exit_s:.1f}s; draining")
                    break
                self._draining.wait(self.poll_s)
        finally:
            self._draining.set()
            if self._server is not None:
                self._server.shutdown()
                self._server.server_close()
            # one last pump: arrivals admitted during shutdown drain
            # through the fleet; the rest of the pending queue is shed
            # with an explicit drain reason (never silently dropped)
            self._pump()
            with self._lock:
                leftovers = list(self._pending)
                for arr in leftovers:
                    depth = len(self._pending)
                    self._pending.remove(arr)
                    reason = "daemon draining: unaccepted at shutdown"
                    self._book(arr.tenant).shed += 1
                    telemetry.counter("daemon.shed_total")
                    telemetry.event("daemon.shed", tenant=arr.tenant,
                                    reason=reason, queue_depth=depth,
                                    path=os.path.basename(arr.path))
                    self._journal({"type": "shed",
                                   "tenant": arr.tenant,
                                   "path": arr.path, "reason": reason,
                                   "queue_depth": depth,
                                   "t_unix": time.time()})
            self._sched.request_drain()
            sched_thread.join()
            self._write_tenants_json()
            if self._journal_fh is not None:
                self._journal_fh.close()
                self._journal_fh = None
        if self._fleet_crash is not None:
            # the fleet died under us (injected kill, real fatal):
            # surface it — the accepted work is journal-manifested, a
            # restarted daemon resumes it with zero re-runs
            raise self._fleet_crash
        return self.result

    def _run_sched(self) -> None:
        try:
            self.result = self._sched.run()
        except BaseException as e:  # noqa: BLE001 - the daemon must
            # observe a fleet crash (injected kill in a soak leg, real
            # fatal) instead of waiting on a dead scheduler forever
            self._fleet_crash = e
            self._draining.set()
