"""Multi-host execution: ``jax.distributed`` init + the file-batch axis.

SURVEY.md §2.4 rows 4-5: the reference processes multi-beam / multi-file
observations with sequential per-file Python loops on one core
(``bin/autozap.py:76``, ``bin/fitkepler.py``); it has no communication
backend at all. The TPU-native scale-out has two layers:

1. **Within a host (ICI)**: the sweep engine's ``mesh`` argument shards DM
   trials / the time axis across local devices (parallel/sweep.py) — no
   code here is involved.
2. **Across hosts (DCN)**: this module. Each host initializes the JAX
   distributed runtime (:func:`initialize`), takes its slice of the file
   list (:func:`shard_files` — the data-parallel batch axis of this
   domain), sweeps its files locally, and merges the per-file candidate
   summaries with a fixed-size all-gather over DCN
   (:func:`allgather_candidates`). Candidate summaries are tiny (top-k
   records per file), so cross-host traffic is bytes, not data — the
   layout that keeps collectives off the raw-data path entirely.

The same entry points are no-ops in a single-process run, so pipelines are
written once: ``initialize()`` returns False and the "all-gather" is the
identity. A two-process CPU integration test exercises the real
``jax.distributed`` path (tests/test_distributed.py).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np
from pypulsar_tpu.tune import knobs

__all__ = [
    "initialize",
    "is_distributed",
    "local_rank",
    "local_count",
    "process_index",
    "process_count",
    "shard_files",
    "allgather_candidates",
    "multi_host_sweep",
    "time_sharded_sweep",
]

# environment surface (set by a launcher / scheduler on every host)
ENV_COORD = "PYPULSAR_TPU_COORDINATOR"  # e.g. "10.0.0.1:9021"
ENV_NPROC = "PYPULSAR_TPU_NUM_PROCESSES"
ENV_PID = "PYPULSAR_TPU_PROCESS_ID"

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host runtime; returns True if distributed.

    Arguments default to the ``PYPULSAR_TPU_{COORDINATOR,NUM_PROCESSES,
    PROCESS_ID}`` environment variables. With no coordinator configured
    (the common single-host case) this is a no-op returning False. Safe to
    call more than once.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or knobs.env_str(ENV_COORD)
    if not coordinator_address:
        return False
    if num_processes is None:
        num_processes = int(knobs.env_int(ENV_NPROC))
    if process_id is None:
        process_id = int(knobs.env_int(ENV_PID))
    if num_processes <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def is_distributed() -> bool:
    return _initialized or process_count() > 1


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def local_rank() -> int:
    """This process's rank WITHOUT touching jax: the launcher env
    (``PYPULSAR_TPU_PROCESS_ID``) when a grid is declared, else the jax
    grid if the distributed runtime is up, else 0. The survey fleet's
    ``--hosts`` launcher and host-id derivation read this — they must
    work on backends (CPU jaxlib) whose collectives cannot even
    initialize."""
    if int(knobs.env_int(ENV_NPROC)) > 1:
        return int(knobs.env_int(ENV_PID))
    if _initialized:
        return process_index()
    return 0


def local_count() -> int:
    """Declared process-grid size, env-first (see :func:`local_rank`)."""
    n = int(knobs.env_int(ENV_NPROC))
    if n > 1:
        return n
    if _initialized:
        return process_count()
    return 1


def shard_files(files: Sequence[str],
                index: Optional[int] = None,
                count: Optional[int] = None) -> List[str]:
    """This host's slice of the observation file list (round-robin, so
    hosts stay balanced when file sizes are similar — the batch axis over
    DCN).

    Surplus-host contract (round 18): with more processes than files the
    high ranks get an EMPTY slice — deliberately, and validated here so
    a mis-wired launcher fails loudly instead of silently double-
    processing (``index >= count`` would alias another rank's files).
    An idle shard is not an idle host: the survey fleet's claim loop
    turns empty-slice hosts into adopters/host-pool workers (they pick
    up orphaned observations the moment a loaded host dies), which is
    the behavior the multi-host tests pin."""
    if index is None:
        index = process_index()
    if count is None:
        count = process_count()
    count = int(count)
    index = int(index)
    if count < 1:
        raise ValueError(f"shard_files count must be >= 1, got {count}")
    if not 0 <= index < count:
        raise ValueError(
            f"shard_files rank {index} outside the {count}-process grid "
            f"[0, {count}): a wrapped rank would alias another host's "
            f"file share")
    return list(files[index::count])


def allgather_candidates(records: np.ndarray, pad_to: int) -> np.ndarray:
    """All-gather fixed-size candidate records across hosts.

    ``records[n, F]`` float64 rows (n <= pad_to); rows are padded with NaN
    to ``pad_to`` so every host contributes the same static shape (the
    collective compiles once). Returns the concatenated valid rows from
    all hosts, on every host. Identity in a single-process run.
    """
    records = np.asarray(records, dtype=np.float64)
    if records.ndim != 2:
        raise ValueError("records must be [n, fields]")
    n, F = records.shape
    if n > pad_to:
        records = records[:pad_to]
        n = pad_to
    padded = np.full((pad_to, F), np.nan)
    padded[:n] = records
    if process_count() == 1:
        gathered = padded[None]
    else:
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(padded))
    flat = gathered.reshape(-1, F)
    return flat[~np.isnan(flat[:, 0])]


def time_sharded_sweep(
    path_or_reader,
    dms,
    nsub: int = 64,
    group_size: int = 32,
    chunk_payload: Optional[int] = None,
    mesh=None,
    widths=None,
    engine: str = "auto",
    rfimask=None,
    rank: Optional[int] = None,
    count: Optional[int] = None,
    checkpoint_base: Optional[str] = None,
    checkpoint_every: int = 16,
    downsamp: int = 1,
    keep_chunk_peaks: bool = False,
):
    """Sweep ONE file with its TIME axis sharded across hosts.

    The wire between host and device is the streamed sweep's measured
    ceiling (BENCHNOTES r4: 63 MB/s tunnel, compute fully hidden), and
    DM-sharding cannot help it — every host still needs every sample.
    Time-sharding does: host ``k`` of ``P`` streams only its contiguous
    window of chunks (1/P of the bytes), windows overlap by the
    dedispersion+boxcar reach exactly as chunks do (overlap-save; the
    windowed `_ReaderSource` reads its seam PAST the window end), and
    what crosses DCN afterwards is one accumulator per host: the f64
    moment sums, f32 window-sum maxima and their positions
    (``sweep.AccumParts``, ~KBs). Merging in window order
    (``merge_accum_parts``) reproduces the sequential sweep exactly up
    to f64 re-association of the moment sums — mb/ab (and therefore
    every peak and its sample position) merge bit-identically, and the
    per-channel baseline comes from the FILE's first block on every host
    so window results share one reference.

    ``rank``/``count`` default to the jax.distributed process grid (and
    may be passed explicitly for in-process testing; see also
    :func:`time_shard_local_accum` for the mergeable per-window piece).
    Every host returns the same finalized ``SweepResult``.
    """
    from pypulsar_tpu.parallel.sweep import finalize_sweep, merge_accum_parts

    if rank is None:
        rank = process_index()
    if count is None:
        count = process_count()
    plan, local = time_shard_local_accum(
        path_or_reader, dms, rank, count, nsub=nsub, group_size=group_size,
        chunk_payload=chunk_payload, mesh=mesh, widths=widths, engine=engine,
        rfimask=rfimask, checkpoint_base=checkpoint_base,
        checkpoint_every=checkpoint_every, downsamp=downsamp,
        keep_chunk_peaks=keep_chunk_peaks)
    parts = _allgather_accums(local, count, with_peaks=keep_chunk_peaks,
                              nr=plan.n_real_trials)
    merged = merge_accum_parts(parts)
    return finalize_sweep(plan, merged.n, merged.s, merged.ss, merged.mb,
                          merged.ab, merged.baseline_sum,
                          chunk_mb=list(merged.chunk_mb) or None,
                          chunk_ab=list(merged.chunk_ab) or None)


def time_shard_local_accum(
    path_or_reader,
    dms,
    rank: int,
    count: int,
    nsub: int = 64,
    group_size: int = 32,
    chunk_payload: Optional[int] = None,
    mesh=None,
    widths=None,
    engine: str = "auto",
    rfimask=None,
    checkpoint_base: Optional[str] = None,
    checkpoint_every: int = 16,
    downsamp: int = 1,
    keep_chunk_peaks: bool = False,
):
    """(plan, AccumParts) for rank's window of the file — the mergeable
    half of :func:`time_sharded_sweep` (windows merge with
    ``sweep.merge_accum_parts`` in rank order). ``downsamp`` sweeps the
    factor-downsampled series (windows align to whole raw bins);
    ``keep_chunk_peaks`` carries per-chunk peak records for multi-event
    single-pulse lists (--all-events)."""
    from pypulsar_tpu.parallel.sweep import DEFAULT_WIDTHS

    if widths is None:
        widths = DEFAULT_WIDTHS
    reader = path_or_reader
    opened = isinstance(path_or_reader, str)
    if opened:
        from pypulsar_tpu.io import filterbank

        reader = filterbank.FilterbankFile(path_or_reader)
    try:
        return _time_shard_local_accum(
            reader, dms, rank, count, nsub, group_size, chunk_payload,
            mesh, widths, engine, rfimask, checkpoint_base,
            checkpoint_every, downsamp=downsamp,
            keep_chunk_peaks=keep_chunk_peaks)
    finally:
        if opened:
            close = getattr(reader, "close", None)
            if close is not None:
                close()


def _time_shard_local_accum(reader, dms, rank, count, nsub, group_size,
                            chunk_payload, mesh, widths, engine, rfimask,
                            checkpoint_base, checkpoint_every, downsamp=1,
                            keep_chunk_peaks=False):
    import jax.numpy as jnp

    from pypulsar_tpu.parallel import make_sweep_plan
    from pypulsar_tpu.parallel.staged import (
        _MaskedSource,
        _ReaderSource,
        _downsampled_blocks,
        _mask_tag,
    )
    from pypulsar_tpu.parallel.sweep import (
        AccumParts,
        SweepCheckpoint,
        sweep_stream,
    )

    factor = max(1, int(downsamp))
    probe = _ReaderSource(reader)  # full-file view for geometry
    T = probe.nsamples // factor   # downsampled samples (the sweep grid)
    dms = np.asarray(dms, dtype=np.float64)
    # group padding so groups divide the mesh axis and land on the
    # compile plane's bucket ladder (same rule as staged._run_step;
    # group_size<=0 resolves inside make_sweep_plan, so resolve it
    # first for the ceiling arithmetic)
    from pypulsar_tpu.parallel.sweep import (
        choose_group_size,
        padded_group_count,
    )

    gs = group_size
    if gs <= 0:
        gs = choose_group_size(dms, probe.frequencies,
                               probe.tsamp * factor, nsub)
    ndm = 1 if mesh is None else mesh.shape["dm"]
    pad_groups_to = padded_group_count(-(-len(dms) // gs), ndm)
    group_size = gs
    plan = make_sweep_plan(dms, probe.frequencies, probe.tsamp * factor,
                           nsub=nsub, group_size=group_size,
                           widths=tuple(widths),
                           pad_groups_to=pad_groups_to)
    if chunk_payload is None:
        from pypulsar_tpu.parallel.sweep import default_chunk_payload

        chunk_payload = default_chunk_payload(plan.min_overlap)
    payload = min(chunk_payload, T)
    if payload <= plan.min_overlap:
        payload = min(T, 2 * plan.min_overlap + 1)

    # common per-channel baseline: the FILE's first (downsampled) block,
    # computed the same way sweep_stream would (f32 mean of the ingested
    # block, mask fill applied first when masking), so a 1-host run
    # bit-matches plain sweep_flat
    src0 = _ReaderSource(reader, 0, min(payload, T) * factor)
    if rfimask is not None:
        src0 = _MaskedSource(src0, rfimask)
    _, first = next(iter(_downsampled_blocks(
        src0, factor, payload, plan.min_overlap)))
    baseline = jnp.mean(jnp.asarray(first, dtype=jnp.float32), axis=1,
                        keepdims=True)

    # contiguous whole-chunk windows, chunk-balanced across hosts
    # (coordinates below are DOWNSAMPLED samples; raw file offsets scale
    # by the factor)
    nchunks = -(-T // payload)
    per = -(-nchunks // count)
    s0 = min(rank * per * payload, T)
    s1 = min((rank + 1) * per * payload, T)
    if s0 >= s1:  # more hosts than chunks: identity contribution
        D, W = plan.n_trials, len(plan.widths)
        return plan, AccumParts(
            0, np.zeros(D), np.zeros(D),
            np.full((D, W), -np.inf, np.float32),
            np.zeros((D, W), np.int64),
            float(np.asarray(baseline, np.float64).sum()))
    src = _ReaderSource(reader, s0 * factor, s1 * factor)
    if rfimask is not None:
        src = _MaskedSource(src, rfimask)
    blocks = _downsampled_blocks(src, factor, payload, plan.min_overlap)
    ckpt = (SweepCheckpoint(f"{checkpoint_base}.r{rank}",
                            every=checkpoint_every)
            if checkpoint_base else None)
    # ds tag only when downsampling: ds=1 results are bit-identical to
    # the pre-downsamp format, and tagging them would spuriously
    # invalidate every existing plain time-shard checkpoint on resume
    ds_tag = f"/ds={factor}" if factor > 1 else ""
    ctx = f"/window={s0}:{s1}{ds_tag}" + _mask_tag(rfimask)

    def block_factory(cursor_ds: int):
        """Seek-resume within this rank's window (round 5): re-root the
        stream at the checkpoint cursor instead of re-shipping the
        window's pre-cursor bytes. The cursor sits on a payload
        boundary, so the re-rooted window keeps the seam alignment."""
        from pypulsar_tpu.parallel.staged import _reroot_source

        seeked = _reroot_source(src, cursor_ds * factor)
        if seeked is None:
            return _downsampled_blocks(src, factor, payload,
                                       plan.min_overlap)
        return _downsampled_blocks(seeked, factor, payload,
                                   plan.min_overlap)

    return plan, sweep_stream(plan, blocks, payload, mesh=mesh,
                              chan_major=True, baseline=baseline,
                              engine=engine, checkpoint=ckpt,
                              checkpoint_context=ctx,
                              keep_chunk_peaks=keep_chunk_peaks,
                              finalize=False,
                              block_factory=block_factory)


def barrier(name: str = "pypulsar_barrier"):
    """Cross-host synchronization point (no-op single-process). Used by
    the time-sharded --write-dats flow: every rank must finish writing
    its segment files before rank 0 concatenates them."""
    if process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def time_sharded_ddplan(
    path_or_reader,
    ddplan,
    nsub: int = 64,
    group_size: int = 32,
    chunk_payload: Optional[int] = None,
    mesh=None,
    widths=None,
    engine: str = "auto",
    rfimask=None,
    rank: Optional[int] = None,
    count: Optional[int] = None,
    checkpoint_base: Optional[str] = None,
    checkpoint_every: int = 16,
):
    """DDplan-staged sweep of ONE file with the TIME axis sharded across
    hosts (VERDICT r4 item 3 — the realistic production shape: a staged
    plan over a single long file whose host->device wire is the
    bottleneck).

    Each DDstep is an independent flat sweep at its own downsampling, so
    the step loop simply runs :func:`time_shard_local_accum` per step —
    each host streams 1/P of the RAW bytes per step, and steps with
    downsamp > 1 additionally downsample on the HOST before the wire
    when that shrinks the shipped bytes further
    (staged._host_downsample_wins: an 8-bit file at downsamp >= 4 ships
    2/downsamp B per raw sample instead of 1 B) — so host k ships
    ~1/(P*max(downsamp/2, 1)) of each step's bytes. Merged accumulators
    cross DCN per step (~KBs). Every host returns the same
    StagedSweepResult; checkpoints go to
    ``{checkpoint_base}.step{i}.r{rank}``.
    """
    from pypulsar_tpu.parallel.staged import StagedSweepResult, StepResult
    from pypulsar_tpu.parallel.sweep import finalize_sweep, merge_accum_parts

    if rank is None:
        rank = process_index()
    if count is None:
        count = process_count()
    steps = []
    for i, st in enumerate(ddplan.DDsteps):
        dms = np.asarray(st.DMs, dtype=np.float64)
        base = f"{checkpoint_base}.step{i}" if checkpoint_base else None
        plan, local = time_shard_local_accum(
            path_or_reader, dms, rank, count, nsub=nsub,
            group_size=group_size, chunk_payload=chunk_payload, mesh=mesh,
            widths=widths, engine=engine, rfimask=rfimask,
            checkpoint_base=base, checkpoint_every=checkpoint_every,
            downsamp=int(st.downsamp))
        parts = _allgather_accums(local, count)
        merged = merge_accum_parts(parts)
        res = finalize_sweep(plan, merged.n, merged.s, merged.ss,
                             merged.mb, merged.ab, merged.baseline_sum)
        # the plan's dt already carries the step's downsampling factor
        steps.append(StepResult(downsamp=int(st.downsamp),
                                dt=float(plan.dt), result=res))
    return StagedSweepResult(steps=steps)


def _allgather_accums(local, count: int, with_peaks: bool = False,
                      nr: int = 0):
    """All ranks' AccumParts, in rank order. Packs every field into one
    f64 matrix so the collective is a single fixed-shape all-gather
    (``ab`` int64 sample positions are exact in f64 below 2^53).
    ``with_peaks`` additionally gathers the per-chunk peak records
    ([nr, W] per chunk; chunk counts differ per rank, so counts gather
    first and arrays pad to the max — every rank must pass the same
    ``with_peaks`` or the collectives deadlock)."""
    from pypulsar_tpu.parallel.sweep import AccumParts

    if count == 1:
        return [local]
    actual = process_count()
    if actual != count:
        # gathering with a mismatched grid would silently drop whole
        # windows (only `actual` rows come back) and finalize wrong SNRs
        raise ValueError(
            f"time-shard count {count} != jax process count {actual}; "
            f"for in-process testing merge time_shard_local_accum parts "
            f"with sweep.merge_accum_parts instead")
    from jax.experimental import multihost_utils

    D, W = local.mb.shape
    packed = np.concatenate([
        np.full(1, float(local.n)),
        np.full(1, local.baseline_sum),
        np.asarray(local.s, np.float64),
        np.asarray(local.ss, np.float64),
        np.asarray(local.mb, np.float64).ravel(),
        np.asarray(local.ab, np.float64).ravel(),
    ])
    gathered = np.asarray(multihost_utils.process_allgather(packed))
    parts = []
    for row in gathered:
        o = 2
        s = row[o:o + D]; o += D
        ss = row[o:o + D]; o += D
        mb = row[o:o + D * W].reshape(D, W).astype(np.float32); o += D * W
        ab = row[o:o + D * W].reshape(D, W).astype(np.int64)
        parts.append(AccumParts(int(row[0]), s, ss, mb, ab, float(row[1])))
    if with_peaks:
        nloc = len(local.chunk_mb)
        counts = np.asarray(multihost_utils.process_allgather(
            np.asarray([nloc], np.int64))).reshape(-1)
        m = int(counts.max())
        if m:
            # native dtypes (f32 peaks, i64 positions) in two gathers:
            # a single f64 buffer would cost 16 B/cell vs these 12 — at
            # survey scale (2700 chunks x 2000 trials x 6 widths) that
            # is hundreds of MB of DCN per host
            mb_buf = np.zeros((m, nr, W), np.float32)
            ab_buf = np.zeros((m, nr, W), np.int64)
            if nloc:
                mb_buf[:nloc] = np.stack(local.chunk_mb)
                ab_buf[:nloc] = np.stack(local.chunk_ab)
            g_mb = np.asarray(multihost_utils.process_allgather(mb_buf))
            g_ab = np.asarray(multihost_utils.process_allgather(ab_buf))
            for r in range(count):
                c = int(counts[r])
                parts[r] = parts[r]._replace(
                    chunk_mb=tuple(g_mb[r, i] for i in range(c)),
                    chunk_ab=tuple(g_ab[r, i] for i in range(c)))
    return parts


def multi_host_sweep(
    files: Sequence[str],
    dms=None,
    nsub: int = 64,
    group_size: int = 32,
    chunk_payload: Optional[int] = None,
    mesh=None,
    topk_per_file: int = 16,
    open_reader=None,
    *,
    ddplan=None,
    downsamp: int = 1,
    widths=None,
    engine: str = "auto",
    rfimask=None,
    checkpoint_base: Optional[str] = None,
    checkpoint_every: int = 16,
    per_file=None,
) -> np.ndarray:
    """Sweep a file list across hosts; return the merged candidate table.

    Every host sweeps ``shard_files(files)`` with the local engine (its
    own ICI mesh if ``mesh`` is given), then the per-file top-k summaries
    are all-gathered over DCN and merged by SNR. Output columns:
    ``(file_index, dm, snr, width_bins, sample, downsamp)``; every host
    returns the same merged table.

    Either a flat ``dms`` grid or a staged ``ddplan``
    (plan.ddplan.DDplan, executed per-step at its own downsampling —
    parallel.staged.sweep_ddplan) drives each file's sweep.
    ``per_file(file_index, path, staged_result)`` runs on the host that
    swept the file, right after its sweep — the artifact hook the CLI
    uses to write real per-file ``.cands``/``.dat`` products (VERDICT r3
    item 5). ``checkpoint_base`` enables in-sweep checkpointing at
    ``{checkpoint_base}.f{i}`` per file.
    """
    from pypulsar_tpu.parallel.staged import sweep_ddplan, sweep_flat
    from pypulsar_tpu.parallel.sweep import DEFAULT_WIDTHS

    if (dms is None) == (ddplan is None):
        raise ValueError("exactly one of dms / ddplan must be given")
    if widths is None:
        widths = DEFAULT_WIDTHS
    if open_reader is None:
        from pypulsar_tpu.io import filterbank

        open_reader = filterbank.FilterbankFile

    rows = []
    files = list(files)
    for fi in range(process_index(), len(files), process_count()):
        reader = open_reader(files[fi])
        ckpt = (f"{checkpoint_base}.f{fi}" if checkpoint_base else None)
        try:
            if ddplan is not None:
                staged = sweep_ddplan(reader, ddplan, nsub=nsub,
                                      group_size=group_size,
                                      widths=widths,
                                      chunk_payload=chunk_payload,
                                      mesh=mesh, engine=engine,
                                      rfimask=rfimask,
                                      checkpoint_path=ckpt,
                                      checkpoint_every=checkpoint_every)
            else:
                staged = sweep_flat(reader, dms, downsamp=downsamp,
                                    nsub=nsub, group_size=group_size,
                                    widths=widths,
                                    chunk_payload=chunk_payload, mesh=mesh,
                                    engine=engine, rfimask=rfimask,
                                    checkpoint_path=ckpt,
                                    checkpoint_every=checkpoint_every)
        finally:
            close = getattr(reader, "close", None)
            if close is not None:
                close()
        if per_file is not None:
            per_file(fi, files[fi], staged)
        for c in staged.best(topk_per_file):
            rows.append([fi, c["dm"], c["snr"], c["width_bins"],
                         c["sample"], c["downsamp"]])
    local = np.asarray(rows, dtype=np.float64).reshape(-1, 6)
    # pad_to must be identical on every host (static collective shape):
    # size for the largest per-host file share
    max_share = -(-len(files) // max(process_count(), 1))
    merged = allgather_candidates(local, pad_to=topk_per_file * max(max_share, 1))
    order = np.argsort(merged[:, 2])[::-1]
    return merged[order]
