"""Multi-host execution: ``jax.distributed`` init + the file-batch axis.

SURVEY.md §2.4 rows 4-5: the reference processes multi-beam / multi-file
observations with sequential per-file Python loops on one core
(``bin/autozap.py:76``, ``bin/fitkepler.py``); it has no communication
backend at all. The TPU-native scale-out has two layers:

1. **Within a host (ICI)**: the sweep engine's ``mesh`` argument shards DM
   trials / the time axis across local devices (parallel/sweep.py) — no
   code here is involved.
2. **Across hosts (DCN)**: this module. Each host initializes the JAX
   distributed runtime (:func:`initialize`), takes its slice of the file
   list (:func:`shard_files` — the data-parallel batch axis of this
   domain), sweeps its files locally, and merges the per-file candidate
   summaries with a fixed-size all-gather over DCN
   (:func:`allgather_candidates`). Candidate summaries are tiny (top-k
   records per file), so cross-host traffic is bytes, not data — the
   layout that keeps collectives off the raw-data path entirely.

The same entry points are no-ops in a single-process run, so pipelines are
written once: ``initialize()`` returns False and the "all-gather" is the
identity. A two-process CPU integration test exercises the real
``jax.distributed`` path (tests/test_distributed.py).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

__all__ = [
    "initialize",
    "is_distributed",
    "process_index",
    "process_count",
    "shard_files",
    "allgather_candidates",
    "multi_host_sweep",
]

# environment surface (set by a launcher / scheduler on every host)
ENV_COORD = "PYPULSAR_TPU_COORDINATOR"  # e.g. "10.0.0.1:9021"
ENV_NPROC = "PYPULSAR_TPU_NUM_PROCESSES"
ENV_PID = "PYPULSAR_TPU_PROCESS_ID"

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host runtime; returns True if distributed.

    Arguments default to the ``PYPULSAR_TPU_{COORDINATOR,NUM_PROCESSES,
    PROCESS_ID}`` environment variables. With no coordinator configured
    (the common single-host case) this is a no-op returning False. Safe to
    call more than once.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get(ENV_COORD)
    if not coordinator_address:
        return False
    if num_processes is None:
        num_processes = int(os.environ.get(ENV_NPROC, "1"))
    if process_id is None:
        process_id = int(os.environ.get(ENV_PID, "0"))
    if num_processes <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def is_distributed() -> bool:
    return _initialized or process_count() > 1


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def shard_files(files: Sequence[str],
                index: Optional[int] = None,
                count: Optional[int] = None) -> List[str]:
    """This host's slice of the observation file list (round-robin, so
    hosts stay balanced when file sizes are similar — the batch axis over
    DCN)."""
    if index is None:
        index = process_index()
    if count is None:
        count = process_count()
    return list(files[index::count])


def allgather_candidates(records: np.ndarray, pad_to: int) -> np.ndarray:
    """All-gather fixed-size candidate records across hosts.

    ``records[n, F]`` float64 rows (n <= pad_to); rows are padded with NaN
    to ``pad_to`` so every host contributes the same static shape (the
    collective compiles once). Returns the concatenated valid rows from
    all hosts, on every host. Identity in a single-process run.
    """
    records = np.asarray(records, dtype=np.float64)
    if records.ndim != 2:
        raise ValueError("records must be [n, fields]")
    n, F = records.shape
    if n > pad_to:
        records = records[:pad_to]
        n = pad_to
    padded = np.full((pad_to, F), np.nan)
    padded[:n] = records
    if process_count() == 1:
        gathered = padded[None]
    else:
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(padded))
    flat = gathered.reshape(-1, F)
    return flat[~np.isnan(flat[:, 0])]


def multi_host_sweep(
    files: Sequence[str],
    dms=None,
    nsub: int = 64,
    group_size: int = 32,
    chunk_payload: Optional[int] = None,
    mesh=None,
    topk_per_file: int = 16,
    open_reader=None,
    *,
    ddplan=None,
    downsamp: int = 1,
    widths=None,
    engine: str = "auto",
    rfimask=None,
    checkpoint_base: Optional[str] = None,
    checkpoint_every: int = 16,
    per_file=None,
) -> np.ndarray:
    """Sweep a file list across hosts; return the merged candidate table.

    Every host sweeps ``shard_files(files)`` with the local engine (its
    own ICI mesh if ``mesh`` is given), then the per-file top-k summaries
    are all-gathered over DCN and merged by SNR. Output columns:
    ``(file_index, dm, snr, width_bins, sample, downsamp)``; every host
    returns the same merged table.

    Either a flat ``dms`` grid or a staged ``ddplan``
    (plan.ddplan.DDplan, executed per-step at its own downsampling —
    parallel.staged.sweep_ddplan) drives each file's sweep.
    ``per_file(file_index, path, staged_result)`` runs on the host that
    swept the file, right after its sweep — the artifact hook the CLI
    uses to write real per-file ``.cands``/``.dat`` products (VERDICT r3
    item 5). ``checkpoint_base`` enables in-sweep checkpointing at
    ``{checkpoint_base}.f{i}`` per file.
    """
    from pypulsar_tpu.parallel.staged import sweep_ddplan, sweep_flat
    from pypulsar_tpu.parallel.sweep import DEFAULT_WIDTHS

    if (dms is None) == (ddplan is None):
        raise ValueError("exactly one of dms / ddplan must be given")
    if widths is None:
        widths = DEFAULT_WIDTHS
    if open_reader is None:
        from pypulsar_tpu.io import filterbank

        open_reader = filterbank.FilterbankFile

    rows = []
    files = list(files)
    for fi in range(process_index(), len(files), process_count()):
        reader = open_reader(files[fi])
        ckpt = (f"{checkpoint_base}.f{fi}" if checkpoint_base else None)
        try:
            if ddplan is not None:
                staged = sweep_ddplan(reader, ddplan, nsub=nsub,
                                      group_size=group_size,
                                      widths=widths,
                                      chunk_payload=chunk_payload,
                                      mesh=mesh, engine=engine,
                                      rfimask=rfimask,
                                      checkpoint_path=ckpt,
                                      checkpoint_every=checkpoint_every)
            else:
                staged = sweep_flat(reader, dms, downsamp=downsamp,
                                    nsub=nsub, group_size=group_size,
                                    widths=widths,
                                    chunk_payload=chunk_payload, mesh=mesh,
                                    engine=engine, rfimask=rfimask,
                                    checkpoint_path=ckpt,
                                    checkpoint_every=checkpoint_every)
        finally:
            close = getattr(reader, "close", None)
            if close is not None:
                close()
        if per_file is not None:
            per_file(fi, files[fi], staged)
        for c in staged.best(topk_per_file):
            rows.append([fi, c["dm"], c["snr"], c["width_bins"],
                         c["sample"], c["downsamp"]])
    local = np.asarray(rows, dtype=np.float64).reshape(-1, 6)
    # pad_to must be identical on every host (static collective shape):
    # size for the largest per-host file share
    max_share = -(-len(files) // max(process_count(), 1))
    merged = allgather_candidates(local, pad_to=topk_per_file * max(max_share, 1))
    order = np.argsort(merged[:, 2])[::-1]
    return merged[order]
