"""Spectral fusion: serve accel-search from the sweep's own spectra.

The streamed handoff (parallel/accelpipe.py) still round-trips every DM
trial through the time domain: the Fourier sweep engine holds each
trial's spectrum ``Xts`` on device, ``irfft``s it to a series chunk,
pulls the chunk to a host buffer — and ``prep_spectra_batch``
immediately undoes all of that with a fresh whole-series ``rfft``. The
accel stage is the chain's measured weak link (15.77x vs 113-9,896x
elsewhere) and already runs at 85% of its FFT roofline (BENCHNOTES
round 6), so the remaining win is doing FEWER transforms, not faster
ones — Fourier-domain dedispersion (PAPERS.md 2110.03482: one forward
transform of the raw data serves every trial; 1201.5380: the
shift-and-sum itself is bandwidth-cheap once the transform is
amortized). This module is that path, in two regimes — and the regime
choice is the parity-gate decision ISSUE 10 called for:

- **stitched** (the DEFAULT — the design that survives the parity
  gate at every geometry): per-chunk dedispersed rows — the sweep's
  own kernel, bit-identical values — scatter straight into a
  device-resident ``[D, T]`` buffer (overlap-save valid windows
  partition the time axis), and ONE fused ``prep_spectra_batch``
  dispatch per DM slice transforms the whole buffer. Candidates are
  BIT-identical to the streamed device-prep path (same rows, same prep
  kernel, per-row math). The series never crosses the host link
  (``specfuse.bytes_on_device``: the per-chunk D2H pull and the prep
  H2D re-ship are both gone) and prep collapses from one dispatch per
  batch to one per slice — on the remote-tunnel deployment every
  dispatch costs ~60 ms before any math (BENCHNOTES). The buffer is
  HBM-resident, which is why the all-at-once option is bounded by the
  2^26-sample / 275 GB cliff parallel/staged.py documents: past the
  ``PYPULSAR_TPU_SPECFUSE_HBM`` budget the caller slices the DM axis,
  one extra raw pass per slice — the accelpipe RAM-slicing contract.
- **decimated** (opt-in via ``PYPULSAR_TPU_SPECFUSE_MODE=decimate``;
  needs a single Fourier chunk covering the observation, ``n_fft % T
  == 0`` — i.e. power-of-two series lengths — and the 'fourier'
  engine): the sweep's spectra kernel
  (ops.fourier_dedisperse.sweep_chunk_spectra) hands over each trial's
  ``Xts`` pre-irfft and DECIMATES it onto the T-point grid — the
  per-trial irfft AND the per-trial whole-series rfft are both gone,
  zero transforms per trial, counted on
  ``specfuse.fft_pairs_elided``. The catch, measured during round 10
  and documented in the kernel's docstring: decimation IS circular
  dedispersion (the 2110.03482 convention), while the time-domain
  engines use PRESTO's zero-padded linear shifts, so the final
  ``max_total_shift`` samples — boundary garbage under either
  convention — differ by real data and the candidate tables are NOT
  byte-identical at toy scale. Hence opt-in, not default: the
  structural win is real and counted, the parity default stays exact.

Both regimes honor the handoff's existing machinery: RAM-budgeted DM
slicing (the caller's), ``halving_dispatch`` OOM recovery on every
device dispatch, ``--mesh k`` DM sharding with spectra staying
``P('dm')``-sharded end to end, journal/resume (the caller's; the
``specfuse.after_stitch`` kill-point marks the new stage boundary), and
prefetch overlap (batch gathers slice the resident planes).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pypulsar_tpu.compile import plane_jit
from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.tune import knobs

__all__ = ["fused_spectra_slice", "spectral_trial_bytes"]


def spectral_trial_bytes(T: int) -> int:
    """Device bytes ONE trial occupies while a slice is fused: the
    stitched series row (4T f32) plus the prepped spectrum planes
    (8*(T//2+1)). The decimated regime skips the series buffer, but
    budgeting for the worse regime keeps the caller's DM-slice choice
    regime-independent (a slice must not OOM because the geometry fell
    back to stitching)."""
    return 4 * T + 8 * (T // 2 + 1)


def _make_sharded_spectra_chunk(mesh, nsub, n_fft, dec_stride, dec_len,
                                mean_len):
    """Spectra kernel with trial groups sharded over the mesh 'dm' axis
    — the decimated regime's twin of sweep.make_sharded_series_chunk.
    The chunk replicates; each device computes only its local groups'
    spectra and the planes concatenate in group order (P('dm')), so the
    values are bit-identical to the unsharded kernel's."""
    from jax.sharding import PartitionSpec as P

    from pypulsar_tpu.ops.fourier_dedisperse import sweep_chunk_spectra_impl
    from pypulsar_tpu.parallel.sweep import shard_map_compat

    def impl(data, s1, s2):
        return sweep_chunk_spectra_impl(data, s1, s2, nsub, n_fft,
                                        dec_stride, dec_len, mean_len)

    fn = shard_map_compat(impl, mesh=mesh,
                          in_specs=(P(), P("dm"), P("dm")),
                          out_specs=(P("dm"), P("dm")))
    # mesh-closing factory: AOT keying is unsound across meshes, so the
    # plane keeps plain-jit dispatch (aot=False) and owns the telemetry
    return plane_jit(fn, stage="specfuse", name="specfuse_sharded_chunk",
                     aot=False)


def fused_spectra_slice(
    reader,
    dms,
    schedule=None,
    downsamp: int = 1,
    nsub: int = 64,
    group_size: int = 32,
    rfimask=None,
    engine: str = "auto",
    chunk_payload: Optional[int] = None,
    mesh=None,
    verbose: bool = False,
) -> dict:
    """One pass over ``reader``: every trial in ``dms`` fused to its
    PREPPED (dereddened) T-point spectrum, device-resident.

    Returns ``dict(re, im, n_real, T, dt_eff, regime)`` — ``re``/``im``
    are ``[Dpad, T//2+1]`` float32 planes (``Dpad`` pads trials to the
    stage-1 group and mesh multiples; rows ``[:n_real]`` are the real
    trials, in ``dms`` order), consumable directly by
    ``accel_search_batch`` via row gathers. ``schedule`` is the
    ``deredden_schedule(T//2+1)`` (built here when omitted).

    ``PYPULSAR_TPU_SPECFUSE_MODE``: ``stitch`` (default — bit-exact
    parity with the streamed path) or ``decimate`` (opt-in
    zero-transforms-per-trial regime with CIRCULAR boundary semantics,
    module docstring; falls back to stitched where its geometry gate
    fails).
    """
    from pypulsar_tpu.fourier.kernels import (
        deredden_schedule,
        prep_spectra_batch,
    )
    from pypulsar_tpu.ops.fourier_dedisperse import (
        fourier_chunk_len,
        sweep_chunk_spectra,
    )
    from pypulsar_tpu.parallel.staged import (
        _MaskedSource,
        _ReaderSource,
        _downsampled_blocks,
        dats_geometry,
    )
    from pypulsar_tpu.parallel.sweep import (
        dedisperse_series_chunk,
        make_sharded_series_chunk,
        make_sweep_plan,
        resolve_engine,
    )
    from pypulsar_tpu.resilience import dataguard
    from pypulsar_tpu.resilience.retry import halving_dispatch

    factor = max(1, int(downsamp))
    dms = np.asarray(dms, dtype=np.float64)
    probe = _ReaderSource(reader)
    # round-17 auto-tuning consult at the fused slice's own geometry
    # (the SPECFUSE_HBM slice budget is this stage's knob); env wins
    from pypulsar_tpu import tune

    tune.apply_cached("specfuse", nchan=len(probe.frequencies),
                      nsamp=int(probe.nsamples) // factor)
    plan, payload, T = dats_geometry(reader, dms, downsamp=factor,
                                     nsub=nsub, group_size=group_size,
                                     chunk_payload=chunk_payload)
    dt_eff = probe.tsamp * factor
    ndm = 1 if mesh is None else int(mesh.shape["dm"])
    dev_ids = ([int(getattr(d, "id", -1)) for d in mesh.devices.flat]
               if mesh is not None else None)
    from pypulsar_tpu.parallel.sweep import padded_group_count

    padded_groups = padded_group_count(plan.n_groups, ndm)
    if padded_groups != plan.n_groups:
        # padded groups replicate the last real trial (group math is
        # independent; rows [:n_real] below are untouched)
        plan = make_sweep_plan(dms, probe.frequencies, dt_eff,
                               nsub=nsub, group_size=plan.group_size,
                               widths=(1,), pad_groups_to=padded_groups)
    if schedule is None:
        schedule = deredden_schedule(T // 2 + 1)

    engine_r = resolve_engine(engine)
    need = payload + plan.min_overlap
    n_fft = fourier_chunk_len(need)
    n_chunks = -(-T // payload)
    # decimate is OPT-IN (circular boundary semantics — module
    # docstring) and additionally geometry-gated; anything else stitches
    decimated = (knobs.env_str("PYPULSAR_TPU_SPECFUSE_MODE") == "decimate"
                 and engine_r == "fourier" and n_chunks == 1
                 and T > 1 and n_fft % T == 0)
    if verbose:
        mode = ("decimated (0 transforms/trial)" if decimated
                else "stitched (%d chunks)" % n_chunks)
        print(f"# specfuse: {len(dms)} trials x {T} samples, "
              f"{mode}, engine={engine_r}")

    src = dataguard.guard_source(_ReaderSource(reader))
    if rfimask is not None:
        src = _MaskedSource(src, rfimask)
    s1b = jnp.asarray(plan.stage1_bins)
    s2b = jnp.asarray(plan.stage2_bins)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec_dm = NamedSharding(mesh, P("dm"))
        s1b = jax.device_put(s1b, spec_dm)
        s2b = jax.device_put(s2b, spec_dm)
    Dpad = plan.n_trials
    n_real = len(dms)
    F = T // 2 + 1

    def group_dispatch(make_whole, make_slice):
        """Run a per-chunk device dispatch over the trial-group axis
        under the OOM-halving policy: ``make_whole()`` dispatches every
        group (the hot path — uses the pre-laid full tables);
        ``make_slice(s1, s2)`` a group slice. Per-group math is
        independent, so concatenated halves are bit-identical."""
        def run(lo, hi):
            faultinject.trip("specfuse.chunk_dispatch")
            if (lo, hi) == (0, plan.n_groups):
                return make_whole()
            s1_sl, s2_sl = s1b[lo:hi], s2b[lo:hi]
            if mesh is not None:
                s1_sl = jax.device_put(s1_sl, spec_dm)
                s2_sl = jax.device_put(s2_sl, spec_dm)
            return make_slice(s1_sl, s2_sl)

        return halving_dispatch(run, plan.n_groups, min_size=ndm,
                                what="specfuse.chunk")

    def _concat(parts):
        outs = [r for _, _, r in parts]
        if len(outs) == 1:
            return outs[0]
        if isinstance(outs[0], tuple):
            return tuple(jnp.concatenate([o[j] for o in outs])
                         for j in range(len(outs[0])))
        return jnp.concatenate(outs)

    attrs = dict(n_trials=n_real, n_samples=int(T),
                 regime="decimated" if decimated else "stitched")
    if dev_ids is not None:
        attrs["dev"] = dev_ids
    with telemetry.span("specfuse_slice", aggregate=False, **attrs):
        if decimated:
            stride, dlen = n_fft // T, F
            sharded_fn = (None if mesh is None else
                          _make_sharded_spectra_chunk(
                              mesh, plan.nsub, n_fft, stride, dlen, T))
            _pos, block = next(iter(_downsampled_blocks(
                src, factor, payload, plan.min_overlap)))
            L = int(block.shape[1])
            if L < need:
                block = jnp.pad(block, ((0, 0), (0, need - L)))
            chunk_attrs = {} if dev_ids is None else {"dev": dev_ids}
            with telemetry.span("specfuse_spectra", **chunk_attrs):
                raw = _concat(group_dispatch(
                    lambda: (sharded_fn(block, s1b, s2b)
                             if sharded_fn is not None else
                             sweep_chunk_spectra(block, s1b, s2b,
                                                 plan.nsub, n_fft, stride,
                                                 dlen, T)),
                    lambda a, b: (sharded_fn(block, a, b)
                                  if sharded_fn is not None else
                                  sweep_chunk_spectra(block, a, b,
                                                      plan.nsub, n_fft,
                                                      stride, dlen, T))))
            telemetry.counter("specfuse.fft_pairs_elided", n_real)
            if dev_ids is not None:
                for d in dev_ids:
                    telemetry.counter(
                        f"device{d}.specfuse.fft_pairs_elided", n_real)
            faultinject.trip("specfuse.after_stitch")  # stage kill-point
            with telemetry.span("specfuse_prep", **chunk_attrs):
                re_p, im_p = prep_spectra_batch(spectra=raw,
                                                schedule=schedule,
                                                mesh=mesh)
            regime = "decimated"
        else:
            sharded_fn = (None if mesh is None else
                          make_sharded_series_chunk(
                              mesh, plan.nsub, payload, plan.max_shift2,
                              engine_r))
            buf = jnp.zeros((Dpad, T), dtype=jnp.float32)
            if mesh is not None:
                buf = jax.device_put(buf, NamedSharding(mesh, P("dm")))
            for pos, block in _downsampled_blocks(src, factor, payload,
                                                  plan.min_overlap):
                L = int(block.shape[1])
                if L < need:  # tail: zero-pad to the static chunk shape
                    block = jnp.pad(block, ((0, 0), (0, need - L)))
                valid = min(payload, T - pos)
                chunk_attrs = dict(valid=int(valid))
                if dev_ids is not None:
                    chunk_attrs["dev"] = dev_ids
                with telemetry.span("specfuse_stitch", **chunk_attrs):
                    series = _concat(group_dispatch(
                        lambda: (sharded_fn(block, s1b, s2b)
                                 if sharded_fn is not None else
                                 dedisperse_series_chunk(
                                     block, s1b, s2b, plan.nsub, payload,
                                     plan.max_shift2, engine_r)),
                        lambda a, b: (sharded_fn(block, a, b)
                                      if sharded_fn is not None else
                                      dedisperse_series_chunk(
                                          block, a, b, plan.nsub, payload,
                                          plan.max_shift2, engine_r))))
                    # the valid window partitions the time axis exactly
                    # (overlap-save): the scatter REPLACES the old D2H
                    # pull of the same f32 values, so the resident
                    # series is bit-identical to the streamed host buf
                    buf = buf.at[:, pos:pos + valid].set(
                        series[:, :valid].astype(jnp.float32))
                telemetry.counter("specfuse.chunks_stitched")
                if dev_ids is not None:
                    for d in dev_ids:
                        telemetry.counter(
                            f"device{d}.specfuse.chunks_stitched")
                if verbose:
                    print(f"# specfuse chunk at {pos}: {valid} samples "
                          f"x {n_real} DMs stitched on device")
            faultinject.trip("specfuse.after_stitch")  # stage kill-point
            prep_attrs = {} if dev_ids is None else {"dev": dev_ids}
            with telemetry.span("specfuse_prep", **prep_attrs):
                def prep_run(lo, hi):
                    return prep_spectra_batch(buf[lo:hi],
                                              schedule=schedule,
                                              mesh=mesh)

                re_p, im_p = _concat(halving_dispatch(
                    prep_run, Dpad, min_size=ndm, what="specfuse.prep"))
            regime = "stitched"
        # the series bytes the streamed path would have moved over the
        # host link (per-chunk D2H pull + prep H2D re-ship), kept on
        # device — the "bytes kept on device" acceptance counter
        telemetry.counter("specfuse.bytes_on_device", 8 * n_real * T)
    return dict(re=re_p, im=im_p, n_real=n_real, T=T, dt_eff=dt_eff,
                regime=regime)
