"""Staged DDplan execution: run each DDstep at its own downsample factor.

The reference's DDplan2b emits a staged plan — per step a (downsample
factor, dDM, numDMs, numsub) block chosen so total smearing stays bounded
while work shrinks as ``numDMs / downsamp`` (reference utils/DDplan2b.py:
202-273) — but defers execution to PRESTO (prepsubband + search, one CPU
core). Here each step becomes its own compiled sharded sweep: separate
static shapes per step (SURVEY.md §7 "DDplan ragged stages: execute
per-step"), with the raw data stream downsampled on device by the step
factor before entering the overlap-save chunk engine.

The per-step work saving the plan encodes is therefore realized on the
TPU: a step at downsamp=f processes T/f samples per trial, so the HBM
traffic of high-DM steps falls geometrically exactly as the reference's
``work_fracts`` predicts.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from pypulsar_tpu.ops import kernels
from pypulsar_tpu.parallel.sweep import (
    DEFAULT_WIDTHS,
    SweepResult,
    make_sweep_plan,
    sweep_stream,
)


@dataclasses.dataclass
class StepResult:
    """One DDstep's sweep output at its own time resolution."""

    downsamp: int
    dt: float  # effective (downsampled) sampling time, seconds
    result: SweepResult

    def candidates(self) -> List[dict]:
        """All (dm, width, snr, sample) records in physical units."""
        out = []
        res = self.result
        for di, dm in enumerate(res.dms):
            for wi, w in enumerate(res.widths):
                out.append(dict(
                    dm=float(dm),
                    snr=float(res.snr[di, wi]),
                    width_bins=int(w),
                    width_sec=float(w * self.dt),
                    sample=int(res.peak_sample[di, wi]),
                    time_sec=float(res.peak_sample[di, wi] * self.dt),
                    downsamp=self.downsamp,
                ))
        return out


@dataclasses.dataclass
class StagedSweepResult:
    """All DDsteps' results plus global candidate selection."""

    steps: List[StepResult]

    @property
    def n_trials(self) -> int:
        return sum(len(s.result.dms) for s in self.steps)

    def best(self, k: int = 10) -> List[dict]:
        """Global top-k candidates (best width per trial) across steps."""
        cands = []
        for s in self.steps:
            res = s.result
            wi = np.argmax(res.snr, axis=1)  # best width per DM trial
            for di, dm in enumerate(res.dms):
                w = res.widths[wi[di]]
                cands.append(dict(
                    dm=float(dm),
                    snr=float(res.snr[di, wi[di]]),
                    width_bins=int(w),
                    width_sec=float(w * s.dt),
                    sample=int(res.peak_sample[di, wi[di]]),
                    time_sec=float(res.peak_sample[di, wi[di]] * s.dt),
                    downsamp=s.downsamp,
                ))
        cands.sort(key=lambda c: -c["snr"])
        return cands[:k]

    def above_threshold(self, snr: float) -> List[dict]:
        """All per-(trial, width) detections above ``snr``, time-ordered."""
        out = [c for s in self.steps for c in s.candidates() if c["snr"] >= snr]
        out.sort(key=lambda c: (c["dm"], c["time_sec"]))
        return out


class _SpectraSource:
    """Block source over an in-memory (possibly device-resident) Spectra."""

    def __init__(self, spectra):
        self.frequencies = np.asarray(spectra.freqs, dtype=np.float64)
        self.tsamp = float(spectra.dt)
        self.nsamples = int(spectra.numspectra)
        self._data = spectra.data

    def chan_major_blocks(self, payload: int, overlap: int):
        pos = 0
        while pos < self.nsamples:
            n = min(payload + overlap, self.nsamples - pos)
            yield pos, self._data[:, pos:pos + n]
            pos += payload


class _ReaderSource:
    """Block source over a file reader (FilterbankFile / PsrfitsFile /
    FilterbankObs): anything with ``frequencies``, ``tsamp`` and either
    ``get_samples(start, N) -> [time, chan]`` or ``get_spectra(start, N)``."""

    def __init__(self, reader):
        self.reader = reader
        self.frequencies = np.asarray(reader.frequencies, dtype=np.float64)
        self.tsamp = float(reader.tsamp)
        for attr in ("number_of_samples", "nspec", "nsamples"):
            n = getattr(reader, attr, None)
            if n is not None:
                self.nsamples = int(n() if callable(n) else n)
                break
        else:
            raise ValueError(f"cannot determine sample count of {reader!r}")

    def chan_major_blocks(self, payload: int, overlap: int):
        get_samples = getattr(self.reader, "get_samples", None)
        get_interval = getattr(self.reader, "get_sample_interval", None)
        pos = 0
        while pos < self.nsamples:
            n = min(payload + overlap, self.nsamples - pos)
            if get_samples is not None:
                block = np.ascontiguousarray(get_samples(pos, n).T)
            elif get_interval is not None:  # fbobs multi-file
                block = np.ascontiguousarray(get_interval(pos, pos + n).T)
            else:
                block = self.reader.get_spectra(pos, n).data
            yield pos, block
            pos += payload


def _make_source(source):
    if hasattr(source, "numspectra"):  # Spectra pytree
        return _SpectraSource(source)
    return _ReaderSource(source)


def _downsampled_blocks(src, factor: int, payload_ds: int, overlap_ds: int):
    """Stream chan-major device blocks downsampled by ``factor``.

    Raw blocks are read at ``factor *`` the downsampled geometry so bin
    boundaries align exactly across chunks; a partial trailing bin is
    dropped (the reference's downsample drops the remainder,
    formats/spectra.py:329-351 semantics)."""
    for pos, block in src.chan_major_blocks(payload_ds * factor,
                                            overlap_ds * factor):
        data = jnp.asarray(block, dtype=jnp.float32)
        if factor > 1:
            nbin = data.shape[1] // factor
            if nbin == 0:
                continue  # tail shorter than one output bin
            data = kernels.downsample(data[:, :nbin * factor], factor)
        yield pos // factor, data


def _run_step(src, dms, factor: int, nsub: int, group_size: int,
              widths: Tuple[int, ...], chunk_payload: Optional[int],
              mesh, verbose: bool = False, label: str = "") -> Optional[StepResult]:
    """Sweep one DM block over ``src`` downsampled by ``factor``."""
    dt_eff = src.tsamp * factor
    n_ds = src.nsamples // factor
    if n_ds == 0:
        return None
    pad_groups_to = None
    if mesh is not None:
        ndm = mesh.shape["dm"]
        G = -(-len(dms) // group_size)
        pad_groups_to = -(-G // ndm) * ndm
    plan = make_sweep_plan(dms, src.frequencies, dt_eff, nsub=nsub,
                           group_size=group_size, widths=widths,
                           pad_groups_to=pad_groups_to)
    payload = n_ds if chunk_payload is None else min(chunk_payload, n_ds)
    if payload <= plan.min_overlap:
        payload = min(n_ds, 2 * plan.min_overlap + 1)
    if verbose:
        print(f"# {label}downsamp={factor} dt={dt_eff:.3e}s "
              f"DMs {dms[0]:.2f}..{dms[-1]:.2f} "
              f"({len(dms)} trials) payload={payload}")
    res = sweep_stream(
        plan,
        _downsampled_blocks(src, factor, payload, plan.min_overlap),
        payload,
        mesh=mesh,
        chan_major=True,
    )
    return StepResult(downsamp=factor, dt=dt_eff, result=res)


def sweep_flat(
    source,
    dms,
    downsamp: int = 1,
    nsub: int = 64,
    group_size: int = 32,
    widths: Sequence[int] = DEFAULT_WIDTHS,
    chunk_payload: Optional[int] = None,
    mesh=None,
    verbose: bool = False,
) -> StagedSweepResult:
    """Single-stage sweep of an explicit DM grid over a file reader or
    Spectra (the flat counterpart of :func:`sweep_ddplan`, sharing its
    streaming/downsampling machinery)."""
    src = _make_source(source)
    step = _run_step(src, np.asarray(dms, dtype=np.float64), int(downsamp),
                     nsub, group_size, tuple(widths), chunk_payload, mesh,
                     verbose=verbose)
    return StagedSweepResult(steps=[] if step is None else [step])


def sweep_ddplan(
    source,
    ddplan,
    nsub: int = 64,
    group_size: int = 32,
    widths: Sequence[int] = DEFAULT_WIDTHS,
    chunk_payload: Optional[int] = None,
    mesh=None,
    verbose: bool = False,
) -> StagedSweepResult:
    """Execute every DDstep of ``ddplan`` over ``source``.

    source: a Spectra, or a reader (FilterbankFile / PsrfitsFile / fbobs).
    Each step sweeps ``step.DMs`` at sampling time ``dt * step.downsamp``
    with its own jit-compiled shapes; chunk_payload is the *downsampled*
    chunk length (default: the whole downsampled series).
    """
    src = _make_source(source)
    steps: List[StepResult] = []
    for si, step in enumerate(ddplan.DDsteps):
        sr = _run_step(src, step.DMs, int(step.downsamp), nsub, group_size,
                       tuple(widths), chunk_payload, mesh, verbose=verbose,
                       label=f"step {si}: ")
        if sr is None:
            break
        steps.append(sr)
    return StagedSweepResult(steps=steps)
