"""Staged DDplan execution: run each DDstep at its own downsample factor.

The reference's DDplan2b emits a staged plan — per step a (downsample
factor, dDM, numDMs, numsub) block chosen so total smearing stays bounded
while work shrinks as ``numDMs / downsamp`` (reference utils/DDplan2b.py:
202-273) — but defers execution to PRESTO (prepsubband + search, one CPU
core). Here each step becomes its own compiled sharded sweep: separate
static shapes per step (SURVEY.md §7 "DDplan ragged stages: execute
per-step"), with the raw data stream downsampled on device by the step
factor before entering the overlap-save chunk engine.

The per-step work saving the plan encodes is therefore realized on the
TPU: a step at downsamp=f processes T/f samples per trial, so the HBM
traffic of high-DM steps falls geometrically exactly as the reference's
``work_fracts`` predicts.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pypulsar_tpu.compile import plane_jit
from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.ops import kernels
from pypulsar_tpu.tune import knobs
from pypulsar_tpu.parallel.sweep import (
    DEFAULT_WIDTHS,
    SweepCheckpoint,
    SweepResult,
    make_sweep_plan,
    sweep_stream,
)


@dataclasses.dataclass
class StepResult:
    """One DDstep's sweep output at its own time resolution."""

    downsamp: int
    dt: float  # effective (downsampled) sampling time, seconds
    result: SweepResult

    def candidates(self) -> List[dict]:
        """All (dm, width, snr, sample) records in physical units."""
        out = []
        res = self.result
        for di, dm in enumerate(res.dms):
            for wi, w in enumerate(res.widths):
                out.append(dict(
                    dm=float(dm),
                    snr=float(res.snr[di, wi]),
                    width_bins=int(w),
                    width_sec=float(w * self.dt),
                    sample=int(res.peak_sample[di, wi]),
                    time_sec=float(res.peak_sample[di, wi] * self.dt),
                    downsamp=self.downsamp,
                ))
        return out


@dataclasses.dataclass
class StagedSweepResult:
    """All DDsteps' results plus global candidate selection."""

    steps: List[StepResult]

    @property
    def n_trials(self) -> int:
        return sum(len(s.result.dms) for s in self.steps)

    def best(self, k: int = 10) -> List[dict]:
        """Global top-k candidates (best width per trial) across steps."""
        cands = []
        for s in self.steps:
            res = s.result
            wi = np.argmax(res.snr, axis=1)  # best width per DM trial
            for di, dm in enumerate(res.dms):
                w = res.widths[wi[di]]
                cands.append(dict(
                    dm=float(dm),
                    snr=float(res.snr[di, wi[di]]),
                    width_bins=int(w),
                    width_sec=float(w * s.dt),
                    sample=int(res.peak_sample[di, wi[di]]),
                    time_sec=float(res.peak_sample[di, wi[di]] * s.dt),
                    downsamp=s.downsamp,
                ))
        cands.sort(key=lambda c: -c["snr"])
        return cands[:k]

    def above_threshold(self, snr: float) -> List[dict]:
        """All per-(trial, width) detections above ``snr``, time-ordered."""
        out = [c for s in self.steps for c in s.candidates() if c["snr"] >= snr]
        out.sort(key=lambda c: (c["dm"], c["time_sec"]))
        return out

    def events(self, snr: float) -> List[dict]:
        """Multi-event single-pulse list: every per-chunk peak above
        ``snr`` across all steps, in physical units (needs the sweep run
        with keep_chunk_peaks)."""
        out = []
        for s in self.steps:
            for e in s.result.events(snr):
                out.append(dict(
                    dm=e["dm"], snr=e["snr"], width_bins=e["width"],
                    width_sec=e["width"] * s.dt,
                    sample=e["sample"], time_sec=e["sample"] * s.dt,
                    downsamp=s.downsamp,
                ))
        out.sort(key=lambda c: (c["dm"], c["time_sec"]))
        return out


def _band_orientation(freqs):
    """(normalized_freqs, flip): high-frequency-first view of a channel
    table (the sweep plan's convention; an ascending table silently sent
    delays to the wrong channels before this normalization)."""
    freqs = np.asarray(freqs, dtype=np.float64)
    flip = len(freqs) > 1 and freqs[0] < freqs[-1]
    return (freqs[::-1].copy() if flip else freqs), flip


class _SpectraSource:
    """Block source over an in-memory (possibly device-resident) Spectra,
    delivered high-frequency-first (see _band_orientation)."""

    def __init__(self, spectra):
        self.frequencies, self._flip = _band_orientation(spectra.freqs)
        self.tsamp = float(spectra.dt)
        self.nsamples = int(spectra.numspectra)
        self._data = spectra.data

    def chan_major_blocks(self, payload: int, overlap: int):
        pos = 0
        while pos < self.nsamples:
            n = min(payload + overlap, self.nsamples - pos)
            block = self._data[:, pos:pos + n]
            # per-block flip: a whole-dataset reversed copy would double
            # device residency for the sweep's lifetime. jnp.flip for
            # device arrays — an eager [::-1] dispatches a strided slice
            # the axon remote-TPU platform does not implement
            if self._flip:
                block = (jnp.flip(block, axis=0)
                         if isinstance(block, jax.Array) else block[::-1])
            yield pos, block
            pos += payload


@plane_jit(static_argnames=("flip", "nbits"), stage="sweep")
def _ingest_tc(raw_tc, flip: bool, nbits: int = 8):
    """Device-side block ingest: [time, chan] native-dtype block ->
    [chan, time] float32, optionally band-flipped. Keeping the transpose,
    widening cast and flip INSIDE one program means an 8-bit file ships
    1 byte/sample over the host->device link (the streamed sweep's
    bottleneck through a remote-accelerator tunnel: ~60-80 MB/s measured,
    BENCHNOTES.md round 4) instead of 4, and no eager per-block ops pay
    dispatch latency. uint->f32 is exact, so results are bit-identical
    to the host-side path.

    ``nbits`` < 8 means ``raw_tc`` is PACKED [time, nchans*nbits//8]
    uint8 (io/filterbank.py sub-byte layout, low bits = lower channel)
    and is unpacked HERE, on device — a 4-bit file ships half the bytes
    of its 8-bit expansion and yields bit-identical f32 ingest (VERDICT
    r4 item 2; parity: tests/test_io.py, tests/test_staged.py)."""
    if nbits < 8:
        spb = 8 // nbits
        mask = jnp.uint8((1 << nbits) - 1)
        parts = [(raw_tc >> jnp.uint8(nbits * i)) & mask
                 for i in range(spb)]
        raw_tc = jnp.stack(parts, axis=-1).reshape(
            raw_tc.shape[0], raw_tc.shape[1] * spb)
    d = raw_tc.T.astype(jnp.float32)
    return jnp.flip(d, axis=0) if flip else d


class _ReaderSource:
    """Block source over a file reader (FilterbankFile / PsrfitsFile /
    FilterbankObs): anything with ``frequencies``, ``tsamp`` and either
    ``get_samples(start, N) -> [time, chan]`` or ``get_spectra(start, N)``.

    ``start``/``end`` bound the source to a sample window whose blocks
    still read their dedispersion overlap PAST ``end`` (into the
    neighbouring window's data, clamped at the file tail) — the
    overlap-save seam contract that lets time-sharded hosts each sweep a
    window and merge accumulators exactly (parallel.distributed.
    time_sharded_sweep). Positions stay file-absolute."""

    def __init__(self, reader, start: int = 0, end: Optional[int] = None):
        self.reader = reader
        self.frequencies, self._flip = _band_orientation(reader.frequencies)
        self.tsamp = float(reader.tsamp)
        for attr in ("number_of_samples", "nspec", "nsamples"):
            n = getattr(reader, attr, None)
            if n is not None:
                self.total = int(n() if callable(n) else n)
                break
        else:
            raise ValueError(f"cannot determine sample count of {reader!r}")
        self.start = int(start)
        self.end = self.total if end is None else min(int(end), self.total)
        if not 0 <= self.start <= self.end:
            raise ValueError(f"bad window [{start}, {end}) of {self.total}")
        self.nsamples = self.end - self.start

    def chan_major_blocks(self, payload: int, overlap: int):
        # Seam contract: interior windows (end < total) must be whole
        # payload multiples — the last in-window block otherwise extends
        # its full payload past `end` into the neighbour's window and the
        # merged moment sums double-count the seam. time_sharded_sweep
        # constructs aligned windows; fail loudly for anyone else.
        if self.end < self.total and (self.end - self.start) % payload:
            raise ValueError(
                f"windowed source [{self.start}, {self.end}) is not a "
                f"whole multiple of payload={payload}; seam samples "
                f"would be double-counted across window boundaries")
        iter_blocks = getattr(self.reader, "iter_blocks", None)
        if iter_blocks is not None and getattr(
                self.reader, "BLOCK_ITER_ARRAYS", False):
            # reader-provided streaming (filterbank: native background
            # prefetch thread, native/prefetch.cpp) — disk reads overlap
            # device compute. Gated on the marker: fbobs.iter_blocks
            # yields Spectra with different stepping semantics and must
            # take the fallback branches below. Blocks ship in the file's
            # NATIVE dtype and are transposed/widened/flipped on device
            # (_ingest_tc): 4x less link traffic for 8-bit files.
            # read_end extends past the window so in-window blocks keep
            # their full overlap; iteration stops at the window end (the
            # iterator would otherwise yield overhang-only tail blocks).
            read_end = min(self.end + overlap, self.total)
            raw_blocks = iter_blocks(payload, overlap, start=self.start,
                                     end=read_end, raw=True)
            nbits = int(getattr(self.reader, "nbits", 8) or 8)
            nbits = nbits if nbits < 8 else 8  # >=8-bit ships unpacked
            for pos, dev in _ship_ahead(raw_blocks):
                if pos >= self.end:
                    break
                yield pos, _ingest_tc(dev, self._flip, nbits)
            return
        get_samples = getattr(self.reader, "get_samples", None)
        get_interval = getattr(self.reader, "get_sample_interval", None)
        pos = self.start
        while pos < self.end:
            n = min(payload + overlap, self.total - pos)
            if get_samples is not None:
                block = np.ascontiguousarray(get_samples(pos, n).T)
            elif get_interval is not None:  # fbobs multi-file
                block = np.ascontiguousarray(get_interval(pos, pos + n).T)
            else:
                block = self.reader.get_spectra(pos, n).data
            yield pos, self._orient(block)
            pos += payload

    def _orient(self, block):
        """High-frequency-first channel rows (every yield goes through
        here so a future reader branch cannot forget the flip)."""
        return block[::-1] if self._flip else block


def _ship_ahead(raw_blocks, depth: int = 2):
    """Host->device ship of streamed blocks on a background thread.

    Through the remote link a `jnp.asarray(block)` effectively blocks the
    calling thread for the whole wire time, and the main sweep loop also
    dispatches programs and drains results — so with everything on one
    thread the wire serializes against all of it (measured 0% overlap,
    BENCHNOTES r4). The link itself DOES move transfers concurrently with
    device execution (measured: 2.0 s compute + 3.0 s ship = 2.4 s
    combined), so shipping from a dedicated thread lets block N+1 ride
    the wire while the main thread dispatches and drains block N.
    In-flight device blocks peak at ``depth + 2`` (queue slots + one the
    worker holds while parked on ``q.put`` + the one yielded to the
    consumer) — ~536 MB of HBM at depth=2 for 134 MB north-star blocks;
    size streaming budgets accordingly.

    This is the shared :func:`parallel.prefetch.prefetch` core (ordering
    preserved, worker errors re-raise in the consumer, abandoned
    consumers stop the worker, PYPULSAR_TPU_SHIP_AHEAD=0 runs inline)
    with the ship as the worker-side transform; queue fill lands on the
    ``sweep.ship.pending_depth`` gauge."""
    from pypulsar_tpu.parallel.prefetch import prefetch

    def ship(item):
        pos, block = item
        if telemetry.is_active():  # counters are thread-safe
            telemetry.counter("h2d.bytes",
                              int(getattr(block, "nbytes", 0) or 0))
        return pos, jnp.asarray(block)

    # retries: a transient wire failure re-ships the (still in hand)
    # host block instead of aborting the whole streamed sweep
    return prefetch(raw_blocks, depth=depth, name="sweep.ship",
                    transform=ship, thread_name="pypulsar-ship-ahead",
                    retries=2)


class _MaskedSource:
    """Decorates a block source with rfifind mask application: masked
    cells are replaced per block with the channel's median-mid80 fill —
    the reference's waterfaller semantics (bin/waterfaller.py:67-100 via
    formats/spectra.py:190-227) applied at the sweep's streaming boundary.
    The wrapped source delivers high-frequency-first rows; .mask channel
    indices are low-frequency-first, so the table flips on upload.

    The [nint, nchan] zap table ships to the device ONCE (~KBs) and each
    block's [C, L] mask expands from interval indices inside the fill
    program — shipping per-block boolean masks would double the wire
    traffic of an 8-bit streamed sweep (the measured bottleneck,
    BENCHNOTES r4)."""

    def __init__(self, src, rfimask):
        self.frequencies = src.frequencies
        self.tsamp = src.tsamp
        self.nsamples = src.nsamples
        self._src = src
        self._mask = rfimask
        self._pts = int(rfimask.ptsperint)
        self._host_table = np.asarray(rfimask._zap_table, dtype=bool)
        self._table = jnp.asarray(
            np.ascontiguousarray(self._host_table[:, ::-1]))  # hi-first

    def chan_major_blocks(self, payload: int, overlap: int):
        nint = self._host_table.shape[0]
        for pos, block in self._src.chan_major_blocks(payload, overlap):
            L = int(block.shape[1])
            i0 = min(pos // self._pts, nint - 1)
            i1 = min((pos + L - 1) // self._pts, nint - 1)
            if self._host_table[i0:i1 + 1].any():
                # split file-absolute pos into (interval base, remainder)
                # on the host: inside jit the arithmetic is int32 (x64
                # off), so pos + arange(L) would overflow for positions
                # past 2^31 samples; base + (rem + arange(L)) // pts is
                # exact for any file length (rem < pts, base < nint)
                block = _masked_block(
                    jnp.asarray(block, dtype=jnp.float32), self._table,
                    min(pos // self._pts, nint - 1), pos % self._pts,
                    self._pts)
            yield pos, block


@plane_jit(static_argnames=("pts",), stage="sweep")
def _masked_block(data, table, base, rem, pts: int):
    """Expand the device-resident [nint, C] zap table to this block's
    [C, L] mask (interval = sample // pts, clamped like
    io.rfimask.get_sample_mask) and apply the median-mid80 fill.
    ``base``/``rem`` are the host-split interval index and in-interval
    offset of the block start (int32-overflow-proof, ADVICE r4)."""
    L = data.shape[1]
    iv = jnp.minimum(base + (rem + jnp.arange(L)) // pts,
                     table.shape[0] - 1)
    return kernels.masked(data, table[iv].T)


def _make_source(source, rfimask=None):
    from pypulsar_tpu.resilience import dataguard

    src = (_SpectraSource(source) if hasattr(source, "numspectra")
           else _ReaderSource(source))
    # dataguard INSIDE the mask wrapper: the mask fill's channel medians
    # must never see a NaN (it would poison the whole channel's fill)
    src = dataguard.guard_source(src)
    if rfimask is not None:
        src = _MaskedSource(src, rfimask)
    return src


def _mask_tag(rfimask) -> str:
    """Checkpoint-context tag identifying the applied mask: a checkpoint
    written with a different (or no) mask must not resume, and the cheap
    source probe only samples the first ~1k samples — zaps in later
    intervals would slip past it."""
    if rfimask is None:
        return ""
    import hashlib

    h = hashlib.sha256()
    h.update(np.int64([rfimask.nchan, rfimask.nint,
                       rfimask.ptsperint]).tobytes())
    h.update(np.packbits(rfimask._zap_table).tobytes())
    return "/mask=" + h.hexdigest()[:16]


def _downsampled_blocks(src, factor: int, payload_ds: int, overlap_ds: int):
    """Stream chan-major device blocks downsampled by ``factor``.

    Raw blocks are read at ``factor *`` the downsampled geometry so bin
    boundaries align exactly across chunks; a partial trailing bin is
    dropped (the reference's downsample drops the remainder,
    formats/spectra.py:329-351 semantics).

    When the reader is integer-sampled and the factor is large enough
    that the exact integer bin sums are SMALLER on the wire than the
    native samples, downsampling happens on the HOST before the ship
    (_host_downsampled_blocks): a DDplan step at downsamp=8 over an
    8-bit file then ships 2/8 = 1/4 of the native bytes (VERDICT r4
    item 3 — the wire is the streamed sweep's measured ceiling).
    Integer sums are exact in uint16/uint32 and in f32, so both paths
    are bit-identical (tests/test_staged.py)."""
    if factor > 1 and _host_downsample_wins(src, factor):
        yield from _host_downsampled_blocks(src, factor, payload_ds,
                                            overlap_ds)
        return
    for pos, block in src.chan_major_blocks(payload_ds * factor,
                                            overlap_ds * factor):
        if telemetry.is_active() and not isinstance(block, jax.Array):
            telemetry.counter("h2d.bytes", 4 * int(np.size(block)))
        data = jnp.asarray(block, dtype=jnp.float32)
        if factor > 1:
            nbin = data.shape[1] // factor
            if nbin == 0:
                continue  # tail shorter than one output bin
            data = kernels.downsample(data[:, :nbin * factor], factor)
        yield pos // factor, data


def _host_downsample_wins(src, factor: int) -> bool:
    """True when host-side downsampling ships fewer bytes than the native
    samples: integer readers only (exact sums; float sum order would
    differ from the device path's), accumulator 2 B (nbits<=8) or 4 B
    (16-bit) per downsampled sample vs nbits/8 per native sample.
    PYPULSAR_TPU_HOST_DOWNSAMP=0/1 overrides the policy."""
    if not isinstance(src, _ReaderSource):
        return False  # masked sources zap at full rate, Spectra is resident
    r = src.reader
    if not (getattr(r, "BLOCK_ITER_ARRAYS", False)
            and getattr(r, "iter_blocks", None)):
        return False
    nbits = int(getattr(r, "nbits", 32) or 32)
    if nbits > 16:
        return False
    if nbits > 8 and factor > 256:
        return False  # uint32 sums past f32's 2^24 integer exactness
    env = knobs.env_str("PYPULSAR_TPU_HOST_DOWNSAMP")
    if env is not None:
        return env != "0"
    acc_bytes = _host_ds_acc_dtype(nbits, factor)().itemsize
    return acc_bytes / factor < nbits / 8


def _host_ds_acc_dtype(nbits: int, factor: int):
    """Accumulator for exact host bin sums: uint16 only while the worst
    case factor*255 fits (factor <= 257); uint32 beyond (and for 16-bit
    samples), still exact in f32 for any factor the policy admits."""
    return np.uint16 if (nbits <= 8 and factor <= 257) else np.uint32


def _host_downsampled_blocks(rsrc, factor: int, payload_ds: int,
                             overlap_ds: int):
    """Raw full-rate blocks -> host unpack (sub-byte) + exact integer
    downsample -> ship the SMALL accumulator blocks -> device ingest.
    Sums of <=257 uint8 (uint16 acc) or <=257 uint16 (uint32 acc) values
    are exact both in the accumulator and in the f32 cast, so results
    are bit-identical to the device downsample path."""
    reader = rsrc.reader
    nbits = int(getattr(reader, "nbits", 8) or 8)
    acc_dtype = _host_ds_acc_dtype(nbits, factor)
    payload_raw = payload_ds * factor
    # same seam contract as chan_major_blocks: interior windows must be
    # whole (raw) payload multiples or merged statistics double-count
    if rsrc.end < rsrc.total and (rsrc.end - rsrc.start) % payload_raw:
        raise ValueError(
            f"windowed source [{rsrc.start}, {rsrc.end}) is not a whole "
            f"multiple of payload={payload_raw}; seam samples would be "
            f"double-counted across window boundaries")
    read_end = min(rsrc.end + overlap_ds * factor, rsrc.total)
    raw_blocks = reader.iter_blocks(payload_raw, overlap_ds * factor,
                                    start=rsrc.start, end=read_end,
                                    raw=True)
    unpack = None
    if nbits < 8:
        from pypulsar_tpu.io.psrfits import _UNPACKERS

        unpack = _UNPACKERS[nbits]

    def ds_blocks():
        for pos, block in raw_blocks:
            if pos >= rsrc.end:
                break
            if unpack is not None:
                block = unpack(block.ravel()).reshape(block.shape[0], -1)
            nbin = block.shape[0] // factor
            if nbin == 0:
                continue
            acc = block[:nbin * factor].reshape(
                nbin, factor, block.shape[1]).sum(axis=1, dtype=acc_dtype)
            yield pos, acc

    for pos, dev in _ship_ahead(ds_blocks()):
        yield pos // factor, _ingest_tc(dev, rsrc._flip, 8)


def _run_step(src, dms, factor: int, nsub: int, group_size: int,
              widths: Tuple[int, ...], chunk_payload: Optional[int],
              mesh, verbose: bool = False, label: str = "",
              checkpoint: Optional[SweepCheckpoint] = None,
              engine: str = "auto",
              keep_chunk_peaks: bool = False,
              ckpt_extra: str = "") -> Optional[StepResult]:
    """Sweep one DM block over ``src`` downsampled by ``factor``.
    ``group_size`` <= 0 picks the largest group within the default
    smearing bound (parallel.sweep.choose_group_size)."""
    dt_eff = src.tsamp * factor
    n_ds = src.nsamples // factor
    if n_ds == 0:
        return None
    if group_size <= 0:
        from pypulsar_tpu.parallel.sweep import choose_group_size

        group_size = choose_group_size(dms, src.frequencies, dt_eff, nsub)
    from pypulsar_tpu.parallel.sweep import padded_group_count

    ndm = 1 if mesh is None else mesh.shape["dm"]
    pad_groups_to = padded_group_count(-(-len(dms) // group_size), ndm)
    plan = make_sweep_plan(dms, src.frequencies, dt_eff, nsub=nsub,
                           group_size=group_size, widths=widths,
                           pad_groups_to=pad_groups_to)
    # default payload is BOUNDED (round 5): the previous whole-file
    # default made a --chunk-less CLI sweep of an hour-scale file try to
    # build one 2^26-sample chunk (a ~275 GB device buffer) — small data
    # still runs single-chunk via the min(). tuned=False: the DETECTION
    # sweep's chunk is part of its results (per-chunk stats, one event
    # per chunk), so the auto-tuner's overlay must not reach it — only
    # env/--chunk (explicit, fingerprinted operator choices) move it
    if chunk_payload is None:
        from pypulsar_tpu.parallel.sweep import default_chunk_payload

        chunk_payload = default_chunk_payload(plan.min_overlap,
                                              tuned=False)
    payload = min(chunk_payload, n_ds)
    if payload <= plan.min_overlap:
        payload = min(n_ds, 2 * plan.min_overlap + 1)
    if verbose:
        print(f"# {label}downsamp={factor} dt={dt_eff:.3e}s "
              f"DMs {dms[0]:.2f}..{dms[-1]:.2f} "
              f"({len(dms)} trials) payload={payload}")

    def block_factory(cursor_ds: int):
        """Re-root the block stream at a checkpoint cursor (seek-resume:
        the cursor always sits on a payload boundary, so the re-rooted
        window honors the seam contract). Falls back to the full stream
        (skip-based replay) for sources that cannot seek."""
        seeked = _reroot_source(src, cursor_ds * factor)
        return _downsampled_blocks(seeked if seeked is not None else src,
                                   factor, payload, plan.min_overlap)

    # sink-only span (aggregate=False): it encloses the sweep loop's
    # aggregated stages, which must stay non-overlapping in the flat table
    with telemetry.span("sweep_step", aggregate=False, downsamp=factor,
                        n_trials=len(dms), payload=int(payload)):
        res = sweep_stream(
            plan,
            _downsampled_blocks(src, factor, payload, plan.min_overlap),
            payload,
            mesh=mesh,
            chan_major=True,
            checkpoint=checkpoint,
            engine=engine,
            keep_chunk_peaks=keep_chunk_peaks,
            checkpoint_context=ckpt_extra,
            block_factory=block_factory,
        )
    return StepResult(downsamp=factor, dt=dt_eff, result=res)


def _reroot_source(src, start_raw: int):
    """A view of ``src`` whose blocks begin at raw sample ``start_raw``
    (same end bound), or None when the source cannot seek. Positions stay
    file-absolute, so the resumed stream's chunks carry the same
    coordinates they had in the original run. (One public entry point:
    the wrapper recursion lives in :func:`_reroot_impl`.)"""
    return _reroot_impl(src, start_raw)


def _reroot_impl(src, start_raw: int):
    from pypulsar_tpu.resilience.dataguard import GuardedSource

    if isinstance(src, _MaskedSource):
        inner = _reroot_impl(src._src, start_raw)
        return None if inner is None else _MaskedSource(inner, src._mask)
    if isinstance(src, GuardedSource):
        # rewrap sharing the SAME quality account: the resumed stream's
        # scrub continues the original tally instead of forking it
        inner = _reroot_impl(src._src, start_raw)
        return None if inner is None else GuardedSource(inner,
                                                        stats=src.stats)
    if isinstance(src, _ReaderSource):
        end = src.end if src.end < src.total else None
        return _ReaderSource(src.reader, start_raw, end)
    return None


def sweep_flat(
    source,
    dms,
    downsamp: int = 1,
    nsub: int = 64,
    group_size: int = 32,
    widths: Sequence[int] = DEFAULT_WIDTHS,
    chunk_payload: Optional[int] = None,
    mesh=None,
    verbose: bool = False,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 16,
    engine: str = "auto",
    keep_chunk_peaks: bool = False,
    rfimask=None,
) -> StagedSweepResult:
    """Single-stage sweep of an explicit DM grid over a file reader or
    Spectra (the flat counterpart of :func:`sweep_ddplan`, sharing its
    streaming/downsampling machinery). ``checkpoint_path`` enables in-sweep
    checkpoint/resume (see SweepCheckpoint); ``rfimask`` (an
    io.rfimask.RfifindMask) applies median-mid80 mask fill per block."""
    src = _make_source(source, rfimask)
    ckpt = (SweepCheckpoint(checkpoint_path, every=checkpoint_every)
            if checkpoint_path else None)
    step = _run_step(src, np.asarray(dms, dtype=np.float64), int(downsamp),
                     nsub, group_size, tuple(widths), chunk_payload, mesh,
                     verbose=verbose, checkpoint=ckpt, engine=engine,
                     keep_chunk_peaks=keep_chunk_peaks,
                     ckpt_extra=_mask_tag(rfimask))
    return StagedSweepResult(steps=[] if step is None else [step])


def sweep_ddplan(
    source,
    ddplan,
    nsub: int = 64,
    group_size: int = 32,
    widths: Sequence[int] = DEFAULT_WIDTHS,
    chunk_payload: Optional[int] = None,
    mesh=None,
    verbose: bool = False,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 16,
    engine: str = "auto",
    rfimask=None,
) -> StagedSweepResult:
    """Execute every DDstep of ``ddplan`` over ``source``.

    source: a Spectra, or a reader (FilterbankFile / PsrfitsFile / fbobs).
    Each step sweeps ``step.DMs`` at sampling time ``dt * step.downsamp``
    with its own jit-compiled shapes; chunk_payload is the *downsampled*
    chunk length (default: the whole downsampled series).

    ``checkpoint_path`` is a base path: step ``i`` streams its in-progress
    accumulator to ``{path}.step{i}.npz`` and, once complete, its full
    result to ``{path}.step{i}.done.npz`` — so a killed run resumes the
    interrupted step mid-stream and loads finished steps from their done
    markers without recompute. All marker files are removed when every
    step has completed; the combined result is bit-identical to an
    uninterrupted run (deterministic accumulation order, see
    SweepCheckpoint).
    """
    from pypulsar_tpu.parallel.sweep import resolve_engine

    src = _make_source(source, rfimask)
    mtag = _mask_tag(rfimask)
    ckpt_context = "engine=%s/meshdm=%s%s" % (
        resolve_engine(engine),
        0 if mesh is None else mesh.shape.get("dm", 0), mtag)
    probe = _source_probe(src) if checkpoint_path else b""
    steps: List[StepResult] = []
    done_fns: List[str] = []
    for si, step in enumerate(ddplan.DDsteps):
        done_fn = (f"{checkpoint_path}.step{si}.done.npz"
                   if checkpoint_path else None)
        fp = (_step_fingerprint(src, step.DMs, int(step.downsamp), nsub,
                                group_size, tuple(widths), chunk_payload,
                                ckpt_context, probe)
              if done_fn else "")
        if done_fn and os.path.exists(done_fn):
            sr = _load_step_result(done_fn, fp)
            if sr is not None:
                if verbose:
                    print(f"# step {si}: resumed from {done_fn}")
                steps.append(sr)
                done_fns.append(done_fn)
                continue
        ckpt = (SweepCheckpoint(f"{checkpoint_path}.step{si}.npz",
                                every=checkpoint_every)
                if checkpoint_path else None)
        sr = _run_step(src, step.DMs, int(step.downsamp), nsub, group_size,
                       tuple(widths), chunk_payload, mesh, verbose=verbose,
                       label=f"step {si}: ", checkpoint=ckpt, engine=engine,
                       ckpt_extra=mtag)
        if sr is None:
            break
        if done_fn:
            _save_step_result(done_fn, sr, fp)
            done_fns.append(done_fn)
        steps.append(sr)
    for fn in done_fns:  # full plan finished: clear the markers
        if os.path.exists(fn):
            os.remove(fn)
    return StagedSweepResult(steps=steps)


def _source_probe(src) -> bytes:
    """A cheap content sample of the input (first ~1k samples of every
    channel): catches the input file being swapped for another of
    identical geometry between checkpoint and resume."""
    try:
        _, block = next(src.chan_major_blocks(min(1024, src.nsamples), 0))
        return np.ascontiguousarray(
            np.asarray(block, dtype=np.float32)).tobytes()
    except Exception:  # noqa: BLE001 - probe is best-effort
        return b""


def _default_fft_len() -> int:
    # the DETECTION sweep's effective default (env > 2^18, overlays
    # excluded — see chunk_fft_len): re-setting the env knob must
    # invalidate default-using checkpoint markers, while auto-tuning
    # (which never reaches the detector) must not
    from pypulsar_tpu.parallel.sweep import chunk_fft_len

    return chunk_fft_len(tuned=False)


def _step_fingerprint(src, dms, factor, nsub, group_size, widths,
                      chunk_payload, context, probe) -> str:
    """Hash of everything that determines a step's result — a done marker
    from different parameters, a different engine/mesh, or a different
    input must not be resumed (the bit-identity contract; engines agree
    only to ~1e-4)."""
    import hashlib

    h = hashlib.sha256()
    for part in (np.asarray(dms, dtype=np.float64).tobytes(),
                 src.frequencies.tobytes(),
                 np.float64([src.tsamp]).tobytes(),
                 # None resolves through default_chunk_payload, so the
                 # sentinel is the (negated) DEFAULT_CHUNK_FFT_LEN:
                 # retuning the library default invalidates only markers
                 # that actually USED the default (fourier chunk rounding
                 # is chunk-length-dependent); explicit --chunk runs are
                 # untouched by the constant and keep their markers
                 np.int64([src.nsamples, factor, nsub, group_size,
                           -_default_fft_len() if chunk_payload is None
                           else chunk_payload]).tobytes(),
                 np.int64(widths).tobytes(),
                 context.encode(), probe):
        h.update(part)
    return h.hexdigest()


def _save_step_result(path: str, sr: StepResult, fingerprint: str) -> None:
    res = sr.result
    tmp = path + ".tmp.npz"
    np.savez(tmp, fingerprint=fingerprint,
             downsamp=sr.downsamp, dt=sr.dt, dms=res.dms,
             widths=np.asarray(res.widths, dtype=np.int64), snr=res.snr,
             peak_sample=res.peak_sample, mean=res.mean, std=res.std)
    os.replace(tmp, path)


def _load_step_result(path: str, fingerprint: str) -> Optional[StepResult]:
    try:
        with np.load(path, allow_pickle=False) as z:
            if str(z["fingerprint"]) != fingerprint:
                return None
            res = SweepResult(
                dms=z["dms"], widths=tuple(int(w) for w in z["widths"]),
                snr=z["snr"], peak_sample=z["peak_sample"],
                mean=z["mean"], std=z["std"])
            return StepResult(downsamp=int(z["downsamp"]),
                              dt=float(z["dt"]), result=res)
    except Exception:  # noqa: BLE001 - corrupt marker -> recompute the step
        return None


def sweep_ddplan_2d(
    source,
    ddplan,
    mesh,
    nsub: int = 64,
    group_size: int = 8,
    widths: Sequence[int] = DEFAULT_WIDTHS,
    engine: str = "auto",
    max_trials_per_step: Optional[int] = None,
) -> StagedSweepResult:
    """Staged DDplan execution over a 2-D {dm, time} device mesh.

    The 1-D path (:func:`sweep_ddplan`) shards trial groups over 'dm' and
    streams time chunks from the host; here each step instead runs as ONE
    sharded program over the whole (downsampled) series with the time axis
    split across the mesh's 'time' axis — halos travel between neighbours
    over ICI via lax.ppermute instead of through host overlap-save
    (parallel.sweep.make_sharded_sweep_chunk_2d). This is the long-context
    layout of SURVEY.md §5 exercised by the driver's multichip dryrun at
    realistic shapes.

    ``max_trials_per_step`` caps each DDstep's trial count (the dryrun uses
    it to bound virtual-CPU wall time while keeping real channel counts and
    sample lengths).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pypulsar_tpu.parallel.sweep import (
        finalize_sweep,
        make_sharded_sweep_chunk_2d,
        padded_group_count,
    )

    src = _make_source(source)
    nd = mesh.shape["dm"]
    nt = mesh.shape["time"]
    steps: List[StepResult] = []
    for si, step in enumerate(ddplan.DDsteps):
        factor = int(step.downsamp)
        dms = np.asarray(step.DMs, dtype=np.float64)
        if max_trials_per_step is not None:
            dms = dms[:max_trials_per_step]
        dt_eff = src.tsamp * factor
        n_ds = src.nsamples // factor
        if n_ds == 0:
            break
        pad_groups_to = padded_group_count(-(-len(dms) // group_size), nd)
        plan = make_sweep_plan(dms, src.frequencies, dt_eff, nsub=nsub,
                               group_size=group_size, widths=tuple(widths),
                               pad_groups_to=pad_groups_to)
        local_payload = n_ds // nt
        if plan.min_overlap >= local_payload:
            raise ValueError(
                f"step {si}: time shard {local_payload} samples does not "
                f"cover the halo {plan.min_overlap}; fewer 'time' shards "
                f"or more data needed")
        T_used = local_payload * nt
        # whole downsampled series on the mesh (one pass; the per-channel
        # baseline keeps the f32 accumulation at fluctuation scale, as in
        # sweep_stream's contract)
        blocks = list(_downsampled_blocks(src, factor, n_ds, 0))
        data = jnp.concatenate([b for _, b in blocks], axis=1)[:, :T_used]
        base = jnp.mean(data, axis=1, keepdims=True)
        base_sum = float(np.asarray(jnp.sum(base), dtype=np.float64))
        data = data - base
        fn = make_sharded_sweep_chunk_2d(
            mesh, plan.nsub, local_payload, plan.min_overlap,
            plan.max_shift2, tuple(plan.widths), engine=engine)
        darr = jax.device_put(data, NamedSharding(mesh, P(None, "time")))
        s1 = jax.device_put(jnp.asarray(plan.stage1_bins),
                            NamedSharding(mesh, P("dm")))
        s2 = jax.device_put(jnp.asarray(plan.stage2_bins),
                            NamedSharding(mesh, P("dm")))
        s, ss, mb, ab = fn(darr, s1, s2)
        jax.block_until_ready((s, ss, mb, ab))
        # mean reported in original units, matching the 1-D staged path
        res = finalize_sweep(plan, T_used, s, ss, mb, ab,
                             baseline_sum=base_sum)
        steps.append(StepResult(downsamp=factor, dt=dt_eff, result=res))
    return StagedSweepResult(steps=steps)


def write_dats_streamed(
    outbase: str,
    reader,
    dms,
    downsamp: int = 1,
    nsub: int = 64,
    group_size: int = 32,
    rfimask=None,
    engine: str = "auto",
    chunk_payload: Optional[int] = None,
    window: Optional[Tuple[int, int]] = None,
    suffix: str = "",
    write_inf: bool = True,
    verbose: bool = False,
) -> List[str]:
    """Stream the file ONCE and write a dedispersed .dat per DM trial.

    The in-memory writer (cli/sweep._write_dats) loads the whole
    observation as a device-resident Spectra — infeasible past HBM for
    the workloads --write-dats exists for (a 900 s x 1024-chan window is
    57.6 GB as f32). This writer streams overlap-save chunks through the
    sweep's own two-stage engine (sweep.dedisperse_series_chunk), so it
    runs at sweep speed on any file length and the written series is
    exactly what the sweep's detections saw. Semantics = PRESTO
    prepsubband (subband dedispersion; reference defers this entire
    stage to PRESTO, SURVEY.md §2.5): values differ from the exact
    per-channel path by one subband smearing, and the file tail is
    zero-padded (linear shifts) rather than wrapped.

    ``window=(s0, s1)`` (DOWNSAMPLED sample coordinates, whole chunk
    multiples — the time-shard seam contract) writes only that span of
    each series; with ``suffix=f".w{rank}"`` each host of a time-sharded
    sweep writes its own segment files, concatenated in rank order by
    cli/sweep (the .dat byte stream is position-ordered, so
    concatenation of whole-chunk windows reproduces the sequential
    file). Returns the written .dat paths.
    """
    factor = max(1, int(downsamp))
    dms = np.asarray(dms, dtype=np.float64)
    dt_eff = _ReaderSource(reader).tsamp * factor
    _plan, _payload, T = dats_geometry(reader, dms, downsamp=factor,
                                       nsub=nsub, group_size=group_size,
                                       chunk_payload=chunk_payload)
    s0, s1 = window if window is not None else (0, T)

    paths = dat_truncate_paths(outbase, dms, suffix)
    for pos, rows in iter_dedispersed_chunks(
            reader, dms, downsamp=factor, nsub=nsub, group_size=group_size,
            rfimask=rfimask, engine=engine, chunk_payload=chunk_payload,
            window=window, verbose=verbose):
        dat_append_rows(paths, rows)
    dat_finalize_paths(paths)
    if write_inf:
        write_dat_infs(outbase, reader, dms, s1 - s0, dt_eff)
    return paths


def dat_truncate_paths(outbase: str, dms, suffix: str = "") -> List[str]:
    """Create (truncated) the per-DM .dat paths — the ONE definition of
    the .dat byte-emitting side, shared with the accel handoff's
    --write-dats tee so the tee-identical contract has a single writer.

    The byte stream accumulates in ``{path}.tmp`` and lands on the final
    name only at :func:`dat_finalize_paths` (tmp + os.replace, the sweep
    checkpoints' discipline): a killed run leaves tmp debris, never a
    truncated ``.dat`` that a later stage would trust as complete."""
    paths = [f"{outbase}_DM{dm:.2f}{suffix}.dat" for dm in dms]
    # truncate once, then reopen per chunk in append mode: holding one
    # descriptor per DM trial would hit the fd limit at prepsubband-
    # scale grids (review r5: --numdms 2000 vs the common 1024 ulimit)
    for p in paths:
        open(p + ".tmp", "wb").close()
    return paths


def dat_append_rows(paths: List[str], rows) -> None:
    """Append one chunk's [D, valid] float32 rows to the per-DM .dat
    byte streams (other half of :func:`dat_truncate_paths`; bytes go to
    the ``.tmp`` staging name until :func:`dat_finalize_paths`)."""
    from pypulsar_tpu.resilience import faultinject

    faultinject.trip("dats.append")  # kill-point: mid-stream .dat write
    for p, row in zip(paths, rows):
        with open(p + ".tmp", "ab") as f:
            row.tofile(f)


def dat_finalize_paths(paths: List[str]) -> None:
    """Atomically publish completed .dat streams (``.tmp`` ->
    final, os.replace): readers only ever see whole files."""
    for p in paths:
        os.replace(p + ".tmp", p)


def iter_dedispersed_chunks(
    reader,
    dms,
    downsamp: int = 1,
    nsub: int = 64,
    group_size: int = 32,
    rfimask=None,
    engine: str = "auto",
    chunk_payload: Optional[int] = None,
    window: Optional[Tuple[int, int]] = None,
    mesh=None,
    verbose: bool = False,
):
    """Stream the file ONCE and yield ``(pos, rows[D, valid] float32)``
    host chunks of every DM trial's two-stage dedispersed series — the
    chunk engine of :func:`write_dats_streamed`, factored out so the
    sweep->accel handoff (parallel.accelpipe) consumes the IDENTICAL
    values the .dat writer would have put on disk without the write +
    re-read round trip (745.9 s of the round-5 configs[4] chain). ``pos``
    is the file-absolute downsampled sample position of the chunk start
    (``window`` bounds which chunks stream); chunk geometry comes from
    :func:`dats_geometry`, so windows must be whole-payload multiples
    (the seam contract). Every value a consumer sees is the f32 the .dat
    byte stream would contain — the paths are bit-identical by
    construction, which the candidate-table parity test pins down.

    ``mesh`` shards the trial groups over its 'dm' axis
    (sweep.make_sharded_series_chunk): each device dedisperses its local
    groups of the replicated chunk, and because per-group math is
    device-count independent the yielded rows stay bit-identical to the
    unsharded stream (the multi-chip byte-parity contract)."""
    from pypulsar_tpu.ops.transfer import pull_host
    from pypulsar_tpu.parallel.sweep import dedisperse_series_chunk

    factor = max(1, int(downsamp))
    dms = np.asarray(dms, dtype=np.float64)
    probe = _ReaderSource(reader)
    plan, payload, T = dats_geometry(reader, dms, downsamp=factor,
                                     nsub=nsub, group_size=group_size,
                                     chunk_payload=chunk_payload)
    dev_ids = None
    sharded_fn = None
    from pypulsar_tpu.parallel.sweep import padded_group_count

    ndm = 1 if mesh is None else int(mesh.shape["dm"])
    padded_groups = padded_group_count(plan.n_groups, ndm)
    if padded_groups != plan.n_groups:
        # padded groups replicate the last real trial; group math is
        # independent, so the real rows below are untouched
        plan = make_sweep_plan(dms, probe.frequencies,
                               probe.tsamp * factor, nsub=nsub,
                               group_size=plan.group_size, widths=(1,),
                               pad_groups_to=padded_groups)
    if mesh is not None:
        from pypulsar_tpu.parallel.sweep import make_sharded_series_chunk

        sharded_fn = make_sharded_series_chunk(
            mesh, plan.nsub, payload, plan.max_shift2, engine)
        dev_ids = [int(getattr(d, "id", -1)) for d in mesh.devices.flat]
    s0, s1 = window if window is not None else (0, T)
    if not 0 <= s0 <= s1 <= T:
        raise ValueError(f"bad window [{s0}, {s1}) of {T}")
    src = _ReaderSource(reader, s0 * factor,
                        min(s1 * factor, probe.total) if s1 < T else None)
    from pypulsar_tpu.resilience import dataguard

    src = dataguard.guard_source(src)
    if rfimask is not None:
        src = _MaskedSource(src, rfimask)
    s1b = jnp.asarray(plan.stage1_bins)
    s2b = jnp.asarray(plan.stage2_bins)
    need = payload + plan.min_overlap

    for pos, block in _downsampled_blocks(src, factor, payload,
                                          plan.min_overlap):
        L = int(block.shape[1])
        if L < need:  # tail: zero-pad to the static chunk shape
            block = jnp.pad(block, ((0, 0), (0, need - L)))
        valid = min(payload, s1 - pos)
        attrs = dict(n_trials=len(dms), valid=int(valid))
        if dev_ids is not None:
            attrs["dev"] = dev_ids
        with telemetry.span("dedisperse_chunk", **attrs):
            if sharded_fn is not None:
                series = sharded_fn(block, s1b, s2b)
            else:
                series = dedisperse_series_chunk(
                    block, s1b, s2b, plan.nsub, payload, plan.max_shift2,
                    engine)
            (host,) = pull_host(series[:, :valid].astype(jnp.float32))
        if verbose:
            print(f"# dats chunk at {pos}: {valid} samples "
                  f"x {len(dms)} DMs")
        telemetry.counter("dedisperse.chunks")
        if dev_ids is not None:
            for d in dev_ids:
                telemetry.counter(f"device{d}.dedisperse.chunks")
        # the plan pads trial groups to the group size; only the real
        # trials leave this generator
        yield pos, np.asarray(host)[:len(dms)]


def dats_geometry(reader, dms, downsamp: int = 1, nsub: int = 64,
                  group_size: int = 32, chunk_payload: Optional[int] = None):
    """(plan, payload, T_ds) the streamed .dat writer will use for these
    parameters — time-sharding callers need the identical chunk size to
    construct whole-chunk windows (the seam contract)."""
    factor = max(1, int(downsamp))
    probe = _ReaderSource(reader)
    T = probe.nsamples // factor
    plan = make_sweep_plan(np.asarray(dms, dtype=np.float64),
                           probe.frequencies, probe.tsamp * factor,
                           nsub=nsub, group_size=group_size, widths=(1,))
    if chunk_payload is None:
        from pypulsar_tpu.parallel.sweep import default_chunk_payload

        chunk_payload = default_chunk_payload(plan.min_overlap)
    payload = min(chunk_payload, T)
    if payload <= plan.min_overlap:
        payload = min(T, 2 * plan.min_overlap + 1)
    return plan, payload, T


def write_dat_infs(outbase: str, reader, dms, N: int, dt: float):
    """PRESTO .inf sidecars for a set of written .dat series (metadata
    mirrors cli/sweep's in-memory writer; split out so a time-sharded
    run's rank 0 can stamp the CONCATENATED length once)."""
    probe = _ReaderSource(reader)
    freqs = np.asarray(probe.frequencies)
    for dm in np.asarray(dms, dtype=np.float64):
        base = f"{outbase}_DM{dm:.2f}"
        make_dat_inf(base, reader, float(dm), N, dt, freqs).to_file(
            base + ".inf")


def make_dat_inf(basenm: str, reader, dm: float, N: int, dt: float,
                 freqs: np.ndarray):
    """InfoData for a dedispersed series of this reader — the ONE place
    .dat sidecar metadata is built (the in-memory writer in cli/sweep
    and the streamed writer both use it)."""
    from pypulsar_tpu.io.infodata import InfoData

    inf = InfoData()
    inf.basenm = os.path.basename(basenm)
    inf.telescope = getattr(reader, "telescope", "unknown") or "unknown"
    inf.object = getattr(reader, "source_name", "synthetic") or "synthetic"
    inf.epoch = float(getattr(reader, "tstart", 0.0) or 0.0)
    inf.N = int(N)
    inf.dt = float(dt)
    inf.DM = float(dm)
    inf.numchan = len(freqs)
    inf.lofreq = float(freqs.min())
    inf.BW = float(abs(freqs.max() - freqs.min()))
    inf.chan_width = float(inf.BW / max(inf.numchan - 1, 1))
    inf.bary = 0
    inf.analyzer = "pypulsar_tpu"
    return inf
