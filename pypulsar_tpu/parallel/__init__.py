from pypulsar_tpu.parallel.mesh import make_mesh  # noqa: F401
from pypulsar_tpu.parallel.sweep import (  # noqa: F401
    SweepPlan,
    make_sweep_plan,
    sweep_spectra,
    SweepResult,
)
