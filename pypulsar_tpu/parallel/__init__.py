from pypulsar_tpu.parallel.mesh import make_mesh  # noqa: F401
from pypulsar_tpu.parallel.sweep import (  # noqa: F401
    SweepCheckpoint,
    SweepPlan,
    choose_group_size,
    make_sweep_plan,
    resolve_engine,
    sweep_spectra,
    SweepResult,
)
