"""Device mesh construction for the sweep engine.

The scale axes of this domain (SURVEY.md §2.4): DM trials (embarrassingly
parallel — the data-parallel analogue), the time axis (long-context analogue,
sharded with halo exchange since dedispersion is a pure per-channel shift),
and multi-beam/multi-file batches across hosts over DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh


def make_mesh(
    axis_sizes: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("dm", "time"),
    devices=None,
) -> Mesh:
    """Build a Mesh over available devices.

    Default: all devices on the 'dm' axis (1 on 'time') — DM-trial sharding
    needs no communication until the final candidate reduction, so it rides
    ICI most efficiently (BASELINE.json north star).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = [n] + [1] * (len(axis_names) - 1)
    if int(np.prod(axis_sizes)) != n:
        raise ValueError(f"axis sizes {axis_sizes} do not multiply to {n} devices")
    dev_array = mesh_utils.create_device_mesh(tuple(axis_sizes), devices=devices)
    return Mesh(dev_array, tuple(axis_names))
