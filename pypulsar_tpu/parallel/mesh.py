"""Device mesh construction + the thread-local gang lease registry.

The scale axes of this domain (SURVEY.md §2.4): DM trials (embarrassingly
parallel — the data-parallel analogue), the time axis (long-context analogue,
sharded with halo exchange since dedispersion is a pure per-channel shift),
and multi-beam/multi-file batches across hosts over DCN.

The **gang lease** half solves the mesh/lease collision: the survey
scheduler hands a stage k exclusive chips, but every mesh-building call
site used to root itself at ``jax.local_devices()[0]`` — two gang-leased
observations would silently build meshes over the SAME chips 0..k-1.
:func:`device_lease` publishes the leased device set thread-locally;
:func:`lease_devices` is the ONE resolver every mesh builder goes
through (the active lease first, then the thread's ``jax.default_device``
as the root of the local-device ring, then plain ``jax.local_devices()``),
so a mesh built inside a lease can only address the leased chips.

The lease registry also carries **device health** (round 12): a
process-global :class:`~pypulsar_tpu.resilience.health.DeviceHealth`
strike account (:func:`device_health`), keyed by REAL jax device ids.
The survey scheduler shares this account (``reset_device_health`` per
fleet) and charges OOMs, collective failures and injected device
faults against the real chips the failing execution was pinned to; a
chip past ``PYPULSAR_TPU_DEVICE_STRIKES`` is quarantined, the
scheduler evicts every lease mapping to it from the pool mid-fleet
(in-flight gangs retry shrunk to the surviving chips), and the
non-leased resolver path here skips quarantined chips
(:func:`healthy_devices`).
"""

from __future__ import annotations

import contextlib
import threading
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from pypulsar_tpu.resilience.health import DeviceHealth

_tls = threading.local()

# process-global strike account, keyed by device/lease id; reset per
# fleet by the survey scheduler (and per test via reset_device_health)
_device_health = DeviceHealth()


def device_health() -> DeviceHealth:
    """The process-global per-device strike/quarantine registry."""
    return _device_health


def reset_device_health(limit: Optional[int] = None) -> DeviceHealth:
    """Fresh strike account (new fleet / test isolation); ``limit``
    overrides ``PYPULSAR_TPU_DEVICE_STRIKES``."""
    global _device_health
    _device_health = DeviceHealth(limit)
    return _device_health


def healthy_devices(devices) -> list:
    """``devices`` minus the quarantined ones — unless that empties the
    list (an all-quarantined host must stay usable: degraded beats
    dead)."""
    kept = [d for d in devices
            if not _device_health.is_quarantined(int(getattr(d, "id", -1)))]
    return kept if kept else list(devices)


@contextlib.contextmanager
def device_lease(devices):
    """Publish ``devices`` as THIS thread's exclusive device gang for the
    block (re-entrant: an inner lease shadows, then restores, the outer).
    The survey scheduler wraps each device-bound stage in one; any mesh
    built below it via :func:`lease_devices` sees only these chips."""
    prev = getattr(_tls, "lease", None)
    _tls.lease = tuple(devices)
    try:
        yield _tls.lease
    finally:
        _tls.lease = prev


def current_lease() -> Optional[tuple]:
    """The active thread's leased device tuple, or None outside a lease."""
    return getattr(_tls, "lease", None)


def lease_device_ids() -> Optional[List[int]]:
    """Integer device ids of the active lease (telemetry attribution
    stamps these on span/counter records), or None outside a lease."""
    lease = current_lease()
    if not lease:
        return None
    return [int(getattr(d, "id", -1)) for d in lease]


def lease_devices(k: Optional[int] = None) -> list:
    """The device set this thread's work may address, optionally cut to
    ``k``. Resolution order: the active :func:`device_lease` (the gang);
    else ``jax.local_devices()`` rotated so the thread's
    ``jax.default_device`` (a single-chip lease) comes first; else plain
    ``jax.local_devices()``. Raises when fewer than ``k`` are
    addressable — a gang must never silently spill past its lease."""
    lease = current_lease()
    if lease:
        # a lease is the scheduler's verdict: it already excluded
        # quarantined chips, so the gang is taken as granted
        devs = list(lease)
    else:
        devs = healthy_devices(jax.local_devices())
        default = None
        try:
            default = jax.config.jax_default_device
        except Exception:  # noqa: BLE001 - config name moved: no rotation
            default = None
        if default is not None and default in devs:
            i = devs.index(default)
            devs = devs[i:] + devs[:i]
    if k is not None:
        if len(devs) < k:
            raise ValueError(
                f"need {k} devices but this thread's lease/host offers "
                f"only {len(devs)} ({[str(d) for d in devs]})")
        devs = devs[:k]
    return devs


def make_mesh(
    axis_sizes: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("dm", "time"),
    devices=None,
) -> Mesh:
    """Build a Mesh over available devices.

    Default: all devices on the 'dm' axis (1 on 'time') — DM-trial sharding
    needs no communication until the final candidate reduction, so it rides
    ICI most efficiently (BASELINE.json north star).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = [n] + [1] * (len(axis_names) - 1)
    if int(np.prod(axis_sizes)) != n:
        raise ValueError(f"axis sizes {axis_sizes} do not multiply to {n} devices")
    dev_array = mesh_utils.create_device_mesh(tuple(axis_sizes), devices=devices)
    return Mesh(dev_array, tuple(axis_names))


def gang_mesh(k: int) -> Mesh:
    """A 1-D 'dm' mesh over this thread's k leased/addressable devices —
    the one-call form every DM-sharding CLI path uses (see module
    docstring for the resolution order)."""
    return make_mesh([k], ("dm",), devices=lease_devices(k))
