"""Single-pulse event grouping: cluster per-(DM, width, chunk) sweep
events into distinct pulse candidates.

The sweep's multi-event output (SweepResult.events / sweep --all-events)
reports every above-threshold cell independently, so one bright pulse
appears once per DM trial and boxcar width that detects it — hundreds of
rows for a strong single pulse. This module reduces that list the way
single-pulse pipelines do (friends-of-friends association in the
(time, DM) plane): events whose peak times fall within ``time_tol`` and
whose DMs are within ``dm_tol`` of another member join the same group,
and each group reports its peak-SNR member plus its extent and
membership count. The reference has no equivalent (its single-pulse
stage, bin/dissect.py, works per rotation on one dedispersed series);
this is the multi-trial counterpart the sweep engine makes necessary.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

__all__ = ["group_events"]


def group_events(
    events: Sequence[dict],
    time_tol: float = 0.02,
    dm_tol: float = 10.0,
) -> List[dict]:
    """Cluster event records into pulse groups.

    ``events``: dicts with at least dm, snr, time_sec (the sweep's event
    schema; width_bins/sample/downsamp are carried through from each
    group's peak member). Association is transitive (friends-of-friends):
    sorted by time, an event joins the current group if it lies within
    ``time_tol`` seconds of the group's latest member and within
    ``dm_tol`` of ANY member's DM; otherwise it opens a new group.

    Returns one record per group, sorted by descending peak SNR::

        {**peak_event, "n_hits": int, "dm_lo": float, "dm_hi": float,
         "time_lo": float, "time_hi": float}
    """
    if not events:
        return []
    ordered = sorted(events, key=lambda e: (e["time_sec"], e["dm"]))
    done: List[Dict] = []
    active: List[Dict] = []
    for ev in ordered:
        t = ev["time_sec"]
        # events arrive time-sorted and an active group's time_hi only
        # grows, so a group that falls out of the time window is retired
        # PERMANENTLY — grouping stays O(n) instead of rescanning every
        # group per event
        still = []
        for g in active:
            (still if t - g["time_hi"] <= time_tol else done).append(g)
        active = still
        # true friends-of-friends: an event touching SEVERAL open groups
        # bridges them — merge all matches into one (greedy first-match
        # would split one physical pulse across rows)
        matches = [g for g in active
                   if g["dm_lo"] - dm_tol <= ev["dm"] <= g["dm_hi"] + dm_tol]
        if not matches:
            active.append(dict(
                peak=ev, n_hits=1, dm_lo=ev["dm"], dm_hi=ev["dm"],
                time_lo=t, time_hi=t))
            continue
        home = matches[0]
        for g in matches[1:]:
            home["n_hits"] += g["n_hits"]
            home["dm_lo"] = min(home["dm_lo"], g["dm_lo"])
            home["dm_hi"] = max(home["dm_hi"], g["dm_hi"])
            home["time_lo"] = min(home["time_lo"], g["time_lo"])
            home["time_hi"] = max(home["time_hi"], g["time_hi"])
            if g["peak"]["snr"] > home["peak"]["snr"]:
                home["peak"] = g["peak"]
            active.remove(g)
        home["n_hits"] += 1
        home["dm_lo"] = min(home["dm_lo"], ev["dm"])
        home["dm_hi"] = max(home["dm_hi"], ev["dm"])
        home["time_hi"] = max(home["time_hi"], t)
        if ev["snr"] > home["peak"]["snr"]:
            home["peak"] = ev

    out = []
    for g in done + active:
        rec = dict(g["peak"])
        rec.update(n_hits=g["n_hits"], dm_lo=g["dm_lo"], dm_hi=g["dm_hi"],
                   time_lo=g["time_lo"], time_hi=g["time_hi"])
        out.append(rec)
    out.sort(key=lambda r: -r["snr"])
    return out
