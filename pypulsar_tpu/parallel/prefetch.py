"""Bounded background prefetch of an iterator — the shared ship-ahead core.

Three hot paths in the framework have the same shape: a producer whose
per-item latency is wire or disk time (host->device block ships, .dat
reads + host prep, batch stacking + device prep) feeding a consumer whose
latency is device time (the sweep chunk kernel, the accel stage scans).
Run on one thread they serialize — the round-4 streamed sweep measured 0%
overlap until the ship moved to its own thread, and the round-5 accel A/B
still showed 6.4 of 8.7 s/spectrum of *serial host time* for exactly this
reason. The fix is always the same bounded producer/consumer pattern, so
it lives here once:

- a single worker thread pulls ``items``, applies ``transform`` (the
  expensive half — e.g. ``jnp.asarray`` riding the wire, or a .dat read),
  and parks results in a FIFO queue of ``depth`` slots;
- the consumer sees items in order; worker exceptions re-raise at the
  consumer's next pull (never swallowed in the thread);
- an abandoned consumer (error or early exit) signals the worker and
  drains the queue so a put-parked worker exits instead of producing the
  rest of a 57 GB stream; a ``close()`` on ``items`` is honored;
- under an active telemetry session the queue fill is recorded to the
  ``{name}.pending_depth`` gauge on every put — tlmsum's gauges table
  then shows how deep the pipeline actually ran. The worker records
  BEFORE parking on a full queue, so the gauge counts its in-hand item
  too: max == depth+1 means the producer kept fully ahead; max 0-1
  means the consumer starved.

Resilience (round 7): the transform retries transient IO errors with
exponential backoff (``retries``/``retry_on`` — a survey pass must not
abort over one NFS hiccup; each retry emits a ``resilience.worker_retry``
telemetry event), and the consumer enforces a per-item deadline
(``timeout``, default ``PYPULSAR_TPU_PREFETCH_TIMEOUT`` or 900 s; 0
disables) so a wedged producer fails LOUDLY with a TimeoutError naming
the pipeline instead of parking the whole run on ``q.get()`` forever.
The worker-side fault point ``{name}.produce`` sits inside the retry
loop, so ``tests/test_resilience.py`` can prove both policies.

``PYPULSAR_TPU_SHIP_AHEAD=0`` disables the thread globally (inline
transform, e.g. for single-threaded debugging); ordering and values are
identical either way — threading only moves WHEN work happens.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Optional, Tuple

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience.locks import TrackedEvent
from pypulsar_tpu.resilience.retry import RETRY_BACKOFF_MAX_S  # noqa: F401
from pypulsar_tpu.tune import knobs

__all__ = ["prefetch"]

ENV_TIMEOUT = "PYPULSAR_TPU_PREFETCH_TIMEOUT"
DEFAULT_TIMEOUT_S = 900.0
# how long the consumer's cleanup path waits for a (possibly wedged)
# worker before abandoning it: the thread is a daemon, so leaking it is
# safe — spinning on join() forever is the wedge we exist to prevent
CLEANUP_DEADLINE_S = 5.0


def _resolve_timeout(timeout: Optional[float]) -> Optional[float]:
    if timeout is None:
        timeout = float(knobs.env_float(ENV_TIMEOUT))
    return None if timeout <= 0 else timeout


def _produce(xf: Callable, item, name: str, retries: int,
             retry_backoff: float, retry_on: Tuple[type, ...]):
    """One item through the (fault-instrumented) transform with the
    shared transient-error retry policy (resilience.retry_transient) —
    used by the worker thread and the inline (SHIP_AHEAD=0) path alike
    so retry semantics cannot diverge."""
    from pypulsar_tpu.resilience import faultinject
    from pypulsar_tpu.resilience.retry import retry_transient

    def attempt():
        faultinject.trip(f"{name}.produce")
        return xf(item)

    return retry_transient(attempt, retries=retries, backoff=retry_backoff,
                           retry_on=retry_on, what=name)


def prefetch(items: Iterable, depth: int = 2, name: str = "prefetch",
             transform: Optional[Callable] = None,
             thread_name: Optional[str] = None,
             retries: int = 0, retry_backoff: float = 0.1,
             retry_on: Tuple[type, ...] = (OSError,),
             timeout: Optional[float] = None):
    """Yield ``transform(item)`` for each item, produced ``depth`` ahead
    on a background thread (see module docstring for the contract).

    ``retries``: transform attempts re-run up to this many times on
    ``retry_on`` exceptions (exponential backoff from ``retry_backoff``
    seconds). ``timeout``: per-item consumer deadline in seconds (None =
    the ``PYPULSAR_TPU_PREFETCH_TIMEOUT`` env default; <= 0 disables)."""
    xf = transform if transform is not None else (lambda it: it)
    gauge_name = f"{name}.pending_depth"

    if knobs.env_str("PYPULSAR_TPU_SHIP_AHEAD") == "0":
        for item in items:
            yield _produce(xf, item, name, retries, retry_backoff,
                           retry_on)
        return

    deadline = _resolve_timeout(timeout)
    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    _done = object()
    stop = TrackedEvent("prefetch.stop")
    # the consumer's causal identity, captured HERE (construction runs
    # on the stage's thread): the worker re-enters it so its telemetry
    # lands on the stage's trace and its beats refresh the stage's
    # heartbeat entry — not the producer thread's nonexistent one
    # (round 21; the PR 7 attribution caveat this closes)
    trace_ctx = telemetry.current_context()

    def worker():
        with telemetry.adopt_context(trace_ctx):
            try:
                for item in items:
                    if stop.is_set():  # consumer gone: stop producing
                        return
                    out = _produce(xf, item, name, retries,
                                   retry_backoff, retry_on)
                    if telemetry.is_active():  # gauges are thread-safe
                        telemetry.gauge(gauge_name, q.qsize() + 1)
                    q.put(out)
            except BaseException as e:  # noqa: BLE001 - re-raised in consumer
                q.put(e)
                return
            q.put(_done)

    t = threading.Thread(target=worker,
                         name=thread_name or f"pypulsar-{name}",
                         daemon=True)
    t.start()
    try:
        while True:
            try:
                item = q.get(timeout=deadline)
            except queue.Empty:
                telemetry.event("resilience.prefetch_timeout",
                                pipeline=name, timeout_s=deadline)
                raise TimeoutError(
                    f"prefetch {name!r}: producer delivered nothing for "
                    f"{deadline:.0f}s (worker "
                    f"{'alive' if t.is_alive() else 'dead'}); the "
                    f"pipeline would otherwise wedge silently — raise "
                    f"{ENV_TIMEOUT} if items legitimately take longer"
                ) from None
            if item is _done:
                break
            if isinstance(item, BaseException):
                raise item
            if telemetry.is_active():
                telemetry.gauge(gauge_name, q.qsize())
            yield item
    finally:
        # consumer abandoned mid-stream (error or early exit): signal the
        # worker, then drain queue slots so a put-parked worker can see
        # the signal and exit instead of producing the rest of the
        # stream. Deadline-bounded: a worker wedged INSIDE its transform
        # never exits, and the cleanup must not inherit its wedge (the
        # thread is a daemon — abandoning it is safe)
        stop.set()
        give_up = time.monotonic() + CLEANUP_DEADLINE_S
        while t.is_alive() and time.monotonic() < give_up:
            try:
                q.get_nowait()
            except queue.Empty:
                t.join(timeout=0.1)
        close = getattr(items, "close", None)
        if close is not None:
            close()
