"""Bounded background prefetch of an iterator — the shared ship-ahead core.

Three hot paths in the framework have the same shape: a producer whose
per-item latency is wire or disk time (host->device block ships, .dat
reads + host prep, batch stacking + device prep) feeding a consumer whose
latency is device time (the sweep chunk kernel, the accel stage scans).
Run on one thread they serialize — the round-4 streamed sweep measured 0%
overlap until the ship moved to its own thread, and the round-5 accel A/B
still showed 6.4 of 8.7 s/spectrum of *serial host time* for exactly this
reason. The fix is always the same bounded producer/consumer pattern, so
it lives here once:

- a single worker thread pulls ``items``, applies ``transform`` (the
  expensive half — e.g. ``jnp.asarray`` riding the wire, or a .dat read),
  and parks results in a FIFO queue of ``depth`` slots;
- the consumer sees items in order; worker exceptions re-raise at the
  consumer's next pull (never swallowed in the thread);
- an abandoned consumer (error or early exit) signals the worker and
  drains the queue so a put-parked worker exits instead of producing the
  rest of a 57 GB stream; a ``close()`` on ``items`` is honored;
- under an active telemetry session the queue fill is recorded to the
  ``{name}.pending_depth`` gauge on every put — tlmsum's gauges table
  then shows how deep the pipeline actually ran. The worker records
  BEFORE parking on a full queue, so the gauge counts its in-hand item
  too: max == depth+1 means the producer kept fully ahead; max 0-1
  means the consumer starved.

``PYPULSAR_TPU_SHIP_AHEAD=0`` disables the thread globally (inline
transform, e.g. for single-threaded debugging); ordering and values are
identical either way — threading only moves WHEN work happens.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, Iterable, Optional

from pypulsar_tpu.obs import telemetry

__all__ = ["prefetch"]


def prefetch(items: Iterable, depth: int = 2, name: str = "prefetch",
             transform: Optional[Callable] = None,
             thread_name: Optional[str] = None):
    """Yield ``transform(item)`` for each item, produced ``depth`` ahead
    on a background thread (see module docstring for the contract)."""
    xf = transform if transform is not None else (lambda it: it)
    gauge_name = f"{name}.pending_depth"

    if os.environ.get("PYPULSAR_TPU_SHIP_AHEAD", "1") == "0":
        for item in items:
            yield xf(item)
        return

    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    _done = object()
    stop = threading.Event()

    def worker():
        try:
            for item in items:
                if stop.is_set():  # consumer gone: don't produce the rest
                    return
                out = xf(item)
                if telemetry.is_active():  # gauges are thread-safe
                    telemetry.gauge(gauge_name, q.qsize() + 1)
                q.put(out)
        except BaseException as e:  # noqa: BLE001 - re-raised in consumer
            q.put(e)
            return
        q.put(_done)

    t = threading.Thread(target=worker,
                         name=thread_name or f"pypulsar-{name}",
                         daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _done:
                break
            if isinstance(item, BaseException):
                raise item
            if telemetry.is_active():
                telemetry.gauge(gauge_name, q.qsize())
            yield item
    finally:
        # consumer abandoned mid-stream (error or early exit): signal the
        # worker, then drain queue slots so a put-parked worker can see
        # the signal and exit instead of producing the rest of the stream
        stop.set()
        while t.is_alive():
            try:
                q.get_nowait()
            except queue.Empty:
                t.join(timeout=0.1)
        close = getattr(items, "close", None)
        if close is not None:
            close()
