"""The DM-trial sweep engine — the framework's headline workload.

Executes a brute-force (or DDplan-driven) dedispersion sweep: for every DM
trial, form the channel-summed dedispersed time series and reduce it to
matched-filter boxcar detection statistics, streaming the time axis in
overlap-save chunks and sharding the DM axis across a device mesh.

Reference treatment: nonexistent — the reference generates the trial list
(utils/DDplan2b.py:253-268) and defers execution to PRESTO, one CPU core,
one trial at a time. This module is the TPU-native design the north star
names: vmapped per-channel shifts over trials, shard_map over the ICI mesh,
lax.top_k candidate reduction.

Algorithm: two-stage subband dedispersion, the same structure DDplan
prescribes with its numsub/dsubDM machinery (reference utils/DDplan2b.py:
132-150) and Spectra.subband implements per-group (formats/spectra.py:96-138):

  stage 1 (per trial-group): shift channels to a group ``subdm`` and sum into
     ``nsub`` subbands — amortizes the full-channel pass over a group of
     nearby trials;
  stage 2 (per trial): shift + sum the nsub subbands at the trial DM.

Cost per chunk: O(G*C*T + D*S*T) HBM traffic instead of O(D*C*T) for direct
per-trial shifts — the reuse factor that makes the sweep bandwidth-feasible.
All shifts are integer bins precomputed host-side in float64 (bit-compatible
with the NumPy twin in tests/test_sweep.py); on device they are static-length
lax.dynamic_slice starts, so everything jits with fixed shapes.

Boundary handling: chunks carry ``overlap`` extra samples (>= max total delay
+ max boxcar width), the overlap-save analogue of ring-attention halo
exchange; in the time-sharded multi-device path the halo comes from the
ICI neighbor via lax.ppermute instead of the host stream.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from functools import partial
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pypulsar_tpu.compile import (
    bucket_rows,
    note_bucket_pad,
    plane_jit,
    register_warmer,
)
from pypulsar_tpu.core import psrmath
from pypulsar_tpu.ops import transfer
from pypulsar_tpu.ops.pallas_kernels import boxcar_stats
from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.tune import knobs
from pypulsar_tpu.utils import profiling

DEFAULT_WIDTHS = (1, 2, 4, 8, 16, 32)

ENGINES = ("gather", "scan", "fourier", "tree")


def shard_map_compat(fn, mesh, in_specs, out_specs,
                     check_vma: Optional[bool] = None):
    """``jax.shard_map`` across jax versions: the top-level API (with its
    ``check_vma`` knob) where it exists, else the older
    ``jax.experimental.shard_map.shard_map`` whose ``check_rep`` is the
    same replication check under its pre-stabilization name."""
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def resolve_engine(engine: str = "auto") -> str:
    """Pick the chunk-kernel formulation.

    'fourier' (ops/fourier_dedisperse.py) is the default on TPU: the
    recorded v5e A/B (BENCHNOTES.md) measured the gather path at ~26 GB/s
    effective (3% of HBM roofline) while the Fourier phase-multiply path
    streams at bandwidth. 'gather' stays the default off-TPU (CPU XLA
    handles the vmapped dynamic_slice fine, and it is the bit-parity
    reference formulation). 'tree' (ops/tree_dedisperse.py) shares adds
    between ALL trials through log2(nchan) pairwise merge levels — the
    production-DM-count engine (round 16); opt-in (never auto-picked:
    its win depends on trial count/density, see the README engine
    matrix). Override with PYPULSAR_TPU_SWEEP_ENGINE.
    """
    if engine != "auto":
        if engine not in ENGINES:
            raise ValueError(f"unknown sweep engine {engine!r}; "
                             f"expected one of {ENGINES + ('auto',)}")
        return engine
    env = knobs.env_str("PYPULSAR_TPU_SWEEP_ENGINE")
    if env and env != "auto":  # "auto" in the env var falls through
        return resolve_engine(env)
    try:
        # resolve through the gang-lease registry (PL002): under a
        # lease the engine choice must reflect the leased chip, not
        # whatever backend device 0 happens to be
        from pypulsar_tpu.parallel.mesh import lease_devices

        platform = lease_devices()[0].platform
    except Exception:  # noqa: BLE001 - backend probing must not fail
        platform = "cpu"
    return "fourier" if platform == "tpu" else "gather"


def choose_group_size(
    dms,
    freqs,
    dt: float,
    nsub: int = 64,
    max_extra_smear_bins: float = 1.0,
    max_group: int = 128,
) -> int:
    """Largest power-of-two stage-1 group size whose extra subband
    smearing stays under ``max_extra_smear_bins`` samples.

    Stage 1 dedisperses each subband at the GROUP's mean DM; a trial at
    the group edge is off by ``(g/2) * dDM``, smearing the worst (lowest)
    subband by ``dm_smear(dDM_off, BW_sub, f_low)``. Larger groups
    amortize the expensive full-channel stage-1 pass over more trials —
    the measured v5e geometry grid (BENCHNOTES.md) has (nsub=64, g=64)
    25% faster than g=32 — and at dense trial spacing (the 4096-trial
    north-star grid has dDM ~ 0.12) the smearing cost of g=64-128 is a
    fraction of a sample. This chooser makes that tradeoff explicit:
    DDplan's own numsub/dsubDM machinery, applied to the engine geometry
    (reference utils/DDplan2b.py:132-150 is the same bound for its
    subband steps)."""
    dms = np.asarray(dms, dtype=np.float64)
    if len(dms) < 2:
        return 1
    ddm = float(np.max(np.abs(np.diff(dms))))
    freqs = np.asarray(freqs, dtype=np.float64)
    f_low = float(freqs.min())
    bw_sub = float(abs(freqs.max() - freqs.min())) / nsub
    g = 1
    while g * 2 <= max_group:  # honors non-power-of-two caps too
        off = g * ddm  # next candidate's worst-case offset = (2g/2)*ddm
        if psrmath.dm_smear(off, bw_sub, f_low) > max_extra_smear_bins * dt:
            break
        g *= 2
    return g


@dataclasses.dataclass(frozen=True)
class SweepPlan:
    """Host-side precomputed geometry of a sweep.

    stage1_bins[G, C]   int32  per-group per-channel shifts (to group subdm)
    stage2_bins[G, g, S] int32 per-trial per-subband shifts (trial dm)
    dms[G*g] float64 trial DMs (padded trials replicated from last real one)
    """

    dms: np.ndarray
    freqs: np.ndarray
    dt: float
    nsub: int
    group_size: int
    stage1_bins: np.ndarray
    stage2_bins: np.ndarray
    subdms: np.ndarray
    n_real_trials: int
    widths: Tuple[int, ...] = DEFAULT_WIDTHS

    @property
    def n_groups(self) -> int:
        return self.stage1_bins.shape[0]

    @property
    def n_trials(self) -> int:
        return self.n_groups * self.group_size

    @property
    def max_shift1(self) -> int:
        return int(self.stage1_bins.max(initial=0))

    @property
    def max_shift2(self) -> int:
        return int(self.stage2_bins.max(initial=0))

    @property
    def max_total_shift(self) -> int:
        return self.max_shift1 + self.max_shift2

    @property
    def min_overlap(self) -> int:
        return self.max_total_shift + max(self.widths)


def make_sweep_plan(
    dms: Sequence[float],
    freqs: np.ndarray,
    dt: float,
    nsub: int = 64,
    group_size: int = 32,
    widths: Tuple[int, ...] = DEFAULT_WIDTHS,
    pad_groups_to: Optional[int] = None,
) -> SweepPlan:
    """Precompute integer shift tables (float64 host math).

    Channels are assumed high-frequency-first (SIGPROC foff<0 order); the
    reference's get_spectra delivers them that way (formats/psrfits.py:175
    flips the band to guarantee it).
    """
    dms = np.asarray(dms, dtype=np.float64)
    freqs = np.asarray(freqs, dtype=np.float64)
    if group_size <= 0:  # auto: largest group within the smearing bound
        group_size = choose_group_size(dms, freqs, dt, nsub)
    C = len(freqs)
    if C > 1 and not np.all(np.diff(freqs) <= 0):
        raise ValueError(
            "make_sweep_plan needs monotonically descending (high-"
            "frequency-first) channels: flip/sort the data and frequency "
            "axes first (the staged block sources flip ascending tables "
            "automatically)")
    if C % nsub:
        raise ValueError(f"nsub={nsub} must divide nchan={C}")
    per = C // nsub
    n_real = len(dms)
    G = -(-n_real // group_size)
    if pad_groups_to is not None:
        if pad_groups_to < G:
            raise ValueError("pad_groups_to smaller than required groups")
        G = pad_groups_to
    padded = np.concatenate([dms, np.repeat(dms[-1], G * group_size - n_real)])

    sub_hif = freqs[np.arange(nsub) * per]  # top freq of each subband
    f_ref = freqs.max()

    stage1 = np.zeros((G, C), dtype=np.int32)
    stage2 = np.zeros((G, group_size, nsub), dtype=np.int32)
    subdms = np.zeros(G, dtype=np.float64)
    for gi in range(G):
        block = padded[gi * group_size : (gi + 1) * group_size]
        subdm = float(np.mean(block))
        subdms[gi] = subdm
        # stage 1: intra-subband shifts at subdm, relative to subband top freq
        d_chan = psrmath.delay_from_DM(subdm, freqs)
        d_ref = np.repeat(psrmath.delay_from_DM(subdm, sub_hif), per)
        stage1[gi] = np.round((d_chan - d_ref) / dt).astype(np.int32)
        # stage 2: per-trial subband shifts, relative to global top freq
        for ti, dm in enumerate(block):
            d_sub = psrmath.delay_from_DM(dm, sub_hif)
            d0 = psrmath.delay_from_DM(dm, f_ref)
            stage2[gi, ti] = np.round((d_sub - d0) / dt).astype(np.int32)

    return SweepPlan(
        dms=padded,
        freqs=freqs,
        dt=float(dt),
        nsub=nsub,
        group_size=group_size,
        stage1_bins=stage1,
        stage2_bins=stage2,
        subdms=subdms,
        n_real_trials=n_real,
        widths=tuple(widths),
    )


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------


DEFAULT_CHUNK_FFT_LEN = 1 << 18
# Round-5 chunk-length A/B on v5e (BENCHNOTES): at the bench geometry
# (1024 chans, 1024 trials) the fourier chunk measures 0.67 G
# trial-samples/s at n=2^17, 0.95 G at 2^18 (+41%), 0.87 G at 2^19 —
# the FFT amortizes and the overlap fraction shrinks up to 2^18, then
# working-set growth wins. 2^18 is the registry default for the
# PYPULSAR_TPU_SWEEP_CHUNK knob (round 17): anywhere a chunk length is
# not explicitly given, :func:`chunk_fft_len` resolves env > tuned
# cache > this constant.


def chunk_fft_len(tuned: bool = True) -> int:
    """The streaming chunk length: the ``PYPULSAR_TPU_SWEEP_CHUNK``
    knob rounded up to a power of two (the FFT/doubling machinery in
    :func:`default_chunk_payload` and the checkpoint fingerprints both
    assume pow2), floored at 2^12 so a typo cannot degenerate the
    stream to sample-sized dispatches.

    ``tuned=False`` resolves env > default only, skipping the
    auto-tuning overlays: the single-pulse DETECTION sweep's chunk is
    part of its results (per-chunk statistics, one event per chunk —
    the documented streaming semantics ``--chunk`` fingerprints), so
    the tuner may move the chunk for the byte-invariant series/handoff
    paths but never for the detector. An env var or ``--chunk`` remains
    an explicit operator choice either way."""
    n = int(knobs.env_int("PYPULSAR_TPU_SWEEP_CHUNK", overlays=tuned))
    n = max(1 << 12, n)
    if n & (n - 1):
        n = 1 << n.bit_length()
    return n


def default_chunk_payload(min_overlap: int, tuned: bool = True) -> int:
    """Default streaming chunk payload: :func:`chunk_fft_len` grown (by
    doubling) until the dedispersion overlap fits in half the FFT."""
    n = chunk_fft_len(tuned)
    while min_overlap >= n // 2:
        n <<= 1
    return n - min_overlap


def _slice_rows(rows, starts, length):
    """rows[N, L] -> [N, length], row i starting at starts[i] (static length)."""
    return jax.vmap(lambda r, s: jax.lax.dynamic_slice(r, (s,), (length,)))(
        rows, starts.astype(jnp.int32)
    )


def _shift_segment_sum(rows, starts, length, seg: int):
    """Fused shift + segment-sum: rows[N, L] with per-row starts ->
    out[N // seg, length], out[s] = sum of seg consecutive shifted rows.

    Scan-based alternative to ``_slice_rows(...).reshape(...).sum(axis=1)``:
    one dynamic_slice per scan step accumulating into the output, which
    lowers to contiguous copies instead of the vmapped gather and never
    materializes the [N, length] intermediate. The recorded v5e A/B
    (BENCHNOTES.md) has both formulations far below HBM bandwidth; the
    Fourier engine supersedes them on TPU."""
    N = rows.shape[0]
    nseg = N // seg
    starts = starts.astype(jnp.int32)

    def body(acc, ci):
        seg_rows = jax.lax.dynamic_slice_in_dim(rows, ci * seg, seg, 0)
        seg_starts = jax.lax.dynamic_slice_in_dim(starts, ci * seg, seg, 0)

        def inner(acc_row, k):
            row = jax.lax.dynamic_slice(
                seg_rows, (k, seg_starts[k]), (1, length))[0]
            return acc_row + row, None

        row0 = jax.lax.dynamic_slice(
            seg_rows, (0, seg_starts[0]), (1, length))[0]
        acc_row, _ = jax.lax.scan(inner, row0, jnp.arange(1, seg))
        return acc, (ci, acc_row)

    _, (_, out) = jax.lax.scan(body, 0, jnp.arange(nseg))
    return out


def _sweep_chunk_impl(
    data,
    stage1_bins,
    stage2_bins,
    nsub: int,
    out_len: int,
    slack2: int,
    widths: Tuple[int, ...],
    stat_len: int,
    engine: str = "gather",
):
    """Process one chunk for all trial groups.

    data[C, L] with L >= out_len + slack2 + max(stage1) ; out_len = chunk
    payload + max boxcar width so boxcars can start anywhere in the payload.
    stat_len <= out_len is the number of samples whose statistics (sum/sumsq)
    belong to this chunk (the payload), so streamed chunks don't double-count
    overlap samples.

    ``engine``: 'gather' (vmapped dynamic_slice), 'scan' (sequential
    dynamic_slice accumulation), 'fourier' (phase-multiply fast path,
    ops/fourier_dedisperse.py — the TPU default via resolve_engine), or
    'auto'. All three agree to f32 rounding (tests/test_sweep.py).

    Returns per-trial (sum[D], sumsq[D], maxbox[D, W], argbox[D, W]).
    """
    engine = resolve_engine(engine)
    if engine == "tree":
        # the tree engine's merge tables are HOST-built (data-dependent
        # dedup) — it dispatches from the Python wrappers (sweep_chunk /
        # dedisperse_series_chunk / the sharded factories), never from
        # inside a traced impl
        raise ValueError(
            "engine='tree' cannot run inside a traced chunk impl; "
            "dispatch through sweep_chunk/dedisperse_series_chunk or "
            "the make_sharded_* factories")
    if engine == "fourier":
        from pypulsar_tpu.ops.fourier_dedisperse import (
            fourier_chunk_len,
            sweep_chunk_fourier_impl,
        )

        # static shift bounds for the LUT phase tables: every sweep path
        # sizes data as out_len + slack2 + max_shift1, so the stage-1
        # bound falls out of the (static) chunk shape
        max_s1 = max(int(data.shape[1]) - out_len - slack2, 0)
        return sweep_chunk_fourier_impl(
            data, stage1_bins, stage2_bins, nsub, out_len, widths,
            stat_len, fourier_chunk_len(data.shape[1]),
            max_shift1=max_s1, max_shift2=slack2,
        )
    C, L = data.shape
    G, g, S = stage2_bins.shape
    per = C // nsub
    L1 = out_len + slack2

    def per_group(carry, xs):
        shift1, shift2 = xs
        if engine == "scan":
            # scan-based formulation (see _shift_segment_sum)
            sub = _shift_segment_sum(data, shift1, L1, per)  # [S, L1]
        else:
            sliced = _slice_rows(data, shift1, L1)  # [C, L1]
            sub = sliced.reshape(nsub, per, L1).sum(axis=1)  # [S, L1]
        ts = jax.vmap(lambda sh: _slice_rows(sub, sh, out_len).sum(axis=0))(
            shift2
        )  # [g, out_len]
        # fused detection stats: Pallas kernel on TPU, lax elsewhere
        # (windows start within the payload region)
        s, ss, mb_g, ab_g = boxcar_stats(ts, widths, stat_len)
        return carry, (s, ss, mb_g, ab_g)

    _, (s, ss, mb, ab) = jax.lax.scan(per_group, 0, (stage1_bins, stage2_bins))
    D = G * g
    return (
        s.reshape(D),
        ss.reshape(D),
        mb.reshape(D, len(widths)),
        ab.reshape(D, len(widths)),
    )


@plane_jit(static_argnames=("nsub", "out_len", "slack2", "widths",
                            "stat_len", "engine"), stage="sweep")
def _sweep_chunk_jit(data, stage1_bins, stage2_bins, nsub, out_len, slack2,
                     widths, stat_len, engine="gather"):
    return _sweep_chunk_impl(
        data, stage1_bins, stage2_bins, nsub, out_len, slack2, widths,
        stat_len, engine=engine
    )


def sweep_chunk(data, stage1_bins, stage2_bins, nsub, out_len, slack2, widths,
                stat_len, engine="gather"):
    """Single-device chunk sweep (see _sweep_chunk_impl). A thin Python
    dispatcher (not itself jitted): the gather/scan/fourier engines run
    as one jitted program; the tree engine first builds (cached) host
    merge tables from the exact shift values, then runs its own jitted
    scans (ops/tree_dedisperse.py)."""
    engine = resolve_engine(engine)
    if engine == "tree":
        from pypulsar_tpu.ops.tree_dedisperse import sweep_chunk_tree

        return sweep_chunk_tree(data, stage1_bins, stage2_bins, out_len,
                                tuple(widths), stat_len)
    return _sweep_chunk_jit(data, stage1_bins, stage2_bins, nsub, out_len,
                            slack2, widths, stat_len, engine=engine)


def dedisperse_series_chunk(data, stage1_bins, stage2_bins, nsub,
                            out_len: int, slack2: int, engine="gather"):
    """Two-stage subband dedispersed SERIES [D, out_len] for one chunk —
    :func:`_sweep_chunk_impl` with the fused detection swapped for the
    raw per-trial time series. The chunk kernel of the streamed .dat
    writer (staged.write_dats_streamed): PRESTO-prepsubband semantics
    (subband dedispersion with the sweep's own stage bins), so the
    written series is exactly what the sweep's detections saw. Python
    dispatcher like :func:`sweep_chunk` (the tree engine builds host
    tables before its jitted scans)."""
    engine = resolve_engine(engine)
    if engine == "tree":
        from pypulsar_tpu.ops.tree_dedisperse import dedisperse_series_tree

        return dedisperse_series_tree(data, stage1_bins, stage2_bins,
                                      out_len)
    return _dedisperse_series_jit(data, stage1_bins, stage2_bins, nsub,
                                  out_len, slack2, engine)


@plane_jit(static_argnames=("nsub", "out_len", "slack2", "engine"),
           stage="sweep")
def _dedisperse_series_jit(data, stage1_bins, stage2_bins, nsub,
                           out_len: int, slack2: int, engine="gather"):
    engine = resolve_engine(engine)
    if engine == "fourier":
        from pypulsar_tpu.ops.fourier_dedisperse import (
            dedisperse_series_fourier_impl,
            fourier_chunk_len,
        )

        return dedisperse_series_fourier_impl(
            data, stage1_bins, stage2_bins, nsub, out_len,
            fourier_chunk_len(data.shape[1]))
    C, L = data.shape
    G, g, S = stage2_bins.shape
    per = C // nsub
    L1 = out_len + slack2

    def per_group(carry, xs):
        shift1, shift2 = xs
        sliced = _slice_rows(data, shift1, L1)
        sub = sliced.reshape(nsub, per, L1).sum(axis=1)
        ts = jax.vmap(lambda sh: _slice_rows(sub, sh, out_len).sum(axis=0))(
            shift2)
        return carry, ts

    _, ts = jax.lax.scan(per_group, 0, (stage1_bins, stage2_bins))
    return ts.reshape(G * g, out_len)


def make_sharded_sweep_chunk(mesh: Mesh, nsub, out_len, slack2, widths,
                             stat_len, engine="gather"):
    """Chunk sweep with trial groups sharded over the mesh 'dm' axis.

    The chunk is replicated to every device; each device scans only its local
    trial groups (shard_map), so there is NO inter-device communication in the
    hot loop — candidates are reduced host-side after streaming. The group
    count must divide the 'dm' axis size (use make_sweep_plan(pad_groups_to=...)).
    """
    engine = resolve_engine(engine)
    if engine == "tree":
        # per-device host-built tables (rows bit-identical to the
        # unsharded tree engine — per-trial merge structure is fixed)
        from pypulsar_tpu.ops.tree_dedisperse import (
            make_sharded_tree_sweep_chunk,
        )

        return make_sharded_tree_sweep_chunk(mesh, out_len, tuple(widths),
                                             stat_len)
    impl = partial(
        _sweep_chunk_impl,
        nsub=nsub,
        out_len=out_len,
        slack2=slack2,
        widths=widths,
        stat_len=stat_len,
        engine=engine,
    )
    fn = shard_map_compat(
        impl,
        mesh=mesh,
        in_specs=(P(), P("dm"), P("dm")),
        out_specs=P("dm"),
    )
    # mesh-closing factory: plane-wrapped for telemetry, aot=False (AOT
    # keying across meshes is unsound; XLA's persistent cache still hits)
    return plane_jit(fn, stage="sweep", name="sweep_sharded_chunk",
                     aot=False)


def make_sharded_series_chunk(mesh: Mesh, nsub, out_len, slack2,
                              engine="gather"):
    """:func:`dedisperse_series_chunk` with trial groups sharded over the
    mesh 'dm' axis — the chunk engine of the DM-sharded sweep->accel
    handoff (parallel.accelpipe). The chunk replicates to every device;
    each device dedisperses only its local trial groups and the [D, out]
    series concatenates in group order (out_specs P('dm')), so the rows a
    consumer sees are BIT-identical to the unsharded kernel's — per-group
    math is device-count independent. The group count must divide the
    'dm' axis size (make_sweep_plan(pad_groups_to=...))."""
    engine = resolve_engine(engine)
    if engine == "tree":
        from pypulsar_tpu.ops.tree_dedisperse import (
            make_sharded_tree_series_chunk,
        )

        return make_sharded_tree_series_chunk(mesh, out_len)

    def impl(data, stage1_bins, stage2_bins):
        return dedisperse_series_chunk(data, stage1_bins, stage2_bins,
                                       nsub, out_len, slack2, engine)

    fn = shard_map_compat(
        impl,
        mesh=mesh,
        in_specs=(P(), P("dm"), P("dm")),
        out_specs=P("dm"),
    )
    return plane_jit(fn, stage="sweep", name="series_sharded_chunk",
                     aot=False)


def make_sharded_sweep_chunk_2d(
    mesh: Mesh, nsub, local_payload, overlap, slack2, widths, engine="gather"
):
    """Chunk sweep sharded over BOTH mesh axes: trial groups over 'dm' and the
    time axis over 'time' (the long-context axis, SURVEY.md §5).

    Each time shard holds [C, local_payload + overlap] after receiving an
    ``overlap``-sample halo from its right neighbor over ICI (lax.ppermute —
    the overlap-save seam exchange; the final shard pads with zeros, matching
    the host-streamed tail). Per-shard boxcar stats are then combined with
    psum (moments) and all_gather+argmax (peaks) along 'time'.

    Input: data[C, T] sharded as P(None, 'time'); stage tables sharded P('dm').
    T must equal local_payload * mesh.shape['time'].
    """
    engine = resolve_engine(engine)
    if engine == "tree":
        raise ValueError(
            "engine='tree' supports the 1-D 'dm' mesh only (its merge "
            "tables are host-built per device); use gather/scan/fourier "
            "on the dm x time mesh")
    W = max(widths)
    out_len = local_payload + W
    nt = mesh.shape["time"]

    def local_fn(data_local, s1_local, s2_local):
        # halo: leading `overlap` samples of the RIGHT neighbor (shard i+1 -> i)
        lead = data_local[:, :overlap]
        halo = jax.lax.ppermute(
            lead, "time", [(i, i - 1) for i in range(1, nt)]
        )
        data_ext = jnp.concatenate([data_local, halo], axis=1)
        s, ss, mb, ab = _sweep_chunk_impl(
            data_ext, s1_local, s2_local, nsub, out_len, slack2, widths,
            stat_len=local_payload, engine=engine,
        )
        # moments: payload regions partition the time axis exactly
        s = jax.lax.psum(s, "time")
        ss = jax.lax.psum(ss, "time")
        # peaks: shift to global sample indices, reduce by max over shards
        ti = jax.lax.axis_index("time")
        ab = ab + ti * local_payload
        mb_all = jax.lax.all_gather(mb, "time")  # [nt, Dl, W]
        ab_all = jax.lax.all_gather(ab, "time")
        k = mb_all.argmax(axis=0)
        mb = jnp.take_along_axis(mb_all, k[None], axis=0)[0]
        ab = jnp.take_along_axis(ab_all, k[None], axis=0)[0]
        return s, ss, mb, ab

    fn = shard_map_compat(
        local_fn,
        mesh=mesh,
        in_specs=(P(None, "time"), P("dm"), P("dm")),
        out_specs=(P("dm"), P("dm"), P("dm"), P("dm")),
        check_vma=False,  # outputs are replicated over 'time' by construction
    )
    return plane_jit(fn, stage="sweep", name="sweep_sharded_chunk_2d",
                     aot=False)


@dataclasses.dataclass
class SweepResult:
    """Accumulated sweep output. ``snr[d, w]`` is the matched-filter SNR of
    the best boxcar of width widths[w] for trial dms[d]:
    (max_w_sum - w*mean) / (sqrt(w)*std) with mean/std over the full series
    (streaming mean/std normalization; the single-block path in
    ops.kernels.boxcar_snr uses the reference's median/std convention and is
    parity-tested against it)."""

    dms: np.ndarray
    widths: Tuple[int, ...]
    snr: np.ndarray  # [D, W]
    peak_sample: np.ndarray  # [D, W] global sample index of best box start
    mean: np.ndarray
    std: np.ndarray
    # with keep_chunk_peaks: per-chunk peak SNRs/samples [nchunks, D, W]
    chunk_snr: Optional[np.ndarray] = None
    chunk_sample: Optional[np.ndarray] = None

    def events(self, threshold: float):
        """Every per-chunk peak above ``threshold`` SNR, as (dm, width,
        snr, sample) records — one event per (chunk, trial, width) cell,
        so a trial can report many pulses across the observation (the
        single-best ``snr``/``peak_sample`` fields keep only the global
        max). Requires the sweep to have run with ``keep_chunk_peaks``;
        raises otherwise."""
        if self.chunk_snr is None:
            raise ValueError(
                "per-chunk peaks were not recorded: run the sweep with "
                "keep_chunk_peaks=True (cli: --all-events)")
        out = []
        nch, D, W = self.chunk_snr.shape
        for ci in range(nch):
            hits = np.argwhere(self.chunk_snr[ci] >= threshold)
            for di, wi in hits:
                out.append(dict(
                    dm=float(self.dms[di]),
                    width=int(self.widths[wi]),
                    snr=float(self.chunk_snr[ci, di, wi]),
                    sample=int(self.chunk_sample[ci, di, wi]),
                ))
        out.sort(key=lambda e: (e["dm"], e["sample"]))
        return out

    def best(self, k: int = 10):
        """Top-k (dm, width, snr, sample) candidates over all trials."""
        flat = self.snr.reshape(-1)
        order = np.argsort(flat)[::-1][:k]
        d, w = np.unravel_index(order, self.snr.shape)
        return [
            dict(
                dm=float(self.dms[di]),
                width=int(self.widths[wi]),
                snr=float(self.snr[di, wi]),
                sample=int(self.peak_sample[di, wi]),
            )
            for di, wi in zip(d, w)
        ]


class AccumParts(NamedTuple):
    """Raw sweep accumulator state (``sweep_stream(finalize=False)``):
    everything :func:`finalize_sweep` needs, in mergeable form. ``mb``
    carries f32 window-sum maxima and ``ab`` their global sample
    positions; ``s``/``ss`` are host-f64 moment sums over ``n`` payload
    samples; ``baseline_sum`` restores original units. ``chunk_mb``/
    ``chunk_ab`` (with ``keep_chunk_peaks``) are the per-chunk peak
    records in stream order — window-local slices of the sequential
    sweep's chunk sequence, so cross-window merging is concatenation."""

    n: int
    s: np.ndarray
    ss: np.ndarray
    mb: np.ndarray
    ab: np.ndarray
    baseline_sum: float
    chunk_mb: tuple = ()
    chunk_ab: tuple = ()


def merge_accum_parts(parts: Sequence["AccumParts"]) -> "AccumParts":
    """Merge per-window accumulators IN ORDER (earliest window first).

    Addition order of the f64 moment sums is then deterministic, and max
    tie-breaking keeps the earliest window's peak — the same choice the
    sequential chunk loop makes (``_Accum.update`` keeps the incumbent on
    ties), so a time-sharded sweep merges to the sequential result up to
    f64 re-association of the moment sums (mb/ab exactly equal). Chunk
    peak records concatenate in window order (= the sequential chunk
    order)."""
    if not parts:
        raise ValueError("no accumulator parts to merge")
    n = parts[0].n
    s = np.array(parts[0].s, dtype=np.float64)
    ss = np.array(parts[0].ss, dtype=np.float64)
    mb = np.array(parts[0].mb)
    ab = np.array(parts[0].ab, dtype=np.int64)
    chunk_mb = tuple(parts[0].chunk_mb)
    chunk_ab = tuple(parts[0].chunk_ab)
    for p in parts[1:]:
        n += p.n
        s += p.s
        ss += p.ss
        better = p.mb > mb
        mb = np.where(better, p.mb, mb)
        ab = np.where(better, p.ab, ab)
        chunk_mb += tuple(p.chunk_mb)
        chunk_ab += tuple(p.chunk_ab)
    return AccumParts(n, s, ss, mb, ab, parts[0].baseline_sum,
                      chunk_mb, chunk_ab)


def _repad_rows(a: np.ndarray, pad: int) -> np.ndarray:
    """Extend the trial axis by ``pad`` copies of the last real row —
    exactly what padded trials (replicated last DM) would have
    accumulated, so a checkpoint saved at one padded width resumes at
    another bit-for-bit."""
    a = np.asarray(a)
    if pad <= 0:
        return a
    return np.concatenate([a, np.repeat(a[-1:], pad, axis=0)], axis=0)


class _Accum:
    def __init__(self, D, W, keep_chunk_peaks: bool = False,
                 n_real: Optional[int] = None):
        self.n = 0
        self.s = np.zeros(D)
        self.ss = np.zeros(D)
        self.mb = np.full((D, W), -np.inf)
        self.ab = np.zeros((D, W), dtype=np.int64)
        # optional per-chunk peak record: one (maxbox, argbox) pair per
        # (chunk, trial, width), stored f32 and sliced to the real trials
        # — ~n_chunks * D * W * 12 bytes (e.g. ~90 MB for a 2000-trial,
        # 2700-chunk survey sweep)
        self.keep_chunk_peaks = keep_chunk_peaks
        self.n_real = D if n_real is None else n_real
        self.chunk_mb: list = []
        self.chunk_ab: list = []

    def update(self, start, stat_len, s, ss, mb, ab):
        self.n += stat_len
        self.s += np.asarray(s, dtype=np.float64)
        self.ss += np.asarray(ss, dtype=np.float64)
        mb = np.asarray(mb)
        ab = np.asarray(ab, dtype=np.int64) + start
        if self.keep_chunk_peaks:
            self.chunk_mb.append(mb[: self.n_real].astype(np.float32))
            self.chunk_ab.append(ab[: self.n_real].copy())
        better = mb > self.mb
        self.mb = np.where(better, mb, self.mb)
        self.ab = np.where(better, ab, self.ab)


class SweepCheckpoint:
    """In-sweep checkpointing for long streams (SURVEY.md §5: the reference
    pipeline is file-granular; a multi-hour 4096-trial sweep needs a
    restart point finer than whole files).

    Persists the host-side accumulator (`_Accum`), the resume cursor (first
    unprocessed payload sample) and the per-channel baseline every ``every``
    drained chunks, written atomically (tmp + rename). Chunk accumulation
    happens in stream order on resume exactly as it would uninterrupted, so
    a killed-and-resumed sweep reproduces the uninterrupted result
    bit-for-bit (tested in tests/test_sweep.py).

    A fingerprint of the plan geometry guards against resuming with
    different parameters: mismatch starts from scratch.
    """

    def __init__(self, path: str, every: int = 16, cleanup: bool = True):
        self.path = path
        self.every = max(1, int(every))
        self.cleanup = cleanup
        self._drained = 0

    @staticmethod
    def _fingerprint(plan: SweepPlan, chunk_payload: int,
                     context: str = "") -> str:
        """``context`` carries everything outside the plan that affects the
        numerics — the resolved engine and the mesh layout — so a
        checkpoint can only resume under the exact configuration that
        wrote it (the bit-identity contract; engines agree only to
        ~1e-4). Only the *real* trials are hashed: padded trials
        replicate the last real DM, so the padded group count (mesh
        divisibility, compile-plane bucket ladder) is an execution
        detail a resume may legally change (round 22)."""
        import hashlib

        h = hashlib.sha256()
        nr = plan.n_real_trials
        for part in (plan.dms[:nr].tobytes(), plan.freqs.tobytes(),
                     np.float64(plan.dt).tobytes(),
                     np.int64([plan.nsub, plan.group_size,
                               plan.n_real_trials, chunk_payload]).tobytes(),
                     np.int64(plan.widths).tobytes(),
                     context.encode()):
            h.update(part)
        return h.hexdigest()

    def load(self, plan: SweepPlan, chunk_payload: int, context: str = "",
             keep_chunk_peaks: bool = False):
        """(acc, cursor, baseline) from a matching checkpoint, else None.
        ``keep_chunk_peaks`` must match the value the checkpoint was
        written with (it is part of the fingerprinted state: a resume
        without the per-chunk record would silently drop events)."""
        if not os.path.exists(self.path):
            return None
        try:
            with np.load(self.path, allow_pickle=False) as z:
                if str(z["fingerprint"]) != self._fingerprint(
                        plan, chunk_payload, context):
                    return None
                has_peaks = "chunk_mb" in z
                if has_peaks != keep_chunk_peaks:
                    return None
                acc = _Accum(plan.n_trials, len(plan.widths),
                             keep_chunk_peaks=keep_chunk_peaks,
                             n_real=plan.n_real_trials)
                acc.n = int(z["n"])
                # checkpoints persist the real rows only; padded trials
                # replicate the last real DM, so their accumulator state
                # is bit-identical to the last real row — rebuild it by
                # replication at whatever padded width THIS run uses
                # (the bucket ladder may have moved between runs)
                pad = plan.n_trials - plan.n_real_trials
                acc.s = _repad_rows(z["s"], pad)
                acc.ss = _repad_rows(z["ss"], pad)
                acc.mb = _repad_rows(z["mb"], pad)
                acc.ab = _repad_rows(z["ab"], pad)
                if keep_chunk_peaks:
                    acc.chunk_mb = list(z["chunk_mb"])
                    acc.chunk_ab = list(z["chunk_ab"])
                return acc, int(z["cursor"]), z["baseline"]
        except Exception:  # noqa: BLE001 - a corrupt checkpoint restarts
            return None

    def save(self, plan: SweepPlan, chunk_payload: int, acc: "_Accum",
             cursor: int, baseline, context: str = "") -> None:
        tmp = self.path + ".tmp.npz"  # .npz suffix: savez must not append
        extra = {}
        if acc.keep_chunk_peaks:
            # every entry is [n_real, W]; the key must exist even before
            # the first drain so load() can tell peak checkpoints apart
            W = acc.mb.shape[1]
            extra["chunk_mb"] = (np.stack(acc.chunk_mb) if acc.chunk_mb
                                 else np.zeros((0, acc.n_real, W),
                                               np.float32))
            extra["chunk_ab"] = (np.stack(acc.chunk_ab) if acc.chunk_ab
                                 else np.zeros((0, acc.n_real, W),
                                               np.int64))
        nr = plan.n_real_trials  # real rows only: see load()
        np.savez(tmp,
                 fingerprint=self._fingerprint(plan, chunk_payload, context),
                 n=acc.n, s=acc.s[:nr], ss=acc.ss[:nr], mb=acc.mb[:nr],
                 ab=acc.ab[:nr],
                 cursor=cursor,
                 baseline=np.asarray(baseline, dtype=np.float32),
                 **extra)
        os.replace(tmp, self.path)

    def on_drained(self, plan, chunk_payload, acc, cursor, baseline,
                   context: str = "", n: int = 1) -> None:
        """Account ``n`` newly drained chunks; save when the count crosses
        an ``every`` boundary. Burst draining accounts a whole batch in
        one call with the batch-end (acc, cursor) — the only state pair
        that is consistent (acc already holds every drained chunk, so a
        mid-batch cursor would double-accumulate on resume)."""
        fire = (self._drained + n) // self.every > self._drained // self.every
        self._drained += n
        if fire:
            telemetry.counter("sweep.checkpoint_saves")
            with profiling.stage("checkpoint_save"):
                self.save(plan, chunk_payload, acc, cursor, baseline,
                          context)

    def finish(self) -> None:
        if self.cleanup and os.path.exists(self.path):
            os.remove(self.path)


def sweep_stream(
    plan: SweepPlan,
    blocks,
    chunk_payload: int,
    mesh: Optional[Mesh] = None,
    chan_major: bool = False,
    baseline=None,
    engine: str = "auto",
    max_pending: Optional[int] = None,
    checkpoint: Optional[SweepCheckpoint] = None,
    keep_chunk_peaks: bool = False,
    block_factory=None,
    checkpoint_context: str = "",
    finalize: bool = True,
) -> SweepResult:
    """Run the sweep over a stream of (startsamp, block) chunks.
    ``checkpoint_context`` is appended to the checkpoint fingerprint
    context for result-affecting state the plan cannot see (e.g. the
    rfifind mask applied by the block source).

    Blocks are [time, chan] host arrays (e.g. FilterbankFile.iter_blocks with
    overlap >= plan.min_overlap) or, with ``chan_major=True``, [chan, time]
    arrays that may already live on device (device-resident datasets slice
    with no host round-trip).

    When ``mesh`` is given, trial groups are sharded over its 'dm' axis via
    shard_map — zero cross-device communication until the final (host-side)
    top-k, the layout the north star prescribes.

    SNR accumulation-order contract (the "bit-exact SNR" policy, BASELINE.md):

    1. A single per-channel baseline — ``baseline`` if given (sweep_spectra
       passes the whole-series per-channel mean so results are independent
       of chunking), else the f32 per-channel mean of the first streamed
       block — is subtracted from every block before dedispersion.
       The SNR is exactly invariant under per-channel constant shifts (every
       window sum of trial d loses ``w * B`` and the series mean loses ``B``
       where ``B = sum_c baseline_c``), so this changes no result in exact
       arithmetic; numerically it removes the DC term so all f32 rounding is
       relative to the *fluctuation* scale, not the offset (8-bit PSRFITS
       data has offsets ~100x sigma, which otherwise costs ~3 decimal digits
       of SNR through catastrophic cancellation in ``maxbox - w*mean``).
    2. On device (f32): stage-1 channel-group sums and stage-2 subband sums
       in XLA reduction order; per-chunk payload sum/sumsq; per-width window
       sums (cumsum-difference in the lax path, dyadic doubling in the
       Pallas kernel) and their running max.
    3. On host (f64): cross-chunk accumulation of the moments, the
       cross-chunk max of the f32 window sums, and the final SNR formula
       ``(maxbox - w*mean) / (sqrt(w)*std)``.

    Guaranteed (and tested, tests/test_sweep.py) bound vs the float64 NumPy
    twin: |dSNR| <= 1e-4 absolute with relative error at f32-ulp scale
    (measured ~1e-6), independent of per-channel DC offsets. End-of-data is
    zero-padded *after* baseline subtraction, i.e. padded samples sit at the
    channel baseline level in original units.
    """
    engine = resolve_engine(engine)
    W = max(plan.widths)
    out_len = chunk_payload + W
    slack2 = plan.max_shift2
    D = plan.n_trials
    acc = _Accum(D, len(plan.widths), keep_chunk_peaks=keep_chunk_peaks,
                 n_real=plan.n_real_trials)
    cursor = 0  # first payload sample not yet accumulated
    ckpt_context = "engine=%s/meshdm=%s%s" % (
        engine, 0 if mesh is None else mesh.shape.get("dm", 0),
        checkpoint_context)
    if checkpoint is not None:
        state = checkpoint.load(plan, chunk_payload, ckpt_context,
                                keep_chunk_peaks=keep_chunk_peaks)
        if state is not None:
            acc, cursor, ckpt_baseline = state
            if baseline is None:
                baseline = ckpt_baseline  # bit-identical resume needs it
            if cursor > 0 and block_factory is not None:
                # seek-resume (round 5): without this, a resumed sweep
                # re-produces (reads AND ships) every pre-cursor block
                # only for the `start < cursor` guard below to drop it —
                # a resume at 65% of the 28.8 GB north star replayed the
                # whole wire. The factory re-roots the stream at the
                # cursor; the guard stays as the correctness backstop.
                blocks = block_factory(cursor)

    s1 = jnp.asarray(plan.stage1_bins)
    s2 = jnp.asarray(plan.stage2_bins)
    if mesh is not None:
        if plan.n_groups % mesh.shape["dm"]:
            raise ValueError(
                f"group count {plan.n_groups} must divide mesh 'dm' axis "
                f"{mesh.shape['dm']}; use make_sweep_plan(pad_groups_to=...)"
            )
        spec = NamedSharding(mesh, P("dm"))
        s1 = jax.device_put(s1, spec)
        s2 = jax.device_put(s2, spec)

    sharded_fns = {}  # stat_len -> compiled sharded chunk fn

    def run_chunk(data, stat_len):
        """Dispatch one chunk over the trial groups; returns a LIST of
        output 4-tuples in group order (normally one entry covering every
        group). A device RESOURCE_EXHAUSTED halves the group axis with
        bounded backoff and re-dispatches the halves
        (resilience.retry.halving_dispatch) — per-group scans share no
        state, so host-side concatenation of the halves is bit-identical
        to the whole dispatch. OOM only surfaces here at dispatch time;
        an async-surfaced OOM at the drain pull stays fatal."""
        from pypulsar_tpu.resilience import faultinject
        from pypulsar_tpu.resilience.retry import halving_dispatch

        ndm = 1 if mesh is None else mesh.shape["dm"]
        n_groups = plan.n_groups

        def dispatch(lo, hi):
            faultinject.trip("sweep.chunk_dispatch")
            whole = (lo, hi) == (0, n_groups)
            s1_sl, s2_sl = (s1, s2) if whole else (s1[lo:hi], s2[lo:hi])
            if mesh is None:
                return sweep_chunk(
                    data, s1_sl, s2_sl, plan.nsub, out_len, slack2,
                    plan.widths, stat_len, engine=engine
                )
            if not whole:  # re-lay the sliced tables on the mesh
                spec_sl = NamedSharding(mesh, P("dm"))
                s1_sl = jax.device_put(s1_sl, spec_sl)
                s2_sl = jax.device_put(s2_sl, spec_sl)
            if stat_len not in sharded_fns:
                sharded_fns[stat_len] = make_sharded_sweep_chunk(
                    mesh, plan.nsub, out_len, slack2, plan.widths,
                    stat_len, engine=engine
                )
            return sharded_fns[stat_len](data, s1_sl, s2_sl)

        return [outs for _, _, outs in halving_dispatch(
            dispatch, n_groups, min_size=ndm, what="sweep.chunk")]

    # Dispatch a few chunks ahead of the host-side accumulate so transfers
    # overlap compute, but bound the depth so queued input buffers (one chunk
    # of HBM each) can be freed. Callers with an HBM budget (bench.py) pass
    # ``max_pending`` explicitly; each pending chunk holds one input buffer.
    MAX_PENDING = 4 if max_pending is None else max(1, int(max_pending))
    DRAIN_BATCH = min(4, MAX_PENDING)
    pending = []  # (start, stat_len, [device output 4-tuples, group order])

    def drain(limit):
        nonlocal cursor
        if len(pending) <= limit:
            return
        # pull EVERY due chunk's outputs in ONE device_get, then
        # accumulate host-side in stream order (bit-identical to
        # per-chunk pulls). Through the axon tunnel each pull waits for
        # whatever put is on the wire (~0.5 s average at streamed block
        # sizes, BENCHNOTES r4) — the 4-bit full-file run spent ~108 s
        # of its 632 s wall in that trap, so batching the pulls divides
        # the per-chunk toll by the batch size (round 5). Outputs are
        # KBs per chunk; the batch adds no meaningful HBM.
        due = []
        while len(pending) > limit:
            due.append(pending.pop(0))
        with profiling.stage("device_wait+accumulate"):
            flat = transfer.pull_host(
                *(arr for _, _, parts in due for outs in parts
                  for arr in outs))
            k = 0
            for start, stat_len, parts in due:
                got = flat[k:k + 4 * len(parts)]
                k += 4 * len(parts)
                if len(parts) == 1:
                    s, ss, mb, ab = got
                else:
                    # OOM-halved chunk: concatenate the group-axis
                    # slices back to the full trial axis (group order
                    # was preserved, so this is the whole dispatch)
                    s, ss, mb, ab = (
                        np.concatenate(got[j::4]) for j in range(4))
                acc.update(start, stat_len, s, ss, mb, ab)
                cursor = start + stat_len
        # outside the stage: checkpoint_save has its own profiling stage
        # and nested stages both record wall time (utils/profiling.py),
        # so saving inside would double-count in the overlap accounting
        if checkpoint is not None:
            checkpoint.on_drained(plan, chunk_payload, acc, cursor,
                                  baseline, ckpt_context, n=len(due))

    need = out_len + slack2 + plan.max_shift1

    def process(start, data, L):
        if L < need:  # end-of-data: pad with zeros (reference pads padval=0)
            data = jnp.pad(data, ((0, 0), (0, need - L)))
        stat_len = min(chunk_payload, L)
        with profiling.stage("dispatch_sweep_chunk"):
            pending.append((start, stat_len, run_chunk(data, stat_len)))
        if telemetry.is_active():
            # one record per streamed chunk: position, payload and the
            # dispatch-pipeline depth at this moment (how far device work
            # ran ahead of the host accumulate)
            telemetry.counter("sweep.chunks")
            telemetry.gauge("sweep.pending_depth", len(pending))
            telemetry.event("sweep.chunk", start=int(start),
                            stat_len=int(stat_len), pending=len(pending))

    # A short block is only legal at end-of-data: hold one block back so we
    # can tell whether the stream continues past its end. A block that is
    # short while later data exists would silently zero-pad real samples and
    # depress every seam SNR — raise instead.
    prev = None
    if baseline is not None:
        baseline = jnp.asarray(baseline, dtype=jnp.float32).reshape(-1, 1)
    # explicit iteration so the time spent PRODUCING each block (disk read
    # wait + host->device ship in the source generator) is attributed to
    # its own profiling stage — the streamed-bench overlap accounting
    # needs transfer separated from device wait (BENCHNOTES.md round 4)
    _block_iter = iter(blocks)
    while True:
        with profiling.stage("block_source"):
            nxt = next(_block_iter, None)
        if nxt is None:
            break
        start, block = nxt
        if start < cursor:  # chunk already accumulated (checkpoint resume)
            continue
        with profiling.stage("host_to_device"):
            was_host = not isinstance(block, jax.Array)
            if chan_major:
                data = jnp.asarray(block, dtype=jnp.float32)
            else:
                data = jnp.asarray(np.ascontiguousarray(block.T), dtype=jnp.float32)
            if was_host and telemetry.is_active():
                telemetry.counter("h2d.bytes", int(data.nbytes))
        if baseline is None:
            # per-channel baseline from the first block (see the SNR
            # accumulation-order contract in the docstring)
            baseline = jnp.mean(data, axis=1, keepdims=True)
        data = data - baseline
        L = data.shape[1]
        if prev is not None:
            pstart, pdata, pL = prev
            if pL < need and pstart + pL < start + L:
                raise ValueError(
                    f"interior block at sample {pstart} has {pL} samples but "
                    f"data continues to sample {start + L}; the sweep needs "
                    f"{need} per block (payload {chunk_payload} + overlap >= "
                    f"plan.min_overlap = {plan.min_overlap}); stream blocks "
                    f"with block_size={chunk_payload} and overlap >= "
                    f"plan.min_overlap"
                )
            process(pstart, pdata, pL)
            # burst drain: let MAX_PENDING chunks queue, then pull them
            # all in one roundtrip (see drain) — a per-block drain would
            # pay the trapped-pull toll once per chunk
            if len(pending) > MAX_PENDING:
                drain(max(MAX_PENDING - DRAIN_BATCH, 0))
        prev = (start, data, L)
    if prev is not None:
        process(*prev)
    drain(0)
    if checkpoint is not None:
        checkpoint.finish()
    if telemetry.is_active():
        telemetry.counter("sweep.trials_completed", plan.n_real_trials)
        telemetry.counter("sweep.payload_samples", int(acc.n))
        telemetry.device_snapshot(tag="sweep_stream_end")

    B = float(np.asarray(baseline, dtype=np.float64).sum()) if baseline is not None else 0.0
    if not finalize:
        # raw accumulator parts, for callers that merge across hosts
        # before the (single) finalize — parallel.distributed.
        # time_sharded_sweep merges windows in time order so the f64
        # accumulation grouping is deterministic
        return AccumParts(acc.n, acc.s, acc.ss, acc.mb, acc.ab, B,
                          tuple(acc.chunk_mb), tuple(acc.chunk_ab))
    return finalize_sweep(plan, acc.n, acc.s, acc.ss, acc.mb, acc.ab, B,
                          chunk_mb=acc.chunk_mb, chunk_ab=acc.chunk_ab)


def finalize_sweep(plan: SweepPlan, n: int, s, ss, mb, ab,
                   baseline_sum: float = 0.0,
                   chunk_mb=None, chunk_ab=None) -> SweepResult:
    """Host-side (float64) SNR formula over accumulated moments + window
    maxima — step 3 of the accumulation-order contract. ``baseline_sum``
    restores the reported mean to original (pre-baseline-subtraction)
    units; snr and std are invariant under the per-channel shift.
    ``chunk_mb``/``chunk_ab`` (lists of per-chunk [D, W] peaks) populate
    the multi-event fields using the same whole-series moments."""
    s = np.asarray(s, dtype=np.float64)
    ss = np.asarray(ss, dtype=np.float64)
    mb = np.asarray(mb, dtype=np.float64)
    ab = np.asarray(ab, dtype=np.int64)
    mean = s / max(n, 1)
    var = np.maximum(ss / max(n, 1) - mean * mean, 0.0)
    std = np.sqrt(var)
    ws = np.array(plan.widths, dtype=np.float64)
    denom = np.sqrt(ws)[None, :] * np.where(std > 0, std, 1.0)[:, None]

    def to_snr(maxbox):
        return (maxbox - ws[None, :] * mean[:, None]) / denom

    snr = to_snr(mb)
    nr = plan.n_real_trials
    chunk_snr = chunk_sample = None
    if chunk_mb:
        # entries are already [:nr] — slice the moments to match (trials
        # can be padded to a group multiple, so nr < D is the norm)
        mean_r = mean[:nr]
        denom_r = denom[:nr]
        chunk_snr = np.stack([
            ((np.asarray(m, dtype=np.float64)[:nr]
              - ws[None, :] * mean_r[:, None]) / denom_r)
            .astype(np.float32)
            for m in chunk_mb])
        chunk_sample = np.stack([np.asarray(a, dtype=np.int64)[:nr]
                                 for a in chunk_ab])
    return SweepResult(
        dms=plan.dms[:nr],
        widths=plan.widths,
        snr=snr[:nr],
        peak_sample=ab[:nr],
        mean=mean[:nr] + baseline_sum,
        std=std[:nr],
        chunk_snr=chunk_snr,
        chunk_sample=chunk_sample,
    )


def padded_group_count(n_groups: int, ndm: int = 1) -> int:
    """Canonical padded trial-group count (round 22): the real group
    count rounded so groups divide the mesh 'dm' axis (``ndm``) and,
    when ``PYPULSAR_TPU_COMPILE_BUCKETS`` is on, up the compile plane's
    bucket ladder. Padded groups replicate the last real trial — the
    real rows are bit-exact regardless of padding — so bucketing trades
    a few redundant trials for executable reuse across nearby DM
    counts. The bucket choice never reaches a checkpoint/journal
    fingerprint (those hash real trials only), so resumes cross
    bucket-ladder changes byte-identically."""
    G = int(n_groups)
    ndm = max(1, int(ndm))
    base = -(-G // ndm) * ndm  # mesh-divisibility floor (pre-round-22)
    padded = bucket_rows(G, multiple=ndm)
    if padded > base:
        note_bucket_pad(base, padded)
    return padded


def _mesh_pad_groups(n_dms: int, group_size: int, mesh) -> Optional[int]:
    """Group padding so trial groups divide the mesh 'dm' axis and land
    on the compile plane's bucket ladder (padded_group_count)."""
    G = -(-n_dms // group_size)
    ndm = 1 if mesh is None else mesh.shape["dm"]
    padded = padded_group_count(G, ndm)
    if mesh is None and padded == G:
        return None  # nothing pads: keep the plan's natural shape
    return padded


def _series_baseline(data):
    """Whole-series per-channel baseline per the SNR contract: host arrays
    get a float64 host mean (cast to f32), device arrays a device mean —
    identical across the streamed and resident paths."""
    if isinstance(data, np.ndarray):
        return np.mean(data, axis=1, keepdims=True,
                       dtype=np.float64).astype(np.float32)
    return jnp.mean(data.astype(jnp.float32), axis=1, keepdims=True)


def sweep_spectra(spectra, dms, nsub=64, group_size=32, widths=DEFAULT_WIDTHS,
                  chunk_payload=None, mesh=None, pad_groups_to=None,
                  engine="auto", max_pending=None) -> SweepResult:
    """Convenience: sweep an in-memory (possibly device-resident) Spectra
    over ``dms``; chunks are device-side slices, no host round-trips."""
    freqs = np.asarray(spectra.freqs, dtype=np.float64)
    if group_size <= 0:
        group_size = choose_group_size(dms, freqs, spectra.dt, nsub)
    if pad_groups_to is None:
        pad_groups_to = _mesh_pad_groups(len(dms), group_size, mesh)
    plan = make_sweep_plan(dms, freqs, spectra.dt, nsub=nsub, group_size=group_size,
                           widths=widths, pad_groups_to=pad_groups_to)
    T = spectra.numspectra
    if chunk_payload is None:
        chunk_payload = T
    data = spectra.data

    def blocks():
        ov = plan.min_overlap
        pos = 0
        while pos < T:
            n = min(chunk_payload + ov, T - pos)
            yield pos, data[:, pos : pos + n]
            pos += chunk_payload

    # whole-series per-channel baseline: makes the result (incl. the padded
    # end-of-data windows) independent of chunk_payload — see the contract.
    # Host arrays stay on host for this (a device round-trip of the full
    # series would defeat chunked streaming's memory bound).
    baseline = _series_baseline(data)
    return sweep_stream(plan, blocks(), chunk_payload, mesh=mesh, chan_major=True,
                        baseline=baseline, engine=engine, max_pending=max_pending)


def sweep_resident(spectra, dms, nsub=64, group_size=32, widths=DEFAULT_WIDTHS,
                   chunk_payload=None, engine="auto",
                   pad_groups_to=None, mesh=None) -> SweepResult:
    """Whole sweep of a device-resident Spectra as ONE compiled program.

    ``sweep_spectra`` dispatches per chunk and pulls per-chunk statistics
    to the host accumulator — the right structure for streamed files, but
    on a remote accelerator every dispatch/pull pays link latency (~60 ms
    on the axon v5e tunnel, BENCHNOTES.md). Here the chunk loop is a
    ``lax.scan`` over device-side slices of the resident dataset: per-chunk
    statistics stack on device and ship in a single transfer, and the host
    combines them in stream order — the SAME f64 cross-chunk accumulation
    the streamed path performs, so results are bit-identical to
    ``sweep_spectra`` with the same chunking (tested).

    The time axis is truncated to a whole number of chunks (bench data is
    sized accordingly; file pipelines should use the streamed path, which
    handles ragged tails). With ``mesh``, trial groups shard over its 'dm'
    axis inside the same single program.
    """
    engine = resolve_engine(engine)
    if engine == "tree":
        raise ValueError(
            "sweep_resident's single compiled program cannot host the "
            "tree engine (host-built merge tables); use the streamed "
            "path (sweep_spectra/sweep_stream) with engine='tree'")
    freqs = np.asarray(spectra.freqs, dtype=np.float64)
    if group_size <= 0:
        group_size = choose_group_size(dms, freqs, spectra.dt, nsub)
    if pad_groups_to is None:
        pad_groups_to = _mesh_pad_groups(len(dms), group_size, mesh)
    plan = make_sweep_plan(dms, freqs, spectra.dt, nsub=nsub,
                           group_size=group_size, widths=tuple(widths),
                           pad_groups_to=pad_groups_to)
    T = spectra.numspectra
    payload = T if chunk_payload is None else min(chunk_payload, T)
    n_chunks = max(T // payload, 1)
    T_used = n_chunks * payload
    W = max(plan.widths)
    out_len = payload + W
    slack2 = plan.max_shift2
    need = out_len + slack2 + plan.max_shift1

    data = jnp.asarray(spectra.data, dtype=jnp.float32)[:, :T_used]
    s1 = jnp.asarray(plan.stage1_bins)
    s2 = jnp.asarray(plan.stage2_bins)
    if mesh is not None:
        if plan.n_groups % mesh.shape["dm"]:
            raise ValueError("group count must divide the mesh 'dm' axis")
        spec_sh = NamedSharding(mesh, P("dm"))
        s1 = jax.device_put(s1, spec_sh)
        s2 = jax.device_put(s2, spec_sh)

    run = _make_resident_runner(plan.nsub, out_len, slack2, plan.widths,
                                payload, need, engine, mesh)
    # baseline parity with sweep_spectra: host f64 mean for host arrays
    # (the docstring's bit-identity contract includes the baseline)
    baseline = jnp.asarray(
        _series_baseline(np.asarray(spectra.data)[:, :T_used]
                         if isinstance(spectra.data, np.ndarray)
                         else data))
    with telemetry.span("sweep_resident_run", n_chunks=n_chunks,
                        payload=int(payload)):
        s, ss, mb, ab = transfer.pull_host(
            *run(data, s1, s2, baseline, n_chunks))
    if telemetry.is_active():
        telemetry.counter("sweep.chunks", n_chunks)
        telemetry.counter("sweep.trials_completed", plan.n_real_trials)
        telemetry.counter("sweep.payload_samples", int(n_chunks * payload))
        telemetry.device_snapshot(tag="sweep_resident_end")
    s = np.asarray(s, dtype=np.float64)
    ss = np.asarray(ss, dtype=np.float64)
    mb = np.asarray(mb)
    ab = np.asarray(ab, dtype=np.int64)
    acc = _Accum(plan.n_trials, len(plan.widths))
    for ci in range(n_chunks):
        acc.update(ci * payload, payload, s[ci], ss[ci], mb[ci], ab[ci])
    B = float(np.asarray(baseline, dtype=np.float64).sum())
    return finalize_sweep(plan, acc.n, acc.s, acc.ss, acc.mb, acc.ab, B)


@functools.lru_cache(maxsize=32)
def _make_resident_runner(nsub, out_len, slack2, widths, payload, need,
                          engine, mesh):
    """Compiled whole-sweep scan program, cached across calls (a fresh
    jit closure per sweep would recompile every invocation)."""
    impl = partial(_sweep_chunk_impl, nsub=nsub, out_len=out_len,
                   slack2=slack2, widths=widths, stat_len=payload,
                   engine=engine)
    if mesh is not None:
        impl = shard_map_compat(impl, mesh=mesh,
                                in_specs=(P(), P("dm"), P("dm")),
                                out_specs=P("dm"))

    # NOT donated: a full-size slice of the caller's Spectra shares its
    # buffer (verified), so donation would invalidate the caller's data on
    # backends that honor it; bench budgeting charges the padded working
    # copy instead
    @plane_jit(static_argnames=("n_chunks",), stage="sweep",
               aot=(mesh is None))
    def run(data, s1, s2, baseline, n_chunks):
        data = data - baseline
        # zero tail pad so the final chunk's overlap reads data-shaped zeros
        padded = jnp.pad(data, ((0, 0), (0, need)))

        def body(carry, ci):
            chunk = jax.lax.dynamic_slice(
                padded, (0, ci * payload), (padded.shape[0], need))
            return carry, impl(chunk, s1, s2)

        _, ys = jax.lax.scan(body, 0, jnp.arange(n_chunks))
        return ys

    return run


# ---------------------------------------------------------------------------
# warm-pool precompile (round 22)

def _warm_sweep(*, dms, freqs, dt, nsub=64, group_size=0,
                widths=DEFAULT_WIDTHS, n_samples=None, downsamp=1,
                chunk_payload=None, engine="auto", **_ignored) -> int:
    """Warm-pool planner for the sweep stage: rebuild the geometry the
    streamed sweep will dispatch (plan, bounded chunk payload, padded
    group tables) and AOT-lower the chunk kernel from abstract arrays —
    no data read, nothing dispatched. Extra geometry keys are ignored
    so one scheduler-side dict can feed every stage's warmer."""
    dms = np.asarray(dms, dtype=np.float64)
    # the plan wants high-frequency-first channels (the block sources
    # flip ascending tables; shapes are order-independent anyway)
    freqs = np.sort(np.asarray(freqs, dtype=np.float64))[::-1].copy()
    if dms.size == 0 or freqs.size == 0 or not dt or dt <= 0:
        return 0
    factor = max(1, int(downsamp))
    dt = float(dt) * factor  # ``dt`` is the RAW header sample time
    if group_size <= 0:
        group_size = choose_group_size(dms, freqs, float(dt), nsub)
    plan = make_sweep_plan(
        dms, freqs, float(dt), nsub=nsub, group_size=group_size,
        widths=tuple(widths),
        pad_groups_to=_mesh_pad_groups(len(dms), group_size, None))
    if chunk_payload is None:
        # the staged CLI's bounded default (tuned=False: detection
        # chunks are results, the tuner's overlay must not move them)
        chunk_payload = default_chunk_payload(plan.min_overlap,
                                              tuned=False)
    if n_samples:
        n_ds = int(n_samples) // factor
        chunk_payload = min(int(chunk_payload), n_ds)
        if chunk_payload <= plan.min_overlap:
            chunk_payload = min(n_ds, 2 * plan.min_overlap + 1)
        if chunk_payload <= 0:
            return 0
    W = max(plan.widths)
    out_len = int(chunk_payload) + W
    need = out_len + plan.max_shift2 + plan.max_shift1
    data = jax.ShapeDtypeStruct((len(freqs), need), np.float32)
    s1 = jax.ShapeDtypeStruct(plan.stage1_bins.shape,
                              plan.stage1_bins.dtype)
    s2 = jax.ShapeDtypeStruct(plan.stage2_bins.shape,
                              plan.stage2_bins.dtype)
    return int(_sweep_chunk_jit.warm(
        data, s1, s2, plan.nsub, out_len, plan.max_shift2,
        tuple(plan.widths), int(chunk_payload),
        engine=resolve_engine(engine)))


register_warmer("sweep", _warm_sweep)
